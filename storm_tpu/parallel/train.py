"""Sharded training step: dp x tp over the device mesh.

The reference is inference-only (models arrive as frozen graphs,
InferenceBolt.java:57); this module closes the loop so models served by the
framework can also be (re)trained on the same slice — and it is the
multi-chip program exercised by ``__graft_entry__.dryrun_multichip``.

Design: pure ``jax.jit`` + committed input shardings (GSPMD propagates the
rest and inserts the ICI collectives):
- batch axis sharded over ``data`` (dp);
- transformer matmul params Megatron-sharded over ``model`` (tp):
  column-parallel qkv/mlp_in, row-parallel o/mlp_out
  (:func:`storm_tpu.parallel.sharding.shard_params_tp`);
- activations constrained to (data, None, model) between blocks, so the
  sequence axis stays local while hidden is tp-sharded;
- gradients/optimizer state inherit param shardings; the dp grad psum is
  inserted by XLA from the sharding annotations (no hand-written NCCL —
  SURVEY.md §2.5 accelerator-collectives row).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from storm_tpu.models.registry import ModelDef
from storm_tpu.parallel.sharding import shard_params_tp, batch_sharding, replicated


def make_train_step(
    model: ModelDef,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-3,
) -> Tuple[Callable, optax.GradientTransformation]:
    """Build a jit-compiled ``(params, opt_state, state, x, y) ->
    (params, opt_state, state, loss)`` step. Shardings are taken from the
    committed shardings of the inputs (GSPMD propagation)."""
    opt = optimizer or optax.adamw(learning_rate)

    def loss_fn(params, state, x, y):
        logits, new_state = model.apply(params, state, x, train=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()
        return loss, new_state

    @jax.jit
    def train_step(params, opt_state, state, x, y):
        (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, x, y
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, loss

    return train_step, opt


def init_sharded_training(
    model: ModelDef,
    mesh: Mesh,
    seed: int = 0,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-3,
):
    """Initialize (params, opt_state, state) placed on the mesh:
    params tp-sharded, optimizer state following params, model state
    replicated. Returns (train_step, params, opt_state, state)."""
    train_step, opt = make_train_step(model, optimizer, learning_rate)
    params, state = model.init(jax.random.PRNGKey(seed))
    params = shard_params_tp(mesh, params)
    state = jax.device_put(state, replicated(mesh))
    # opt.init under jit: output shardings propagate from the sharded params.
    opt_state = jax.jit(opt.init)(params)
    return train_step, params, opt_state, state


def train_one_step(
    train_step: Callable,
    mesh: Mesh,
    params,
    opt_state,
    state,
    x: np.ndarray,
    y: np.ndarray,
):
    """Place one (x, y) batch dp-sharded and run the step."""
    xs = jax.device_put(x, batch_sharding(mesh))
    ys = jax.device_put(y, batch_sharding(mesh))
    return train_step(params, opt_state, state, xs, ys)
