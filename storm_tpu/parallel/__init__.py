"""Parallelism strategies over the TPU mesh (SURVEY.md §2.4 checklist):
dp/tp (mesh, sharding, train), pp (pipeline), sp (ring_attention, sequence),
ep (moe).

Submodules that pull heavier deps (optax for training, the model registry)
are imported lazily so inference-only paths (`storm_tpu.infer`,
`storm_tpu.main serve`) never pay for them at import time.
"""

from storm_tpu.parallel.mesh import make_mesh, default_mesh
from storm_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    shard_batch,
    shard_params_tp,
)

_LAZY = {
    "ring_attention": ("storm_tpu.parallel.ring_attention", "ring_attention"),
    "pipeline_apply": ("storm_tpu.parallel.pipeline", "pipeline_apply"),
    "init_pp_training": ("storm_tpu.parallel.pipeline", "init_pp_training"),
    "moe_init": ("storm_tpu.parallel.moe", "moe_init"),
    "moe_layer": ("storm_tpu.parallel.moe", "moe_layer"),
    "moe_block": ("storm_tpu.parallel.moe", "moe_block"),
    "shard_moe_params": ("storm_tpu.parallel.moe", "shard_moe_params"),
    "seq_parallel_block": ("storm_tpu.parallel.sequence", "seq_parallel_block"),
    "seq_parallel_encoder": ("storm_tpu.parallel.sequence", "seq_parallel_encoder"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "make_mesh",
    "default_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params_tp",
    *_LAZY,
]
