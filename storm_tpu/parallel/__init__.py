from storm_tpu.parallel.mesh import make_mesh, default_mesh
from storm_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    shard_batch,
    shard_params_tp,
)

__all__ = [
    "make_mesh",
    "default_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params_tp",
]
