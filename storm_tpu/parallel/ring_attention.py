"""Ring attention: sequence-parallel attention over the device mesh.

The reference has no sequence axis at all (fixed 4-D image tensors,
InstObj.java:8; SURVEY.md §5.7) — but long-context models served by this
framework need attention over sequences that do not fit one chip. This is
the TPU-idiomatic construction:

- the sequence axis is sharded over a mesh axis (``shard_map``);
- each device computes blockwise attention of its local queries against the
  KV shard it currently holds, carrying online-softmax statistics
  (running max ``m``, denominator ``l``, unnormalized accumulator ``acc``);
- KV shards rotate around the ring with ``lax.ppermute`` — the collective
  rides ICI neighbor links, overlapping with the next block's compute under
  XLA's scheduler (the pattern of Liu et al.'s Ring Attention, built from
  public JAX primitives);
- after ``n`` hops every query has seen every key; the carry normalizes to
  the exact softmax result — bitwise-independent of how many ways the
  sequence was sharded (up to float reassociation).

Non-causal (bidirectional) attention, matching the ViT/encoder workloads
this framework serves; a causal variant would add a step-index mask.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _partial_attention(q, k, v, scale):
    """Blockwise attention with online-softmax statistics.

    q: (B, H, Sq, D); k/v: (B, H, Sk, D) ->
    (acc: (B, H, Sq, D) unnormalized, m: (B, H, Sq), l: (B, H, Sq))
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return acc, m, l


def _merge(m, l, acc, m_j, l_j, acc_j):
    m_new = jnp.maximum(m, m_j)
    a = jnp.exp(m - m_new)
    b = jnp.exp(m_j - m_new)
    return (
        m_new,
        l * a + l_j * b,
        acc * a[..., None] + acc_j * b[..., None],
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = "data",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact softmax(q k^T * scale) v with the sequence axis sharded over
    ``mesh[seq_axis]``. Inputs/outputs are global (B, H, S, D) arrays whose
    S axis is (or will be) sharded; S must divide evenly by the axis size."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[seq_axis]
    if q.shape[2] % n:
        raise ValueError(f"sequence {q.shape[2]} not divisible by {n}-way {seq_axis!r}")
    spec = P(None, None, seq_axis, None)
    perm = [(i, (i + 1) % n) for i in range(n)]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def inner(ql, kl, vl):
        acc, m, l = _partial_attention(ql, kl, vl, scale)

        def body(carry, _):
            k_cur, v_cur, m, l, acc = carry
            # Rotate KV shards one hop around the ring (ICI neighbors).
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            acc_j, m_j, l_j = _partial_attention(ql, k_nxt, v_nxt, scale)
            m, l, acc = _merge(m, l, acc, m_j, l_j, acc_j)
            return (k_nxt, v_nxt, m, l, acc), None

        # scan (static trip count), not fori_loop: reverse-mode AD must flow
        # through the ring for sequence-parallel training.
        (_, _, m, l, acc), _ = lax.scan(body, (kl, vl, m, l, acc), None, length=n - 1)
        return (acc / l[..., None]).astype(ql.dtype)

    return inner(q, k, v)
