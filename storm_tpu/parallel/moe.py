"""Expert parallelism: a mixture-of-experts MLP with experts sharded over a
mesh axis (GShard-style dispatch).

Absent from the reference (SURVEY.md §2.4 EP row: "no MoE anywhere"); built
here the declarative TPU way rather than with hand-written all-to-alls:

- expert weights are stacked on a leading E axis and sharded over the
  ``expert`` mesh axis (each device holds E / n_expert_shards experts);
- tokens pick a top-1 expert via a learned gate; a capacity-bounded one-hot
  dispatch tensor turns routing into three einsums (dispatch, expert MLP,
  combine) — all MXU work, no gather/scatter;
- with tokens sharded over ``data`` and experts over ``expert``, XLA/GSPMD
  lowers the dispatch/combine einsums into the all-to-all pattern on ICI;
  user code contains zero explicit collectives (SURVEY.md §2.5).

Capacity semantics: each expert processes at most
``ceil(tokens / E * capacity_factor)``; overflow tokens are dropped (their
output is 0 through the residual connection) — standard GShard/Switch
behavior, deterministic and shape-static for XLA.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def moe_init(
    rng, dim: int, mlp_dim: int, n_experts: int, dtype=jnp.float32
) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(dim)
    scale_out = 1.0 / math.sqrt(mlp_dim)
    return {
        "gate": (jax.random.normal(k1, (dim, n_experts), dtype) * scale_in),
        "w_in": (jax.random.normal(k2, (n_experts, dim, mlp_dim), dtype) * scale_in),
        "b_in": jnp.zeros((n_experts, mlp_dim), dtype),
        "w_out": (jax.random.normal(k3, (n_experts, mlp_dim, dim), dtype) * scale_out),
        "b_out": jnp.zeros((n_experts, dim), dtype),
    }


def moe_param_specs(expert_axis: str = "expert") -> dict:
    """PartitionSpecs matching :func:`moe_init`: experts sharded on their
    leading axis, gate replicated."""
    return {
        "gate": P(),
        "w_in": P(expert_axis),
        "b_in": P(expert_axis),
        "w_out": P(expert_axis),
        "b_out": P(expert_axis),
    }


def shard_moe_params(mesh: Mesh, params: dict, expert_axis: str = "expert") -> dict:
    specs = moe_param_specs(expert_axis)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in params.items()
    }


def moe_layer(
    p: dict,
    x: jnp.ndarray,
    capacity_factor: float = 1.25,
    aux_loss_weight: float = 1e-2,
):
    """Top-1 MoE MLP over tokens.

    ``x``: (..., dim) — leading dims are flattened into a token axis.
    Returns ``(y, aux_loss)``: y has x's shape (overflowed tokens yield 0);
    ``aux_loss`` is the Switch-Transformer load-balancing loss (mean over
    experts of fraction-of-tokens x mean-gate-prob, scaled by E), already
    multiplied by ``aux_loss_weight``.
    """
    orig_shape = x.shape
    dim = orig_shape[-1]
    tokens = x.reshape(-1, dim)
    n = tokens.shape[0]
    e = p["w_in"].shape[0]
    cap = max(1, math.ceil(n / e * capacity_factor))

    logits = (tokens @ p["gate"].astype(tokens.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)
    expert = jnp.argmax(probs, axis=-1)  # (N,)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # (N, E)

    # Position of each token within its chosen expert's queue; >= cap drops.
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (N, E)
    keep = onehot * (pos < cap)  # (N, E)
    pos_cap = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32), cap,
                             dtype=jnp.float32)  # (N, C)
    dispatch = jnp.einsum("ne,nc->nec", keep, pos_cap)  # (N, E, C)
    gate_val = jnp.sum(probs * keep, axis=-1)  # (N,)
    combine = dispatch * gate_val[:, None, None]  # (N, E, C)

    xt = tokens.astype(jnp.float32)
    xe = jnp.einsum("nec,nd->ecd", dispatch, xt).astype(tokens.dtype)  # (E, C, d)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", xe, p["w_in"].astype(xe.dtype))
        + p["b_in"].astype(xe.dtype)[:, None, :]
    )
    ye = (
        jnp.einsum("ech,ehd->ecd", h, p["w_out"].astype(h.dtype))
        + p["b_out"].astype(h.dtype)[:, None, :]
    )  # (E, C, d)
    y = jnp.einsum("nec,ecd->nd", combine, ye.astype(jnp.float32))

    # Switch load-balancing loss: encourages uniform routing.
    frac_tokens = jnp.mean(onehot, axis=0)  # (E,)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux = aux_loss_weight * e * jnp.sum(frac_tokens * mean_prob)

    return y.astype(x.dtype).reshape(orig_shape), aux


def moe_block_init(rng, dim: int, mlp_dim: int, num_heads: int, n_experts: int):
    """A transformer block whose MLP is an MoE: ln1/attn/ln2 as in the ViT
    block, MoE replacing the dense MLP."""
    from storm_tpu.ops import layers as L
    from storm_tpu.ops.attention import mha_init

    k1, k2 = jax.random.split(rng)
    return {
        "ln1": L.layernorm_init(dim),
        "attn": mha_init(k1, dim, num_heads),
        "ln2": L.layernorm_init(dim),
        "moe": moe_init(k2, dim, mlp_dim, n_experts),
    }


def moe_block(p: dict, x: jnp.ndarray, num_heads: int,
              capacity_factor: float = 1.25):
    """(B, S, D) -> ((B, S, D), aux_loss)."""
    from storm_tpu.ops import layers as L
    from storm_tpu.ops.attention import multi_head_attention

    x = x + multi_head_attention(p["attn"], L.layernorm(p["ln1"], x), num_heads)
    h, aux = moe_layer(p["moe"], L.layernorm(p["ln2"], x),
                       capacity_factor=capacity_factor)
    return x + h, aux
