"""Typed configuration for the whole framework.

Replaces the reference's four config mechanisms (SURVEY.md §5.6): compile-time
parallelism constants (MainTopology.java:25-28), three positional CLI args
(:36-38), edit-the-source cluster endpoints (:33-34), and hard-coded model
metadata (InferenceBolt.java:83-86) — with one dataclass tree loadable from
TOML/JSON and overridable from the CLI. Nothing requires a rebuild.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from storm_tpu.cascade.policy import CascadeConfig


@dataclass
class BatchConfig:
    """Micro-batching policy for the inference operator.

    The reference runs batch=1 per ``session.run`` (InferenceBolt.java:80-86);
    here batches are formed up to ``max_batch`` or until ``max_wait_ms``
    elapses, and padded up to the nearest of ``buckets`` so XLA compiles a
    small, fixed set of shapes.
    """

    max_batch: int = 256
    max_wait_ms: float = 5.0
    # Padding buckets (ascending). Batches are padded to the smallest bucket
    # >= their size; the final entry must equal max_batch.
    buckets: tuple = (8, 32, 128, 256)
    # Batches allowed in flight per operator instance: one computing on
    # device while the next accumulates/pads. Deeper pipelining amortizes
    # high per-launch dispatch latency (remote/tunneled devices) at the
    # cost of tail latency.
    max_inflight: int = 2
    # Work-conserving dispatch: flush the pending batch whenever an
    # in-flight slot is free instead of waiting out max_wait_ms. Batch
    # size then adapts to load (idle device -> tiny batches, low latency;
    # saturated device -> slots stay busy, batches fill toward max_batch
    # while waiting). The deadline still applies as a fallback bound.
    eager: bool = False
    # Split-phase device pipeline depth: batches allowed inside the ENGINE
    # between dispatch (stage -> device_put -> async jit launch) and fetch
    # (blocking device->host copy on the engine's fetch thread), so the
    # H2D of batch N+1 overlaps the compute of batch N and the D2H of
    # batch N-1. 0 disables the pipeline entirely and restores the fully
    # serialized pad/put/fwd/fetch predict (the pre-pipeline engine).
    # Distinct from ``max_inflight``, which bounds batches per OPERATOR
    # task; the ring bounds batches per shared engine across all tasks.
    pipeline_depth: int = 2
    # Preallocated host staging buffers per padded bucket shape (the
    # zero-copy staging pool: one fused write replaces the concat + pad +
    # cast copies of the stacked path). Each in-flight batch holds one
    # buffer from dispatch until its fetch completes. 0 = auto
    # (pipeline_depth + 1, so a dispatch never waits on a recycling fetch).
    staging_pool: int = 0
    # Per-engine continuous batching: batch formation moves out of the
    # operator into one slot-level queue per shared engine
    # (storm_tpu/infer/continuous.py). All replicas, the serve
    # cross-batcher, and cascade escalations co-batch; a dispatcher
    # refills a pipeline-ring slot the moment it frees instead of
    # waiting for a per-bolt deadline tick. False keeps the legacy
    # per-operator MicroBatcher/LaneBatcher path.
    continuous: bool = False
    # Fairness starvation bound for the continuous queue's weighted
    # round-robin: a tenant:lane key passed over for this many batch
    # formations is served first in the next one.
    starvation_rounds: int = 4
    # Per-batch deadline on the fetch side of the dispatch/fetch ring:
    # a batch whose device result is not ready within this many ms after
    # launch fails with EngineWatchdogTimeout — failing ONLY its own
    # sources (the exception-isolation contract) and releasing its ring
    # slot + staging buffer, instead of wedging the fetch thread forever.
    # 0 disables the watchdog (plain block_until_ready).
    watchdog_ms: float = 0.0
    # Consecutive watchdog trips that quarantine the engine: it is
    # dropped from the shared-engine cache (so the next build is a fresh
    # replacement) and refuses new dispatches. 0 = never quarantine.
    watchdog_trips: int = 3
    # Batch-native egress: records that arrived together as a RecordFrame
    # leave as ONE coalesced predictions payload per dispatched batch
    # (one encode, one emit, one output message). False restores the
    # one-output-message-per-record contract even for frame ingress —
    # for downstream consumers (or harnesses) that count/key per-record
    # messages — while keeping the zero-copy ingress + view-decode path.
    frame_egress: bool = True

    def __post_init__(self) -> None:
        if float(self.watchdog_ms) < 0:
            raise ValueError(
                f"batch.watchdog_ms must be >= 0, got {self.watchdog_ms!r}")
        if int(self.watchdog_trips) < 0:
            raise ValueError(
                "batch.watchdog_trips must be >= 0, got "
                f"{self.watchdog_trips!r}")
        if int(self.starvation_rounds) < 1:
            raise ValueError(
                "batch.starvation_rounds must be >= 1, got "
                f"{self.starvation_rounds!r}")
        if int(self.pipeline_depth) < 0:
            raise ValueError(
                f"batch.pipeline_depth must be >= 0, got {self.pipeline_depth!r}")
        if int(self.staging_pool) < 0:
            raise ValueError(
                f"batch.staging_pool must be >= 0, got {self.staging_pool!r}")
        self.buckets = tuple(sorted(set(int(b) for b in self.buckets)))
        if not self.buckets:
            self.buckets = (self.max_batch,)
        if self.buckets[-1] != self.max_batch:
            self.buckets = tuple(b for b in self.buckets if b < self.max_batch) + (
                self.max_batch,
            )

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]


@dataclass
class ModelConfig:
    """Which model an inference operator runs, and how.

    Replaces the hard-coded SavedModel blob + tensor names
    (InferenceBolt.java:57, :83-84) with a registry name and an optional
    checkpoint path (artifact store instead of ship-model-inside-the-jar,
    InferenceBolt.java:49-51).
    """

    name: str = "lenet5"  # key into storm_tpu.models.registry
    checkpoint: Optional[str] = None  # orbax checkpoint dir; None = random init
    dtype: str = "bfloat16"  # compute dtype on TPU
    num_classes: int = 10
    input_shape: tuple = (28, 28, 1)  # per-instance HWC
    seed: int = 0
    # Extra kwargs for the registry builder (e.g. mobilenetv2 width=0.5,
    # vit depth overrides) — family-specific knobs without config schema churn.
    extra: dict = dataclasses.field(default_factory=dict)
    # 'float' keeps params in the compute dtype; 'int8' stores weight-only
    # quantized params (int8 + per-output-channel scales) in HBM and
    # dequantizes inside the jit program — ~2-4x smaller param footprint,
    # XLA fuses the dequant into the first use (w8a16 serving).
    weights: str = "float"
    # Wire dtype for the host->device transfer. None ships the compute dtype
    # (bf16 = half the bytes of f32); "uint8" affine-quantizes per batch on
    # the host and dequantizes on device inside the jit program — 4x fewer
    # bytes than f32 over the PCIe/tunnel link, which is the streaming
    # bottleneck (BENCH_NOTES.md). Lossy (8-bit) and therefore opt-in.
    transfer_dtype: Optional[str] = None
    # Persistent XLA compilation-cache directory. A restarted daemon
    # reloads compiled executables from disk instead of re-tracing and
    # re-compiling every bucket shape (the reference pays model load on
    # every worker start, InferenceBolt.java:44-62; here recompiles are
    # the analogous cold-start cost). "" disables.
    compile_cache_dir: str = ""

    def __post_init__(self) -> None:
        if self.transfer_dtype not in (None, "uint8"):
            raise ValueError(f"unsupported transfer_dtype {self.transfer_dtype!r}")
        if self.weights not in ("float", "int8", "int8_fused"):
            raise ValueError(
                "model.weights must be float|int8|int8_fused, "
                f"got {self.weights!r}")


@dataclass
class ShardingConfig:
    """How the operator's work maps onto the TPU mesh.

    ``data_parallel`` is the TPU-native meaning of the reference's
    ``INFERENCE_BOLT_PARAL = 4`` (MainTopology.java:27): shards of the batch
    axis over the ICI mesh rather than replicated JVM executors.
    """

    data_parallel: int = 1  # dp axis size (0 = use all available devices)
    tensor_parallel: int = 1  # tp axis size (param sharding)
    # sp axis size: shard the SEQUENCE axis of long-context models across
    # chips (ring attention over ICI) — for sequences whose activations
    # exceed one chip. Only models publishing ``apply_sp`` (e.g.
    # longseq_encoder) can serve with sp > 1; mutually exclusive with
    # tensor_parallel for serving.
    sequence_parallel: int = 1
    # ep axis size: shard MoE expert tensors over chips for serving (the
    # routing einsums lower to all-to-alls). Only meaningful for MoE
    # families; mutually exclusive with tp/sp for serving.
    expert_parallel: int = 1
    axis_names: tuple = ("data", "model")


@dataclass
class OffsetsConfig:
    """Stream-position policy for the ingest spout.

    ``policy='latest'`` reproduces the reference's freshness-over-completeness
    semantics (start at latest, ignore stored offsets, drop backlog —
    MainTopology.java:101-103). ``policy='resume'`` commits offsets and
    resumes, which the reference deliberately lacked (SURVEY.md §5.4).
    """

    # 'txn': resolve positions from committed offsets like 'resume', but
    # NEVER commit on ack — a transactional sink commits the consumed
    # offsets inside its producer transaction (KIP-98 exactly-once); a
    # spout-side commit would race ahead of uncommitted output.
    policy: str = "latest"  # 'latest' | 'earliest' | 'resume' | 'txn'
    max_behind: Optional[int] = 0  # drop records more than N offsets behind; None = unbounded
    group_id: Optional[str] = None  # None = fresh random group per run (reference behavior)
    # True: partitions come from Kafka consumer-group coordination
    # (JoinGroup/SyncGroup) instead of static task-index assignment —
    # spout tasks then cooperate with ANY consumer sharing the group.
    # Requires a wire-protocol broker (KafkaWireBroker).
    group_protocol: bool = False

    def __post_init__(self) -> None:
        if self.group_protocol and not self.group_id:
            # every task would otherwise mint its own uuid group and be
            # assigned ALL partitions -> N-fold duplicate consumption
            raise ValueError(
                "offsets.group_protocol requires an explicit group_id "
                "(tasks must share one group to split partitions)")
        if self.policy not in ("latest", "earliest", "resume", "txn"):
            raise ValueError(f"unknown offsets policy {self.policy!r}")
        if self.policy == "txn" and not self.group_id:
            raise ValueError(
                "offsets.policy='txn' requires an explicit group_id — the "
                "transactional sink commits offsets to it, and a restart "
                "must resume from the SAME group to be exactly-once")
        if self.policy == "txn" and self.max_behind is not None:
            raise ValueError(
                "offsets.policy='txn' requires max_behind=None — dropping "
                "stale records under a freshness clamp contradicts the "
                "exactly-once contract (set it explicitly)")
        if self.policy == "txn" and self.group_protocol:
            # TxnOffsetCommit v0 carries no group generation (KIP-447
            # fencing is post-reference-era): a task whose partition was
            # rebalanced away could still commit a STALE offset for it
            # inside a transaction, regressing the group position and
            # duplicating records — exactly what 'txn' promises not to do.
            # Static task-index assignment has no handoffs, so no window.
            raise ValueError(
                "offsets.policy='txn' requires group_protocol=False: "
                "v0-era TxnOffsetCommit has no rebalance fencing, so a "
                "revoked partition's in-flight offsets could regress the "
                "group position (use static partition assignment)")


@dataclass
class SinkConfig:
    """Producer-side delivery policy: the three ack modes of the reference's
    KafkaBolt (async-with-callback / sync / fire-and-forget,
    KafkaBolt.java:129-155)."""

    mode: str = "async"  # 'async' | 'sync' | 'fire_and_forget' | 'transactional'
    acks: int = 1  # mirrors acks=1 (MainTopology.java:113)
    # mode='transactional' (exactly-once egress, KIP-98): tuples buffer
    # into one transaction per micro-batch and ack only after commit.
    txn_batch: int = 64
    txn_ms: float = 100.0
    # Consumer group to commit consumed offsets to INSIDE the producer
    # transaction (AddOffsetsToTxn/TxnOffsetCommit) — closing the KIP-98
    # consume-transform-produce loop. Must equal the spout's
    # offsets.group_id, with offsets.policy='txn'. None = egress-only
    # transactions (offsets commit separately; effectively-once across a
    # crash between produce and offset commit).
    offsets_group: Optional[str] = None

    def __post_init__(self) -> None:
        if self.mode not in ("async", "sync", "fire_and_forget",
                             "transactional"):
            raise ValueError(f"unknown sink mode {self.mode!r}")


@dataclass
class TopologyConfig:
    """Topology-level knobs: the reference's parallelism constants
    (MainTopology.java:25-28) plus runtime policies, all runtime-settable."""

    name: str = "inference-topology"
    spout_parallelism: int = 2  # KAFKA_SPOUT_PARAL
    inference_parallelism: int = 4  # INFERENCE_BOLT_PARAL
    sink_parallelism: int = 2  # KAFKA_BOLT_PARAL
    max_spout_pending: int = 2048  # in-flight roots per spout instance
    # Records per emitted spout tuple. 1 = the reference's per-record
    # granularity; N>1 amortizes ledger/executor overhead at high message
    # rates (replay granularity becomes the chunk). BENCH_NOTES.md.
    spout_chunk: int = 1
    # Tuple-value scheme (Storm StringScheme vs RawScheme,
    # MainTopology.java:100): "string" = decode records to str (compatible
    # with every component incl. shell/multilang and the JSON dist wire);
    # "raw" = emit broker bytes untouched, skipping a bytes->str->bytes
    # round trip on the inference hot path. Under dist-run, "raw" needs
    # wire_format="binary" (the default) to cross worker boundaries.
    # DEPRECATION NOTE (r19): under dist-run the effective default is now
    # "raw" (+ spout_frames) whenever wire_format="binary" and no scheme
    # was pinned in the config file or via --set; wire_format="json" still
    # pins "string" (raw bytes cannot cross the JSON wire — the submit
    # check rejects that combination with an actionable error). The
    # "string"-everywhere dist default is deprecated; pin
    # topology.spout_scheme="string" explicitly to keep it.
    spout_scheme: str = "string"
    # Batch-native ingress (r19 zero-copy plan): with scheme="raw" and
    # spout_chunk>1, each chunk rides as ONE RecordFrame tuple value
    # (runtime/frames.py) — routing moves a reference instead of N
    # payload objects, the dist wire carries the frame as one slot, and
    # egress coalesces to one predictions payload per frame group.
    # Replay/ack granularity is unchanged (the chunk). Off by default
    # locally; dist-run turns it on alongside the raw-scheme default.
    spout_frames: bool = False
    # Inter-worker tuple wire under dist-run: "binary" = length-prefixed
    # CRC-protected frames (storm_tpu/dist/wire.py; bytes/ndarray values
    # cross without re-encoding), with per-peer fallback to JSON for
    # workers that don't advertise the binary version (mixed-version
    # clusters); "json" = pin the legacy envelope everywhere — the
    # compatibility wire for multilang/shell bolts and old receivers.
    wire_format: str = "binary"
    # Shared-memory delivery lane between CO-LOCATED dist workers (same
    # host key, negotiated via the control ping): the sender writes the
    # encoded delivery frame once into a multiprocessing.shared_memory
    # segment and ships only a small CRC-protected header over the TCP
    # stream; the receiver decodes zero-copy views over the segment.
    # Cross-host peers (or payloads under shm_min_bytes, where segment
    # setup costs more than the copy it saves) fall back to TCP frames.
    shm_wire: bool = True
    shm_min_bytes: int = 65536
    message_timeout_s: float = 30.0  # at-least-once replay timeout
    inbox_capacity: int = 4096  # bounded executor queues (backpressure)
    tick_interval_s: float = 0.0  # 0 = no tick tuples
    checkpoint_interval_s: float = 5.0  # stateful-bolt checkpoint cadence
    state_dir: str = ""  # durable bolt-state dir; "" = in-memory backend
    # Per-task resource hints for resource-aware dist placement (Storm's
    # RAS): {"component-id": {"memory_mb": N, "cpu": pct}}.
    component_resources: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.wire_format not in ("binary", "json"):
            raise ValueError(
                f"unknown wire_format {self.wire_format!r} "
                "(expected 'binary' or 'json')")


@dataclass
class BrokerConfig:
    """Where records come from / go to. Replaces the empty-string
    ``zkHosts``/``bootstrap`` edit-the-source fields (MainTopology.java:33-34)."""

    kind: str = "memory"  # 'memory' | 'kafka'
    bootstrap: str = ""  # host:port list for kind='kafka'
    input_topic: str = "input"
    output_topic: str = "output"
    dead_letter_topic: str = "dead-letter"
    partitions: int = 4  # partitions for memory broker topics
    # 'v1' = 0.11-era message sets (the reference's broker generation);
    # 'v2' = KIP-98 record batches (CRC32C), what modern brokers store.
    message_format: str = "v1"
    # KIP-98 idempotent produce (requires message_format='v2'): retried
    # sends reuse their sequence, so the broker appends at most once —
    # the sink's retry path stops duplicating records.
    idempotent: bool = False
    # Egress codec for kind='kafka' (None = uncompressed); gzip/snappy/lz4,
    # message_format='v2' only. Ingest decodes all three regardless.
    compression: Optional[str] = None
    # Consumer isolation (kind='kafka'): 'read_committed' fetches via
    # Fetch v4 (KIP-98) and filters aborted transactions' records — what
    # an exactly-once pipeline's INPUT side should use when upstream
    # producers are transactional. Default matches pre-KIP-98 consumers.
    isolation: str = "read_uncommitted"
    # Transport security (kind='kafka'). 0.11-era brokers already spoke
    # SASL/SSL; the reference never configured it (MainTopology.java:
    # 95-118) but a production contract should. SASL mechanism: PLAIN
    # (the era's standard; tokens are raw pre-KIP-152 frames).
    security_protocol: str = "PLAINTEXT"  # | SSL | SASL_PLAINTEXT | SASL_SSL
    # PLAIN (era standard) | SCRAM-SHA-256 | SCRAM-SHA-512 (KIP-84;
    # password never crosses the wire, server signature verified)
    sasl_mechanism: str = "PLAIN"
    sasl_username: str = ""
    sasl_password: str = ""
    ssl_cafile: str = ""  # CA bundle for broker cert verification
    # self-signed broker certs without a matching SAN: keep encryption +
    # chain verification, skip only hostname matching
    ssl_check_hostname: bool = True
    # explicit, separate opt-out of CERT verification entirely
    # (encryption without authentication — last resort)
    ssl_verify: bool = True

    def security_dict(self) -> Optional[dict]:
        """The wire client's ``security`` parameter, or None for
        PLAINTEXT (no handshake overhead on the default path)."""
        if self.security_protocol == "PLAINTEXT":
            return None
        return {
            "protocol": self.security_protocol,
            "sasl_mechanism": self.sasl_mechanism,
            "sasl_username": self.sasl_username,
            "sasl_password": self.sasl_password,
            "ssl_cafile": self.ssl_cafile or None,
            "ssl_check_hostname": self.ssl_check_hostname,
            "ssl_verify": self.ssl_verify,
        }

    def __post_init__(self) -> None:
        if self.kind not in ("memory", "kafka"):
            raise ValueError(f"broker.kind must be memory|kafka, got {self.kind!r}")
        if self.idempotent and self.message_format != "v2":
            raise ValueError(
                "broker.idempotent requires broker.message_format='v2'")
        if self.message_format not in ("v1", "v2"):
            raise ValueError(
                f"broker.message_format must be v1|v2, got {self.message_format!r}")
        if self.compression is not None:
            if self.compression not in ("gzip", "snappy", "lz4"):
                raise ValueError(
                    f"broker.compression must be gzip|snappy|lz4, "
                    f"got {self.compression!r}")
            if self.message_format != "v2":
                raise ValueError(
                    "broker.compression requires broker.message_format='v2'")
        if self.isolation not in ("read_uncommitted", "read_committed"):
            raise ValueError(
                f"broker.isolation must be read_uncommitted|read_committed, "
                f"got {self.isolation!r}")
        if self.security_protocol not in (
                "PLAINTEXT", "SSL", "SASL_PLAINTEXT", "SASL_SSL"):
            raise ValueError(
                "broker.security_protocol must be PLAINTEXT|SSL|"
                f"SASL_PLAINTEXT|SASL_SSL, got {self.security_protocol!r}")
        # lazy import: config is foundational and the connectors package
        # imports it back at module load (spout/sink), so a top-level
        # import here would cycle through a half-initialized module
        from storm_tpu.connectors.kafka_protocol import SASL_MECHANISMS

        if self.sasl_mechanism not in SASL_MECHANISMS:
            raise ValueError(
                "broker.sasl_mechanism must be one of "
                f"{'|'.join(SASL_MECHANISMS)}, got {self.sasl_mechanism!r}")
        if (self.security_protocol.startswith("SASL")
                and not self.sasl_username):
            raise ValueError(
                "broker.security_protocol=SASL_* requires sasl_username "
                "(mechanism PLAIN)")


def _apply_section(target, values: dict) -> None:
    """Apply a dict of key->value onto a config dataclass instance, coercing
    lists to tuples where the field is a tuple and re-running validation."""
    for k, v in values.items():
        if not hasattr(target, k):
            raise KeyError(f"unknown config key {k!r} for {type(target).__name__}")
        cur = getattr(target, k)
        if isinstance(cur, tuple) and isinstance(v, list):
            v = tuple(v)
        setattr(target, k, v)
        if k == "spout_scheme" and isinstance(target, TopologyConfig):
            # dist-run defaults the scheme to "raw" ONLY when the user
            # never pinned one (file or CLI override) — see main.py.
            target._scheme_pinned = True
    if hasattr(target, "__post_init__"):
        target.__post_init__()


#: The env var the dist controller exports its resolved control-plane
#: token through (and every client falls back to). Single source of truth
#: for transport/ctl/controller — config.py so the CLI doesn't need grpc.
CONTROL_TOKEN_ENV = "STORM_TPU_CONTROL_TOKEN"


def env_control_token() -> str:
    """The ONE env-fallback read shared by the UI, dist plane, and ctl —
    resolution must never diverge between the binary's serving modes."""
    import os

    return os.environ.get(CONTROL_TOKEN_ENV, "")


@dataclass
class ControlConfig:
    """Control-plane authentication (VERDICT r4 missing #4).

    The Kafka edge carries SASL/SSL (BrokerConfig), but the surfaces that
    can kill/rebalance/swap a topology — the UI admin POST routes and the
    dist controller<->worker gRPC — would otherwise be plaintext and
    unauthenticated; the same era-argument that justified broker security
    (reference pom.xml:55-78) applies to them.

    ``auth_token`` is a shared secret: requests must carry it
    (``Authorization: Bearer <token>`` on HTTP, ``x-storm-tpu-token``
    gRPC metadata), mismatches are rejected and logged. ``"env:NAME"``
    reads the secret from environment variable NAME so it never lives in
    a config file. ``""`` (the default) falls back to
    $STORM_TPU_CONTROL_TOKEN — one posture for the UI, the dist gRPC
    plane, and ctl alike — and disables auth only when that is also
    unset (loopback-dev, the previous behavior). The dist controller
    exports the resolved token to its spawned workers via the same var."""

    auth_token: str = ""
    #: Directory for the controller's write-ahead journal ("" = no
    #: journal: a controller crash forgets the mesh and a restart
    #: rebuilds every worker from scratch). With a journal dir, a
    #: restarted controller replays the log and REATTACHES to live
    #: workers — warm engines stay warm.
    journal_dir: str = ""
    #: Compact (snapshot + truncate) the journal after this many
    #: appends since the last snapshot.
    journal_snapshot_every: int = 64
    #: Whether a journal-backed controller attempts reattach on start
    #: (False = always cold-rebuild, e.g. after deliberate mesh wipe).
    reattach: bool = True

    def __post_init__(self) -> None:
        if int(self.journal_snapshot_every) < 1:
            raise ValueError("control.journal_snapshot_every must be >= 1")

    def resolve_token(self) -> str:
        import os

        t = self.auth_token
        if t.startswith("env:"):
            name = t[4:]
            val = os.environ.get(name, "")
            if not val:
                raise ValueError(
                    f"control.auth_token says {t!r} but ${name} is unset/empty")
            return val
        return t or env_control_token()


@dataclass
class TracingConfig:
    """Per-record distributed tracing + flight recorder (runtime/tracing.py).

    Off by default: ``sample_rate=0`` keeps the hot path allocation-free
    (sampled context objects are only minted for sampled roots)."""

    # Fraction of root tuples that carry a TraceContext (0 = off, 1 = all).
    sample_rate: float = 0.0
    # Completed traces kept in the in-process ring buffer (per process).
    store_capacity: int = 256
    # e2e latency above which the sink logs a flight-recorder SLO-breach
    # event (0 = disabled).
    slo_ms: float = 0.0
    # JSONL flight-recorder file ("" = in-memory ring only).
    flight_path: str = ""
    # In-memory flight-recorder ring size (events).
    flight_capacity: int = 512
    # Rotation: roll flight_path -> .1 -> ... when it exceeds this size,
    # keeping at most flight_max_files generations.
    flight_max_bytes: int = 4 * 1024 * 1024
    flight_max_files: int = 3

    def __post_init__(self) -> None:
        if not 0.0 <= float(self.sample_rate) <= 1.0:
            raise ValueError(
                f"tracing.sample_rate must be in [0, 1], got {self.sample_rate!r}")


@dataclass
class ObsConfig:
    """Continuous profiling & SLO-burn observatory (storm_tpu/obs/).

    The per-(engine, bucket) cost profiler itself is always-on and
    near-free (one dict update per device batch — see
    BENCH_OBS_OVERHEAD_r11.json); ``enabled`` gates the *control loop*:
    the Observatory task that steps the burn tracker, publishes occupancy
    gauges, and runs the regression sentinel. The burn tracker needs
    ``tracing.slo_ms`` set — without it the sink never counts breaches
    and burn stays 0.
    """

    enabled: bool = False
    # Observatory step cadence (burn tracker + occupancy gauges).
    interval_s: float = 1.0
    # SLO objective: fraction of delivered records inside tracing.slo_ms.
    # The error budget is 1 - slo_objective.
    slo_objective: float = 0.99
    # Multi-window burn: both windows must exceed burn_threshold to trip
    # (fast reacts, slow de-flaps). Burn 1.0 = spending budget exactly.
    burn_fast_window_s: float = 60.0
    burn_slow_window_s: float = 600.0
    burn_threshold: float = 1.0
    # Regression sentinel: compare live stage costs against this
    # PROFILE_*.json snapshot ("" = sentinel off); flag a (engine,
    # bucket, stage) cell when live mean > regression_factor x baseline,
    # once it has at least min_samples live observations.
    baseline_path: str = ""
    regression_factor: float = 1.5
    sentinel_interval_s: float = 10.0
    min_samples: int = 20
    # Bottleneck attribution (obs/bottleneck.py): a component counts as
    # "at capacity" above capacity_hot busy-fraction of the wallclock
    # window (also the Autoscaler's named-bottleneck scale-up trigger);
    # an edge is "growing" above lag_growth_eps rows/s; a saturated but
    # no-longer-growing inbox still attributes above lag_depth_hot
    # queued records; no leader is named below bottleneck_min_score
    # (an idle topology has no bottleneck).
    capacity_hot: float = 0.8
    lag_growth_eps: float = 1.0
    lag_depth_hot: int = 64
    bottleneck_min_score: float = 0.4
    # Copy ledger (obs/copyledger.py): a ``copy_amplification_high``
    # flight event fires when the windowed amplification ratio (bytes
    # moved / bytes ingested) exceeds this ceiling; 0 disables the
    # check. De-flapped: the event re-arms only after the ratio falls
    # back under 80% of the ceiling.
    copy_amp_ceiling: float = 32.0

    def __post_init__(self) -> None:
        if self.interval_s <= 0 or self.sentinel_interval_s <= 0:
            raise ValueError("obs intervals must be > 0")
        if not 0.0 < float(self.capacity_hot) <= 1.0:
            raise ValueError(
                f"obs.capacity_hot must be in (0, 1], got "
                f"{self.capacity_hot!r}")
        if self.lag_growth_eps < 0 or self.lag_depth_hot < 0:
            raise ValueError("obs lag thresholds must be >= 0")
        if self.bottleneck_min_score < 0:
            raise ValueError("obs.bottleneck_min_score must be >= 0")
        if not 0.0 < float(self.slo_objective) < 1.0:
            raise ValueError(
                f"obs.slo_objective must be in (0, 1), got "
                f"{self.slo_objective!r}")
        if (self.burn_fast_window_s <= 0
                or self.burn_slow_window_s < self.burn_fast_window_s):
            raise ValueError(
                "need 0 < obs.burn_fast_window_s <= obs.burn_slow_window_s")
        if self.regression_factor <= 1.0:
            raise ValueError("obs.regression_factor must be > 1")
        if self.copy_amp_ceiling < 0:
            raise ValueError("obs.copy_amp_ceiling must be >= 0")


@dataclass
class PlanConfig:
    """SLO-aware joint planner (storm_tpu/plan/): offline solve + online
    correct.

    The offline half (``storm-tpu plan``, ``bench.py --plan``) needs no
    config at all — it solves over a ProfileStore snapshot for an explicit
    (rate, SLO) target. This section configures the *online* half: when
    ``enabled``, the daemon attaches a :class:`storm_tpu.plan.corrector.
    PlanCorrector` to the Observatory loop; it consumes the bottleneck
    verdict + SLO-burn tracker and moves only the named limiter's knob,
    and the Autoscaler defers its own global scale-up to it.
    """

    enabled: bool = False
    # Offline solve at daemon startup when both targets are set and a
    # profile baseline is available (obs.baseline_path or live curves):
    # the plan is logged and served on the /plan route; it is NOT applied
    # automatically — apply is an operator decision (docs/OPERATIONS.md).
    rate_rows_s: float = 0.0
    slo_p99_ms: float = 0.0
    # Solver feasibility margin: candidates must keep predicted device
    # utilization at or below this fraction.
    headroom: float = 0.8
    # Compile-cost amortization horizon for shapes not yet warm.
    horizon_s: float = 600.0
    # Framework overhead floor added to every predicted e2e p99 (host
    # scheduling, serialization, transport — everything outside the
    # profiled device stages and the modeled batching waits).
    overhead_ms: float = 15.0
    # Charged for a cold shape when the profile has no compile sample yet.
    default_compile_ms: float = 500.0
    # A (engine, bucket) curve with fewer device-stage samples than this
    # counts as "cold" in coverage and is excluded from the solve.
    min_samples: int = 8
    # ---- online corrector ----------------------------------------------------
    correct: bool = True
    # Consecutive hot Observatory steps (burn tripped AND a named leader)
    # before the corrector moves a knob.
    hot_steps: int = 2
    # Consecutive calm steps before one correction step is reverted.
    calm_steps: int = 6
    # Post-move cooldown steps during which the corrector holds still
    # (hysteresis: one bounded step, then watch).
    hold_steps: int = 3
    # Hard parallelism bound for corrector moves; 0 = per-kind defaults
    # (ACCEL_MAX_PARALLELISM for inference bolts, CPU_MAX_PARALLELISM
    # otherwise — see runtime/autoscale.py).
    max_parallelism: int = 0

    def __post_init__(self) -> None:
        if not 0.0 < float(self.headroom) <= 1.0:
            raise ValueError(
                f"plan.headroom must be in (0, 1], got {self.headroom!r}")
        if self.rate_rows_s < 0 or self.slo_p99_ms < 0:
            raise ValueError("plan targets must be >= 0")
        if self.horizon_s <= 0:
            raise ValueError("plan.horizon_s must be > 0")
        if self.overhead_ms < 0 or self.default_compile_ms < 0:
            raise ValueError("plan cost floors must be >= 0")
        if min(self.hot_steps, self.calm_steps) < 1 or self.hold_steps < 0:
            raise ValueError(
                "need plan.hot_steps/calm_steps >= 1 and hold_steps >= 0")
        if self.min_samples < 1:
            raise ValueError("plan.min_samples must be >= 1")
        if self.max_parallelism < 0:
            raise ValueError("plan.max_parallelism must be >= 0 (0 = auto)")


@dataclass
class QosConfig:
    """Admission control & QoS: per-tenant token-bucket rate limiting at the
    spout edge, weighted priority lanes with earliest-deadline-first batch
    formation in the inference operator, and an adaptive load-shedding
    controller that drops best-effort traffic *before* the autoscaler
    reacts (scale-out takes seconds; shedding takes one control step).

    Off by default: ``enabled=False`` keeps every hot path untouched — no
    record classification, no extra tuple field, FIFO batch formation.

    A record's tenant and lane ride on its broker key, ``tenant:lane``
    (both optional): ``b"gold:high"`` is tenant *gold* in lane *high*,
    ``b"gold"`` is tenant *gold* in ``default_lane``, and a key-less
    record is tenant = its topic, lane = ``default_lane``.
    """

    enabled: bool = False
    # Priority lanes, highest priority first. Keys naming an unknown lane
    # (or no lane at all) fall into ``default_lane``.
    lanes: tuple = ("high", "normal", "best_effort")
    default_lane: str = "normal"
    # Per-lane delivery deadlines (ms after broker append), aligned with
    # ``lanes``: batch formation is earliest-deadline-first over these, so
    # a fresh high-deadline record preempts queued best-effort ones
    # instead of FIFO-queuing behind them.
    lane_deadline_ms: tuple = (50.0, 200.0, 1000.0)
    # Token-bucket admission at the spout edge: records/sec per tenant
    # (0 = unlimited). ``tenant_rates`` overrides the default per tenant
    # id. Each spout task gets an even split of the tenant's rate (static
    # partition assignment spreads a tenant's records across tasks).
    tenant_rate: float = 0.0
    tenant_burst_s: float = 1.0  # bucket depth, in seconds of rate
    tenant_rates: dict = field(default_factory=dict)
    # Load-shedding controller: cadence + signal thresholds + hysteresis.
    # A signal is *hot* when above its threshold; ``shed_hot_steps``
    # consecutive hot intervals raise the shed level by one,
    # ``shed_calm_steps`` consecutive calm intervals (every signal below
    # half its threshold) lower it. Level N sheds the N lowest-priority
    # lanes; the top lane is never shed.
    shed_interval_s: float = 1.0
    shed_inbox_frac: float = 0.5   # inference inbox occupancy fraction
    shed_wait_ms: float = 0.0      # batch-wait p95 threshold (0 = off)
    shed_breach_rate: float = 1.0  # sink SLO breaches/sec (needs tracing.slo_ms)
    shed_hot_steps: int = 2
    shed_calm_steps: int = 5
    # Graceful degradation for shed traffic: "" rejects with a typed
    # ``overloaded`` record on the output topic (fast, never times out);
    # a model registry name routes shed lanes to that (cheaper) engine
    # instead of rejecting.
    degrade_model: str = ""

    def __post_init__(self) -> None:
        self.lanes = tuple(str(lane) for lane in self.lanes)
        self.lane_deadline_ms = tuple(float(x) for x in self.lane_deadline_ms)
        if not self.lanes or len(set(self.lanes)) != len(self.lanes):
            raise ValueError("qos.lanes must be non-empty and unique")
        if len(self.lane_deadline_ms) != len(self.lanes):
            raise ValueError(
                f"qos.lane_deadline_ms has {len(self.lane_deadline_ms)} "
                f"entries for {len(self.lanes)} lanes")
        if self.default_lane not in self.lanes:
            raise ValueError(
                f"qos.default_lane {self.default_lane!r} not in qos.lanes")
        if self.shed_interval_s <= 0:
            raise ValueError("qos.shed_interval_s must be > 0")
        if self.shed_hot_steps < 1 or self.shed_calm_steps < 1:
            raise ValueError("qos shed hot/calm steps must be >= 1")

    # ---- lane helpers (one definition shared by spout/operator/shedder) ---

    def lane_index(self, lane: Optional[str]) -> int:
        """Priority index of ``lane`` (0 = highest); unknown lanes get the
        default lane's index."""
        try:
            return self.lanes.index(lane)
        except ValueError:
            return self.lanes.index(self.default_lane)

    def deadline_for(self, lane: Optional[str]) -> float:
        return self.lane_deadline_ms[self.lane_index(lane)]

    @property
    def max_shed_level(self) -> int:
        """Highest useful shed level: every lane but the top one shed."""
        return len(self.lanes) - 1

    def shed_eligible(self, lane: Optional[str], level: int) -> bool:
        """Does shed ``level`` drop ``lane``? Level N sheds the N
        lowest-priority lanes; the top lane never sheds."""
        if level <= 0:
            return False
        shed_from = len(self.lanes) - min(int(level), self.max_shed_level)
        return self.lane_index(lane) >= shed_from

    def rate_for(self, tenant: str) -> float:
        return float(self.tenant_rates.get(tenant, self.tenant_rate))


@dataclass
class ResilienceConfig:
    """Transport retry / circuit-breaker / replay-pacing knobs (round 14).

    TOML: ``[resilience]``. These parameterize
    :mod:`storm_tpu.resilience`: the deadline-budgeted retry policy
    wrapping WorkerClient RPCs, the per-peer circuit breaker in the
    PeerSender path, and the token bucket that paces post-recovery
    replay drains.
    """

    # Retry policy (exponential backoff + full jitter).
    retry_attempts: int = 4
    retry_base_ms: float = 50.0
    retry_cap_ms: float = 2000.0
    # Total wall-clock budget across all attempts of one logical send.
    retry_deadline_s: float = 30.0
    # Circuit breaker: consecutive failures that open a peer's circuit,
    # and how long it stays open before the half-open probe.
    circuit_failures: int = 5
    circuit_reset_s: float = 3.0
    # Replay-storm suppression: tuples/s a sender pushes at a freshly
    # recovered peer during the pacing window. 0 = auto (derived from
    # max_spout_pending over the window, i.e. the ledger's own bound).
    replay_rate: float = 0.0
    replay_window_s: float = 10.0

    def __post_init__(self) -> None:
        if int(self.retry_attempts) < 1:
            raise ValueError("resilience.retry_attempts must be >= 1, got "
                             f"{self.retry_attempts!r}")
        for name in ("retry_base_ms", "retry_cap_ms", "retry_deadline_s",
                     "circuit_reset_s", "replay_rate", "replay_window_s"):
            if float(getattr(self, name)) < 0:
                raise ValueError(
                    f"resilience.{name} must be >= 0, got "
                    f"{getattr(self, name)!r}")
        if int(self.circuit_failures) < 1:
            raise ValueError("resilience.circuit_failures must be >= 1, "
                             f"got {self.circuit_failures!r}")


@dataclass
class ChaosConfig:
    """Dist-grade fault injection (round 14). TOML: ``[chaos]``.

    Rides ``cfg.to_dict()`` through the submit recipe, so arming it on
    the controller arms every worker's process-wide injector
    (:mod:`storm_tpu.resilience.chaos`). All injections are logged as
    ``chaos_injection`` flight events. NEVER enable in production; the
    daemon/soak/bench drive it to measure recovery, not to serve.
    """

    enabled: bool = False
    seed: int = 0
    # Added latency per outbound Deliver/Ack RPC (+ uniform jitter).
    wire_latency_ms: float = 0.0
    wire_jitter_ms: float = 0.0
    # Fraction of outbound send attempts dropped (raised as ChaosDrop,
    # which the retry/circuit stack treats as a real outage).
    wire_drop_pct: float = 0.0
    # Fraction of outbound frames bit-flipped — exercises the CRC check
    # in dist/wire.py and the WireError -> replay path behind it.
    corrupt_pct: float = 0.0
    # Engine-hang injection: hold each injected batch's result this long
    # (arm per-batch via the worker 'chaos' control RPC knob
    # engine_hang_next; the config only sets the hold duration).
    engine_hang_ms: float = 0.0
    # Daemon-driven worker chaos: SIGKILL a random worker every this many
    # seconds under ``dist`` runs (0 = off). Recovery comes from the
    # heartbeat monitor; the kill itself is logged by the controller.
    kill_worker_s: float = 0.0
    # Daemon-driven controller chaos: this many seconds into a dist run
    # the daemon abandons its controller (drops every handle, workers
    # keep serving) and builds a fresh one from the journal to prove
    # reattach (0 = off; requires control.journal_dir).
    kill_controller_s: float = 0.0

    def __post_init__(self) -> None:
        for name in ("wire_drop_pct", "corrupt_pct"):
            v = float(getattr(self, name))
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"chaos.{name} must be in [0, 1], got {v!r}")
        for name in ("wire_latency_ms", "wire_jitter_ms", "engine_hang_ms",
                     "kill_worker_s", "kill_controller_s"):
            if float(getattr(self, name)) < 0:
                raise ValueError(
                    f"chaos.{name} must be >= 0, got "
                    f"{getattr(self, name)!r}")


@dataclass
class PipelineConfig:
    """One model pipeline (spout -> inference -> sink) inside a multi-model
    topology: several of these share one process and one TPU slice
    (BASELINE.json config 5, "MNIST+CIFAR bolts sharing one v5e-8"). Params
    for each model are co-resident in HBM; compiled executables are cached
    per (model, bucket) by the engine layer."""

    name: str = "pipeline"
    model: ModelConfig = field(default_factory=ModelConfig)
    batch: BatchConfig = field(default_factory=BatchConfig)
    sharding: ShardingConfig = field(default_factory=ShardingConfig)
    offsets: OffsetsConfig = field(default_factory=OffsetsConfig)
    input_topic: str = "input"
    output_topic: str = "output"
    dead_letter_topic: str = "dead-letter"
    # Records per spout tuple for THIS pipeline; 0 = inherit
    # topology.spout_chunk.
    spout_chunk: int = 0
    # "" = inherit topology.spout_scheme (see TopologyConfig).
    spout_scheme: str = ""
    spout_parallelism: int = 1
    inference_parallelism: int = 1
    sink_parallelism: int = 1

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineConfig":
        p = cls()
        for k, v in d.items():
            if not hasattr(p, k):
                raise KeyError(f"unknown pipeline key {k!r}")
            cur = getattr(p, k)
            if dataclasses.is_dataclass(cur) and isinstance(v, dict):
                _apply_section(cur, v)
            else:
                setattr(p, k, v)
        return p


@dataclass
class Config:
    topology: TopologyConfig = field(default_factory=TopologyConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    batch: BatchConfig = field(default_factory=BatchConfig)
    sharding: ShardingConfig = field(default_factory=ShardingConfig)
    offsets: OffsetsConfig = field(default_factory=OffsetsConfig)
    sink: SinkConfig = field(default_factory=SinkConfig)
    broker: BrokerConfig = field(default_factory=BrokerConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    qos: QosConfig = field(default_factory=QosConfig)
    # Continuous profiling & SLO-burn observatory (storm_tpu/obs/): cost
    # curves the planner consumes + burn-rate shed signal. TOML: [obs].
    obs: ObsConfig = field(default_factory=ObsConfig)
    # SLO-aware joint planner (storm_tpu/plan/): offline cost-model solve
    # over the profile curves + online bottleneck-named corrector in the
    # Observatory loop. TOML: [plan].
    plan: PlanConfig = field(default_factory=PlanConfig)
    # Confidence-gated model cascade (storm_tpu/cascade/): tiered serving
    # where easy records accept at a cheap tier and only the hard residue
    # escalates to the flagship. TOML: [cascade].
    cascade: CascadeConfig = field(default_factory=CascadeConfig)
    # Mesh resilience (storm_tpu/resilience/): transport retry policy,
    # per-peer circuit breakers, replay pacing. TOML: [resilience].
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    # Dist-grade fault injection for drills/benches. TOML: [chaos].
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    # Multi-model topology: non-empty => ``run`` builds one spout->infer->sink
    # chain per entry instead of the single-model DAG. TOML: [[pipelines]].
    pipelines: list = field(default_factory=list)

    # ---- loading / overriding -------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        cfg = cls()
        cfg.apply_dict(d)
        return cfg

    def apply_dict(self, d: dict) -> None:
        for section, values in d.items():
            if not hasattr(self, section):
                raise KeyError(f"unknown config section {section!r}")
            if section == "pipelines":
                if not isinstance(values, list):
                    raise TypeError("config section 'pipelines' must be a list of tables")
                self.pipelines = [
                    v if isinstance(v, PipelineConfig) else PipelineConfig.from_dict(v)
                    for v in values
                ]
                continue
            sub = getattr(self, section)
            if not isinstance(values, dict):
                raise TypeError(f"config section {section!r} must be a table/dict")
            _apply_section(sub, values)

    @classmethod
    def load(cls, path: str | Path) -> "Config":
        """Load TOML or JSON config file."""
        path = Path(path)
        text = path.read_text()
        if path.suffix == ".json":
            return cls.from_dict(json.loads(text))
        import tomllib

        return cls.from_dict(tomllib.loads(text))

    def apply_overrides(self, overrides: list) -> None:
        """Apply ``section.key=value`` CLI overrides."""
        patch: dict = {}
        for item in overrides:
            key, _, raw = item.partition("=")
            if not _:
                raise ValueError(f"override must be section.key=value: {item!r}")
            section, _, k = key.partition(".")
            try:
                val = json.loads(raw)
            except json.JSONDecodeError:
                val = raw
            patch.setdefault(section, {})[k] = val
        self.apply_dict(patch)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
