"""Logging setup (the reference used commons-logging/slf4j defaults;
here one call configures structured, rate-friendly logs)."""

from __future__ import annotations

import logging
import os


def setup_logging(level: str = "") -> None:
    level = level or os.environ.get("STORM_TPU_LOG", "INFO")
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
