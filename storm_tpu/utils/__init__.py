from storm_tpu.utils.logging import setup_logging

__all__ = ["setup_logging"]
