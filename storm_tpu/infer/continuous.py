"""Per-engine continuous batching: one slot-level queue feeds the device.

The deadline micro-batchers (:class:`~storm_tpu.infer.batcher.MicroBatcher`,
:class:`~storm_tpu.qos.lanes.LaneBatcher`) form batches PER OPERATOR TASK:
under parallelism the device sees each replica's fragment — the measured
cause of the 8-bolts-slower-than-1 inversion (ROADMAP item 3). BatchGen
(PAPERS.md) argues batch formation must be decoupled from operator topology
and run continuously at the device. This module is that decoupling: every
replica of an inference bolt, the gRPC serve path's cross-batcher, and
cascade escalation residues all ``submit`` rows into ONE queue per shared
engine, and a dedicated dispatcher thread refills a pipeline-ring slot the
moment it frees (extending the split-phase ring of
:mod:`storm_tpu.infer.engine`) instead of waiting for a per-bolt deadline
tick.

Dispatch rule (work-conserving slot refill):

- ``max_batch`` rows pending  -> dispatch (the ring provides backpressure);
- a ring slot is free AND at least one batch is already in flight ->
  dispatch immediately (the freed-slot refill — batches size themselves to
  whatever coalesced while the device worked, exactly BatchGen's
  continuous former);
- the device is fully idle -> ``eager`` dispatches on arrival, otherwise
  the oldest row ages to ``max_wait_ms`` (the deadline batcher's latency
  floor is preserved for trickle traffic).

Fairness moves here from the LaneBatcher: rows queue per ``tenant:lane``
key, batch formation orders keys earliest-deadline-first (lane deadlines
from :class:`~storm_tpu.config.QosConfig`, so a fresh high-priority record
still preempts queued best-effort), takes rows weighted-round-robin across
keys (weight = lane priority), and a key passed over
``BatchConfig.starvation_rounds`` consecutive formations is promoted to the
front of the next batch regardless of deadline order.

Exactly-once is preserved PER SOURCE: ``submit`` returns a handle whose
future resolves to that record's own row slice — when a coalesced batch
fails, every member future gets the exception and each source fails/replays
its own tuples independently; nothing is shared but the device round trip.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from storm_tpu.config import BatchConfig, QosConfig
from storm_tpu.runtime.tracing import DEVICE_SUBSTAGES


class Submission:
    """One submitted record inside the continuous queue.

    ``future`` resolves (on the engine's fetch thread) to this record's
    ``(n, K)`` prediction rows — or to the exception that failed the
    coalesced batch it rode in. ``batch_span`` carries the shared device
    span id of the batch that served it (None untraced), so a cascade
    escalation can link the next tier's spans back."""

    __slots__ = ("data", "payload", "ts", "enq", "lane", "tenant", "source",
                 "deadline", "future", "batch_span")

    def __init__(self, data, payload, ts: float, enq: float,
                 lane: Optional[str], tenant: Optional[str], source: str,
                 deadline: float) -> None:
        self.data = data
        self.payload = payload
        self.ts = ts
        self.enq = enq
        self.lane = lane
        self.tenant = tenant
        self.source = source
        self.deadline = deadline
        self.future: Future = Future()
        self.batch_span: Optional[str] = None

    @property
    def rows(self) -> int:
        return int(self.data.shape[0])


class ContinuousBatcher:
    """One continuous batch former per shared engine.

    Thread-safe ``submit`` from any thread (event loop, gRPC handlers,
    completion callbacks); a single dispatcher thread owns batch formation
    and ``engine.dispatch`` (so per-engine dispatch order is total), and
    the engine's fetch thread resolves member futures via a done-callback.
    The engine is held weakly — the process engine cache must stay able to
    evict idle engines; a dead engine fails pending submissions."""

    def __init__(self, engine, cfg: BatchConfig,
                 qos: Optional[QosConfig] = None) -> None:
        self.cfg = cfg
        self.qos = qos if (qos is not None and qos.enabled) else None
        self._engine_ref = weakref.ref(engine)
        self.engine_name = getattr(
            getattr(engine, "model_cfg", None), "name",
            type(engine).__name__)
        # Ring capacity: how many batches the engine keeps in flight. The
        # dispatcher mirrors it with _inflight so "a slot just freed" is a
        # local decision; engine.dispatch's own ring acquire stays the hard
        # bound (an engine without a ring serializes at capacity 1).
        self.capacity = max(1, int(getattr(engine, "ring_capacity",
                                           getattr(engine, "pipeline_depth",
                                                   1)) or 1))
        self._cond = threading.Condition()
        # tenant:lane key -> FIFO of Submissions (deadlines monotone per key)
        self._queues: "OrderedDict[tuple, deque]" = OrderedDict()
        self._skipped: Dict[tuple, int] = {}
        self._pending_rows = 0
        self._inflight = 0
        self._force = False  # flush(): dispatch regardless of deadline
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # ---- stats (read by the qos UI route / tests) ----
        self.batches = 0
        self.rows_dispatched = 0
        self.fair_rows: Dict[tuple, int] = {}
        self.fair_starved: Dict[tuple, int] = {}
        self.last_batch: Optional[dict] = None
        self._fills: deque = deque(maxlen=256)
        # ---- observability bindings (first binder wins) ----
        self._metrics = None
        self._cid: Optional[str] = None
        self._tracer = None
        self._flight = None
        self._trace_of: Optional[Callable] = None
        self._link_of: Optional[Callable] = None
        self._span_name = "device_execute"
        self._m: Dict[str, object] = {}

    # ---- binding -------------------------------------------------------------

    def bind(self, metrics, component_id: str, tracer=None, flight=None,
             trace_of: Optional[Callable] = None,
             link_of: Optional[Callable] = None,
             span_name: str = "device_execute") -> None:
        """Attach the observability surfaces. Idempotent with first-binder-
        wins semantics: replicas sharing one engine all call this; the
        queue is per engine, so its metrics land once, under the first
        binder's component id."""
        with self._cond:
            if self._metrics is not None:
                return
            self._metrics = metrics
            self._cid = component_id
            self._tracer = tracer
            self._flight = flight
            self._trace_of = trace_of
            self._link_of = link_of
            self._span_name = span_name
            m, cid = metrics, component_id
            self._m = {
                "batch_size": m.histogram(cid, "batch_size"),
                "batch_fill": m.histogram(cid, "batch_fill"),
                "device_ms": m.histogram(cid, "device_ms"),
                "batch_wait": m.histogram(cid, "batch_wait_ms"),
                "disp_wait": m.histogram(cid, "dispatch_wait_ms"),
                "infer": m.counter(cid, "instances_inferred"),
                "coalesced": m.counter(cid, "coalesced_sources"),
                "substage": {key: m.histogram(cid, key)
                             for key, _ in DEVICE_SUBSTAGES},
            }

    # ---- submission ----------------------------------------------------------

    def _key(self, tenant: Optional[str], lane: Optional[str]) -> tuple:
        if self.qos is not None:
            lane = lane if lane in self.qos.lanes else self.qos.default_lane
        return (tenant or "default", lane or "default")

    def _deadline_ms(self, lane: Optional[str]) -> float:
        if self.qos is not None:
            return self.qos.deadline_for(lane)
        return self.cfg.max_wait_ms

    def submit(self, data: np.ndarray, payload=None,
               ts: Optional[float] = None, lane: Optional[str] = None,
               tenant: Optional[str] = None,
               source: str = "anon") -> Submission:
        """Enqueue one record's rows; returns a :class:`Submission` whose
        future resolves to this record's own prediction slice. Never
        blocks — per-source backpressure (``max_inflight``) is the
        caller's contract, the engine ring is the device-side bound."""
        now = time.perf_counter()
        base = ts if ts is not None else now
        sub = Submission(
            data, payload, base, now, lane, tenant, source,
            base + self._deadline_ms(lane) / 1e3)
        with self._cond:
            if self._closed:
                raise RuntimeError("continuous batcher is closed")
            self._queues.setdefault(
                self._key(tenant, lane), deque()).append(sub)
            self._pending_rows += sub.rows
            self._ensure_thread_locked()
            self._cond.notify_all()
        return sub

    def flush(self) -> None:
        """Force-dispatch everything pending (graceful drain): the force
        flag sticks until the queue empties, so a flush moves multiple
        max_batch-sized batches if that much is queued."""
        with self._cond:
            self._force = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __len__(self) -> int:
        return self._pending_rows

    @property
    def inflight(self) -> int:
        return self._inflight

    # ---- dispatcher thread ---------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"storm-tpu-contbatch-{self.engine_name}")
            self._thread.start()

    def _oldest_enq_locked(self) -> float:
        return min(q[0].enq for q in self._queues.values() if q)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._closed:
                        return
                    if self._pending_rows == 0:
                        self._force = False
                        self._cond.wait()
                        continue
                    now = time.perf_counter()
                    full = self._pending_rows >= self.cfg.max_batch
                    slot_free = self._inflight < self.capacity
                    due = (now - self._oldest_enq_locked()) * 1e3 >= \
                        self.cfg.max_wait_ms
                    if full or self._force or (slot_free and (
                            self._inflight > 0 or self.cfg.eager or due)):
                        # full/forced batches may dispatch with every slot
                        # busy: engine.dispatch parks on the ring — that IS
                        # the backpressure, and the park happens on this
                        # thread, never the event loop.
                        break
                    if slot_free:
                        # Idle + non-eager: age toward the deadline.
                        wait_s = self.cfg.max_wait_ms / 1e3 - (
                            now - self._oldest_enq_locked())
                        self._cond.wait(timeout=max(wait_s, 1e-4))
                    else:
                        # Every slot busy and not enough rows to force a
                        # park: wait for the next slot-free notify.
                        self._cond.wait()
                items = self._form_locked()
                self._inflight += 1
            self._dispatch(items)

    # ---- batch formation (EDF + weighted round-robin + starvation bound) -----

    def _lane_weight(self, key: tuple) -> int:
        if self.qos is None:
            return 1
        # Higher-priority lanes draw proportionally more rows per pass:
        # weight = n_lanes - lane_index (highest lane = n, lowest = 1).
        return len(self.qos.lanes) - self.qos.lane_index(key[1])

    def _form_locked(self) -> List[Submission]:
        """Take up to ``max_batch`` rows across keys. Key order: starved
        keys first (passed over >= starvation_rounds formations, most
        starved first), then earliest head-of-line deadline — EDF across
        tenants and lanes, so LaneBatcher's preemption semantics hold.
        Within the order, rows are taken weighted-round-robin so one
        flooding key cannot monopolize a batch while others wait."""
        max_rows = max(1, self.cfg.max_batch)
        rounds = max(1, int(getattr(self.cfg, "starvation_rounds", 4)))
        keys = [k for k, q in self._queues.items() if q]
        starved = sorted(
            (k for k in keys if self._skipped.get(k, 0) >= rounds),
            key=lambda k: -self._skipped.get(k, 0))
        rest = sorted((k for k in keys if k not in starved),
                      key=lambda k: self._queues[k][0].deadline)
        order = starved + rest
        for k in starved:
            self.fair_starved[k] = self.fair_starved.get(k, 0) + 1
            if self._metrics is not None and self.qos is not None:
                self._metrics.counter(
                    "qos", f"fair_starved_{k[0]}_{k[1]}").inc()
        items: List[Submission] = []
        size = 0
        capped = False
        while not capped:
            progressed = False
            for k in order:
                q = self._queues[k]
                for _ in range(self._lane_weight(k)):
                    if not q:
                        break
                    n = q[0].rows
                    if items and size + n > max_rows:
                        # Mirror the micro-batchers: leftovers stay pending
                        # (an oversized single record still ships alone —
                        # the engine pads per shape rather than crash).
                        capped = True
                        break
                    items.append(q.popleft())
                    size += n
                    progressed = True
                    if size >= max_rows:
                        capped = True
                        break
                if capped:
                    break
            if not progressed:
                break
        self._pending_rows -= size
        contributed: Dict[tuple, int] = {}
        for it in items:
            k = self._key(it.tenant, it.lane)
            contributed[k] = contributed.get(k, 0) + it.rows
        for k, n in contributed.items():
            self._skipped[k] = 0
            self.fair_rows[k] = self.fair_rows.get(k, 0) + n
            if self._metrics is not None and self.qos is not None:
                self._metrics.counter(
                    "qos", f"fair_rows_{k[0]}_{k[1]}").inc(n)
        for k in keys:
            if k not in contributed and self._queues.get(k):
                self._skipped[k] = self._skipped.get(k, 0) + 1
        if self._pending_rows == 0:
            self._force = False
        return items

    # ---- device round trip ---------------------------------------------------

    def _dispatch(self, items: List[Submission]) -> None:
        """Runs on the dispatcher thread. ``engine.dispatch`` may park on
        the pipeline ring — bounded, and exactly the backpressure the
        split-phase engine defines. Every path (success, engine failure,
        evicted engine) funnels into :meth:`_finish`, which owns the
        single slot decrement."""
        t0 = time.perf_counter()
        try:
            engine = self._engine_ref()
            if engine is None:
                raise RuntimeError(
                    f"engine {self.engine_name!r} was evicted with rows "
                    "queued")
            if self._m:
                for it in items:
                    self._m["batch_wait"].observe((t0 - it.enq) * 1e3)
            dispatch = getattr(engine, "dispatch", None)
            if dispatch is None:
                # predict-only engines (plain test doubles): serialized.
                parts = [it.data for it in items]
                x = parts[0] if len(parts) == 1 else np.concatenate(parts)
                out = engine.predict(x)
                self._finish(items, out, None, None, t0,
                             time.perf_counter())
                return
            handle = dispatch([it.data for it in items])
        except BaseException as e:  # noqa: BLE001 - fail ONLY this batch
            self._finish(items, None, e, None, t0, time.perf_counter())
            return
        t1 = time.perf_counter()
        if self._m:
            # Slot wait: time parked on the engine ring (the continuous
            # analogue of the operator's dispatch-semaphore wait).
            self._m["disp_wait"].observe((t1 - t0) * 1e3)
        handle.future.add_done_callback(
            lambda f, its=items, h=handle, a=t0, b=t1:
            self._on_done(its, f, h, a, b))

    def _on_done(self, items: List[Submission], fut: Future, handle,
                 t_form: float, t_disp: float) -> None:
        """Engine fetch-thread callback: free the mirrored slot FIRST (the
        dispatcher can refill while we slice results), then resolve every
        member future."""
        exc = fut.exception()
        out = None if exc is not None else fut.result()
        self._finish(items, out, exc, handle, t_form, time.perf_counter(),
                     t_disp)

    def _finish(self, items, out, exc, handle, t_form, t_done,
                t_disp=None) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()
        rows = sum(it.rows for it in items)
        t_disp = t_disp if t_disp is not None else t_form
        if exc is not None:
            # Exactly-once per source: every member record fails with the
            # batch's exception and each source replays ITS OWN tuples.
            for it in items:
                it.future.set_exception(exc)
            return
        padded = rows
        if handle is not None:
            padded = int(getattr(handle, "padded", rows) or rows)
        fill = rows / max(padded, 1)
        sources = {it.source for it in items}
        self.batches += 1
        self.rows_dispatched += rows
        self._fills.append(fill)
        self.last_batch = {
            "rows": rows, "padded": padded, "fill": round(fill, 4),
            "records": len(items), "sources": sorted(sources)}
        batch_span = None
        if self._tracer is not None and self._tracer.active:
            batch_span = self._trace(items, t_disp, t_done, handle, fill,
                                     len(sources))
        if batch_span is not None:
            for it in items:
                it.batch_span = batch_span
        if self._m:
            self._m["batch_size"].observe(rows)
            self._m["batch_fill"].observe(fill)
            self._m["device_ms"].observe((t_done - t_disp) * 1e3)
            self._m["infer"].inc(rows)
            self._m["coalesced"].inc(len(sources))
            timings = getattr(handle, "timings", None) if handle else None
            if timings:
                for key, _ in DEVICE_SUBSTAGES:
                    if key in timings:
                        self._m["substage"][key].observe(timings[key])
        if self._flight is not None:
            self._flight.event(
                "batch_formed", throttle_s=1.0,
                component=self._cid or "continuous",
                size=rows, records=len(items),
                fill=round(fill, 3), sources=len(sources),
                device_ms=round((t_done - t_disp) * 1e3, 3),
                continuous=True)
        ofs = 0
        for it in items:
            n = it.rows
            it.future.set_result(out[ofs:ofs + n])
            ofs += n

    def _trace(self, items, t0, t1, handle, fill, n_sources):
        """Continuous-mode analogue of the operator's ``_trace_batch``:
        queue_wait per sampled record, one shared device span linked to
        all members, with batch_fill/sources attrs."""
        tracer = self._tracer
        cid = self._cid or "continuous"
        traced = []
        for it in items:
            ctx = self._trace_of(it.payload) if self._trace_of else None
            if ctx is not None:
                # Escalated records link back to the span of the tier
                # that escalated them (link_of), chaining the journey.
                back = self._link_of(it.payload) if self._link_of else None
                traced.append((it, ctx, tracer.record(
                    ctx, "queue_wait", cid, it.enq or t0, t0,
                    links=(back,) if back else ())))
        if not traced:
            return None
        batch_span = tracer.new_span_id()
        links = tuple(qid for _, _, qid in traced)
        attrs = {"batch_size": sum(it.rows for it in items),
                 "records": len(items), "fill": round(fill, 3),
                 "sources": n_sources, "continuous": True}
        timings = getattr(handle, "timings", None) if handle else None
        if timings:
            for key, _ in DEVICE_SUBSTAGES:
                if key in timings:
                    attrs[key] = round(timings[key], 3)
        for _, ctx, qid in traced:
            tracer.record(ctx, self._span_name, cid, t0, t1,
                          span_id=batch_span, parent_id=qid,
                          links=links, attrs=attrs)
        return batch_span

    # ---- introspection -------------------------------------------------------

    def fill_median(self) -> Optional[float]:
        if not self._fills:
            return None
        return float(np.median(list(self._fills)))

    def stats(self) -> dict:
        """Fairness + fill summary for the qos UI route."""
        with self._cond:
            pending = {f"{k[0]}:{k[1]}": sum(s.rows for s in q)
                       for k, q in self._queues.items() if q}
            # Queue-age occupancy signal for the observatory: how long the
            # oldest queued record has been waiting (0 when idle).
            oldest_ms = 0.0
            if any(q for q in self._queues.values()):
                oldest_ms = max(
                    0.0,
                    (time.perf_counter() - self._oldest_enq_locked()) * 1e3)
        med = self.fill_median()
        return {
            "engine": self.engine_name,
            "capacity": self.capacity,
            "inflight": self._inflight,
            "pending_rows": self._pending_rows,
            "oldest_ms": round(oldest_ms, 3),
            "pending_by_key": pending,
            "batches": self.batches,
            "rows": self.rows_dispatched,
            "batch_fill_p50": None if med is None else round(med, 4),
            "fair_rows": {f"{k[0]}:{k[1]}": v
                          for k, v in self.fair_rows.items()},
            "fair_starved": {f"{k[0]}:{k[1]}": v
                             for k, v in self.fair_starved.items()},
            "last_batch": self.last_batch,
        }


# ---- per-engine registry ------------------------------------------------------

# One ContinuousBatcher per live engine object: replicas, the serve path,
# and cascade tiers sharing an engine (via the shared_engine cache) get the
# SAME queue — that identity is what makes them co-batch. Entries hold the
# engine weakly (a finalizer closes the queue when the engine is evicted),
# so the cache's orphan-refcount eviction keeps working.
_REGISTRY: Dict[int, ContinuousBatcher] = {}
_REGISTRY_LOCK = threading.Lock()


def continuous_for(engine, cfg: BatchConfig,
                   qos: Optional[QosConfig] = None) -> ContinuousBatcher:
    """The engine's continuous queue, created on first use. ``cfg``/``qos``
    apply on creation only (first caller wins) — all sources sharing an
    engine share one formation policy, like they share its buckets."""
    key = id(engine)
    with _REGISTRY_LOCK:
        cb = _REGISTRY.get(key)
        if cb is not None and cb._engine_ref() is engine:
            return cb
        cb = ContinuousBatcher(engine, cfg, qos)
        _REGISTRY[key] = cb

        def _drop(k=key):
            with _REGISTRY_LOCK:
                dead = _REGISTRY.pop(k, None)
            if dead is not None:
                dead.close()

        weakref.finalize(engine, _drop)
        return cb


def registry_stats() -> List[dict]:
    """Stats for every live continuous queue (the qos UI route)."""
    with _REGISTRY_LOCK:
        cbs = [cb for cb in _REGISTRY.values()
               if cb._engine_ref() is not None]
    return [cb.stats() for cb in cbs]


def _reset_registry() -> None:
    """Test hook: close and drop every queue."""
    with _REGISTRY_LOCK:
        cbs = list(_REGISTRY.values())
        _REGISTRY.clear()
    for cb in cbs:
        cb.close()
