"""The inference operator — the heart of the system (reference
InferenceBolt.java, SURVEY.md §3.3).

Per-tuple flow, redesigned for the async device boundary:

1. decode the ``{"instances": ...}`` payload (native C++ parser when built;
   the reference's Jackson parse, InferenceBolt.java:76);
2. validate against the model's input shape — a mismatch or parse failure
   emits a :class:`DeadLetter` on the ``dead_letter`` stream and acks
   (the reference emitted ``null`` and acked, :92-99 — poison input should
   never wedge the stream, but it should also never masquerade as output);
3. feed the micro-batcher; a full batch (or deadline flush) dispatches to
   the shared :class:`InferenceEngine` on a worker thread — the event loop
   keeps consuming while the TPU computes (the reference blocked its
   executor thread in ``session.run`` at batch 1);
4. when the batch returns, emit one ``{"predictions": ...}`` tuple per
   input record (anchored) and ack — acks are *deferred* until the device
   round-trip completes, preserving at-least-once across the async boundary
   (SURVEY.md §7 "Hard parts").

Failures inside the device call fail every tuple in the batch -> spout
replay (the reference swallowed inference errors)."""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Optional, Sequence, Set

import numpy as np

from storm_tpu.api.schema import (
    DeadLetter, Overloaded, SchemaError, decode_instances, encode_predictions)
from storm_tpu.config import BatchConfig, Config, ModelConfig, ShardingConfig
from storm_tpu.infer.batcher import Batch, MicroBatcher
from storm_tpu.infer.engine import InferenceEngine, shared_engine
from storm_tpu.runtime.base import Bolt, OutputCollector, TopologyContext
from storm_tpu.runtime.tracing import DEVICE_SUBSTAGES, NOT_SAMPLED, span
from storm_tpu.runtime.tuples import Tuple, Values


class _ChunkHandle:
    """Ref-counted completion for a chunked input tuple (BrokerSpout
    ``chunk=N``): N records share one upstream tuple; it is acked when every
    record completes, failed (once) if any record's batch fails. Poison
    records dead-letter individually and count as completed — one bad record
    must not replay the whole chunk forever."""

    __slots__ = ("tuple", "remaining", "failed")

    def __init__(self, t: Tuple, n: int) -> None:
        self.tuple = t
        self.remaining = n
        self.failed = False

    def done(self, ok: bool, collector: OutputCollector) -> None:
        self.failed |= not ok
        self.remaining -= 1
        if self.remaining == 0:
            (collector.fail if self.failed else collector.ack)(self.tuple)


class InferenceBolt(Bolt):
    def __init__(
        self,
        model: Optional[ModelConfig] = None,
        batch: Optional[BatchConfig] = None,
        sharding: Optional[ShardingConfig] = None,
        engine: Optional[InferenceEngine] = None,
        warmup: bool = True,
        passthrough: Sequence[str] = (),
        qos=None,
    ) -> None:
        self.model_cfg = model or ModelConfig()
        self.batch_cfg = batch or BatchConfig()
        self.sharding_cfg = sharding or ShardingConfig()
        self._engine = engine
        self._warmup = warmup
        # Input fields copied verbatim onto every output tuple (both
        # streams). How a DRPC request id rides through the operator —
        # Storm's LinearDRPCTopologyBuilder threads return-info the same way.
        self.passthrough = tuple(passthrough)
        # QosConfig (config.py) or None. When enabled: earliest-deadline-
        # first batch formation (storm_tpu.qos.lanes) instead of FIFO, and
        # shed-eligible tuples are degraded/rejected while the shed level
        # (gauge ("qos", "shed_level")) is raised.
        self.qos = qos if (qos is not None and qos.enabled) else None

    def clone(self) -> "InferenceBolt":
        return InferenceBolt(
            self.model_cfg, self.batch_cfg, self.sharding_cfg, self._engine,
            self._warmup, self.passthrough, self.qos
        )

    def declare_output_fields(self):
        fields = ("message",) + self.passthrough
        return {"default": fields, "dead_letter": fields}

    def _extras(self, t: Tuple):
        # Default-tolerant: a stream that doesn't carry a passthrough field
        # (e.g. a Kafka spout sharing this bolt with a DRPC spout) yields
        # None rather than poisoning the whole batch with a KeyError.
        return [t.get(f, None) for f in self.passthrough]

    def prewarm(self) -> None:
        """Build + warm the engine OFF the event loop, before this replica
        receives any traffic — called by ``rebalance`` on a worker thread
        when scaling out (warm scale-up: a cold compile must neither block
        the loop nor ride on live tuples). ``prepare`` then finds the
        engine already built and skips the in-loop warmup. Idempotent: the
        process-level engine cache makes repeat calls cheap. An engine
        injected at construction (the NullEngine bench path) is kept, not
        replaced — same contract as prepare()."""
        self._engine = self._engine or shared_engine(
            self.model_cfg, self.sharding_cfg, self.batch_cfg)
        if self._warmup:
            self._engine.warmup()
        # The QoS degrade engine compiles here too — its whole purpose is
        # serving SHED traffic at peak overload, the one moment an XLA
        # compile on the hot path is least affordable. prepare() then
        # finds it in the process cache already warm.
        if self.qos is not None and self.qos.degrade_model:
            deg = shared_engine(
                dataclasses.replace(
                    self.model_cfg, name=self.qos.degrade_model),
                self.sharding_cfg, self.batch_cfg)
            if self._warmup:
                deg.warmup()
        self._prewarmed = True

    def prepare(self, context: TopologyContext, collector: OutputCollector) -> None:
        super().prepare(context, collector)
        # Shared across operator tasks: params live once in HBM; the mesh is
        # the parallelism (vs. the reference's per-bolt model replica).
        self.engine = self._engine or shared_engine(
            self.model_cfg, self.sharding_cfg, self.batch_cfg
        )
        if self._warmup and not getattr(self, "_prewarmed", False):
            self.engine.warmup()
        if self.qos is not None:
            from storm_tpu.qos.lanes import LaneBatcher

            self.batcher = LaneBatcher(self.batch_cfg, self.qos)
        else:
            self.batcher = MicroBatcher(self.batch_cfg)
        self._flush_task: Optional[asyncio.Task] = None
        self._inflight: Set[asyncio.Task] = set()
        self._dispatch_sem = asyncio.Semaphore(
            max(1, self.batch_cfg.max_inflight))
        self._eager = getattr(self.batch_cfg, "eager", False)
        # Eager dispatches created but not yet through sem.acquire():
        # locked() alone is optimistic (the task acquires a tick later),
        # and two same-tick arrivals would otherwise each ship a tiny batch.
        self._eager_pending = 0
        m = context.metrics
        cid = context.component_id
        self._m_batch = m.histogram(cid, "batch_size")
        self._m_device_ms = m.histogram(cid, "device_ms")
        self._m_dead = m.counter(cid, "dead_lettered")
        self._m_infer = m.counter(cid, "instances_inferred")
        # Latency-decomposition stages (bench.py --latency-breakdown): the
        # e2e append->deliver clock attributed into where time actually
        # goes. decode_ms/encode_ms come from span(); these cover the gaps.
        self._m_ingest = m.histogram(cid, "ingest_lag_ms")  # append -> bolt
        self._m_batch_wait = m.histogram(cid, "batch_wait_ms")  # in batcher
        self._m_disp_wait = m.histogram(cid, "dispatch_wait_ms")  # sem queue
        # Split-phase pipeline substages (engine dispatch/fetch timings):
        # together they decompose device_ms, so --latency-breakdown keeps
        # them OUT of the stage sum (device_ms already counts that time).
        self._m_substage = {
            key: m.histogram(cid, key) for key, _ in DEVICE_SUBSTAGES}
        # QoS: the shed level is read per tuple, so cache the gauge (the
        # LoadShedController publishes through the same registry); the
        # degrade engine (cheaper model variant for shed traffic) shares
        # the process-level engine cache and is warmed HERE — lazy compile
        # on the first shed would land the XLA cliff exactly at peak
        # overload (unless prewarm() already did both off-loop).
        if self.qos is not None:
            self._shed_gauge = m.gauge("qos", "shed_level")
            self._m_shed = m.counter(cid, "shed_rejected")
            self._m_degraded = m.counter(cid, "shed_degraded")
            if self.qos.degrade_model:
                self._degrade_engine = shared_engine(
                    dataclasses.replace(
                        self.model_cfg, name=self.qos.degrade_model),
                    self.sharding_cfg, self.batch_cfg)
                if self._warmup and not getattr(self, "_prewarmed", False):
                    self._degrade_engine.warmup()
            else:
                self._degrade_engine = None
            # One degrade call in flight at a time: the degrade path is
            # unbatched (per shed tuple), so it must not be able to starve
            # the primary engine's thread pool under overload — when the
            # slot is busy, shed traffic falls back to typed rejection.
            self._degrade_sem = asyncio.Semaphore(1)
        # Distributed tracing + flight recorder (runtime/tracing.py).
        self._tracer = getattr(context, "tracer", None)
        self._flight = getattr(context, "flight", None)
        if self._flight is not None:
            # Cold XLA compiles ride the hot path (a new bucket shape) —
            # exactly the latency cliff a post-mortem needs to see.
            self.engine.on_compile = (
                lambda shape, ms, cid=cid, fl=self._flight: fl.event(
                    "xla_compile", component=cid, batch_shape=shape,
                    compile_ms=round(ms, 1)))

    # ---- ingest --------------------------------------------------------------

    # Batch items are either a raw Tuple (one record per tuple) or a
    # _ChunkHandle (chunked ingestion). These two helpers are the only
    # places that distinguish them.

    @staticmethod
    def _anchor_of(item) -> Tuple:
        return item.tuple if isinstance(item, _ChunkHandle) else item

    def _complete(self, item, ok: bool) -> None:
        if isinstance(item, _ChunkHandle):
            item.done(ok, self.collector)
        elif ok:
            self.collector.ack(item)
        else:
            self.collector.fail(item)

    def _decode_checked(self, payload, root_ts):
        """Decode + shape-validate one record (raises SchemaError)."""
        with span(self.context.metrics, self.context.component_id, "decode"):
            inst = decode_instances(payload, ts=root_ts)
        if tuple(inst.data.shape[1:]) != self.engine.input_shape:
            raise SchemaError(
                f"instance shape {tuple(inst.data.shape[1:])} != model "
                f"input {self.engine.input_shape}"
            )
        return inst

    async def _emit_dead_letter(self, anchor: Tuple, payload, error: str) -> None:
        self._m_dead.inc()
        if isinstance(payload, (bytes, bytearray)):
            # raw-scheme tuples: the DLQ envelope is JSON, so carry the
            # payload as text, not a bytes repr
            payload = payload.decode("utf-8", "replace")
        dl = DeadLetter(payload=str(payload), error=error)
        await self.collector.emit(
            Values([dl.to_json(), *self._extras(anchor)]),
            stream="dead_letter", anchors=[anchor],
        )

    def _kick_flush(self) -> None:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # loop torn down mid-finalizer (cluster shutdown race)
        if self._eager and len(self.batcher) and \
                not self._dispatch_sem.locked() and not self._eager_pending:
            # Work-conserving: a device slot is free and records are
            # waiting — dispatch now rather than age toward the deadline.
            # Under load every slot is busy, this branch never fires, and
            # batches fill toward max_batch while they queue.
            batch = self.batcher.take_all()
            if batch is not None:
                self._eager_pending += 1
                task = asyncio.get_running_loop().create_task(
                    self._dispatch(batch))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
                # Decrement when the task finishes — however it finishes.
                # A cancel BEFORE the coroutine's first step never enters
                # _dispatch, so an in-body decrement would leak the counter
                # and permanently disable eager dispatch for this bolt.
                task.add_done_callback(
                    lambda _t: setattr(
                        self, "_eager_pending", self._eager_pending - 1))
                return
        if len(self.batcher) and (self._flush_task is None or self._flush_task.done()):
            self._flush_task = asyncio.get_running_loop().create_task(
                self._deadline_flush()
            )

    async def execute(self, t: Tuple) -> None:
        if t.root_ts:
            # Stage 1 of the decomposition: broker append -> bolt arrival
            # (broker queueing + spout fetch/decode + inter-operator hop).
            self._m_ingest.observe((time.perf_counter() - t.root_ts) * 1e3)
        payload = t.get("message")
        lane = t.get("qos_lane", None) if self.qos is not None else None
        if self.qos is not None:
            level = int(self._shed_gauge.value)
            if level > 0 and self.qos.shed_eligible(lane, level):
                # Shed BEFORE decode: the whole point is spending nothing
                # on traffic we will not serve at full fidelity.
                await self._shed_tuple(t, payload, lane, level)
                return
        if isinstance(payload, (list, tuple)):
            await self._execute_chunk(t, payload, lane)
            return
        try:
            inst = self._decode_checked(payload, t.root_ts)
        except SchemaError as e:
            await self._dead_letter(t, payload, str(e))
            return
        batch = self._batcher_add(t, inst.data, t.root_ts or None, lane)
        while batch is not None:
            await self._dispatch(batch)
            # Drain any batch parked at max_batch behind the one just
            # taken (add returns at most one batch per call; a full one
            # must not sit until the deadline).
            batch = self.batcher.take_ready()
        self._kick_flush()

    def _batcher_add(self, item, data, ts, lane):
        if self.qos is not None:
            return self.batcher.add(item, data, ts=ts, lane=lane)
        return self.batcher.add(item, data, ts=ts)

    async def _execute_chunk(self, t: Tuple, payloads, lane=None) -> None:
        handle = _ChunkHandle(t, len(payloads))
        for payload in payloads:
            try:
                inst = self._decode_checked(payload, t.root_ts)
            except SchemaError as e:
                # Dead-letter the record, keep the chunk alive: anchored to
                # the chunk tuple, completed as handled.
                await self._emit_dead_letter(t, payload, str(e))
                handle.done(True, self.collector)
                continue
            batch = self._batcher_add(handle, inst.data, t.root_ts or None,
                                      lane)
            while batch is not None:
                await self._dispatch(batch)
                batch = self.batcher.take_ready()
        self._kick_flush()

    async def _dead_letter(self, t: Tuple, payload: str, error: str) -> None:
        """Poison input: route to the dead-letter stream and ack (replaying
        a parse failure can never succeed; the reference's emit-null-and-ack
        at InferenceBolt.java:92-99 is the anti-pattern this replaces)."""
        await self._emit_dead_letter(t, payload, error)
        self.collector.ack(t)

    # ---- QoS shedding --------------------------------------------------------

    async def _shed_tuple(self, t: Tuple, payload, lane, level: int) -> None:
        """Graceful degradation for a shed-eligible tuple while the shed
        level is raised: serve it on the cheaper degrade engine when one is
        configured and free, otherwise answer immediately with a typed
        ``Overloaded`` record — either way the client gets a parseable
        response *now* instead of a timeout, and the tuple acks (shedding
        must never trigger replay: replaying rejected load is more load)."""
        payloads = payload if isinstance(payload, (list, tuple)) else [payload]
        degraded = False
        if self._degrade_engine is not None and not self._degrade_sem.locked():
            degraded = await self._degrade(t, payloads)
        if not degraded:
            msg = Overloaded(lane=lane or "", shed_level=level).to_json()
            for _ in payloads:
                await self.collector.emit(
                    Values([msg, *self._extras(t)]), anchors=[t])
            self._m_shed.inc(len(payloads))
        action = "degrade" if degraded else "reject"
        if self._flight is not None:
            self._flight.event(
                "shed_" + action, throttle_s=1.0,
                component=self.context.component_id,
                lane=lane, level=level, records=len(payloads))
        ctx = t.trace
        if (ctx is not None and ctx is not NOT_SAMPLED
                and self._tracer is not None and self._tracer.active):
            now = time.perf_counter()
            self._tracer.record(
                ctx, "qos_shed", self.context.component_id,
                t.root_ts or now, now,
                attrs={"lane": lane or "", "level": level, "action": action})
        self.collector.ack(t)

    async def _degrade(self, t: Tuple, payloads) -> bool:
        """Run shed traffic on the cheaper model variant, unbatched (one
        predict per shed tuple, single slot — see the semaphore note in
        prepare). Returns False (caller rejects instead) on any decode or
        shape mismatch: the degrade path must stay cheap and infallible."""
        eng = self._degrade_engine
        try:
            arrs = [decode_instances(p, ts=t.root_ts).data for p in payloads]
        except SchemaError:
            return False
        x = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
        if tuple(x.shape[1:]) != eng.input_shape:
            return False
        async with self._degrade_sem:
            try:
                out = await asyncio.to_thread(eng.predict, x)
            except Exception as e:
                self.collector.report_error(e)
                return False
        i = 0
        for arr in arrs:
            n = arr.shape[0]
            msg = encode_predictions(out[i:i + n])
            i += n
            await self.collector.emit(
                Values([msg, *self._extras(t)]), anchors=[t])
        self._m_degraded.inc(len(payloads))
        return True

    # ---- batching / dispatch -------------------------------------------------

    async def _deadline_flush(self) -> None:
        """Runs while records are pending; never cancelled mid-dispatch (a
        cancel between take and dispatch would silently drop the batch), it
        just exits when the batcher drains."""
        while True:
            oldest = self.batcher.oldest_ts
            if oldest is None:
                return
            wait_s = self.batch_cfg.max_wait_ms / 1e3 - (time.perf_counter() - oldest)
            if wait_s > 0:
                await asyncio.sleep(wait_s)
            batch = self.batcher.take_if_due()
            while batch is not None:
                await self._dispatch(batch)
                batch = self.batcher.take_ready()

    async def _dispatch(self, batch: Batch) -> None:
        # NB: _eager_pending is decremented by a done-callback on the eager
        # task (see _kick_flush), NOT here — a cancel while parked on the
        # semaphore (or before the first step) must still restore it.
        t0 = time.perf_counter()
        # Stage: accumulation in the batcher (deadline vs fill), per
        # record from batcher entry to flush. Observed BEFORE the
        # semaphore so batch_wait and dispatch_queue partition the clock
        # instead of overlapping.
        for it in batch.items:
            if it.enq:
                self._m_batch_wait.observe((t0 - it.enq) * 1e3)
        await self._dispatch_sem.acquire()
        # Stage: wait for a free device slot (max_inflight backpressure).
        self._m_disp_wait.observe((time.perf_counter() - t0) * 1e3)
        task = asyncio.get_running_loop().create_task(self._run_batch(batch))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def _trace_batch(self, batch: Batch, t0: float, t1: float,
                     timings=None) -> None:
        """Span bookkeeping for one device round trip: a ``queue_wait``
        span per SAMPLED record (batcher entry -> device start) and ONE
        shared ``device_execute`` span — same span id in every
        participating trace, linked to all member record spans — so the
        fan-in of N records into one batch is first-class in the trace
        (and queue-wait vs. device time separable per record). Only
        called when the tracer is active; per-record work only for
        sampled records."""
        tracer = self._tracer
        cid = self.context.component_id
        traced = []
        for it in batch.items:
            ctx = self._anchor_of(it.payload).trace
            if ctx is not None:
                traced.append((ctx, tracer.record(
                    ctx, "queue_wait", cid, it.enq or t0, t0)))
        if not traced:
            return
        batch_span = tracer.new_span_id()
        links = tuple(qid for _, qid in traced)
        attrs = {"batch_size": batch.size, "records": len(batch.items)}
        if timings:
            # Split-phase decomposition of this span's wall time: where the
            # device round trip went (staging+H2D vs compute vs D2H).
            for key, _ in DEVICE_SUBSTAGES:
                if key in timings:
                    attrs[key] = round(timings[key], 3)
        for ctx, qid in traced:
            tracer.record(ctx, "device_execute", cid, t0, t1,
                          span_id=batch_span, parent_id=qid,
                          links=links, attrs=attrs)

    async def _run_batch(self, batch: Batch) -> None:
        try:
            dispatch = getattr(self.engine, "dispatch", None)
            t0 = time.perf_counter()
            timings = None
            if dispatch is not None:
                # Split-phase path: dispatch (stage into the engine's
                # pooled buffer + H2D + async launch) runs on a worker
                # thread because it can park on the engine's bounded ring;
                # the result future resolves from the engine's fetch
                # thread. The dispatch semaphore stays held for the full
                # round trip, so max_inflight backpressure and deferred
                # acks keep their pre-pipeline semantics.
                handle = await asyncio.to_thread(dispatch, batch.parts())
                out = await asyncio.wrap_future(handle.future)
                timings = handle.timings
            else:
                # Engines without the split-phase surface (degrade path,
                # custom test doubles): the serialized predict.
                out = await asyncio.to_thread(self.engine.predict,
                                              batch.stack())
            t1 = time.perf_counter()
            self._m_device_ms.observe((t1 - t0) * 1e3)
            if timings:
                for key, _ in DEVICE_SUBSTAGES:
                    if key in timings:
                        self._m_substage[key].observe(timings[key])
            self._m_batch.observe(batch.size)
            self._m_infer.inc(batch.size)
            if self._tracer is not None and self._tracer.active:
                self._trace_batch(batch, t0, t1, timings)
            if self._flight is not None:
                # Sampled (throttled) batch-formed events: enough to see
                # batch-size/device-time behavior in a post-mortem without
                # a per-batch firehose at production rates.
                self._flight.event(
                    "batch_formed", throttle_s=1.0,
                    component=self.context.component_id,
                    size=batch.size, records=len(batch.items),
                    device_ms=round((t1 - t0) * 1e3, 3))
            for item, preds in batch.split(out):
                anchor = self._anchor_of(item)
                with span(self.context.metrics, self.context.component_id,
                          "encode"):
                    msg = encode_predictions(preds)
                await self.collector.emit(
                    Values([msg, *self._extras(anchor)]),
                    anchors=[anchor],
                )
                self._complete(item, True)
        except Exception as e:
            # Device/compile failure: fail every tuple in the batch -> replay.
            self.collector.report_error(e)
            for item in batch.items:
                self._complete(item.payload, False)
        finally:
            self._dispatch_sem.release()
            # Freed a slot: eagerly pull whatever queued while we ran.
            self._kick_flush()

    async def swap_model(self, model_cfg: ModelConfig) -> None:
        """Zero-downtime model swap (the reference ships its model inside
        the application jar, InferenceBolt.java:49-57 — redeploying means a
        full topology restart; here a new checkpoint/model goes live under
        traffic). The new engine is built and warmed on a worker thread,
        then the reference is switched atomically: batches already in
        flight finish on the old engine, later batches use the new one.
        The old engine stays in the process cache for instant rollback
        (swap back) at the cost of its HBM footprint.

        Swapping to a different ``input_shape`` may fail-and-replay tuples
        decoded under the old shape that are still in the batcher —
        at-least-once delivery covers them."""

        def build() -> InferenceEngine:
            eng = shared_engine(model_cfg, self.sharding_cfg, self.batch_cfg)
            eng.warmup()
            return eng

        new_engine = await asyncio.to_thread(build)
        self.engine = new_engine
        self.model_cfg = model_cfg

    async def tick(self) -> None:
        batch = self.batcher.take_if_due()
        while batch is not None:
            await self._dispatch(batch)
            batch = self.batcher.take_ready()

    async def flush(self) -> None:
        """Drain: dispatch whatever is pending and wait for in-flight
        batches, so a graceful stop never strands undecoded acks."""
        batch = self.batcher.take_all()
        if batch is not None:
            await self._dispatch(batch)
        while self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)

    def cleanup(self) -> None:
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
        self._flush_task = None
