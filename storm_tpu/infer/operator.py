"""The inference operator — the heart of the system (reference
InferenceBolt.java, SURVEY.md §3.3).

Per-tuple flow, redesigned for the async device boundary:

1. decode the ``{"instances": ...}`` payload (native C++ parser when built;
   the reference's Jackson parse, InferenceBolt.java:76);
2. validate against the model's input shape — a mismatch or parse failure
   emits a :class:`DeadLetter` on the ``dead_letter`` stream and acks
   (the reference emitted ``null`` and acked, :92-99 — poison input should
   never wedge the stream, but it should also never masquerade as output);
3. feed the micro-batcher; a full batch (or deadline flush) dispatches to
   the shared :class:`InferenceEngine` on a worker thread — the event loop
   keeps consuming while the TPU computes (the reference blocked its
   executor thread in ``session.run`` at batch 1);
4. when the batch returns, emit one ``{"predictions": ...}`` tuple per
   input record (anchored) and ack — acks are *deferred* until the device
   round-trip completes, preserving at-least-once across the async boundary
   (SURVEY.md §7 "Hard parts").

Failures inside the device call fail every tuple in the batch -> spout
replay (the reference swallowed inference errors)."""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Sequence, Set

import numpy as np

from storm_tpu.api.schema import (
    DeadLetter, Overloaded, SchemaError, decode_instances, encode_predictions)
from storm_tpu.cascade.policy import CascadeConfig
from storm_tpu.cascade.router import CascadeRouter, Escalated
from storm_tpu.config import BatchConfig, Config, ModelConfig, ShardingConfig
from storm_tpu.infer.batcher import Batch, MicroBatcher
from storm_tpu.infer.engine import InferenceEngine, shared_engine
from storm_tpu.obs import copyledger as _copyledger
from storm_tpu.runtime.base import Bolt, OutputCollector, TopologyContext
from storm_tpu.runtime.frames import RecordFrame
from storm_tpu.runtime.tracing import DEVICE_SUBSTAGES, NOT_SAMPLED, span
from storm_tpu.runtime.tuples import Tuple, Values


class _ChunkHandle:
    """Ref-counted completion for a chunked input tuple (BrokerSpout
    ``chunk=N``): N records share one upstream tuple; it is acked when every
    record completes, failed (once) if any record's batch fails. Poison
    records dead-letter individually and count as completed — one bad record
    must not replay the whole chunk forever."""

    __slots__ = ("tuple", "remaining", "failed", "frame")

    def __init__(self, t: Tuple, n: int, frame: bool = False) -> None:
        self.tuple = t
        self.remaining = n
        self.failed = False
        # frame=True: the chunk arrived as a RecordFrame (batch-native
        # ingress) — egress coalesces this handle's records into ONE
        # predictions payload per dispatched batch (see _run_batch).
        self.frame = frame

    def done(self, ok: bool, collector: OutputCollector) -> None:
        self.failed |= not ok
        self.remaining -= 1
        if self.remaining == 0:
            (collector.fail if self.failed else collector.ack)(self.tuple)


class InferenceBolt(Bolt):
    def __init__(
        self,
        model: Optional[ModelConfig] = None,
        batch: Optional[BatchConfig] = None,
        sharding: Optional[ShardingConfig] = None,
        engine: Optional[InferenceEngine] = None,
        warmup: bool = True,
        passthrough: Sequence[str] = (),
        qos=None,
        cascade: Optional[CascadeConfig] = None,
    ) -> None:
        self.model_cfg = model or ModelConfig()
        self.batch_cfg = batch or BatchConfig()
        self.sharding_cfg = sharding or ShardingConfig()
        self._engine = engine
        self._warmup = warmup
        # Input fields copied verbatim onto every output tuple (both
        # streams). How a DRPC request id rides through the operator —
        # Storm's LinearDRPCTopologyBuilder threads return-info the same way.
        self.passthrough = tuple(passthrough)
        # QosConfig (config.py) or None. When enabled: earliest-deadline-
        # first batch formation (storm_tpu.qos.lanes) instead of FIFO, and
        # shed-eligible tuples are degraded/rejected while the shed level
        # (gauge ("qos", "shed_level")) is raised.
        self.qos = qos if (qos is not None and qos.enabled) else None
        # CascadeConfig (cascade/policy.py) or None: confidence-gated
        # tiered serving — records enter at tier 0 and only the
        # low-confidence residue escalates toward the flagship.
        self.cascade = cascade if (cascade is not None
                                   and cascade.enabled) else None

    def clone(self) -> "InferenceBolt":
        return InferenceBolt(
            self.model_cfg, self.batch_cfg, self.sharding_cfg, self._engine,
            self._warmup, self.passthrough, self.qos, self.cascade
        )

    def declare_output_fields(self):
        fields = ("message",) + self.passthrough
        return {"default": fields, "dead_letter": fields}

    def _extras(self, t: Tuple):
        # Default-tolerant: a stream that doesn't carry a passthrough field
        # (e.g. a Kafka spout sharing this bolt with a DRPC spout) yields
        # None rather than poisoning the whole batch with a KeyError.
        return [t.get(f, None) for f in self.passthrough]

    def prewarm(self) -> None:
        """Build + warm the engine OFF the event loop, before this replica
        receives any traffic — called by ``rebalance`` on a worker thread
        when scaling out (warm scale-up: a cold compile must neither block
        the loop nor ride on live tuples). ``prepare`` then finds the
        engine already built and skips the in-loop warmup. Idempotent: the
        process-level engine cache makes repeat calls cheap. An engine
        injected at construction (the NullEngine bench path) is kept, not
        replaced — same contract as prepare()."""
        from storm_tpu.obs.profile import ensure_installed

        ensure_installed()  # before the cold compiles, as in prepare()
        self._engine = self._engine or shared_engine(
            self.model_cfg, self.sharding_cfg, self.batch_cfg)
        if self._warmup:
            self._engine.warmup()
        # Cascade tiers compile here too (the QoS degrade tier included —
        # its whole purpose is serving SHED traffic at peak overload, the
        # one moment an XLA compile on the hot path is least affordable).
        # prepare() then finds them in the process cache already warm.
        cas = self._cascade_cfg()
        if cas is not None:
            probe = CascadeRouter(cas, qos=self.qos)
            for i in range(len(cas.tiers)):
                mc = probe.tier_model(i, self.model_cfg)
                if mc is self.model_cfg:
                    continue  # the flagship engine, warmed above
                eng = shared_engine(mc, self.sharding_cfg, self.batch_cfg)
                if self._warmup:
                    eng.warmup()
        self._prewarmed = True

    def _cascade_cfg(self) -> Optional[CascadeConfig]:
        """The effective cascade: the explicit config when given, else a
        synthesized two-tier shed-only cascade for ``qos.degrade_model``
        (the old cheaper-model-behind-a-semaphore degrade path, now just
        a cascade whose tier 0 serves pinned shed traffic with normal
        batching and ``max_inflight`` concurrency)."""
        if self.cascade is not None:
            return self.cascade
        if self.qos is not None and self.qos.degrade_model:
            return CascadeConfig(
                enabled=True,
                tiers=(self.qos.degrade_model, self.model_cfg.name),
                thresholds=(0.0,), shed_only=True)
        return None

    def prepare(self, context: TopologyContext, collector: OutputCollector) -> None:
        super().prepare(context, collector)
        # Cost profiler (storm_tpu/obs): point the engine layer's profile
        # sink at the process ProfileStore BEFORE any engine builds or
        # warms up, so warmup's cold compiles land in the per-shape
        # compile table. Idempotent, near-free per batch.
        from storm_tpu.obs.profile import ensure_installed

        ensure_installed()
        _copyledger.ensure_installed()  # byte-side twin, same lifecycle
        # Shared across operator tasks: params live once in HBM; the mesh is
        # the parallelism (vs. the reference's per-bolt model replica).
        self.engine = self._engine or shared_engine(
            self.model_cfg, self.sharding_cfg, self.batch_cfg
        )
        prewarmed = getattr(self, "_prewarmed", False)
        if self._warmup and not prewarmed:
            self.engine.warmup()
        # Cascade (explicit config, or synthesized from qos.degrade_model):
        # one shared engine + residue batcher per tier. The operator keeps
        # owning tasks, acks, and the dispatch semaphore — max_inflight now
        # bounds device round trips ACROSS tiers.
        cas = self._cascade_cfg()
        if cas is not None:
            self._router = CascadeRouter(cas, qos=self.qos)
            self._router.build(
                self.model_cfg, self.sharding_cfg, self.batch_cfg,
                build_engine=lambda mc: shared_engine(
                    mc, self.sharding_cfg, self.batch_cfg),
                flagship=self.engine,
                warmup=self._warmup and not prewarmed)
        else:
            self._router = None
        if self.qos is not None:
            from storm_tpu.qos.lanes import LaneBatcher

            self.batcher = LaneBatcher(self.batch_cfg, self.qos)
        else:
            self.batcher = MicroBatcher(self.batch_cfg)
        if self._router is not None:
            # Cascade ingest goes through the tier batchers; self.batcher
            # stays as an alias of the default entry tier's batcher so
            # introspection (len, tests) keeps working.
            entry0 = cas.last_tier if cas.shed_only else 0
            self.batcher = self._router.tiers[entry0].batcher
            self._sources = [
                (t.index, t.batcher) for t in self._router.tiers]
        else:
            self._sources = [(None, self.batcher)]
        self._flush_task: Optional[asyncio.Task] = None
        self._inflight: Set[asyncio.Task] = set()
        self._dispatch_sem = asyncio.Semaphore(
            max(1, self.batch_cfg.max_inflight))
        self._eager = getattr(self.batch_cfg, "eager", False)
        # Eager dispatches created but not yet through sem.acquire():
        # locked() alone is optimistic (the task acquires a tick later),
        # and two same-tick arrivals would otherwise each ship a tiny batch.
        self._eager_pending = 0
        m = context.metrics
        cid = context.component_id
        self._m_batch = m.histogram(cid, "batch_size")
        self._m_device_ms = m.histogram(cid, "device_ms")
        self._m_dead = m.counter(cid, "dead_lettered")
        self._m_infer = m.counter(cid, "instances_inferred")
        # Latency-decomposition stages (bench.py --latency-breakdown): the
        # e2e append->deliver clock attributed into where time actually
        # goes. decode_ms/encode_ms come from span(); these cover the gaps.
        self._m_ingest = m.histogram(cid, "ingest_lag_ms")  # append -> bolt
        self._m_batch_wait = m.histogram(cid, "batch_wait_ms")  # in batcher
        self._m_disp_wait = m.histogram(cid, "dispatch_wait_ms")  # sem queue
        # Fragmentation metrics (both dispatch paths, so the continuous
        # A/B has a baseline): rows dispatched / padded bucket capacity,
        # and how many distinct sources each dispatched batch coalesced
        # (always 1 on the per-task deadline path).
        self._m_fill = m.histogram(cid, "batch_fill")
        self._m_coalesced = m.counter(cid, "coalesced_sources")
        # Split-phase pipeline substages (engine dispatch/fetch timings):
        # together they decompose device_ms, so --latency-breakdown keeps
        # them OUT of the stage sum (device_ms already counts that time).
        self._m_substage = {
            key: m.histogram(cid, key) for key, _ in DEVICE_SUBSTAGES}
        if self._router is not None:
            self._router.bind_metrics(m, cid)
        # QoS: the shed level is read per tuple, so cache the gauge (the
        # LoadShedController publishes through the same registry). The
        # degrade path now lives in the cascade: qos.degrade_model
        # synthesizes a shed-only cascade whose tier 0 serves pinned shed
        # traffic — batched, under the normal max_inflight concurrency —
        # replacing the old unbatched single-slot degrade semaphore.
        if self.qos is not None:
            self._shed_gauge = m.gauge("qos", "shed_level")
            self._m_shed = m.counter(cid, "shed_rejected")
            self._m_degraded = m.counter(cid, "shed_degraded")
        # Distributed tracing + flight recorder (runtime/tracing.py).
        self._tracer = getattr(context, "tracer", None)
        self._flight = getattr(context, "flight", None)
        if self._flight is not None:
            # Cold XLA compiles ride the hot path (a new bucket shape) —
            # exactly the latency cliff a post-mortem needs to see.
            hook = (
                lambda shape, ms, cid=cid, fl=self._flight: fl.event(
                    "xla_compile", component=cid, batch_shape=shape,
                    compile_ms=round(ms, 1)))
            self.engine.on_compile = hook
            if self._router is not None:
                for rt in self._router.tiers:
                    try:
                        rt.engine.on_compile = hook
                    except AttributeError:
                        pass  # slotted test double
        # Engine quarantine -> replacement (batch.watchdog_trips): the
        # watchdog quarantines on the fetch thread; this hook records it
        # and rebuilds a fresh shared engine on a background thread (the
        # quarantined one was evicted from the cache), swapping it in once
        # warmed. Until then dispatch raises EngineQuarantined, those
        # batches fail, and their sources replay — fail-and-replay, never
        # wedge.
        self._m_quarantined = m.gauge(cid, "engine_quarantined")
        self._m_wd_trips = m.counter(cid, "watchdog_trips")
        try:
            self.engine.on_quarantine = self._engine_quarantined
        except AttributeError:
            pass  # slotted test double
        # Continuous batching (BatchGen, ROADMAP item 3): batch formation
        # moves OFF this task into the engine's shared slot-level queue —
        # every replica, the serve cross-batcher, and cascade residues
        # co-batch there. The per-task batchers above stay as admission
        # shims (shed/lane classification still happens here); they just
        # never accumulate.
        self._continuous = bool(getattr(self.batch_cfg, "continuous", False))
        self._cbs = {}
        if self._continuous:
            from storm_tpu.infer.continuous import continuous_for

            trace_of = lambda p: self._anchor_of(p).trace  # noqa: E731
            link_of = (  # noqa: E731
                lambda p: p.link_span if isinstance(p, Escalated) else None)
            if self._router is not None:
                for rt in self._router.tiers:
                    tcb = continuous_for(rt.engine, self.batch_cfg, self.qos)
                    tcb.bind(m, cid, tracer=self._tracer,
                             flight=self._flight, trace_of=trace_of,
                             link_of=link_of,
                             span_name=f"cascade_tier{rt.index}")
                    self._cbs[rt.index] = tcb
            else:
                cb = continuous_for(self.engine, self.batch_cfg, self.qos)
                cb.bind(m, cid, tracer=self._tracer, flight=self._flight,
                        trace_of=trace_of, link_of=link_of,
                        span_name="device_execute")
                self._cbs[None] = cb
            # Per-task backpressure: the dispatch semaphore bounded
            # BATCHES in flight; here the queue owns batching, so the
            # task bounds its outstanding ROWS at the equivalent
            # max_inflight * max_batch.
            self._cb_cap = (max(1, self.batch_cfg.max_inflight)
                            * max(1, self.batch_cfg.max_batch))
            self._cb_rows = 0
            self._cb_room = asyncio.Event()
            self._cb_room.set()
            self._cb_source = f"{cid}#{context.task_index}"

    # ---- quarantine -> replacement -------------------------------------------

    def _engine_quarantined(self, trips: int) -> None:
        """Engine watchdog callback (fires ONCE, on the fetch thread):
        record the quarantine, then prewarm a replacement off-thread and
        swap it in. Batches dispatched in between fail fast
        (EngineQuarantined) and their sources replay."""
        import threading

        self._m_quarantined.set(1)
        self._m_wd_trips.inc(trips)
        if self._flight is not None:
            self._flight.event(
                "engine_quarantined", component=self.context.component_id,
                model=self.model_cfg.name, trips=trips)
        old = self.engine

        def rebuild() -> None:
            try:
                # The quarantined engine was evicted from the shared
                # cache, so this builds (and warms) a genuinely fresh one.
                eng = shared_engine(
                    self.model_cfg, self.sharding_cfg, self.batch_cfg)
                if self._warmup:
                    eng.warmup()
                try:
                    eng.on_compile = old.on_compile
                    eng.on_quarantine = self._engine_quarantined
                except AttributeError:
                    pass
                self.engine = eng
                # Re-aim the continuous batcher (it holds the engine it
                # dispatches to) at the replacement.
                if getattr(self, "_cbs", None) and None in self._cbs:
                    from storm_tpu.infer.continuous import continuous_for

                    cb = continuous_for(eng, self.batch_cfg, self.qos)
                    m = self.context.metrics
                    cb.bind(m, self.context.component_id,
                            tracer=self._tracer, flight=self._flight,
                            trace_of=lambda p: self._anchor_of(p).trace,
                            span_name="device_execute")
                    self._cbs[None] = cb
                self._m_quarantined.set(0)
                if self._flight is not None:
                    self._flight.event(
                        "engine_replaced",
                        component=self.context.component_id,
                        model=self.model_cfg.name)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "replacement engine build failed; component stays "
                    "quarantined (batches fail fast and replay)")

        threading.Thread(target=rebuild, name="engine-replace",
                         daemon=True).start()

    # ---- ingest --------------------------------------------------------------

    # Batch items are a raw Tuple (one record per tuple), a _ChunkHandle
    # (chunked ingestion), or either wrapped in Escalated while riding a
    # cascade escalation tier. These two helpers are the only places that
    # distinguish them — completion always unwraps to the ORIGINAL tuple,
    # so deferred acks and replay are tier-blind (exactly-once preserved).

    @staticmethod
    def _anchor_of(item) -> Tuple:
        if isinstance(item, Escalated):
            item = item.payload
        return item.tuple if isinstance(item, _ChunkHandle) else item

    def _complete(self, item, ok: bool) -> None:
        if isinstance(item, Escalated):
            item = item.payload
        if isinstance(item, _ChunkHandle):
            item.done(ok, self.collector)
        elif ok:
            self.collector.ack(item)
        else:
            self.collector.fail(item)

    @staticmethod
    def _egress_groups(emit):
        """Partition an emit list into frame egress groups, order
        preserved: consecutive-or-not members of the same frame
        ``_ChunkHandle`` coalesce under it; everything else stays a
        singleton keyed ``None``. Returns ``[(handle|None, [(item,
        preds), ...]), ...]``."""
        out = []
        index = {}
        for item, preds in emit:
            base = item.payload if isinstance(item, Escalated) else item
            if isinstance(base, _ChunkHandle) and base.frame:
                i = index.get(id(base))
                if i is None:
                    index[id(base)] = len(out)
                    out.append((base, [(item, preds)]))
                else:
                    out[i][1].append((item, preds))
            else:
                out.append((None, [(item, preds)]))
        return out

    def _decode_checked(self, payload, root_ts):
        """Decode + shape-validate one record (raises SchemaError)."""
        with span(self.context.metrics, self.context.component_id, "decode"):
            inst = decode_instances(payload, ts=root_ts)
        if tuple(inst.data.shape[1:]) != self.engine.input_shape:
            raise SchemaError(
                f"instance shape {tuple(inst.data.shape[1:])} != model "
                f"input {self.engine.input_shape}"
            )
        if _copyledger.active():
            # Copy ledger: the parse writes a fresh float32 array — the
            # ~57 us/record tax ROADMAP item 2 wants decomposed. Bytes
            # are the array produced; the JSON text length rides in the
            # spout rows (scheme/ingest), not here. On the tensor-view
            # fast path nothing was written (the array is a view over
            # the payload buffer): the row stays, the zeros prove it.
            if inst.view:
                _copyledger.record("json_decode", 0, copies=0, allocs=0,
                                   records=1,
                                   engine=self.context.component_id)
            else:
                _copyledger.record("json_decode", inst.data.nbytes, copies=1,
                                   allocs=1, records=1,
                                   engine=self.context.component_id)
        return inst

    def _encode_ledgered(self, preds, records: int = 1):
        """``encode_predictions`` + the copy-ledger ``json_encode`` hop:
        the serialization writes one fresh payload per emit.

        Raw-scheme topologies (``_bytes_egress``) get the payload as
        utf-8 BYTES: the sink produces those bytes verbatim, so the
        legacy ``sink_encode`` re-encode hop (which duplicated every
        payload byte, BENCH_COPY_r18) disappears from the path. String
        topologies keep the str contract (the JSON dist wire and
        multilang bolts cannot carry bytes)."""
        msg = encode_predictions(preds)
        if self._bytes_egress:
            payload = msg.encode("utf-8")
            if _copyledger.active():
                _copyledger.record("json_encode", len(payload), copies=1,
                                   allocs=1, records=records,
                                   engine=self.context.component_id)
            return payload
        if _copyledger.active():
            _copyledger.record("json_encode", len(msg), copies=1, allocs=1,
                               records=records,
                               engine=self.context.component_id)
        return msg

    async def _emit_dead_letter(self, anchor: Tuple, payload, error: str) -> None:
        self._m_dead.inc()
        if isinstance(payload, memoryview):
            # frame-record views: materialize before the envelope (also
            # releases the view's hold on its wire/shm backing buffer)
            payload = bytes(payload)
        if isinstance(payload, (bytes, bytearray)):
            # raw-scheme tuples: the DLQ envelope is JSON, so carry the
            # payload as text, not a bytes repr
            payload = payload.decode("utf-8", "replace")
        dl = DeadLetter(payload=str(payload), error=error)
        await self.collector.emit(
            Values([dl.to_json(), *self._extras(anchor)]),
            stream="dead_letter", anchors=[anchor],
        )

    def __getattr__(self, name):
        # `_sources` is assigned in prepare(); bolts built without it
        # (partial skeletons in tests, subclasses overriding prepare)
        # see their plain `batcher` as the only drain source.
        if name == "_sources":
            return [(None, self.batcher)]
        # Flipped lazily by execute() on the first raw-scheme payload;
        # partial skeletons that never execute default to str egress.
        if name == "_bytes_egress":
            return False
        raise AttributeError(name)

    def _pending(self) -> int:
        return sum(len(b) for _, b in self._sources)

    def batcher_stats(self) -> dict:
        """Aggregate depth/age of this task's admission batcher(s) — the
        obs edge watermarks (EdgeLagTracker) read every batching mode
        through this one shape. Continuous mode reports ~0 here by
        design: batch formation lives in the shared engine queue, whose
        depth/oldest-age surface via ``ContinuousBatcher.stats`` and
        ``Observatory.occupancy``."""
        rows = depth = 0
        oldest_ms = 0.0
        for _tier, b in self._sources:
            stats_fn = getattr(b, "stats", None)
            if stats_fn is None:
                continue
            st = stats_fn()
            rows += st["pending_rows"]
            depth += st["depth"]
            oldest_ms = max(oldest_ms, st["oldest_ms"])
        return {"pending_rows": rows, "depth": depth,
                "oldest_ms": round(oldest_ms, 3),
                "continuous": bool(getattr(self, "_continuous", False))}

    def _kick_flush(self) -> None:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # loop torn down mid-finalizer (cluster shutdown race)
        if self._eager and self._pending() and \
                not self._dispatch_sem.locked() and not self._eager_pending:
            # Work-conserving: a device slot is free and records are
            # waiting — dispatch now rather than age toward the deadline.
            # Under load every slot is busy, this branch never fires, and
            # batches fill toward max_batch while they queue.
            batch, tier = None, None
            for tier, b in self._sources:
                batch = b.take_all()
                if batch is not None:
                    break
            if batch is not None:
                self._eager_pending += 1
                task = asyncio.get_running_loop().create_task(
                    self._dispatch(batch, tier))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
                # Decrement when the task finishes — however it finishes.
                # A cancel BEFORE the coroutine's first step never enters
                # _dispatch, so an in-body decrement would leak the counter
                # and permanently disable eager dispatch for this bolt.
                task.add_done_callback(
                    lambda _t: setattr(
                        self, "_eager_pending", self._eager_pending - 1))
                return
        if self._pending() and (self._flush_task is None or self._flush_task.done()):
            self._flush_task = asyncio.get_running_loop().create_task(
                self._deadline_flush()
            )

    async def execute(self, t: Tuple) -> None:
        if t.root_ts:
            # Stage 1 of the decomposition: broker append -> bolt arrival
            # (broker queueing + spout fetch/decode + inter-operator hop).
            self._m_ingest.observe((time.perf_counter() - t.root_ts) * 1e3)
        payload = t.get("message")
        if not self._bytes_egress and isinstance(
                payload, (bytes, bytearray, memoryview, RecordFrame)):
            # Raw-scheme ingress observed: predictions leave as utf-8
            # bytes so the sink produces them verbatim (no sink_encode
            # re-copy). Sticky for the bolt's lifetime — a topology's
            # scheme is uniform.
            self._bytes_egress = True
        lane = t.get("qos_lane", None) if self.qos is not None else None
        level = int(self._shed_gauge.value) if self.qos is not None else 0
        if level > 0 and self.qos.shed_eligible(lane, level):
            if self._router is None:
                # Shed BEFORE decode: with no cascade to degrade onto, the
                # whole point is spending nothing on traffic we will not
                # serve at full fidelity.
                await self._shed_tuple(t, payload, lane, level)
                return
            # Cascade degrade: the record serves at tier 0 — pinned there
            # by decide(), batched, under normal max_inflight concurrency —
            # so fall through to the regular ingest path.
            n = (len(payload)
                 if isinstance(payload, (list, tuple, RecordFrame)) else 1)
            self._m_degraded.inc(n)
            if self._flight is not None:
                self._flight.event(
                    "shed_degrade", throttle_s=1.0,
                    component=self.context.component_id,
                    lane=lane, level=level, records=n)
        entry = (self._router.entry_tier(lane, level)
                 if self._router is not None else None)
        if isinstance(payload, (list, tuple, RecordFrame)):
            await self._execute_chunk(t, payload, lane, entry)
            return
        try:
            inst = self._decode_checked(payload, t.root_ts)
        except SchemaError as e:
            await self._dead_letter(t, payload, str(e))
            return
        await self._ingest(t, inst.data, t.root_ts or None, lane, entry)
        self._kick_flush()

    async def _ingest(self, item, data, ts, lane, entry) -> None:
        """Add one record to its entry batcher (a cascade tier's when a
        router is active, the plain operator batcher otherwise) and drain
        every batch that comes due — add returns at most one batch per
        call; a full one must not sit until the deadline."""
        if getattr(self, "_continuous", False):
            await self._submit_record(item, data, ts, lane, entry)
            return
        if entry is None:
            b, tier = self.batcher, None
        else:
            b, tier = self._router.tiers[entry].batcher, entry
        if self.qos is not None:
            batch = b.add(item, data, ts=ts, lane=lane)
        else:
            batch = b.add(item, data, ts=ts)
        while batch is not None:
            await self._dispatch(batch, tier)
            batch = b.take_ready()

    async def _execute_chunk(self, t: Tuple, payloads, lane=None,
                             entry=None) -> None:
        # frame_egress=False keeps the one-output-message-per-record
        # contract for frame ingress: the handle is marked non-frame so
        # egress never coalesces (zero-copy ingress/decode is unaffected).
        handle = _ChunkHandle(t, len(payloads),
                              frame=(isinstance(payloads, RecordFrame)
                                     and getattr(self.batch_cfg,
                                                 "frame_egress", True)))
        for payload in payloads:
            try:
                inst = self._decode_checked(payload, t.root_ts)
            except SchemaError as e:
                # Dead-letter the record, keep the chunk alive: anchored to
                # the chunk tuple, completed as handled.
                await self._emit_dead_letter(t, payload, str(e))
                handle.done(True, self.collector)
                continue
            await self._ingest(handle, inst.data, t.root_ts or None, lane,
                               entry)
        self._kick_flush()

    # ---- continuous batching path --------------------------------------------

    async def _submit_record(self, item, data, ts, lane, entry) -> None:
        """Hand one record to its tier's shared continuous queue and
        complete it from a per-record task. Backpressure is row-counted
        per task (``max_inflight * max_batch`` outstanding rows — the
        row-equivalent of the dispatch semaphore, which bounded whole
        batches); the engine's pipeline ring stays the device-side
        bound."""
        n = int(data.shape[0])
        while self._cb_rows >= self._cb_cap:
            self._cb_room.clear()
            await self._cb_room.wait()
        self._cb_rows += n
        tenant = (self._anchor_of(item).get("qos_tenant", None)
                  if self.qos is not None else None)
        sub = self._cbs[entry].submit(
            data, payload=item, ts=ts, lane=lane, tenant=tenant,
            source=self._cb_source)
        task = asyncio.get_running_loop().create_task(
            self._finish_record(sub, entry, n))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _finish_record(self, sub, tier, n_rows: int) -> None:
        """Await one submission through as many cascade tiers as it
        needs, then emit + complete — the continuous analogue of
        ``_run_batch``'s emit/escalate block at record granularity.
        A queue/device failure at ANY tier fails the ORIGINAL tuple
        (``_complete`` unwraps ``Escalated``), so the record replays
        from tier 0 — exactly-once semantics identical to the batch
        path."""
        item = sub.payload
        try:
            while True:
                out = await asyncio.wrap_future(sub.future)
                if tier is None:
                    preds = out
                    break
                level = (int(self._shed_gauge.value)
                         if self.qos is not None else 0)
                merged, residue, info = self._router.decide_item(
                    item, sub.data, out, sub.lane, tier, level, ts=sub.ts)
                if residue is None:
                    preds = merged
                    break
                wrapper = residue.payload
                # Chain the trace: the next tier's queue_wait span links
                # back to the span of the batch that escalated this row.
                wrapper.link_span = sub.batch_span
                if self._flight is not None:
                    self._flight.event(
                        "cascade_escalation", throttle_s=1.0,
                        component=self.context.component_id,
                        tier=tier, model=self._router.tiers[tier].name,
                        escalation_rate=round(
                            self._router.escalation_rate(), 4), **info)
                item = wrapper
                tier += 1
                sub = self._cbs[tier].submit(
                    residue.data, payload=wrapper, ts=residue.ts,
                    lane=residue.lane, tenant=sub.tenant,
                    source=self._cb_source)
            anchor = self._anchor_of(item)
            with span(self.context.metrics, self.context.component_id,
                      "encode"):
                msg = self._encode_ledgered(preds)
            await self.collector.emit(
                Values([msg, *self._extras(anchor)]), anchors=[anchor])
            self._complete(item, True)
        except Exception as e:
            self.collector.report_error(e)
            self._complete(item, False)
        finally:
            self._cb_rows -= n_rows
            if self._cb_rows < self._cb_cap:
                self._cb_room.set()

    async def _dead_letter(self, t: Tuple, payload: str, error: str) -> None:
        """Poison input: route to the dead-letter stream and ack (replaying
        a parse failure can never succeed; the reference's emit-null-and-ack
        at InferenceBolt.java:92-99 is the anti-pattern this replaces)."""
        await self._emit_dead_letter(t, payload, error)
        self.collector.ack(t)

    # ---- QoS shedding --------------------------------------------------------

    async def _shed_tuple(self, t: Tuple, payload, lane, level: int) -> None:
        """Typed rejection for a shed-eligible tuple while the shed level
        is raised and no cascade exists: answer immediately with an
        ``Overloaded`` record — the client gets a parseable response *now*
        instead of a timeout, and the tuple acks (shedding must never
        trigger replay: replaying rejected load is more load). Graceful
        degradation lives in the cascade: a configured ``qos.degrade_model``
        pins shed traffic to cascade tier 0, so this path is reject-only."""
        payloads = (payload
                    if isinstance(payload, (list, tuple, RecordFrame))
                    else [payload])
        msg = Overloaded(lane=lane or "", shed_level=level).to_json()
        for _ in payloads:
            await self.collector.emit(
                Values([msg, *self._extras(t)]), anchors=[t])
        self._m_shed.inc(len(payloads))
        if self._flight is not None:
            self._flight.event(
                "shed_reject", throttle_s=1.0,
                component=self.context.component_id,
                lane=lane, level=level, records=len(payloads))
        ctx = t.trace
        if (ctx is not None and ctx is not NOT_SAMPLED
                and self._tracer is not None and self._tracer.active):
            now = time.perf_counter()
            self._tracer.record(
                ctx, "qos_shed", self.context.component_id,
                t.root_ts or now, now,
                attrs={"lane": lane or "", "level": level,
                       "action": "reject"})
        self.collector.ack(t)

    # ---- batching / dispatch -------------------------------------------------

    async def _deadline_flush(self) -> None:
        """Runs while records are pending; never cancelled mid-dispatch (a
        cancel between take and dispatch would silently drop the batch), it
        just exits when the batcher drains."""
        while True:
            oldest = min(
                (b.oldest_ts for _, b in self._sources
                 if b.oldest_ts is not None), default=None)
            if oldest is None:
                return
            wait_s = self.batch_cfg.max_wait_ms / 1e3 - (time.perf_counter() - oldest)
            if wait_s > 0:
                await asyncio.sleep(wait_s)
            for tier, b in self._sources:
                batch = b.take_if_due()
                while batch is not None:
                    await self._dispatch(batch, tier)
                    batch = b.take_ready()

    def _spawn_dispatch(self, batch: Batch, tier: Optional[int]) -> None:
        """Dispatch on a fresh task — for callers that must NOT await the
        dispatch semaphore (``_escalate`` runs under ``_run_batch``, which
        still HOLDS a semaphore slot: awaiting _dispatch there deadlocks
        at max_inflight=1)."""
        task = asyncio.get_running_loop().create_task(
            self._dispatch(batch, tier))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _dispatch(self, batch: Batch, tier: Optional[int] = None) -> None:
        # NB: _eager_pending is decremented by a done-callback on the eager
        # task (see _kick_flush), NOT here — a cancel while parked on the
        # semaphore (or before the first step) must still restore it.
        t0 = time.perf_counter()
        # Stage: accumulation in the batcher (deadline vs fill), per
        # record from batcher entry to flush. Observed BEFORE the
        # semaphore so batch_wait and dispatch_queue partition the clock
        # instead of overlapping.
        for it in batch.items:
            if it.enq:
                self._m_batch_wait.observe((t0 - it.enq) * 1e3)
        await self._dispatch_sem.acquire()
        # Stage: wait for a free device slot (max_inflight backpressure).
        self._m_disp_wait.observe((time.perf_counter() - t0) * 1e3)
        task = asyncio.get_running_loop().create_task(
            self._run_batch(batch, tier))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def _trace_batch(self, batch: Batch, t0: float, t1: float,
                     timings=None, tier: Optional[int] = None,
                     fill: Optional[float] = None):
        """Span bookkeeping for one device round trip: a ``queue_wait``
        span per SAMPLED record (batcher entry -> device start) and ONE
        shared device span — ``device_execute``, or ``cascade_tier{i}``
        when a cascade tier served the batch — same span id in every
        participating trace, linked to all member record spans — so the
        fan-in of N records into one batch is first-class in the trace
        (and queue-wait vs. device time separable per record). Escalated
        records' queue_wait spans link back to the span of the tier that
        escalated them, chaining a hard record's tier-to-tier journey.
        Only called when the tracer is active; per-record work only for
        sampled records. Returns the shared span's id (None when no
        member record is sampled) for escalation links."""
        tracer = self._tracer
        cid = self.context.component_id
        traced = []
        for it in batch.items:
            ctx = self._anchor_of(it.payload).trace
            if ctx is not None:
                links = ()
                if isinstance(it.payload, Escalated) and it.payload.link_span:
                    links = (it.payload.link_span,)
                traced.append((ctx, tracer.record(
                    ctx, "queue_wait", cid, it.enq or t0, t0, links=links)))
        if not traced:
            return None
        batch_span = tracer.new_span_id()
        links = tuple(qid for _, qid in traced)
        name = "device_execute" if tier is None else f"cascade_tier{tier}"
        attrs = {"batch_size": batch.size, "records": len(batch.items)}
        if fill is not None:
            attrs["fill"] = round(fill, 3)
        if tier is not None:
            attrs["tier"] = tier
            attrs["model"] = self._router.tiers[tier].name
        if timings:
            # Split-phase decomposition of this span's wall time: where the
            # device round trip went (staging+H2D vs compute vs D2H).
            for key, _ in DEVICE_SUBSTAGES:
                if key in timings:
                    attrs[key] = round(timings[key], 3)
        for ctx, qid in traced:
            tracer.record(ctx, name, cid, t0, t1,
                          span_id=batch_span, parent_id=qid,
                          links=links, attrs=attrs)
        return batch_span

    async def _run_batch(self, batch: Batch,
                         tier: Optional[int] = None) -> None:
        rt = None if tier is None else self._router.tiers[tier]
        engine = self.engine if rt is None else rt.engine
        try:
            dispatch = getattr(engine, "dispatch", None)
            t0 = time.perf_counter()
            timings = None
            handle = None
            if dispatch is not None:
                # Split-phase path: dispatch (stage into the engine's
                # pooled buffer + H2D + async launch) runs on a worker
                # thread because it can park on the engine's bounded ring;
                # the result future resolves from the engine's fetch
                # thread. The dispatch semaphore stays held for the full
                # round trip, so max_inflight backpressure and deferred
                # acks keep their pre-pipeline semantics.
                handle = await asyncio.to_thread(dispatch, batch.parts())
                out = await asyncio.wrap_future(handle.future)
                timings = handle.timings
            else:
                # Engines without the split-phase surface (custom test
                # doubles): the serialized predict.
                out = await asyncio.to_thread(engine.predict,
                                              batch.stack())
            t1 = time.perf_counter()
            self._m_device_ms.observe((t1 - t0) * 1e3)
            if rt is not None and rt.m_device is not None:
                rt.m_device.observe((t1 - t0) * 1e3)
            if timings:
                for key, _ in DEVICE_SUBSTAGES:
                    if key in timings:
                        self._m_substage[key].observe(timings[key])
            self._m_batch.observe(batch.size)
            self._m_infer.inc(batch.size)
            # Fragmentation: rows / padded bucket capacity. Per-task
            # deadline batches are single-source by construction, so the
            # coalesced counter advances by 1 — the baseline the
            # continuous queue's multi-source batches compare against.
            padded = (int(getattr(handle, "padded", 0) or 0)
                      or self.batch_cfg.bucket_for(batch.size))
            fill = batch.size / max(padded, 1)
            self._m_fill.observe(fill)
            self._m_coalesced.inc()
            batch_span = None
            if self._tracer is not None and self._tracer.active:
                batch_span = self._trace_batch(batch, t0, t1, timings,
                                               tier, fill)
            if self._flight is not None:
                # Sampled (throttled) batch-formed events: enough to see
                # batch-size/device-time behavior in a post-mortem without
                # a per-batch firehose at production rates.
                self._flight.event(
                    "batch_formed", throttle_s=1.0,
                    component=self.context.component_id,
                    size=batch.size, records=len(batch.items),
                    fill=round(fill, 3), sources=1,
                    device_ms=round((t1 - t0) * 1e3, 3),
                    **({} if rt is None else {"tier": tier,
                                              "model": rt.name}))
            if rt is None:
                emit = batch.split(out)
                escalated, info = (), None
            else:
                level = (int(self._shed_gauge.value)
                         if self.qos is not None else 0)
                emit, escalated, info = self._router.decide(
                    batch, out, tier, level)
            # Batch egress: records that arrived together as a RecordFrame
            # leave together — their predictions concatenate into ONE
            # payload per (frame, dispatched batch), killing the
            # per-record json_encode fan-out (r19 zero-copy plan). Other
            # items keep the one-payload-per-record contract.
            for handle, group in self._egress_groups(emit):
                if handle is None:
                    item, preds = group[0]
                    anchor = self._anchor_of(item)
                    with span(self.context.metrics,
                              self.context.component_id, "encode"):
                        msg = self._encode_ledgered(preds)
                    await self.collector.emit(
                        Values([msg, *self._extras(anchor)]),
                        anchors=[anchor],
                    )
                    self._complete(item, True)
                    continue
                anchor = handle.tuple
                preds = (group[0][1] if len(group) == 1 else
                         np.concatenate([p for _, p in group], axis=0))
                with span(self.context.metrics, self.context.component_id,
                          "encode"):
                    msg = self._encode_ledgered(preds, records=len(group))
                await self.collector.emit(
                    Values([msg, *self._extras(anchor)]),
                    anchors=[anchor],
                )
                for item, _ in group:
                    self._complete(item, True)
            if escalated:
                if self._flight is not None:
                    self._flight.event(
                        "cascade_escalation", throttle_s=1.0,
                        component=self.context.component_id, **info)
                await self._escalate(escalated, tier + 1, batch_span)
        except Exception as e:
            # Device/compile failure: fail every tuple in the batch ->
            # spout replay (an escalation tier failure fails the ORIGINAL
            # tuples — _complete unwraps Escalated — so the records replay
            # from tier 0, never half-served).
            self.collector.report_error(e)
            for item in batch.items:
                self._complete(item.payload, False)
        finally:
            self._dispatch_sem.release()
            # Freed a slot: eagerly pull whatever queued while we ran.
            self._kick_flush()

    async def _escalate(self, items, tier: int, link_span) -> None:
        """Re-batch the low-confidence residue into the next tier's
        batcher, preserving each record's original data/deadline/lane.
        Ready batches go through _spawn_dispatch (never awaited: this
        coroutine runs under _run_batch, which holds a semaphore slot)."""
        rt = self._router.tiers[tier]
        b = rt.batcher
        for it in items:
            payload = it.payload
            if isinstance(payload, Escalated):
                payload.link_span = link_span
            else:
                payload = Escalated(payload, link_span)
            if self.qos is not None:
                batch = b.add(payload, it.data, ts=it.ts, lane=it.lane)
            else:
                batch = b.add(payload, it.data, ts=it.ts)
            while batch is not None:
                self._spawn_dispatch(batch, tier)
                batch = b.take_ready()
        self._kick_flush()

    async def swap_model(self, model_cfg: ModelConfig) -> None:
        """Zero-downtime model swap (the reference ships its model inside
        the application jar, InferenceBolt.java:49-57 — redeploying means a
        full topology restart; here a new checkpoint/model goes live under
        traffic). The new engine is built and warmed on a worker thread,
        then the reference is switched atomically: batches already in
        flight finish on the old engine, later batches use the new one.
        The old engine stays in the process cache for instant rollback
        (swap back) at the cost of its HBM footprint.

        Swapping to a different ``input_shape`` may fail-and-replay tuples
        decoded under the old shape that are still in the batcher —
        at-least-once delivery covers them."""

        def build() -> InferenceEngine:
            eng = shared_engine(model_cfg, self.sharding_cfg, self.batch_cfg)
            eng.warmup()
            return eng

        old_engine = self.engine
        new_engine = await asyncio.to_thread(build)
        if getattr(self, "_router", None) is not None:
            # The cascade tier serving the flagship follows the swap (the
            # tiers sharing the old engine object by identity — normally
            # just the last one).
            for rt in self._router.tiers:
                if rt.engine is old_engine:
                    rt.engine = new_engine
                    rt.model_cfg = model_cfg
        self.engine = new_engine
        self.model_cfg = model_cfg

    async def tick(self) -> None:
        for tier, b in self._sources:
            batch = b.take_if_due()
            while batch is not None:
                await self._dispatch(batch, tier)
                batch = b.take_ready()

    async def flush(self) -> None:
        """Drain: dispatch whatever is pending and wait for in-flight
        batches, so a graceful stop never strands undecoded acks. Loops
        because finishing a cascade tier's batches can re-fill a LATER
        tier's batcher with escalated residue."""
        if getattr(self, "_continuous", False):
            # Force the shared queues to dispatch and wait for this
            # task's per-record completions. Re-flush on a short period:
            # a record escalating mid-drain enqueues into a LATER tier's
            # queue after its flush already drained.
            while self._inflight:
                for cb in set(self._cbs.values()):
                    cb.flush()
                await asyncio.wait(list(self._inflight), timeout=0.05)
            return
        while True:
            for tier, b in self._sources:
                batch = b.take_all()
                while batch is not None:
                    await self._dispatch(batch, tier)
                    batch = b.take_all()
            while self._inflight:
                await asyncio.gather(
                    *list(self._inflight), return_exceptions=True)
            if not self._pending():
                return

    def cleanup(self) -> None:
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
        self._flush_task = None
