"""Deadline-based micro-batcher.

The reference runs one ``session.run`` per Kafka record at batch 1
(InferenceBolt.java:80-86, SURVEY.md §3.3 "no micro-batching, no cross-tuple
amortization") — the single biggest performance defect to fix for TPU, where
throughput comes from large MXU-friendly batches. Policy (BatchConfig):
dispatch when ``max_batch`` instances are waiting OR the oldest instance has
waited ``max_wait_ms`` — bounding the latency cost of batching so the p50
Kafka->Kafka target holds at low rates too.

Pure accumulation logic, no asyncio here (the operator owns timing/tasks):
easy to unit-test, like the reference's mkProducer seam philosophy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

from storm_tpu.config import BatchConfig


@dataclass
class BatchItem:
    payload: Any  # opaque per-record context (the runtime tuple)
    data: np.ndarray  # (n_i, *instance_shape)
    ts: float  # deadline clock: root (append) time when known
    # batcher-entry time (always perf_counter-now at add): what the
    # batch-wait stage of the latency decomposition is measured from, and
    # the start of a sampled record's queue_wait trace span (the operator's
    # _trace_batch — batcher entry to device dispatch).
    enq: float = 0.0
    # QoS priority lane (None outside QoS mode). Carried so the EDF lane
    # batcher (storm_tpu.qos.lanes) and per-lane metrics can attribute the
    # item without re-deriving it from the tuple.
    lane: Optional[str] = None


@dataclass
class Batch:
    items: List[BatchItem]
    size: int  # total instances

    def stack(self) -> np.ndarray:
        return np.concatenate([it.data for it in self.items], axis=0)

    def parts(self) -> List[np.ndarray]:
        """Per-item arrays for the engine's split-phase ``dispatch``: the
        engine stages them straight into its pooled padded buffer with one
        fused write, so no concatenated intermediate ever exists."""
        return [it.data for it in self.items]

    def split(self, out: np.ndarray) -> List[Tuple[Any, np.ndarray]]:
        """Slice a (size, K) result back per item."""
        res = []
        ofs = 0
        for it in self.items:
            n = it.data.shape[0]
            res.append((it.payload, out[ofs : ofs + n]))
            ofs += n
        return res


class MicroBatcher:
    def __init__(self, cfg: BatchConfig) -> None:
        self.cfg = cfg
        self._items: List[BatchItem] = []
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def oldest_ts(self) -> Optional[float]:
        return self._items[0].ts if self._items else None

    def stats(self) -> dict:
        """Depth/age summary, key-parity with ``LaneBatcher.stats`` and
        the queue half of ``ContinuousBatcher.stats`` — the obs edge
        watermarks read every batching mode through one shape. Age is
        measured from batcher *entry* (``enq``), not the deadline clock:
        it answers "how long has work sat here", not "how late is it"."""
        now = time.perf_counter()
        oldest = self._items[0].enq if self._items else None
        return {
            "kind": "fifo",
            "pending_rows": self._count,
            "depth": len(self._items),
            "oldest_ms": (round(max(0.0, (now - oldest) * 1e3), 3)
                          if oldest is not None else 0.0),
            "pending_by_lane": {},
        }

    def add(self, payload: Any, data: np.ndarray, ts: Optional[float] = None) -> Optional[Batch]:
        """Add one record (n_i instances). Returns a ready Batch when the
        max_batch threshold is reached, else None.

        A record that would overshoot max_batch first flushes the pending
        batch and starts a new one, so no emitted batch exceeds max_batch
        (a single record larger than max_batch still forms its own
        oversized batch — the engine pads per-shape rather than crash)."""
        n = data.shape[0]
        flushed: Optional[Batch] = None
        if self._count and self._count + n > self.cfg.max_batch:
            flushed = self._take()
        now = time.perf_counter()
        self._items.append(
            BatchItem(payload, data, ts if ts is not None else now, now)
        )
        self._count += n
        if self._count >= self.cfg.max_batch:
            if flushed is None:
                return self._take()
            # Rare: both the old batch flushed AND the new record alone
            # reaches max_batch. ``add`` still returns one batch, but the
            # new full one must NOT sit until the deadline — the caller
            # drains it immediately via ``take_ready()``.
        return flushed

    def take_ready(self) -> Optional[Batch]:
        """Drain a pending batch that already reached max_batch (the
        two-batches-in-one-add case above). Call in a loop after every
        ``add`` that returned a batch; returns None when nothing full is
        parked."""
        if self._count >= self.cfg.max_batch:
            return self._take()
        return None

    def take_if_due(self, now: Optional[float] = None) -> Optional[Batch]:
        """Returns the pending batch if the oldest record exceeded the
        deadline, else None."""
        if not self._items:
            return None
        now = now if now is not None else time.perf_counter()
        if (now - self._items[0].ts) * 1e3 >= self.cfg.max_wait_ms:
            return self._take()
        return None

    def take_all(self) -> Optional[Batch]:
        return self._take() if self._items else None

    def _take(self) -> Batch:
        b = Batch(self._items, self._count)
        self._items = []
        self._count = 0
        return b
