"""The TPU inference engine: jit-compiled model apply over a device mesh.

Replaces the reference's inference engine layer (layer 4, SURVEY.md §1):
``SavedModelBundle.load`` + per-tuple ``session.run`` over JNI
(InferenceBolt.java:57, :80-86) becomes a jit-compiled JAX function over a
``Mesh`` with the batch axis sharded across ``data`` and params replicated
(or TP-sharded across ``model``). One engine is shared by all inference
operator tasks on a host — the mesh, not operator replication, is the
parallelism (the reference instead loaded one full model copy per bolt).

Outputs are softmax probabilities, matching the reference's fetch of
``"output/Softmax:0"`` (InferenceBolt.java:84).
"""

from __future__ import annotations

import gc
import logging
import queue
import sys
import threading
import time
import weakref
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
from storm_tpu.models.registry import ModelDef, build_model, load_or_init
from storm_tpu.obs import copyledger as _copyledger
from storm_tpu.parallel.mesh import make_mesh
from storm_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    shard_params_ep,
    shard_params_tp,
)

logger = logging.getLogger(__name__)


# ---- weight-only int8 quantization (w8a16 serving) ----------------------------


def quantize_params(params, min_ndim: int = 2):
    """f32/bf16 param pytree -> same tree with weight leaves replaced by
    ``{"__q": int8, "__s": f32 per-output-channel scales}``.

    Symmetric per-output-channel (last axis) quantization; leaves below
    ``min_ndim`` (biases, norm scales) stay full precision — they are tiny
    and precision-critical."""
    def quant(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim < min_ndim or \
                leaf.dtype.kind not in "fV":  # V: bfloat16 shows as void-kind
            return leaf
        w = np.asarray(leaf, np.float32)
        axes = tuple(range(w.ndim - 1))
        scale = np.max(np.abs(w), axis=axes) / 127.0
        scale = np.maximum(scale, 1e-12).astype(np.float32)
        q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
        return {"__q": q, "__s": scale}

    return jax.tree.map(quant, params)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and "__q" in x


def dequantize_params(qparams, dtype, keep_dense: bool = False):
    """Inverse of :func:`quantize_params`; runs INSIDE jit so XLA fuses the
    int8->dtype multiply into each weight's first use.

    ``keep_dense=True`` ("int8_fused" mode) leaves dense-layer weights
    quantized: :func:`storm_tpu.ops.layers.dense` detects them and runs
    the Pallas fused dequant-matmul, so they stay int8 all the way to
    VMEM. "Dense-layer weight" is identified by tree path — a 2-D qleaf
    under a ``"w"`` key, the `dense_init` layout — NOT by rank alone:
    other 2-D params (e.g. the MoE gate) are consumed as raw arrays and
    must be dequantized here. Conv kernels (4-D, also ``"w"``) are
    dequantized — XLA's conv has no fused-dequant kernel equivalent."""
    def deq(path, l):
        if not _is_qleaf(l):
            return l
        if keep_dense and l["__q"].ndim == 2 and path and \
                getattr(path[-1], "key", None) == "w":
            return l
        return l["__q"].astype(dtype) * l["__s"].astype(dtype)

    return jax.tree_util.tree_map_with_path(deq, qparams, is_leaf=_is_qleaf)


# ---- split-phase pipeline plumbing --------------------------------------------


class StagingPool:
    """Preallocated, recycled host staging buffers keyed by (shape, dtype).

    The dispatch phase stages a batch into one of these with a single
    fused write (replacing the ``np.concatenate`` + pad-``concatenate`` +
    ``astype`` copies of the stacked path), hands it to ``device_put``,
    and keeps holding it until the batch's FETCH completes — jax backends
    may alias a suitably-aligned host buffer instead of copying (CPU
    zero-copy donation), so recycling before the dependent execution
    finished could corrupt an in-flight batch. ``limit`` bounds buffers
    per key; ``acquire`` blocks (on the caller's worker thread) when that
    many are in flight, which the pipeline ring normally prevents.
    """

    def __init__(self, limit: int) -> None:
        self.limit = max(1, int(limit))
        self.allocated = 0  # fresh np.empty calls ever made (alloc guard)
        self._lock = threading.Lock()
        self._free: Dict[tuple, List[np.ndarray]] = {}
        self._sems: Dict[tuple, threading.Semaphore] = {}

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype))
        with self._lock:
            sem = self._sems.get(key)
            if sem is None:
                sem = self._sems[key] = threading.Semaphore(self.limit)
        sem.acquire()
        with self._lock:
            free = self._free.setdefault(key, [])
            if free:
                return free.pop()
            self.allocated += 1
        return np.empty(shape, dtype)

    def release(self, buf: np.ndarray) -> None:
        key = (buf.shape, np.dtype(buf.dtype))
        with self._lock:
            self._free.setdefault(key, []).append(buf)
            sem = self._sems[key]
        sem.release()

    def stats(self) -> Dict[str, int]:
        """Utilization snapshot for the observatory's occupancy gauges:
        buffers ever allocated, currently free, and (the difference) held
        by in-flight batches."""
        with self._lock:
            free = sum(len(v) for v in self._free.values())
            return {"allocated": self.allocated, "free": free,
                    "in_use": max(0, self.allocated - free),
                    "limit": self.limit}


class EngineWatchdogTimeout(RuntimeError):
    """A batch overran ``batch.watchdog_ms`` on the fetch ring.

    Raised on the fetch thread INSIDE the per-batch try, so it rides the
    existing isolation path: only the stuck batch's future fails (its
    sources replay) and the ring/staging slots are released — the device
    program may still be running, but the pipeline stops waiting on it."""


class EngineQuarantined(RuntimeError):
    """Dispatch refused: this engine tripped its watchdog
    ``batch.watchdog_trips`` times in a row and is quarantined. Callers
    fail the batch (sources replay) until the operator swaps in a
    replacement engine (see InferenceOperator's on_quarantine hook)."""


class InflightBatch:
    """Handle for one batch inside the split-phase pipeline.

    ``future`` resolves (on the engine's fetch thread) to the host
    ``np.ndarray`` result sliced to the true batch size — or to the
    exception that failed THIS batch only. ``timings`` carries the
    per-phase wall-clock attribution once known: ``h2d_ms`` (staging +
    host->device transfer + async jit launch; includes XLA compile on a
    cold bucket shape), ``compute_ms`` (launch -> results ready, i.e.
    device queue + execute) and ``d2h_ms`` (the blocking device->host
    copy). ``compute_ms``/``d2h_ms`` are filled by the fetch phase, so
    read them only after ``future`` resolves.
    """

    __slots__ = ("future", "n", "padded", "timings", "profile_key", "_out",
                 "_buf", "_t_launched", "watchdog_ms", "on_done")

    def __init__(self, n: int, padded: int) -> None:
        self.future: Future = Future()
        self.n = n
        self.padded = padded
        self.timings: Dict[str, float] = {}
        # Cost-profile attribution: which engine's curve this batch feeds
        # (set by dispatch; None = don't profile, e.g. test doubles).
        self.profile_key: Optional[str] = None
        self._out = None  # device array, dropped after fetch
        self._buf = None  # staging buffer, recycled after fetch
        self._t_launched = 0.0
        # Watchdog contract (set by dispatch): fetch waits at most
        # watchdog_ms (0 = forever) and reports the outcome to on_done —
        # a bound engine method, so the handle pins the engine only while
        # this batch is in flight (the fetch THREAD still holds no ref).
        self.watchdog_ms = 0.0
        self.on_done = None

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.future.result(timeout)


def _fetch_loop(fetch_q: "queue.SimpleQueue", ring: threading.Semaphore,
                staging: StagingPool) -> None:
    """Dedicated fetch thread: completes in-flight batches in dispatch
    order. Blocking here is the point — one batch's device->host RTT
    overlaps the NEXT batch's staging/H2D (dispatch holds the lock, fetch
    never does) and the one-after's device compute. Module-level so the
    thread never references the engine (see _ensure_fetch_thread); a None
    sentinel (engine finalizer, tests) shuts it down."""
    while True:
        handle = fetch_q.get()
        if handle is None:
            return
        try:
            _watchdog_wait(handle)
            t1 = time.perf_counter()
            res = np.asarray(handle._out)
            t2 = time.perf_counter()
            handle.timings["compute_ms"] = (t1 - handle._t_launched) * 1e3
            handle.timings["d2h_ms"] = (t2 - t1) * 1e3
            handle._out = None
            handle.future.set_result(res[:handle.n])
            # Copy ledger: the blocking device->host materialization is
            # one full-result copy into a fresh host array.
            _copyledger.record("d2h", res.nbytes, copies=1, allocs=1,
                               records=handle.n,
                               engine=handle.profile_key or "-")
            # Cost profiler (storm_tpu/obs/profile.py): per-(engine,
            # bucket) curves fed right where all three phase timings are
            # finally known. One sink check per BATCH; must never fail
            # (or even slow) a batch.
            sink = _profile_sink
            if sink is not None and handle.profile_key is not None:
                try:
                    sink.record_batch(handle.profile_key, handle.padded,
                                      handle.n, handle.timings)
                except Exception:
                    pass
        except BaseException as e:  # noqa: BLE001 - fail ONLY this batch
            handle._out = None
            handle.future.set_exception(e)
            _notify_done(handle, e)
        else:
            _notify_done(handle, None)
        finally:
            buf, handle._buf = handle._buf, None
            if buf is not None:
                staging.release(buf)
            ring.release()


def _watchdog_wait(handle: InflightBatch) -> None:
    """Wait for the batch's device result, bounded by ``watchdog_ms``.

    With no deadline (or a result object that can't report readiness)
    this is the plain blocking wait. With one, poll ``is_ready()`` —
    jax.Array exposes it without blocking — and raise
    :class:`EngineWatchdogTimeout` past the deadline so the stuck batch
    fails alone instead of wedging the whole fetch ring behind it."""
    out = handle._out
    ms = handle.watchdog_ms
    is_ready = getattr(out, "is_ready", None)
    if ms <= 0 or is_ready is None:
        out.block_until_ready()
        return
    deadline = time.monotonic() + ms / 1e3
    while not is_ready():
        if time.monotonic() > deadline:
            raise EngineWatchdogTimeout(
                f"batch (n={handle.n}, padded={handle.padded}) exceeded "
                f"watchdog_ms={ms:g} on the fetch ring")
        time.sleep(min(0.002, ms / 1e4))
    out.block_until_ready()


class _HangingResult:
    """Chaos wrapper: a device result that refuses to report ready until
    its hold expires (:meth:`ChaosInjector.engine_hang_s`) — gives the
    fetch-ring watchdog a genuinely stuck batch to catch without having
    to wedge a real device program."""

    __slots__ = ("_inner", "_until")

    def __init__(self, inner, until: float) -> None:
        self._inner = inner
        self._until = until

    def is_ready(self) -> bool:
        if time.monotonic() < self._until:
            return False
        ir = getattr(self._inner, "is_ready", None)
        return True if ir is None else ir()

    def block_until_ready(self):
        rem = self._until - time.monotonic()
        if rem > 0:
            time.sleep(rem)
        bur = getattr(self._inner, "block_until_ready", None)
        if bur is not None:
            bur()
        return self

    def __array__(self, dtype=None):
        a = np.asarray(self._inner)
        return a if dtype is None else a.astype(dtype, copy=False)


def _notify_done(handle: InflightBatch, exc) -> None:
    cb = handle.on_done
    handle.on_done = None  # drop the engine ref with the batch
    if cb is None:
        return
    try:
        cb(exc)
    except Exception:
        pass  # a watchdog accounting hook must never fail the loop


# ---- cost-profile sink (storm_tpu/obs/profile.py) ----------------------------

# Process-wide observer for completed batches + cold compiles, same spirit
# as the per-engine ``on_compile`` hook but installed once for every
# engine (the ProfileStore is process-scoped, like the engine cache).
# None = profiling off; the hot path pays one global read per batch.
_profile_sink = None


def set_profile_sink(sink) -> None:
    """Install (or, with None, remove) the process profile sink. ``sink``
    needs ``record_batch(key, padded, rows, timings)`` and
    ``record_compile(key, padded, ms)`` — see
    :class:`storm_tpu.obs.profile.ProfileStore`."""
    global _profile_sink
    _profile_sink = sink


def _report_compile(key: str, padded: int, ms: float) -> None:
    sink = _profile_sink
    if sink is not None:
        try:
            sink.record_compile(key, padded, ms)
        except Exception:
            pass  # an observability hook must never fail a batch


_COMPILE_CACHE_DIR: Optional[str] = None


def enable_compile_cache(cache_dir: str, min_compile_secs: float = 0.1) -> None:
    """Turn on jax's persistent executable cache (process-global, applied
    once — jax latches the directory at first compile). Restarted daemons
    then reload compiled bucket shapes instead of re-tracing. Also lowers
    the min-compile-time persistence gate from jax's 1.0s default so the
    small models in the zoo are cached too. Called from engine init when
    ``ModelConfig.compile_cache_dir`` is set; callable directly at daemon
    startup."""
    global _COMPILE_CACHE_DIR
    if _COMPILE_CACHE_DIR is not None:
        if _COMPILE_CACHE_DIR != cache_dir:
            logger.warning(
                "compile cache already latched at %s; ignoring %s "
                "(jax supports one cache dir per process)",
                _COMPILE_CACHE_DIR, cache_dir,
            )
        return
    _COMPILE_CACHE_DIR = cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs)


class InferenceEngine:
    def __init__(
        self,
        model_cfg: ModelConfig,
        sharding_cfg: Optional[ShardingConfig] = None,
        batch_cfg: Optional[BatchConfig] = None,
        mesh=None,
        softmax: bool = True,
    ) -> None:
        self.model_cfg = model_cfg
        self.sharding_cfg = sharding_cfg or ShardingConfig()
        self.batch_cfg = batch_cfg or BatchConfig()
        if getattr(model_cfg, "compile_cache_dir", ""):
            enable_compile_cache(model_cfg.compile_cache_dir)
        self.model: ModelDef = build_model(
            model_cfg.name,
            num_classes=model_cfg.num_classes,
            input_shape=tuple(model_cfg.input_shape),
            **getattr(model_cfg, "extra", {}),
        )
        self.dtype = jnp.dtype(model_cfg.dtype)
        # Serving parallelism beyond DP: at most ONE of tp/sp/ep sizes the
        # mesh's second axis (composing them needs a 3D mesh — train-side
        # territory; serving keeps one knob per engine).
        #   tp — Megatron param sharding ("model" axis);
        #   sp — sequence axis sharded, ring attention ("seq" axis; needs
        #        an SP-aware model forward, ModelDef.apply_sp);
        #   ep — MoE expert tensors sharded ("expert" axis; apply is
        #        unchanged, GSPMD lowers dispatch/combine to all-to-alls).
        self.sp = int(getattr(self.sharding_cfg, "sequence_parallel", 1))
        self.ep = int(getattr(self.sharding_cfg, "expert_parallel", 1))
        tp_req = int(self.sharding_cfg.tensor_parallel)
        if sum(x > 1 for x in (tp_req, self.sp, self.ep)) > 1:
            raise ValueError(
                "tensor_parallel, sequence_parallel, and expert_parallel "
                "are mutually exclusive for serving")
        if self.sp > 1:
            if self.model.apply_sp is None:
                raise ValueError(
                    f"model {model_cfg.name!r} has no apply_sp; "
                    "sequence_parallel > 1 needs an SP-aware family "
                    "(e.g. longseq_encoder)")
            if self.model.input_shape[0] % self.sp:
                raise ValueError(
                    f"sequence {self.model.input_shape[0]} not divisible "
                    f"by sequence_parallel={self.sp}")
        if self.sp > 1:
            axis2, size2 = "seq", self.sp
        elif self.ep > 1:
            axis2, size2 = "expert", self.ep
        else:
            axis2, size2 = None, tp_req
        self.mesh = mesh if mesh is not None else make_mesh(
            self.sharding_cfg.data_parallel,
            size2,
            ("data", axis2) if axis2 else self.sharding_cfg.axis_names,
        )
        self.data_axis = ("data" if axis2
                          else self.sharding_cfg.axis_names[0])
        # Multi-process serving (global mesh spanning several OS
        # processes, e.g. multi-host slices): device_put of the SAME host
        # batch from every process onto a global sharding is the SPMD
        # contract jax supports natively, but fetching results needs an
        # explicit cross-process allgather — np.asarray on a
        # non-fully-addressable array raises. Certified by
        # tests/test_dist.py::test_multiprocess_serving.
        self._multiprocess = any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat)
        self._lock = threading.Lock()
        # Split-phase pipeline state (see dispatch/_fetch_loop). Depth 0 or
        # multi-process serving (the results fetch is a cross-process
        # COLLECTIVE that must stay ordered under the dispatch lock)
        # disable the ring and fall back to the serialized predict.
        depth = max(0, int(getattr(self.batch_cfg, "pipeline_depth", 2)))
        self.pipeline_depth = 0 if self._multiprocess else depth
        pool = int(getattr(self.batch_cfg, "staging_pool", 0)) \
            or self.pipeline_depth + 1
        self._staging = StagingPool(pool)
        self._ring: Optional[threading.Semaphore] = (
            threading.BoundedSemaphore(self.pipeline_depth)
            if self.pipeline_depth else None)
        self._fetch_q: "queue.SimpleQueue[Optional[InflightBatch]]" = \
            queue.SimpleQueue()
        self._fetch_thread: Optional[threading.Thread] = None
        self._fetch_thread_lock = threading.Lock()
        # Dispatch slots visible to the continuous batcher: ring depth when
        # pipelined, else the single serialized predict slot.
        self.ring_capacity = max(1, self.pipeline_depth)
        # Watchdog / quarantine state (batch.watchdog_ms, watchdog_trips):
        # consecutive fetch-deadline trips counted on the fetch thread via
        # the handle's on_done hook; at the threshold the engine flips to
        # quarantined (dispatch raises EngineQuarantined) and fires
        # on_quarantine exactly once so the operator can swap a fresh one.
        self.quarantined = False
        self.on_quarantine = None
        self._watchdog_trips = 0
        self._watchdog_lock = threading.Lock()

        params, state = load_or_init(self.model, model_cfg.checkpoint, model_cfg.seed)
        if self.ep > 1:
            # Fail loudly on misconfig — silent full replication across an
            # expert mesh would burn ep-fold HBM/compute while the user
            # believes experts are sharded. Same key set as
            # shard_params_ep (one source of truth: moe_param_specs).
            from storm_tpu.parallel.moe import moe_param_specs

            expert_keys = {
                k for k, spec in moe_param_specs().items()
                if "expert" in (spec or ())
            }
            expert_dims = []
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    params)[0]:
                keys = [getattr(k, "key", None) for k in path]
                if "moe" in keys and keys[-1] in expert_keys:
                    expert_dims.append(leaf.shape[0])
            if not expert_dims:
                raise ValueError(
                    f"model {model_cfg.name!r} has no MoE params; "
                    "expert_parallel > 1 needs an MoE family "
                    "(e.g. moe_vit_tiny)")
            if any(e % self.ep for e in expert_dims):
                raise ValueError(
                    f"n_experts {set(expert_dims)} not divisible by "
                    f"expert_parallel={self.ep}")
        cast = lambda t: jax.tree.map(
            lambda a: a.astype(self.dtype) if a.dtype == jnp.float32 else a, t
        )
        # Param placement: replicate on a pure-DP mesh; Megatron-style TP
        # shard when the mesh has a non-trivial model axis. This is what the
        # reference structurally cannot do — its model is one opaque blob
        # per bolt (InferenceBolt.java:57), so a model that doesn't fit one
        # device cannot be served; here `tensor_parallel > 1` splits the
        # attention/MLP kernels across the model axis and XLA inserts the
        # ICI psum on the row-parallel matmuls.
        self.model_axis = (
            self.sharding_cfg.axis_names[1]
            if len(self.sharding_cfg.axis_names) > 1 else "model")
        self.tp = int(self.mesh.shape.get(self.model_axis, 1))
        if self.tp > 1:
            place_params = lambda t: shard_params_tp(
                self.mesh, t, self.model_axis)
        elif self.ep > 1:
            place_params = lambda t: shard_params_ep(self.mesh, t, "expert")
        else:
            place_params = lambda t: jax.device_put(t, replicated(self.mesh))
        # Cross-process placement only accepts HOST buffers (each process
        # supplies the same value and jax takes its local shards); a
        # committed single-device jax array would demand a cross-host
        # device transfer the backend refuses. Init/orbax hand us
        # committed arrays, so materialize to numpy first.
        _hostify = (lambda t: jax.tree.map(
            lambda a: np.asarray(a) if hasattr(a, "dtype") else a, t)
        ) if self._multiprocess else (lambda t: t)
        if self._multiprocess:
            _inner_place = place_params
            place_params = lambda t: _inner_place(_hostify(t))
        # BN statistics stay f32 (cast only f32 leaves to compute dtype would
        # nuke them too) — so cast params only; state is small and stays f32.
        self._w8 = getattr(model_cfg, "weights", "float") in (
            "int8", "int8_fused")
        self._w8_fused = getattr(model_cfg, "weights", "float") == "int8_fused"
        if self._w8:
            # int8 weights + scales live in HBM; dequant happens inside the
            # jit program (fused), so the stored footprint is ~1/2 of bf16.
            # Non-quantized leaves (biases, norm params) still get the
            # compute-dtype cast — an f32 bias-add would promote every
            # downstream activation to f32 and defeat w8a16.
            qtree = jax.tree.map(
                lambda l: l if _is_qleaf(l) else (
                    l.astype(self.dtype) if l.dtype == jnp.float32 else l),
                quantize_params(params), is_leaf=_is_qleaf,
            )
            self.params = place_params(qtree)
        else:
            self.params = place_params(cast(params))
        self.state = jax.device_put(_hostify(state), replicated(self.mesh))
        # jit must pin params to their committed placement (replicated OR
        # TP-sharded) — read the shardings off the placed arrays so both
        # paths share one code path.
        p_shardings = jax.tree.map(lambda a: a.sharding, self.params)

        apply = self.model.apply
        apply_sp = self.model.apply_sp
        out_shard = batch_sharding(self.mesh, self.data_axis)
        if self.sp > 1:
            # inputs (N, S, ...): batch over data, sequence over seq
            x_shard = NamedSharding(self.mesh, P(self.data_axis, "seq"))
        else:
            x_shard = out_shard
        dtype = self.dtype
        w8 = self._w8

        w8_fused = self._w8_fused
        sp = self.sp
        mesh_ref = self.mesh

        def fwd(params, state, x):
            if w8:
                params = dequantize_params(params, dtype, keep_dense=w8_fused)
            if sp > 1:
                logits, _ = apply_sp(params, state, x, mesh_ref, "seq",
                                     train=False)
            else:
                logits, _ = apply(params, state, x, train=False)
            logits = logits.astype(jnp.float32)
            return jax.nn.softmax(logits, axis=-1) if softmax else logits

        self._fwd = jax.jit(
            fwd,
            in_shardings=(p_shardings, replicated(self.mesh), x_shard),
            out_shardings=out_shard,
        )
        # uint8 transfer path: the wire carries affine-quantized bytes plus a
        # per-batch (scale, offset); dequantization runs on device inside the
        # same jit program, so XLA fuses it into the first conv/matmul's input.
        self._quantize = model_cfg.transfer_dtype == "uint8"

        def fwd_q(params, state, xq, scale, offset):
            x = (xq.astype(jnp.float32) * scale + offset).astype(dtype)
            return fwd(params, state, x)

        self._fwd_q = jax.jit(
            fwd_q,
            in_shardings=(
                p_shardings,
                replicated(self.mesh),
                x_shard,
                replicated(self.mesh),
                replicated(self.mesh),
            ),
            out_shardings=out_shard,
        )
        self._x_sharding = x_shard
        self._scalar_sharding = replicated(self.mesh)
        self.compiled_batches: set = set()
        # Observability hook: called as ``on_compile(padded_batch, ms)``
        # the first time a bucket shape executes (= XLA compile on the hot
        # path). The inference operator wires it to the flight recorder.
        self.on_compile = None
        # Cost-profile identity: which curve this engine's batches feed in
        # the process ProfileStore. Checkpoint-qualified so cascade tiers /
        # swap variants sharing a registry name keep separate curves.
        ckpt = getattr(model_cfg, "checkpoint", None)
        self.profile_key = (f"{model_cfg.name}@{ckpt}" if ckpt
                            else model_cfg.name)

    # ---- occupancy telemetry (storm_tpu/obs) ---------------------------------

    @property
    def ring_inflight(self) -> int:
        """Pipeline-ring slots currently occupied by in-flight batches.
        Reads the semaphore's internal counter — telemetry only (the
        value can be a step stale; the ring itself stays the bound)."""
        if self._ring is None:
            return 0
        return max(0, self.pipeline_depth - self._ring._value)

    def staging_stats(self) -> Dict[str, int]:
        return self._staging.stats()

    # ---- memory accounting ---------------------------------------------------

    def param_bytes(self) -> int:
        """Device bytes held by this engine's params+state (per replica).
        The multi-model co-residency budget (BASELINE config 5) is the sum
        of these across live engines — see :func:`engine_inventory`."""
        return sum(
            x.nbytes for t in (self.params, self.state)
            for x in jax.tree.leaves(t) if hasattr(x, "nbytes")
        )

    def param_bytes_per_device(self) -> int:
        """Largest per-device slice of params+state actually resident in
        HBM. Pure DP: equals :meth:`param_bytes` (full replica everywhere).
        TP: the sharded kernels contribute ~1/tp each, so a model bigger
        than one chip's HBM fits when ``param_bytes_per_device`` does."""
        per: Dict[int, int] = {}
        for t in (self.params, self.state):
            for x in jax.tree.leaves(t):
                for s in getattr(x, "addressable_shards", ()):
                    did = s.device.id
                    per[did] = per.get(did, 0) + s.data.nbytes
        return max(per.values(), default=0)

    # ---- shape management ----------------------------------------------------

    @property
    def input_shape(self) -> Tuple[int, ...]:
        return tuple(self.model.input_shape)

    def pad_batch(self, n: int) -> int:
        """Pad a batch size to the compiled-bucket grid, respecting the mesh:
        every bucket must divide evenly across the data axis. Oversized
        batches (a single record larger than max_batch) round up to the
        next dp multiple instead of crashing — they just compile one extra
        shape."""
        dp = self.mesh.shape[self.data_axis]
        b = self.batch_cfg.bucket_for(n)
        if b < n:
            b = n
        return max(dp, ((b + dp - 1) // dp) * dp)

    def warmup(self, buckets: Optional[Tuple[int, ...]] = None) -> None:
        """Pre-compile the bucket shapes so first traffic doesn't hit XLA
        compile latency (the deadline batcher depends on stable latencies)."""
        for b in buckets or self.batch_cfg.buckets:
            n = self.pad_batch(b)
            if n in self.compiled_batches:
                continue
            x = np.zeros((n, *self.input_shape), self.dtype)
            np.asarray(self.predict(x))

    # ---- the hot call --------------------------------------------------------

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Blocking batched forward: pad -> device -> fwd -> host.

        Called from a worker thread (asyncio.to_thread) so the event loop
        keeps batching while the device computes. Thread-safe. With the
        split-phase pipeline enabled this is one ``dispatch`` + wait; with
        ``pipeline_depth=0`` (or multi-process serving) it is the fully
        serialized stage/put/fwd/fetch chain.
        """
        if self._ring is None:
            return self._predict_serial(x)
        return self.dispatch((x,)).future.result()

    def dispatch(self, parts: Sequence[np.ndarray]) -> InflightBatch:
        """Split-phase entry: stage ``parts`` (per-record arrays, already
        shape-validated) into a pooled staging buffer with one fused
        write, ship it to the device and launch the jit program
        asynchronously; the blocking results fetch happens on the
        engine's dedicated fetch thread in dispatch order. Returns an
        :class:`InflightBatch` immediately — its future resolves to the
        host result (or the exception that failed THIS batch only).

        Blocking (bounded): when ``pipeline_depth`` batches are already
        in flight the call parks on the ring until a fetch completes, so
        call it from a worker thread, never the event loop. With the
        pipeline disabled it degrades to the serialized predict wrapped
        in an already-resolved handle.
        """
        if self.quarantined:
            raise EngineQuarantined(
                f"engine {self.model_cfg.name!r} is quarantined after "
                f"{self._watchdog_trips} consecutive watchdog trips")
        n = sum(int(p.shape[0]) for p in parts)
        handle = InflightBatch(n, self.pad_batch(n))
        handle.profile_key = self.profile_key
        wd = float(getattr(self.batch_cfg, "watchdog_ms", 0.0) or 0.0)
        if wd > 0:
            handle.watchdog_ms = wd
            handle.on_done = self._watchdog_note
        if self._ring is None:
            x = parts[0] if len(parts) == 1 else np.concatenate(parts)
            try:
                handle.future.set_result(self._predict_serial(x))
            except BaseException as e:  # noqa: BLE001 - fail ONLY this batch
                handle.future.set_exception(e)
            return handle
        self._ensure_fetch_thread()
        self._ring.acquire()
        try:
            self._dispatch_phase(handle, parts)
        except BaseException as e:  # noqa: BLE001 - fail ONLY this batch
            buf, handle._buf = handle._buf, None
            if buf is not None:
                self._staging.release(buf)
            self._ring.release()
            handle.future.set_exception(e)
            return handle
        self._fetch_q.put(handle)
        return handle

    def _stage(self, buf: np.ndarray, parts: Sequence[np.ndarray],
               n: int) -> None:
        """The ONE host-side write of the dispatch phase: copy each part
        into the preallocated padded buffer (casting to the buffer dtype
        as it lands) and zero the padding rows — fusing what the stacked
        path did in three full-batch copies (concat, pad-concat, astype)."""
        ofs = 0
        for p in parts:
            k = p.shape[0]
            buf[ofs:ofs + k] = p
            ofs += k
        if ofs < buf.shape[0]:
            buf[ofs:] = 0

    def _dispatch_phase(self, handle: InflightBatch,
                        parts: Sequence[np.ndarray]) -> None:
        t0 = time.perf_counter()
        padded, n = handle.padded, handle.n
        cold = padded not in self.compiled_batches
        if self._quantize:
            # Stage at full precision first (range must come from the real
            # rows), then affine-quantize IN PLACE in the f32 buffer and
            # cast once into the uint8 wire buffer — no temporaries beyond
            # the two pooled buffers. The f32 buffer never reaches jax, so
            # it recycles immediately; the uint8 one is held until fetch.
            f32 = self._staging.acquire((padded, *self.input_shape),
                                        np.float32)
            try:
                self._stage(f32, parts, n)
                lo = float(f32[:n].min())
                hi = float(f32[:n].max())
                scale = np.float32(max((hi - lo) / 255.0, 1e-12))
                offset = np.float32(lo)
                buf = self._staging.acquire((padded, *self.input_shape),
                                            np.uint8)
                handle._buf = buf
                np.subtract(f32, offset, out=f32)
                np.divide(f32, scale, out=f32)
                np.rint(f32, out=f32)
                np.clip(f32, 0, 255, out=f32)
                np.copyto(buf, f32, casting="unsafe")
            finally:
                self._staging.release(f32)
            # Copy ledger: quantized staging is two full-batch passes —
            # the fused f32 stage write plus the uint8 cast into the
            # wire buffer (the in-place affine passes rewrite the same
            # f32 bytes; they are not counted as extra copies).
            _copyledger.record("staging", f32.nbytes + buf.nbytes,
                               copies=2, records=n,
                               engine=self.profile_key or "-")
            with self._lock:
                xd = jax.device_put(buf, self._x_sharding)
                out = self._fwd_q(self.params, self.state, xd, scale, offset)
        else:
            buf = self._staging.acquire((padded, *self.input_shape),
                                        self.dtype)
            handle._buf = buf
            self._stage(buf, parts, n)
            # Copy ledger: the ONE fused host-side write of the
            # dispatch phase (pad + cast into the pooled buffer).
            _copyledger.record("staging", buf.nbytes, copies=1,
                               records=n, engine=self.profile_key or "-")
            with self._lock:
                xd = jax.device_put(buf, self._x_sharding)
                out = self._fwd(self.params, self.state, xd)
        t1 = time.perf_counter()
        # Copy ledger: host->device transfer of the staged buffer (a CPU
        # backend may alias instead of copying, but the bytes handed to
        # device_put are the same either way). Recorded after t1 so the
        # hook never leaks into the h2d_ms timing it sits beside.
        _copyledger.record("h2d", buf.nbytes, copies=1, records=n,
                           engine=self.profile_key or "-")
        self.compiled_batches.add(padded)
        if cold:
            _report_compile(self.profile_key, padded, (t1 - t0) * 1e3)
            if self.on_compile is not None:
                try:
                    self.on_compile(padded, (t1 - t0) * 1e3)
                except Exception:
                    pass  # an observability hook must never fail a batch
        hold = self._chaos_hang_s()
        if hold > 0:
            out = _HangingResult(out, time.monotonic() + hold)
        handle._out = out
        handle._t_launched = t1
        # Staging + H2D + async launch (plus XLA compile when cold — the
        # on_compile event disambiguates the cliff in a post-mortem).
        handle.timings["h2d_ms"] = (t1 - t0) * 1e3

    @staticmethod
    def _chaos_hang_s() -> float:
        """One-shot engine-hang injection (chaos control RPC); 0 when the
        injector is unarmed — the common case pays one global read."""
        from storm_tpu.resilience.chaos import get_injector

        return get_injector().engine_hang_s()

    def _watchdog_note(self, exc) -> None:
        """Fetch-thread callback (InflightBatch.on_done): count
        CONSECUTIVE watchdog trips; at ``batch.watchdog_trips`` flip to
        quarantined exactly once, fire ``on_quarantine`` (the operator's
        replacement hook) and evict this engine from the shared cache so
        the next ``shared_engine`` call builds a fresh one."""
        if not isinstance(exc, EngineWatchdogTimeout):
            # A hung batch that eventually lands still reports success
            # here — keep the trip count once quarantined so the
            # fail-fast message names the real streak.
            if exc is None and not self.quarantined:
                with self._watchdog_lock:
                    self._watchdog_trips = 0
            return
        limit = int(getattr(self.batch_cfg, "watchdog_trips", 0) or 0)
        with self._watchdog_lock:
            self._watchdog_trips += 1
            trips = self._watchdog_trips
            if limit <= 0 or trips < limit or self.quarantined:
                return
            self.quarantined = True
        logger.error(
            "engine %s QUARANTINED after %d consecutive watchdog trips "
            "(watchdog_ms=%g); dispatch now refuses batches until a "
            "replacement is swapped in",
            self.model_cfg.name, trips, getattr(self.batch_cfg,
                                                "watchdog_ms", 0.0))
        # Evict BEFORE the replacement hook: the hook rebuilds via
        # shared_engine off-thread, and a cache hit on the still-cached
        # quarantined engine would "swap in" the dead engine forever.
        try:
            unload_engine(self)
        except Exception:
            logger.exception("evicting quarantined engine failed")
        cb = self.on_quarantine
        if cb is not None:
            try:
                cb(trips)
            except Exception:
                logger.exception("on_quarantine hook failed")

    def _ensure_fetch_thread(self) -> None:
        if self._fetch_thread is not None:
            return
        with self._fetch_thread_lock:
            if self._fetch_thread is None:
                # The thread must NOT hold the engine (not even via a bound
                # method): cache eviction (set_engine_cache_limit) detects
                # orphaned engines by refcount, and a long-lived thread
                # reference would pin every engine that ever dispatched.
                # It gets only the queue/ring/pool — none of which hold
                # params — and a finalizer stops it when the engine dies.
                t = threading.Thread(
                    target=_fetch_loop,
                    args=(self._fetch_q, self._ring, self._staging),
                    daemon=True,
                    name=f"storm-tpu-fetch-{self.model_cfg.name}")
                t.start()
                self._fetch_thread = t
                weakref.finalize(self, self._fetch_q.put, None)

    # _fetch_loop is module-level (see _ensure_fetch_thread for why).

    def _predict_serial(self, x: np.ndarray) -> np.ndarray:
        """The pre-pipeline serialized chain (pad -> cast -> device_put ->
        fwd -> fetch, one batch at a time). Kept as the ``pipeline_depth=0``
        escape hatch and as the multi-process path — the cross-process
        allgather is a collective whose issue order the dispatch lock must
        cover end to end (see :meth:`_gather_locked`)."""
        n = x.shape[0]
        padded = self.pad_batch(n)
        cold = padded not in self.compiled_batches
        t_compile = time.perf_counter() if cold else 0.0
        if self._quantize:
            # Range from the real rows only (padding would drag lo to 0).
            lo = float(x.min())
            hi = float(x.max())
            scale = np.float32(max((hi - lo) / 255.0, 1e-12))
            offset = np.float32(lo)
        if padded != n:
            x = np.concatenate([x, np.zeros((padded - n, *x.shape[1:]), x.dtype)])
        if self._quantize:
            xw = np.clip(np.rint((x - offset) / scale), 0, 255).astype(np.uint8)
            with self._lock:
                xd = jax.device_put(xw, self._x_sharding)
                out = self._fwd_q(self.params, self.state, xd, scale, offset)
                gathered = self._gather_locked(out)
        else:
            # Cast on the HOST (ml_dtypes gives numpy a bfloat16) so the
            # host->device transfer ships half the bytes — the tunnel/PCIe
            # link is the streaming bottleneck, not the cast.
            if x.dtype != self.dtype:
                x = x.astype(self.dtype)
            with self._lock:
                xd = jax.device_put(x, self._x_sharding)
                out = self._fwd(self.params, self.state, xd)
                gathered = self._gather_locked(out)
        self.compiled_batches.add(padded)
        if cold:
            ms = (time.perf_counter() - t_compile) * 1e3
            _report_compile(self.profile_key, padded, ms)
            if self.on_compile is not None:
                try:
                    self.on_compile(padded, ms)
                except Exception:
                    pass  # an observability hook must never fail a batch
        if gathered is None:
            # single-process: the host fetch happens OUTSIDE the lock so
            # one batch's device->host RTT doesn't serialize the next
            # batch's dispatch (max_inflight pipelining)
            gathered = np.asarray(out)
        return gathered[:n]

    def _gather_locked(self, out) -> "Optional[np.ndarray]":
        """Multi-process results fetch — a cross-process COLLECTIVE
        (process_allgather), so it must stay under the dispatch lock:
        every process has to issue its device_put/forward/gather sequence
        in one consistent order, and the lock serializes this process's
        side of that contract. The other half is the caller's: in
        multi-process serving every process feeds identical batches in
        identical order (one operator task per process — see
        tests/mh_serve_worker.py; concurrent tasks could still interleave
        lock ACQUISITION differently across processes). Returns None in
        single-process mode (fetch happens outside the lock)."""
        if not self._multiprocess:
            return None
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(out, tiled=True)


# ---- engine sharing across operator tasks ------------------------------------

_ENGINES: "OrderedDict[tuple, InferenceEngine]" = OrderedDict()
_ENGINES_LOCK = threading.Lock()
# key -> in-progress build; concurrent shared_engine calls for the same key
# wait on it instead of each allocating a full duplicate param copy.
_BUILDS: Dict[tuple, Future] = {}
# Optional hard cap on total cached param bytes; None = cap at 85% of the
# device HBM limit when known (the threshold round 1 only warned about).
# Eviction only ever drops engines nothing outside the cache references,
# so a cap can never force a live engine to be rebuilt as a duplicate.
_ENGINE_CACHE_LIMIT: Optional[int] = None
# Auxiliary engines (round 20): non-classify engines — the decode tier's
# DecodeEngine above all — register here (weakly) so the observatory's
# occupancy sweep enumerates them alongside the classify cache without
# this module importing their packages. They manage their own lifecycle;
# the cache's HBM cap and eviction never touch them.
_AUX_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


def register_aux_engine(engine) -> None:
    """Surface an externally-owned engine through :func:`live_engines`
    (weak — dropping the last strong ref unregisters it)."""
    with _ENGINES_LOCK:
        _AUX_ENGINES.add(engine)


def _freeze(v):
    """Hashable deep-freeze for cache keys (TOML arrays arrive as lists)."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    return v


def shared_engine(
    model_cfg: ModelConfig,
    sharding_cfg: Optional[ShardingConfig] = None,
    batch_cfg: Optional[BatchConfig] = None,
) -> InferenceEngine:
    """One engine per (model, dtype, shape, mesh) per process: operator tasks
    share params in HBM instead of the reference's per-replica model copies
    (InferenceBolt.java:57-58 + per-bolt Model boxes in the diagram)."""
    key = (
        model_cfg.name,
        model_cfg.dtype,
        model_cfg.transfer_dtype,
        tuple(model_cfg.input_shape),
        model_cfg.num_classes,
        model_cfg.checkpoint,
        model_cfg.seed,
        getattr(model_cfg, "weights", "float"),
        getattr(model_cfg, "compile_cache_dir", ""),
        # builder kwargs are part of the model identity (width=0.5 vs 1.0
        # must not share one cached engine); deep-freeze so TOML-sourced
        # list values stay hashable
        _freeze(getattr(model_cfg, "extra", {})),
        (sharding_cfg.data_parallel, sharding_cfg.tensor_parallel,
         getattr(sharding_cfg, "sequence_parallel", 1),
         getattr(sharding_cfg, "expert_parallel", 1))
        if sharding_cfg
        else None,
        # Batch policy is part of the identity: pad_batch/warmup read the
        # engine's buckets, so two operators with different batching must
        # not share one engine.
        (batch_cfg.max_batch, tuple(batch_cfg.buckets),
         getattr(batch_cfg, "pipeline_depth", 2),
         getattr(batch_cfg, "staging_pool", 0)) if batch_cfg else None,
    )
    with _ENGINES_LOCK:
        if key in _ENGINES:
            _ENGINES.move_to_end(key)  # LRU: most-recently-used last
            return _ENGINES[key]
        fut = _BUILDS.get(key)
        owner = fut is None
        if owner:
            fut = Future()
            _BUILDS[key] = fut
    if not owner:
        # Another thread owns the build: wait for its result instead of
        # allocating a duplicate param copy — N bolt tasks swapping the
        # same model concurrently must cost ONE build (param HBM +
        # compile), not N. The owner's finally below guarantees this
        # future resolves (value or exception) — no unbounded hang.
        return fut.result()
    # We own the build. Build OUTSIDE the lock: compile can take tens of
    # seconds and the UI thread polls engine_inventory under this lock.
    # The try starts IMMEDIATELY after registration so an async exception
    # (KeyboardInterrupt) landing anywhere before completion still pops
    # the _BUILDS entry and resolves the future — a stale entry would
    # serve a phantom engine forever; an unresolved future would hang
    # waiters (no timeout) permanently.
    engine = None
    try:
        engine = InferenceEngine(model_cfg, sharding_cfg, batch_cfg)
        if _insert_would_exceed_budget(engine):
            # Collect BEFORE taking the lock: an engine held only by a
            # reference cycle (e.g. a completed swap's rollback closure)
            # looks externally-referenced to the refcount probe until the
            # cycle collector runs. gc.collect() under _ENGINES_LOCK would
            # stall every cache reader for a full-heap pass AND can
            # deadlock — finalizers may re-enter the cache (unload_engine,
            # inventory), and the lock is not reentrant.
            gc.collect()
        with _ENGINES_LOCK:
            _ENGINES[key] = engine
            try:
                _evict_to_budget_locked(keep=key)
                _log_hbm_inventory()
            except Exception:
                # Bookkeeping only: the engine is built and cached —
                # neither the owner nor the waiters should fail because
                # eviction or the inventory log hiccuped.
                logger.exception("engine cache bookkeeping failed")
    finally:
        with _ENGINES_LOCK:
            _BUILDS.pop(key, None)
        if engine is not None:
            fut.set_result(engine)
        else:
            exc = sys.exc_info()[1]
            fut.set_exception(
                exc
                if exc is not None
                else RuntimeError("engine build aborted before completion")
            )
    return engine


class NullEngine:
    """Device-free engine: ``predict`` returns a uniform distribution
    instantly. Plugs into ``InferenceBolt(engine=NullEngine(...))`` to
    measure the FRAMEWORK's share of the Kafka->Kafka path — broker
    queueing, spout fetch/decode, batching, executor hops, encode,
    produce — with device time pinned to zero (the evidence behind the
    <50 ms framework-overhead claim; bench.py --latency-breakdown).

    Not a mock of the full InferenceEngine surface — just the protocol the
    operator uses: ``input_shape``, ``warmup``, ``predict``,
    ``dispatch``."""

    def __init__(self, input_shape: Tuple[int, ...], num_classes: int) -> None:
        self.input_shape = tuple(input_shape)
        self.num_classes = int(num_classes)
        self.ring_capacity = 1

    def warmup(self, buckets=None) -> None:  # no device, nothing to compile
        pass

    def predict(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        return np.full((n, self.num_classes), 1.0 / self.num_classes,
                       np.float32)

    def dispatch(self, parts: Sequence[np.ndarray]) -> InflightBatch:
        # Already-resolved handle with zeroed phase timings: the stage
        # table then shows the framework path with h2d/compute/d2h ~0,
        # same as device_ms under predict.
        n = sum(int(p.shape[0]) for p in parts)
        handle = InflightBatch(n, n)
        handle.timings = {"h2d_ms": 0.0, "compute_ms": 0.0, "d2h_ms": 0.0}
        handle.future.set_result(
            np.full((n, self.num_classes), 1.0 / self.num_classes,
                    np.float32))
        return handle


def unload_engine(engine: InferenceEngine) -> bool:
    """Drop ``engine`` from the process cache so its HBM can be reclaimed
    once no bolt references it (live model swaps otherwise accumulate
    rollback engines forever). Returns True if it was cached."""
    with _ENGINES_LOCK:
        for k, e in list(_ENGINES.items()):
            if e is engine:
                del _ENGINES[k]
                return True
    return False


def set_engine_cache_limit(max_param_bytes: Optional[int]) -> None:
    """Cap total cached engine param bytes; least-recently-used engines are
    dropped from the cache on the next ``shared_engine`` insert. ``None``
    restores the default (85% of device HBM when the backend reports it).

    **Best-effort semantics**: only *orphaned* engines (no references
    outside the cache) are evicted — dropping one a bolt still serves from
    would free nothing and force a duplicate build. Orphan detection is
    refcount-based (CPython only; elsewhere nothing is ever evicted), so an
    engine pinned by a reference *cycle* stays resident until the cycle
    collector runs — eviction triggers ``gc.collect()`` first when over
    budget to break such cycles. Degradation is always in the safe
    direction (keep, never double-free), but the cap is a target, not a
    hard bound."""
    global _ENGINE_CACHE_LIMIT
    with _ENGINES_LOCK:
        _ENGINE_CACHE_LIMIT = max_param_bytes


def _refs_of_value(d: dict, k) -> int:
    """getrefcount of ``d[k]`` through one fixed call shape, so the
    internal-reference overhead is identical between the calibration probe
    and the real check (CPython's calling convention changed this count
    between 3.10 and 3.11 — never hard-code it)."""
    return sys.getrefcount(d[k])


_REF_BASELINE: Optional[int] = None


def _ref_baseline() -> int:
    """Refcount of an object whose ONLY reference is a dict value, measured
    through :func:`_refs_of_value` at runtime on this interpreter."""
    global _REF_BASELINE
    if _REF_BASELINE is None:
        _REF_BASELINE = _refs_of_value({0: object()}, 0)
    return _REF_BASELINE


def _externally_referenced(k: tuple) -> bool:
    """Best-effort: does anything OUTSIDE the cache still hold ``_ENGINES[k]``?
    Non-CPython lacks refcount semantics — treat everything as referenced
    (never evict; degrades to round 1's warn-only behavior, which is safe)."""
    try:
        return _refs_of_value(_ENGINES, k) > _ref_baseline()
    except Exception:  # pragma: no cover - non-CPython
        return True


def _cache_limit() -> Optional[int]:
    limit = _ENGINE_CACHE_LIMIT
    if limit is None:
        hbm = _device_hbm_limit()
        limit = int(0.85 * hbm) if hbm else None
    return limit


def _insert_would_exceed_budget(engine: "InferenceEngine") -> bool:
    """Brief-lock budget probe used to decide whether to gc.collect()
    before inserting ``engine`` (the collect itself must run unlocked)."""
    limit = _cache_limit()
    if limit is None:
        return False
    with _ENGINES_LOCK:
        total = sum(e.param_bytes_per_device() for e in _ENGINES.values())
    return total + engine.param_bytes_per_device() > limit


def _evict_to_budget_locked(keep: tuple) -> None:
    limit = _cache_limit()
    if limit is None:
        return
    # Per-DEVICE bytes: the budget is one chip's HBM, and TP-sharded
    # engines only hold ~1/tp of their params on each device — counting
    # global bytes would evict orphans that actually fit.
    total = sum(e.param_bytes_per_device() for e in _ENGINES.values())
    for k in list(_ENGINES):  # oldest first
        if total <= limit:
            break
        if k == keep:  # never evict the engine being handed out
            continue
        if _externally_referenced(k):
            # A bolt still serves from it: evicting would free nothing AND
            # make the next lookup build a duplicate param copy — worse HBM
            # pressure than doing nothing. Only orphans (e.g. rollback
            # engines left behind by completed model swaps) are dropped.
            continue
        e = _ENGINES.pop(k)
        per_dev = e.param_bytes_per_device()
        total -= per_dev
        logger.info(
            "evicted orphaned LRU engine %s (%.1fMB/device) from cache "
            "(budget %.1fMB)",
            e.model_cfg.name, per_dev / 1e6, limit / 1e6)
        del e  # drop the last reference -> HBM reclaimed


def live_engines() -> list:
    """Strong refs to every cached engine (observatory occupancy sweep:
    ring/staging state lives on the engine objects, not in
    :func:`engine_inventory`'s attribution rows)."""
    with _ENGINES_LOCK:
        return list(_ENGINES.values()) + list(_AUX_ENGINES)


def engine_inventory() -> dict:
    """Live engines in this process and their per-replica HBM param
    footprints — the multi-model co-residency budget (BASELINE config 5;
    engines accumulate across pipelines and live model swaps)."""
    with _ENGINES_LOCK:
        engines = list(_ENGINES.values())
    rows = [
        {
            "model": e.model_cfg.name,
            # Distinguishes cascade tiers / swap variants that share a
            # registry name but serve different weights.
            "checkpoint": getattr(e.model_cfg, "checkpoint", None) or None,
            "weights": getattr(e.model_cfg, "weights", "float"),
            "dtype": str(e.dtype),
            "param_bytes": e.param_bytes(),
            # What one chip actually holds (≈ param_bytes/tp when sharded)
            # — the figure the 85% HBM warning and cache budget use.
            "param_bytes_per_device": e.param_bytes_per_device(),
        }
        for e in engines
    ]
    return {"engines": rows,
            "total_param_bytes": sum(r["param_bytes"] for r in rows),
            "total_param_bytes_per_device": sum(
                r["param_bytes_per_device"] for r in rows)}


def _device_hbm_limit() -> Optional[int]:
    try:
        stats = jax.devices()[0].memory_stats()
        if stats:
            return stats.get("bytes_limit")
    except Exception:  # pragma: no cover - backend-dependent
        pass
    return None


def _log_hbm_inventory() -> None:
    # Called with _ENGINES_LOCK held (param_bytes only reads engine attrs).
    # Per-DEVICE bytes: the limit being compared against is one chip's HBM,
    # and TP-sharded engines hold only ~1/tp of their params per device.
    rows = [(e.model_cfg.name, e.param_bytes_per_device())
            for e in _ENGINES.values()]
    total = sum(b for _, b in rows)
    limit = _device_hbm_limit()
    detail = ", ".join(f"{n}={b / 1e6:.1f}MB" for n, b in rows)
    logger.info(
        "engine HBM inventory: %s (total %.1fMB/device)", detail, total / 1e6)
    if limit and total > 0.85 * limit:
        logger.warning(
            "co-resident engine params at %.0f%% of device memory "
            "(%.1fMB of %.1fMB per device) — multi-model HBM budget "
            "nearly exhausted",
            100 * total / limit, total / 1e6, limit / 1e6,
        )
