from storm_tpu.infer.engine import (
    InferenceEngine,
    NullEngine,
    set_engine_cache_limit,
    shared_engine,
    unload_engine,
)
from storm_tpu.infer.operator import InferenceBolt

__all__ = [
    "InferenceEngine",
    "NullEngine",
    "shared_engine",
    "unload_engine",
    "set_engine_cache_limit",
    "InferenceBolt",
]
