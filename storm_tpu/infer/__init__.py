from storm_tpu.infer.engine import InferenceEngine, shared_engine
from storm_tpu.infer.operator import InferenceBolt

__all__ = ["InferenceEngine", "shared_engine", "InferenceBolt"]
