from storm_tpu.infer.engine import (
    InferenceEngine,
    set_engine_cache_limit,
    shared_engine,
    unload_engine,
)
from storm_tpu.infer.operator import InferenceBolt

__all__ = [
    "InferenceEngine",
    "shared_engine",
    "unload_engine",
    "set_engine_cache_limit",
    "InferenceBolt",
]
