"""Datasets + a small convergence trainer for closing the trained-model loop.

The reference's entire purpose is serving a TRAINED classifier
(/root/reference/README.md:16-18; InferenceBolt loads a trained graph and
fetches its softmax, InferenceBolt.java:57,83-86) — the model arrives
pre-trained inside the jar. This package supplies what that leaves out of
tree: a real dataset that ships with the environment (scikit-learn's
handwritten digits — 1797 genuine 8x8 scans, no download) and a trainer
built on :mod:`storm_tpu.parallel.train`, so the serving-path accuracy
claims (uint8 wire, int8 weights, sharded serving) can be validated against
a model that actually classifies, not random init.
"""

from storm_tpu.data.digits import load_digits_nhwc, train_to_convergence

__all__ = ["load_digits_nhwc", "train_to_convergence"]
