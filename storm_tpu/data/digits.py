"""Real handwritten-digits data + a convergence trainer.

Data: scikit-learn's bundled digits set (1797 samples of real 8x8
handwritten digit scans, values 0..16). It is the one genuine image-
classification dataset available offline in this environment, and it is
MNIST's task at small scale — the reference's headline workload
(reference README.md:16-18). Images are upscaled by integer replication to
the model's input resolution (LeNet-5's native 32x32, or 28x28) and
normalized to [0, 1]; channels are replicated for RGB-shaped models
(resnet20's CIFAR shape).

Trainer: plain mini-batch loop over :func:`storm_tpu.parallel.train.
make_train_step` — the same jit step the multi-chip dryrun certifies —
run until the held-out accuracy stops improving or ``max_epochs`` is hit.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("storm_tpu.data")


def load_digits_nhwc(
    input_shape: Tuple[int, int, int] = (32, 32, 1),
    test_fraction: float = 0.25,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(x_train, y_train, x_test, y_test): float32 NHWC in [0,1], int32 labels.

    The 8x8 source is integer-upscaled (pixel replication) to the nearest
    multiple of 8 <= (H, W) and zero-padded to exactly (H, W) if needed, so
    LeNet's 32x32 and the zoo's 28x28 both work without interpolation
    artifacts.
    """
    from sklearn.datasets import load_digits  # bundled data, no download

    h, w, c = input_shape
    d = load_digits()
    imgs = d.images.astype(np.float32) / 16.0  # (N, 8, 8) in [0,1]
    labels = d.target.astype(np.int32)

    kh, kw = max(1, h // 8), max(1, w // 8)
    imgs = np.repeat(np.repeat(imgs, kh, axis=1), kw, axis=2)
    ph, pw = h - imgs.shape[1], w - imgs.shape[2]
    if ph or pw:
        imgs = np.pad(imgs, ((0, 0), (ph // 2, ph - ph // 2),
                             (pw // 2, pw - pw // 2)))
    x = np.repeat(imgs[..., None], c, axis=-1)  # (N, H, W, C)

    rng = np.random.default_rng(seed)
    order = rng.permutation(len(x))
    x, labels = x[order], labels[order]
    n_test = int(len(x) * test_fraction)
    return (x[n_test:], labels[n_test:], x[:n_test], labels[:n_test])


def train_to_convergence(
    model,
    x_train: np.ndarray,
    y_train: np.ndarray,
    x_val: Optional[np.ndarray] = None,
    y_val: Optional[np.ndarray] = None,
    batch_size: int = 128,
    max_epochs: int = 60,
    learning_rate: float = 1e-3,
    patience: int = 8,
    seed: int = 0,
    mesh=None,
):
    """Train ``model`` until val accuracy plateaus; returns
    (params, state, history) with params/state fetched to host (ready for
    :func:`storm_tpu.models.registry.save_checkpoint`).

    ``mesh``: optional Mesh to dp/tp-shard the step over (the
    parallel/train.py path); None trains on the default device.
    """
    import jax
    import jax.numpy as jnp

    from storm_tpu.models.registry import init_params
    from storm_tpu.parallel.train import make_train_step

    if mesh is not None:
        from storm_tpu.parallel.train import init_sharded_training

        train_step, params, opt_state, state = init_sharded_training(
            model, mesh, seed=seed, learning_rate=learning_rate)
    else:
        train_step, opt = make_train_step(model, learning_rate=learning_rate)
        params, state = init_params(model, seed)
        opt_state = jax.jit(opt.init)(params)

    @jax.jit
    def eval_logits(params, state, x):
        logits, _ = model.apply(params, state, x, train=False)
        return logits

    def accuracy(params, state, x, y) -> float:
        preds = []
        for i in range(0, len(x), 512):
            preds.append(np.argmax(np.asarray(
                eval_logits(params, state, jnp.asarray(x[i:i + 512]))), -1))
        return float((np.concatenate(preds) == y).mean())

    # Persistable state = the structure model.init declares (BatchNorm
    # running stats etc.). Training-only extras a train=True apply folds
    # in (e.g. moe_aux_loss) must NOT reach the checkpoint — restore
    # matches against the init structure and would fail.
    _, state0 = init_params(model, seed)

    def persistable(st):
        if isinstance(st, dict) and isinstance(state0, dict):
            return {k: v for k, v in st.items() if k in state0}
        return st

    rng = np.random.default_rng(seed)
    history = []
    best_acc, best_snapshot, stale = -1.0, None, 0
    n = len(x_train)
    for epoch in range(max_epochs):
        order = rng.permutation(n)
        losses = []
        for i in range(0, n - batch_size + 1, batch_size):
            idx = order[i:i + batch_size]
            xb, yb = jnp.asarray(x_train[idx]), jnp.asarray(y_train[idx])
            if mesh is not None:
                from storm_tpu.parallel.sharding import batch_sharding

                xb = jax.device_put(xb, batch_sharding(mesh))
                yb = jax.device_put(yb, batch_sharding(mesh))
            params, opt_state, state, loss = train_step(
                params, opt_state, state, xb, yb)
            losses.append(float(loss))
        val_acc = (accuracy(params, state, x_val, y_val)
                   if x_val is not None else float("nan"))
        history.append({"epoch": epoch, "loss": float(np.mean(losses)),
                        "val_acc": val_acc})
        log.info("epoch %d loss %.4f val_acc %.4f", epoch,
                 history[-1]["loss"], val_acc)
        if x_val is None:
            continue
        if val_acc > best_acc + 1e-4:
            best_acc, stale = val_acc, 0
            best_snapshot = (jax.device_get(params),
                             jax.device_get(persistable(state)))
        else:
            stale += 1
            if stale >= patience:
                break
    if best_snapshot is not None:
        return best_snapshot[0], best_snapshot[1], history
    return jax.device_get(params), jax.device_get(persistable(state)), history
