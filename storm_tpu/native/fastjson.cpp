// Fast {"instances": [[[[...]]]]} JSON parser -> contiguous float32 buffer.
//
// The per-tuple hot path of the reference is a Jackson JSON parse plus JNI
// float-array copies (InferenceBolt.java:76-86). This is the TPU-native
// equivalent: one pass over the payload bytes, floats decoded with
// std::from_chars straight into a single contiguous buffer that NumPy wraps
// zero-copy on the Python side (storm_tpu/native/__init__.py), ready for a
// single host->device transfer.
//
// Contract (mirrors storm_tpu.api.schema.decode_instances):
//   - top-level object must contain an "instances" key; other keys are
//     skipped structurally;
//   - value must be a rectangular nested array, max rank 8; raggedness,
//     non-numeric leaves, empty dims and malformed JSON are errors;
//   - returns a malloc'd float buffer (caller frees via stpu_free) and the
//     shape/rank via out-params; on error returns nullptr with a
//     thread-local message in *err_out.
//
// Build: make -C storm_tpu/native   (g++ -O3 -shared -fPIC)

#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int kMaxRank = 8;

thread_local std::string g_err;

struct Parser {
  const char* p;
  const char* end;
  std::vector<float> out;
  int64_t shape[kMaxRank];
  int rank = -1;  // set on first full descent

  explicit Parser(const char* buf, size_t len) : p(buf), end(buf + len) {
    for (int64_t& s : shape) s = -1;
    // ~7 bytes per "0.1234," literal: one reserve sized off the payload
    // avoids every growth-realloc copy of the output buffer.
    out.reserve(len / 6 + 16);
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool fail(const std::string& msg) {
    g_err = msg;
    return false;
  }

  bool expect(char c) {
    skip_ws();
    if (p >= end || *p != c) return fail(std::string("expected '") + c + "'");
    ++p;
    return true;
  }

  // Parse a JSON string (only used for keys; escapes are skipped, not decoded).
  bool parse_string(std::string* s) {
    skip_ws();
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    const char* start = p;
    while (p < end) {
      if (*p == '\\') {
        p += 2;
        continue;
      }
      if (*p == '"') {
        if (s) s->assign(start, p - start);
        ++p;
        return true;
      }
      ++p;
    }
    return fail("unterminated string");
  }

  // Structurally skip any JSON value (for non-"instances" keys).
  bool skip_value() {
    skip_ws();
    if (p >= end) return fail("unexpected end of input");
    char c = *p;
    if (c == '"') return parse_string(nullptr);
    if (c == '{' || c == '[') {
      char open = c, close = (c == '{') ? '}' : ']';
      int depth = 0;
      while (p < end) {
        if (*p == '"') {
          if (!parse_string(nullptr)) return false;
          continue;
        }
        if (*p == open) ++depth;
        if (*p == close && --depth == 0) {
          ++p;
          return true;
        }
        ++p;
      }
      return fail("unterminated container");
    }
    // number / literal: consume until delimiter
    while (p < end && *p != ',' && *p != '}' && *p != ']' &&
           !std::isspace(static_cast<unsigned char>(*p)))
      ++p;
    return true;
  }

  // Fixed-point decimal fast path: sign, <=15 digits, optional '.', no
  // exponent — covers pixel/probability literals. The <=15-digit mantissa is
  // exact in a uint64->double, and negative powers of ten up to 1e15 are
  // exact doubles, so one double divide + one float cast is correctly
  // rounded to within 1 ulp of from_chars (which remains the fallback).
  bool parse_float_fast(float* out_v) {
    static constexpr double kPow10[16] = {
        1e0, 1e1, 1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
        1e8, 1e9, 1e10, 1e11, 1e12, 1e13, 1e14, 1e15};
    const char* q = p;
    bool neg = false;
    if (q < end && *q == '-') {
      neg = true;
      ++q;
    }
    uint64_t mant = 0;
    int digits = 0;
    while (q < end && static_cast<unsigned>(*q - '0') <= 9) {
      mant = mant * 10 + static_cast<unsigned>(*q - '0');
      ++q;
      ++digits;
    }
    int frac = 0;
    if (q < end && *q == '.') {
      ++q;
      const char* fs = q;
      while (q < end && static_cast<unsigned>(*q - '0') <= 9) {
        mant = mant * 10 + static_cast<unsigned>(*q - '0');
        ++q;
      }
      frac = static_cast<int>(q - fs);
      digits += frac;
    }
    if (digits == 0 || digits > 15 || frac > 15) return false;
    if (q < end && (*q == 'e' || *q == 'E')) return false;
    double d = static_cast<double>(mant);
    if (frac) d /= kPow10[frac];
    *out_v = static_cast<float>(neg ? -d : d);
    p = q;
    return true;
  }

  // Tight loop for the innermost dimension: numbers only, no per-element
  // recursion or depth checks — this is where ~all the bytes are.
  bool parse_leaf_array(int depth, int64_t* count) {
    int64_t n = 0;
    while (true) {
      skip_ws();
      if (p >= end) return fail("unterminated array");
      if (*p == '[') return fail("instances is ragged (mixed nesting depth)");
      float v;
      if (!parse_float_fast(&v)) {
        auto res = std::from_chars(p, end, v);
        if (res.ec != std::errc())
          return fail("instances contains a non-numeric leaf");
        p = res.ptr;
      }
      out.push_back(v);
      ++n;
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        break;
      }
      return fail("expected ',' or ']' in array");
    }
    if (shape[depth] == -1) {
      shape[depth] = n;
    } else if (shape[depth] != n) {
      return fail("instances is ragged (inconsistent lengths)");
    }
    *count = n;
    return true;
  }

  // Parse the nested array at `depth`; returns element count via *count.
  bool parse_array(int depth, int64_t* count) {
    if (depth >= kMaxRank) return fail("instances exceeds max rank 8");
    if (!expect('[')) return false;
    skip_ws();
    if (p < end && *p == ']') {
      ++p;
      return fail("instances has an empty dimension");
    }
    if (depth == rank - 1) return parse_leaf_array(depth, count);
    int64_t n = 0;
    while (true) {
      skip_ws();
      if (p >= end) return fail("unterminated array");
      if (*p == '[') {
        int64_t sub = 0;
        if (!parse_array(depth + 1, &sub)) return false;
      } else {
        return fail("instances is ragged (mixed nesting depth)");
      }
      ++n;
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        break;
      }
      return fail("expected ',' or ']' in array");
    }
    if (shape[depth] == -1) {
      shape[depth] = n;
    } else if (shape[depth] != n) {
      return fail("instances is ragged (inconsistent lengths)");
    }
    *count = n;
    return true;
  }

  bool parse_instances_value() {
    skip_ws();
    if (p >= end || *p != '[')
      return fail("\"instances\" must be a nested array");
    // First, probe nesting depth to fix the rank (scan leading '[').
    const char* q = p;
    int depth = 0;
    while (q < end) {
      if (*q == '[') {
        ++depth;
        ++q;
        continue;
      }
      if (std::isspace(static_cast<unsigned char>(*q))) {
        ++q;
        continue;
      }
      break;
    }
    if (depth == 0 || depth > kMaxRank) return fail("bad instances nesting");
    rank = depth;
    int64_t n = 0;
    return parse_array(0, &n);
  }

  bool parse_document() {
    skip_ws();
    if (!expect('{')) return fail("payload is not a JSON object");
    bool found = false;
    skip_ws();
    if (p < end && *p == '}') return fail("payload missing \"instances\" key");
    while (true) {
      std::string key;
      if (!parse_string(&key)) return false;
      if (!expect(':')) return false;
      if (key == "instances") {
        if (!parse_instances_value()) return false;
        found = true;
      } else {
        if (!skip_value()) return false;
      }
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        skip_ws();
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        break;
      }
      return fail("expected ',' or '}' in object");
    }
    if (!found) return fail("payload missing \"instances\" key");
    skip_ws();
    if (p != end) return fail("trailing bytes after JSON document");
    return true;
  }
};

}  // namespace

extern "C" {

// Returns a malloc'd float32 buffer (or nullptr on error; *err_out then
// points at a thread-local message). Caller frees with stpu_free.
float* stpu_parse_instances(const char* buf, size_t len, int64_t* shape_out,
                            int32_t* rank_out, const char** err_out) {
  Parser parser(buf, len);
  if (!parser.parse_document()) {
    if (err_out) *err_out = g_err.c_str();
    return nullptr;
  }
  int rank = parser.rank;
  int64_t expected = 1;
  for (int i = 0; i < rank; ++i) expected *= parser.shape[i];
  if (expected != static_cast<int64_t>(parser.out.size())) {
    g_err = "instances is ragged (element count mismatch)";
    if (err_out) *err_out = g_err.c_str();
    return nullptr;
  }
  float* result =
      static_cast<float*>(std::malloc(parser.out.size() * sizeof(float)));
  if (!result) {
    g_err = "out of memory";
    if (err_out) *err_out = g_err.c_str();
    return nullptr;
  }
  std::memcpy(result, parser.out.data(), parser.out.size() * sizeof(float));
  for (int i = 0; i < rank; ++i) shape_out[i] = parser.shape[i];
  *rank_out = rank;
  return result;
}

void stpu_free(void* p) { std::free(p); }

// Serialize an (n, k) float32 matrix to the {"predictions": [[...]]} wire
// form (PredObj.java:9-17 equivalent). Numbers are rounded to 7 decimal
// places then printed with shortest round-trip (std::to_chars), matching the
// Python path's json.dumps(round(float64, 7)) to within the rounding-mode
// ulp. Returns a malloc'd buffer (caller frees via stpu_free); *len_out gets
// the byte length. Non-finite values are emitted as JSON-python tokens
// (NaN/Infinity), mirroring json.dumps defaults.
char* stpu_format_predictions(const float* data, int64_t n, int64_t k,
                              size_t* len_out) {
  std::string s;
  s.reserve(static_cast<size_t>(n * k) * 12 + 24);
  s += "{\"predictions\": [";
  char buf[32];
  for (int64_t i = 0; i < n; ++i) {
    s += (i ? ", [" : "[");
    for (int64_t j = 0; j < k; ++j) {
      if (j) s += ", ";
      double v = static_cast<double>(data[i * k + j]);
      if (v != v) {
        s += "NaN";
        continue;
      }
      if (v > 1.7e308 || v < -1.7e308) {
        s += (v > 0 ? "Infinity" : "-Infinity");
        continue;
      }
      double r = std::round(v * 1e7) / 1e7;
      auto res = std::to_chars(buf, buf + sizeof(buf), r);
      s.append(buf, res.ptr - buf);
    }
    s += "]";
  }
  s += "]}";
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (!out) return nullptr;
  std::memcpy(out, s.data(), s.size() + 1);
  *len_out = s.size();
  return out;
}

}  // extern "C"
