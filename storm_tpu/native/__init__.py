"""Native (C++) acceleration layer, loaded via ctypes with Python fallback.

The reference's hot-path marshalling was Jackson JSON parse + JNI float-array
copies (InferenceBolt.java:76-86). Here the equivalent is a C++ shared library
(``libstormtpu.so``) that parses ``{"instances": ...}`` payloads straight into
a contiguous float32 buffer handed to NumPy zero-copy. If the library has not
been built (``make -C storm_tpu/native``), every entry point degrades to a
pure-Python implementation — functionality is identical, only slower.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Optional

import numpy as np

_LIB_PATH = Path(__file__).parent / "libstormtpu.so"
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

import threading

_tls = threading.local()

_MAX_RANK = 8


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("STORM_TPU_NO_NATIVE"):
        return None
    if not _LIB_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.stpu_parse_instances.restype = ctypes.c_void_p
        lib.stpu_parse_instances.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64),  # out shape[_MAX_RANK]
            ctypes.POINTER(ctypes.c_int32),  # out rank
            ctypes.POINTER(ctypes.c_char_p),  # out error message
        ]
        lib.stpu_free.restype = None
        lib.stpu_free.argtypes = [ctypes.c_void_p]
        try:
            lib.stpu_format_predictions.restype = ctypes.c_void_p
            lib.stpu_format_predictions.argtypes = [
                ctypes.c_void_p,  # float* data
                ctypes.c_int64,  # n
                ctypes.c_int64,  # k
                ctypes.POINTER(ctypes.c_size_t),  # out length
            ]
        except AttributeError:  # stale .so without the serializer
            pass
        try:
            lib.stpu_tensor_encode.restype = ctypes.c_void_p
            lib.stpu_tensor_encode.argtypes = [
                ctypes.c_void_p,  # data
                ctypes.c_int,  # dtype code
                ctypes.c_int,  # ndim
                ctypes.POINTER(ctypes.c_int64),  # shape
                ctypes.POINTER(ctypes.c_size_t),  # out length
            ]
            lib.stpu_tensor_decode.restype = ctypes.c_int
            lib.stpu_tensor_decode.argtypes = [
                ctypes.c_void_p,  # buf (address; caller keeps the buffer alive)
                ctypes.c_size_t,  # len
                ctypes.POINTER(ctypes.c_int),  # out dtype
                ctypes.POINTER(ctypes.c_int),  # out ndim
                ctypes.POINTER(ctypes.c_int64),  # out shape[_MAX_RANK]
                ctypes.POINTER(ctypes.c_size_t),  # out body offset
                ctypes.POINTER(ctypes.c_size_t),  # out body length
            ]
        except AttributeError:  # stale .so without the tensor marshaller
            pass
        try:
            lib.stpu_crc32c.restype = ctypes.c_uint32
            lib.stpu_crc32c.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_uint32,
            ]
        except AttributeError:  # stale .so without crc32c
            pass
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def parse_instances_native(payload: str | bytes) -> Optional[np.ndarray]:
    """Parse an ``{"instances": ...}`` JSON payload with the C++ parser.

    Returns ``None`` when the native library is unavailable (caller falls back
    to the Python path). Raises :class:`storm_tpu.api.schema.SchemaError` on a
    malformed payload, same as the Python path.
    """
    lib = _load()
    if lib is None:
        return None
    from storm_tpu.api.schema import SchemaError

    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    # Out-params are reused per thread — allocating fresh ctypes objects per
    # call measurably showed up in the per-message profile.
    tl = _tls
    try:
        shape, rank, rank_ref, err, err_ref = tl.bufs
    except AttributeError:
        shape = (ctypes.c_int64 * _MAX_RANK)()
        rank = ctypes.c_int32(0)
        err = ctypes.c_char_p(None)
        tl.bufs = (shape, rank, ctypes.byref(rank), err, ctypes.byref(err))
        shape, rank, rank_ref, err, err_ref = tl.bufs
    err.value = None
    ptr = lib.stpu_parse_instances(payload, len(payload), shape, rank_ref, err_ref)
    if not ptr:
        msg = err.value.decode("utf-8", "replace") if err.value else "native parse failed"
        raise SchemaError(msg)
    shp = tuple(int(shape[i]) for i in range(rank.value))
    n = 1
    for s in shp:
        n *= s
    # Single memmove out of the C buffer into a NumPy-owned array (the
    # previous as_array+np.array dance cost ~35us/msg in wrapper overhead).
    out = np.empty(n, np.float32)
    ctypes.memmove(out.ctypes.data, ptr, n * 4)
    lib.stpu_free(ptr)
    return out.reshape(shp)


# Dtype codes shared with arrow_tensor.cpp (enum DType).
_DTYPE_TO_CODE = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int8): 4,
    np.dtype(np.uint16): 5,
    np.dtype(np.int16): 6,
    np.dtype(np.uint32): 7,
    np.dtype(np.int32): 8,
    np.dtype(np.uint64): 9,
    np.dtype(np.int64): 10,
}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}


def encode_tensor_native(x: np.ndarray) -> Optional[bytes]:
    """Encode a NumPy array as an Arrow IPC tensor message with the C++
    marshaller (SURVEY.md §2.2: the zero-copy host↔engine boundary). Returns
    ``None`` when the native library is unavailable or the dtype is outside
    Arrow's tensor element types (caller falls back to pyarrow)."""
    lib = _load()
    if lib is None or not hasattr(lib, "stpu_tensor_encode"):
        return None
    code = _DTYPE_TO_CODE.get(x.dtype)
    if code is None or x.ndim < 1 or x.ndim > _MAX_RANK:
        return None
    x = np.ascontiguousarray(x)
    shape = (ctypes.c_int64 * _MAX_RANK)(*x.shape, *([0] * (_MAX_RANK - x.ndim)))
    length = ctypes.c_size_t(0)
    ptr = lib.stpu_tensor_encode(
        x.ctypes.data, code, x.ndim, shape, ctypes.byref(length)
    )
    if not ptr:
        return None
    out = ctypes.string_at(ptr, length.value)
    lib.stpu_free(ptr)
    return out


_RC_UNSUPPORTED = 100  # valid Arrow tensor, but a layout we don't view raw


def decode_tensor_native(buf) -> Optional[np.ndarray]:
    """Decode an Arrow IPC tensor message with the C++ parser.

    ``buf`` may be ``bytes``, ``bytearray``, or ``memoryview`` (any buffer
    object). The returned array is a zero-copy view over ``buf``'s body
    bytes. Returns ``None`` when the native library is unavailable OR the
    message is valid but uses a layout the raw-view path doesn't support
    (e.g. Fortran-order strides) — callers fall back to pyarrow. Raises
    ``ValueError`` on genuinely malformed input."""
    lib = _load()
    if lib is None or not hasattr(lib, "stpu_tensor_decode"):
        return None
    # frombuffer accepts any buffer object without copying and keeps `buf`
    # alive via the returned array's .base chain.
    raw = np.frombuffer(buf, dtype=np.uint8)
    dtype = ctypes.c_int(0)
    ndim = ctypes.c_int(0)
    shape = (ctypes.c_int64 * _MAX_RANK)()
    body_off = ctypes.c_size_t(0)
    body_len = ctypes.c_size_t(0)
    rc = lib.stpu_tensor_decode(
        raw.ctypes.data,
        raw.size,
        ctypes.byref(dtype),
        ctypes.byref(ndim),
        shape,
        ctypes.byref(body_off),
        ctypes.byref(body_len),
    )
    if rc == _RC_UNSUPPORTED:
        return None
    if rc != 0:
        raise ValueError(f"malformed Arrow tensor message (native rc={rc})")
    dt = _CODE_TO_DTYPE[dtype.value]
    shp = tuple(int(shape[i]) for i in range(ndim.value))
    view = raw[body_off.value : body_off.value + body_len.value]
    return view.view(dt).reshape(shp)


def format_predictions_native(arr: np.ndarray) -> Optional[str]:
    """Serialize an (N, K) float array to ``{"predictions": [[...]]}`` with
    the C++ writer. Returns ``None`` when unavailable (caller falls back to
    the Python path)."""
    lib = _load()
    if lib is None or not hasattr(lib, "stpu_format_predictions"):
        return None
    a = np.ascontiguousarray(arr, dtype=np.float32)
    if a.ndim == 1:
        a = a[None, :]
    if a.ndim != 2:
        return None
    length = ctypes.c_size_t(0)
    ptr = lib.stpu_format_predictions(
        a.ctypes.data, a.shape[0], a.shape[1], ctypes.byref(length)
    )
    if not ptr:
        return None
    s = ctypes.string_at(ptr, length.value).decode("ascii")
    lib.stpu_free(ptr)
    return s


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — Kafka record-batch v2 checksum
# ---------------------------------------------------------------------------

_CRC32C_TABLE = None


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-Python table fallback (same polynomial as crc32c.cpp)."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C over ``data`` (incremental: pass a previous result as crc)."""
    lib = _load()
    if lib is not None and hasattr(lib, "stpu_crc32c"):
        return lib.stpu_crc32c(data, len(data), crc)
    return _crc32c_py(data, crc)
