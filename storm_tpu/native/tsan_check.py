"""ThreadSanitizer check for the native layer (SURVEY.md §5.2).

Build the instrumented library and hammer every exported hot path from 8
threads under TSan:

    make -C storm_tpu/native tsan-check

Any data race prints a ``WARNING: ThreadSanitizer`` report; a clean run
ends with TSAN-HAMMER-OK. (libtsan must be LD_PRELOADed because the .so
is dlopened — the Makefile target handles that.)
"""

import ctypes, threading
import pathlib

_here = pathlib.Path(__file__).resolve().parent
lib = ctypes.CDLL(str(_here / "libstormtpu_tsan.so"))
lib.stpu_parse_instances.restype = ctypes.c_void_p
lib.stpu_parse_instances.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.POINTER(ctypes.c_int32),
                                     ctypes.POINTER(ctypes.c_char_p)]
lib.stpu_free.restype = None
lib.stpu_free.argtypes = [ctypes.c_void_p]
lib.stpu_crc32c.restype = ctypes.c_uint32
lib.stpu_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
lib.stpu_tensor_encode.restype = ctypes.c_void_p
lib.stpu_tensor_encode.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_size_t)]
payload = ('{"instances": [' + ",".join(
    "[" + ",".join("[[0.5,0.25,0.125]]" for _ in range(4)) + "]" for _ in range(8)
) + ']}').encode()
data = bytes(range(256)) * 64

def worker(n):
    import array
    shape = (ctypes.c_int64 * 8)()
    rank = ctypes.c_int32(0)
    err = ctypes.c_char_p(None)
    buf = array.array("f", [0.5] * 96)
    eshape = (ctypes.c_int64 * 8)(8, 4, 3, 0, 0, 0, 0, 0)
    elen = ctypes.c_size_t(0)
    addr, _ = buf.buffer_info()
    for _ in range(n):
        p = lib.stpu_parse_instances(payload, len(payload), shape,
                                     ctypes.byref(rank), ctypes.byref(err))
        assert p
        lib.stpu_free(p)
        lib.stpu_crc32c(data, len(data), 0)
        q = lib.stpu_tensor_encode(addr, 0, 3, eshape, ctypes.byref(elen))
        assert q
        lib.stpu_free(q)

threads = [threading.Thread(target=worker, args=(300,)) for _ in range(8)]
for t in threads: t.start()
for t in threads: t.join()
print("TSAN-HAMMER-OK: 8 threads x 300 iterations (parse+crc32c+arrow-encode)")
