// Arrow IPC tensor marshalling, no external dependencies.
//
// The reference crossed its host<->engine boundary with two JNI float-array
// copies per tuple (InferenceBolt.java:80, :86).  Here the boundary is the
// Arrow IPC Tensor message (SURVEY.md SS2.2 north star: a C++ zero-copy
// marshalling path, not a Python stand-in): this file hand-rolls the
// flatbuffer metadata for Message{version:V5, header:Tensor, bodyLength}
// and parses the same — wire-compatible with pyarrow's
// ipc.write_tensor/read_tensor in both directions (verified in
// tests/test_native.py).
//
// Encapsulated message layout (Arrow format docs):
//   [FFFFFFFF][int32 metadata_len][flatbuffer, padded][body]
// with the body 64-byte aligned from message start (matching pyarrow) and
// Buffer{offset,length} in the metadata locating the tensor bytes, so the
// decode side can hand back a pointer INTO the received buffer — zero-copy.
//
// The flatbuffer builder below is the minimal general mechanism: buffers
// build back-to-front; `pos` is the offset-from-end of an object's start;
// a uoffset field at pos P referring to target T stores P - T; a table's
// soffset stores pos(vtable) - pos(table); vtable slots store
// pos(table) - pos(field).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// Minimal flatbuffer builder (back-to-front)
// ---------------------------------------------------------------------------

constexpr size_t kFbCap = 4096;  // metadata for ndim<=8 fits in well under 1K

struct FB {
  uint8_t buf[kFbCap];
  size_t head = kFbCap;  // index of first used byte; decreases as we write

  size_t pos() const { return kFbCap - head; }

  // Pad so that a `size`-byte scalar written after `additional` more bytes
  // lands aligned to `size` (same contract as the reference builders' Prep).
  void prep(size_t size, size_t additional = 0) {
    size_t used = pos() + additional;
    size_t pad = (~used + 1) & (size - 1);
    head -= pad;
    std::memset(buf + head, 0, pad);
  }

  template <typename T>
  void push(T v) {
    prep(sizeof(T));
    head -= sizeof(T);
    std::memcpy(buf + head, &v, sizeof(T));
  }

  // Write a uoffset (u32) pointing at an object whose pos() was `target`.
  void push_uoffset(size_t target) {
    prep(4);
    head -= 4;
    uint32_t v = static_cast<uint32_t>(pos() - target);
    std::memcpy(buf + head, &v, 4);
  }

  // Vector of int64 (e.g. strides). Returns vector pos (points at count).
  size_t vec_i64(const int64_t* vals, size_t n) {
    prep(4, 8 * n);
    prep(8, 8 * n);
    for (size_t i = n; i-- > 0;) {
      head -= 8;
      std::memcpy(buf + head, &vals[i], 8);
    }
    push<uint32_t>(static_cast<uint32_t>(n));
    return pos();
  }

  // Vector of table offsets (e.g. shape: [TensorDim]).
  size_t vec_offsets(const size_t* targets, size_t n) {
    prep(4, 4 * n);
    for (size_t i = n; i-- > 0;) push_uoffset(targets[i]);
    push<uint32_t>(static_cast<uint32_t>(n));
    return pos();
  }

  // --- table construction -------------------------------------------------
  // Usage: write fields (any order), recording slots; then end_table().
  struct Slot {
    uint16_t off = 0;  // pos(table) - pos(field); patched in end_table
    size_t field_pos = 0;
    uint8_t size = 0;
    bool present = false;
  };
  Slot slots[8];
  int nslots = 0;

  void start_table(int n) {
    nslots = n;
    for (int i = 0; i < n; i++) slots[i] = Slot{};
  }

  template <typename T>
  void field_scalar(int slot, T v) {
    push<T>(v);
    slots[slot] = {0, pos(), sizeof(T), true};
  }

  void field_offset(int slot, size_t target) {
    push_uoffset(target);
    slots[slot] = {0, pos(), 4, true};
  }

  // Inline struct (e.g. Buffer{offset,length}), `align`-aligned.
  void field_struct(int slot, const void* bytes, size_t size, size_t align) {
    prep(align, 0);
    head -= size;
    std::memcpy(buf + head, bytes, size);
    slots[slot] = {0, pos(), static_cast<uint8_t>(size), true};
  }

  size_t end_table() {
    // soffset placeholder at the table start
    prep(4);
    head -= 4;
    size_t table_pos = pos();
    size_t table_idx = head;
    uint16_t table_size = 4;
    for (int i = 0; i < nslots; i++) {
      if (!slots[i].present) continue;
      slots[i].off = static_cast<uint16_t>(table_pos - slots[i].field_pos);
      uint16_t end = slots[i].off + slots[i].size;
      if (end > table_size) table_size = end;
    }
    // vtable (after the table in write order => lower address side)
    prep(2, 2 * nslots + 4);
    for (int i = nslots; i-- > 0;) {
      head -= 2;
      std::memcpy(buf + head, &slots[i].off, 2);
    }
    push<uint16_t>(table_size);
    push<uint16_t>(static_cast<uint16_t>(4 + 2 * nslots));
    size_t vt_pos = pos();
    int32_t soffset = static_cast<int32_t>(vt_pos - table_pos);
    std::memcpy(buf + table_idx, &soffset, 4);
    return table_pos;
  }

  // Finish with the root uoffset; returns the start index. Pads so the
  // total flatbuffer length is 8-aligned (min_align: we store int64 fields,
  // whose in-buffer alignment is relative to the buffer END).
  size_t finish(size_t root) {
    prep(8, 4);
    push_uoffset(root);
    return head;
  }
};

// Dtype codes shared with Python (storm_tpu/native/__init__.py).
enum DType {
  DT_F32 = 0, DT_F64 = 1, DT_F16 = 2,
  DT_U8 = 3, DT_I8 = 4, DT_U16 = 5, DT_I16 = 6,
  DT_U32 = 7, DT_I32 = 8, DT_U64 = 9, DT_I64 = 10,
};

int dtype_itemsize(int dt) {
  switch (dt) {
    case DT_U8: case DT_I8: return 1;
    case DT_F16: case DT_U16: case DT_I16: return 2;
    case DT_F32: case DT_U32: case DT_I32: return 4;
    default: return 8;
  }
}

// Arrow flatbuffer enum values (format/Schema.fbs, format/Message.fbs).
constexpr uint8_t kTypeInt = 2;            // union Type.Int
constexpr uint8_t kTypeFloatingPoint = 3;  // union Type.FloatingPoint
constexpr uint8_t kHeaderTensor = 4;       // union MessageHeader.Tensor
constexpr int16_t kMetadataV5 = 4;
constexpr int16_t kPrecisionHalf = 0, kPrecisionSingle = 1, kPrecisionDouble = 2;

// ---------------------------------------------------------------------------
// Flatbuffer reader helpers
// ---------------------------------------------------------------------------

struct Reader {
  const uint8_t* fb;
  size_t len;

  template <typename T>
  bool rd(size_t off, T* out) const {
    if (off + sizeof(T) > len) return false;
    std::memcpy(out, fb + off, sizeof(T));
    return true;
  }

  // Absolute offset of table field `slot`, or 0 if absent/out of range.
  size_t field(size_t table, int slot) const {
    int32_t soff;
    if (!rd(table, &soff)) return 0;
    size_t vt = static_cast<size_t>(static_cast<int64_t>(table) - soff);
    uint16_t vt_size;
    if (!rd(vt, &vt_size)) return 0;
    size_t slot_off = 4 + 2 * static_cast<size_t>(slot);
    if (slot_off + 2 > vt_size) return 0;
    uint16_t foff;
    if (!rd(vt + slot_off, &foff)) return 0;
    return foff ? table + foff : 0;
  }

  // Follow a uoffset stored at `at`.
  size_t indirect(size_t at) const {
    uint32_t u;
    if (!rd(at, &u)) return 0;
    return at + u;
  }
};

}  // namespace

extern "C" {

void stpu_free(void* p);  // fastjson.cpp

// Encode `data` (C-contiguous, dtype code `dtype`, shape `shape[ndim]`) as a
// full Arrow IPC tensor message. Returns a malloc'd buffer (caller frees via
// stpu_free); *out_len receives its length. NULL on bad args.
uint8_t* stpu_tensor_encode(const void* data, int dtype, int ndim,
                            const int64_t* shape, size_t* out_len) {
  if (dtype < 0 || dtype > DT_I64 || ndim < 1 || ndim > 8 || !data || !shape)
    return nullptr;
  int64_t itemsize = dtype_itemsize(dtype);
  int64_t nelem = 1;
  for (int i = 0; i < ndim; i++) {
    if (shape[i] < 0) return nullptr;
    nelem *= shape[i];
  }
  int64_t body_len = nelem * itemsize;

  FB fb;

  // Type table: Int{bitWidth,is_signed} or FloatingPoint{precision}.
  size_t type_tbl;
  uint8_t type_type;
  if (dtype == DT_F16 || dtype == DT_F32 || dtype == DT_F64) {
    type_type = kTypeFloatingPoint;
    int16_t prec = dtype == DT_F16   ? kPrecisionHalf
                   : dtype == DT_F32 ? kPrecisionSingle
                                     : kPrecisionDouble;
    fb.start_table(1);
    fb.field_scalar<int16_t>(0, prec);
    type_tbl = fb.end_table();
  } else {
    type_type = kTypeInt;
    bool is_signed = dtype == DT_I8 || dtype == DT_I16 || dtype == DT_I32 ||
                     dtype == DT_I64;
    fb.start_table(2);
    fb.field_scalar<uint8_t>(1, is_signed ? 1 : 0);
    fb.field_scalar<int32_t>(0, static_cast<int32_t>(8 * itemsize));
    type_tbl = fb.end_table();
  }

  // shape: [TensorDim{size}]  (name omitted — optional field)
  size_t dims[8];
  for (int i = 0; i < ndim; i++) {
    fb.start_table(2);
    fb.field_scalar<int64_t>(0, shape[i]);
    dims[i] = fb.end_table();
  }
  size_t shape_vec = fb.vec_offsets(dims, ndim);

  // strides (bytes, row-major contiguous) — pyarrow writes them, so do we.
  int64_t strides[8];
  int64_t acc = itemsize;
  for (int i = ndim; i-- > 0;) {
    strides[i] = acc;
    acc *= shape[i];
  }
  size_t strides_vec = fb.vec_i64(strides, ndim);

  // Tensor table: type_type(0), type(1), shape(2), strides(3), data(4)
  int64_t buffer_struct[2] = {0, body_len};  // Buffer{offset,length}
  fb.start_table(5);
  fb.field_struct(4, buffer_struct, 16, 8);
  fb.field_offset(3, strides_vec);
  fb.field_offset(2, shape_vec);
  fb.field_offset(1, type_tbl);
  fb.field_scalar<uint8_t>(0, type_type);
  size_t tensor_tbl = fb.end_table();

  // Message table: version(0), header_type(1), header(2), bodyLength(3)
  fb.start_table(4);
  fb.field_scalar<int64_t>(3, body_len);
  fb.field_offset(2, tensor_tbl);
  fb.field_scalar<uint8_t>(1, kHeaderTensor);
  fb.field_scalar<int16_t>(0, kMetadataV5);
  size_t msg_tbl = fb.end_table();

  size_t start = fb.finish(msg_tbl);
  size_t fb_len = kFbCap - start;

  // Pad metadata so the body starts 64-aligned from message start (pyarrow
  // convention; readers only require the metadata_len bookkeeping).
  size_t meta_len = (8 + fb_len + 63) & ~size_t{63};
  meta_len -= 8;
  size_t total = 8 + meta_len + static_cast<size_t>(body_len);

  uint8_t* out = static_cast<uint8_t*>(std::malloc(total));
  if (!out) return nullptr;
  uint32_t cont = 0xFFFFFFFFu;
  std::memcpy(out, &cont, 4);
  int32_t ml = static_cast<int32_t>(meta_len);
  std::memcpy(out + 4, &ml, 4);
  std::memcpy(out + 8, fb.buf + start, fb_len);
  std::memset(out + 8 + fb_len, 0, meta_len - fb_len);
  std::memcpy(out + 8 + meta_len, data, static_cast<size_t>(body_len));
  *out_len = total;
  return out;
}

// Parse an Arrow IPC tensor message. On success returns 0 and fills dtype,
// ndim, shape[8], body_off/body_len (byte range of the tensor data INSIDE
// `buf` — the caller can view it zero-copy). Nonzero on malformed input,
// non-tensor messages, or non-contiguous strides.
int stpu_tensor_decode(const uint8_t* buf, size_t len, int* dtype, int* ndim,
                       int64_t* shape, size_t* body_off, size_t* body_len) {
  if (!buf || len < 16) return 1;
  uint32_t cont;
  std::memcpy(&cont, buf, 4);
  size_t meta_at = 4;
  if (cont != 0xFFFFFFFFu) {
    // pre-0.15 framing: no continuation marker, metadata length first
    meta_at = 0;
  }
  int32_t meta_len;
  std::memcpy(&meta_len, buf + meta_at, 4);
  size_t fb_start = meta_at + 4;
  if (meta_len <= 0 || fb_start + static_cast<size_t>(meta_len) > len) return 2;
  Reader r{buf + fb_start, static_cast<size_t>(meta_len)};

  size_t root = r.indirect(0);
  if (!root) return 3;
  uint8_t header_type = 0;
  size_t f = r.field(root, 1);
  if (!f || !r.rd(f, &header_type) || header_type != kHeaderTensor) return 4;
  f = r.field(root, 2);
  if (!f) return 5;
  size_t tensor = r.indirect(f);
  int64_t body_length = 0;
  f = r.field(root, 3);
  if (f) r.rd(f, &body_length);

  // Tensor.type
  uint8_t type_type = 0;
  f = r.field(tensor, 0);
  if (!f || !r.rd(f, &type_type)) return 6;
  f = r.field(tensor, 1);
  if (!f) return 6;
  size_t type_tbl = r.indirect(f);
  int dt;
  if (type_type == kTypeFloatingPoint) {
    // Omitted field means the schema default (0 = HALF), not SINGLE.
    int16_t prec = kPrecisionHalf;
    f = r.field(type_tbl, 0);
    if (f) r.rd(f, &prec);
    dt = prec == kPrecisionHalf ? DT_F16 : prec == kPrecisionDouble ? DT_F64 : DT_F32;
  } else if (type_type == kTypeInt) {
    int32_t bits = 0;
    uint8_t is_signed = 0;
    f = r.field(type_tbl, 0);
    if (f) r.rd(f, &bits);
    f = r.field(type_tbl, 1);
    if (f) r.rd(f, &is_signed);
    switch (bits) {
      case 8: dt = is_signed ? DT_I8 : DT_U8; break;
      case 16: dt = is_signed ? DT_I16 : DT_U16; break;
      case 32: dt = is_signed ? DT_I32 : DT_U32; break;
      case 64: dt = is_signed ? DT_I64 : DT_U64; break;
      default: return 100;  // valid Arrow, not viewable raw -> fall back
    }
  } else {
    return 100;  // unsupported element type (e.g. Decimal) -> fall back
  }
  int64_t itemsize = dtype_itemsize(dt);

  // Tensor.shape
  f = r.field(tensor, 2);
  if (!f) return 8;
  size_t shape_vec = r.indirect(f);
  uint32_t n;
  if (!r.rd(shape_vec, &n)) return 8;
  // Rank 0 or >8 is valid Arrow but outside this fast path's shape buffer —
  // signal fallback, not corruption.
  if (n < 1 || n > 8) return 100;
  int64_t nelem = 1;
  for (uint32_t i = 0; i < n; i++) {
    size_t dim_tbl = r.indirect(shape_vec + 4 + 4 * i);
    if (!dim_tbl) return 8;
    int64_t sz = 0;
    size_t sf = r.field(dim_tbl, 0);
    if (sf) r.rd(sf, &sz);
    if (sz < 0) return 8;
    shape[i] = sz;
    // Adversarial metadata must not overflow nelem*itemsize into a "valid"
    // body range (the decode output is a raw view over the buffer).
    if (__builtin_mul_overflow(nelem, sz, &nelem)) return 8;
  }
  int64_t nbytes;
  if (__builtin_mul_overflow(nelem, itemsize, &nbytes)) return 8;

  // Tensor.strides — the body is handed back as a raw view, so only
  // C-contiguous layouts are supported. Valid-but-unsupported layouts
  // (e.g. Fortran order) return the distinct STPU_TENSOR_UNSUPPORTED so the
  // caller can fall back to a general reader rather than reject the message.
  f = r.field(tensor, 3);
  if (f) {
    size_t sv = r.indirect(f);
    uint32_t sn;
    if (!r.rd(sv, &sn) || sn != n) return 9;
    int64_t acc = itemsize;
    for (uint32_t i = n; i-- > 0;) {
      int64_t got;
      if (!r.rd(sv + 4 + 8 * i, &got)) return 9;
      if (shape[i] > 1 && got != acc) return 100;  // STPU_TENSOR_UNSUPPORTED
      acc *= shape[i];
    }
  }

  // Tensor.data: Buffer{offset,length} struct, relative to body start.
  f = r.field(tensor, 4);
  if (!f) return 10;
  int64_t buf_off, buf_len;
  if (!r.rd(f, &buf_off) || !r.rd(f + 8, &buf_len)) return 10;
  if (buf_off < 0 || buf_len < nbytes) return 10;
  // The data range must sit inside the declared message body too, not just
  // inside the raw buffer (a writer's Buffer and bodyLength must agree).
  if (body_length > 0 &&
      (buf_off > body_length || buf_len > body_length - buf_off))
    return 10;
  size_t body_start = fb_start + static_cast<size_t>(meta_len);
  size_t off = body_start + static_cast<size_t>(buf_off);
  if (off > len || static_cast<size_t>(nbytes) > len - off) return 11;

  *dtype = dt;
  *ndim = static_cast<int>(n);
  *body_off = off;
  *body_len = static_cast<size_t>(nbytes);
  return 0;
}

}  // extern "C"
