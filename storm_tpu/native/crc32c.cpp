// CRC32C (Castagnoli, reflected polynomial 0x82F63B78) — the checksum of
// Kafka's record-batch format v2 (KIP-98). Table-driven, 4-way slicing;
// ~1.5 GB/s, far above broker link rates. Exposed to Python via ctypes
// (storm_tpu/native/__init__.py) with a pure-Python table fallback.

#include <cstdint>
#include <cstddef>

namespace {

struct Tables {
  uint32_t t[4][256];
  Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
      t[1][i] = (t[0][i] >> 8) ^ t[0][t[0][i] & 0xFF];
      t[2][i] = (t[1][i] >> 8) ^ t[0][t[1][i] & 0xFF];
      t[3][i] = (t[2][i] >> 8) ^ t[0][t[2][i] & 0xFF];
    }
  }
};

const Tables kTables;

}  // namespace

extern "C" {

// Incremental: pass the previous return value as `crc` to continue
// (initial call: crc = 0).
uint32_t stpu_crc32c(const uint8_t* buf, size_t len, uint32_t crc) {
  crc = ~crc;
  const uint32_t (*t)[256] = kTables.t;
  while (len >= 4) {
    crc ^= static_cast<uint32_t>(buf[0]) | (static_cast<uint32_t>(buf[1]) << 8) |
           (static_cast<uint32_t>(buf[2]) << 16) | (static_cast<uint32_t>(buf[3]) << 24);
    crc = t[3][crc & 0xFF] ^ t[2][(crc >> 8) & 0xFF] ^
          t[1][(crc >> 16) & 0xFF] ^ t[0][crc >> 24];
    buf += 4;
    len -= 4;
  }
  while (len--) crc = (crc >> 8) ^ t[0][(crc ^ *buf++) & 0xFF];
  return ~crc;
}

}  // extern "C"
