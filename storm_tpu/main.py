"""CLI entry point.

The reference's CLI is ``storm jar ... dke.model.MainTopology <name>
<inputTopic> <outputTopic>`` with cluster endpoints hard-coded in source and
a fixed 1-hour run window ending in a hard kill (MainTopology.java:32-42,
:71-77). Equivalent here, minus the quirks::

    python -m storm_tpu.main run <name> <input-topic> <output-topic> \
        [--config cfg.toml] [--set section.key=value ...] [--duration SECS]

    python -m storm_tpu.main serve --model resnet20 --port 50051

    python -m storm_tpu.main info

``run`` builds the reference topology shape (spout -> inference -> sink,
plus a dead-letter sink) and runs as a daemon: SIGINT/SIGTERM (or
--duration) triggers deactivate -> drain -> kill, the graceful teardown the
reference lacked. ``serve`` starts the standalone gRPC TPU worker."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time

from storm_tpu.config import Config
from storm_tpu.utils.logging import setup_logging


def _make_sink(cfg: Config, broker, topic):
    from storm_tpu.connectors import BrokerSink, TransactionalBrokerSink

    if cfg.sink.mode == "transactional":
        return TransactionalBrokerSink(broker, topic, cfg.sink)
    return BrokerSink(broker, topic, cfg.sink)


def build_standard_topology(cfg: Config, broker):
    """The reference DAG (MainTopology.java:59-63) under our runtime."""
    from storm_tpu.connectors import BrokerSpout
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import TopologyBuilder

    # QoS (config.qos): the spout classifies/admits records and emits the
    # lane; the operator carries it through to the sink (per-lane e2e
    # histograms) via passthrough.
    qos = cfg.qos if cfg.qos.enabled else None
    # Confidence-gated cascade (config.cascade): tiered serving inside the
    # inference bolt — cheap tiers accept the easy records, only the
    # low-confidence residue escalates to the flagship.
    cascade = cfg.cascade if cfg.cascade.enabled else None
    tb = TopologyBuilder()
    tb.set_spout(
        "kafka-spout",
        BrokerSpout(broker, cfg.broker.input_topic, cfg.offsets,
                    chunk=cfg.topology.spout_chunk,
                    scheme=cfg.topology.spout_scheme,
                    qos=qos, frames=cfg.topology.spout_frames),
        parallelism=cfg.topology.spout_parallelism,
    )
    tb.set_bolt(
        "inference-bolt",
        InferenceBolt(cfg.model, cfg.batch, cfg.sharding, qos=qos,
                      cascade=cascade,
                      passthrough=("qos_lane",) if qos else ()),
        parallelism=cfg.topology.inference_parallelism,
    ).shuffle_grouping("kafka-spout")
    tb.set_bolt(
        "kafka-bolt",
        _make_sink(cfg, broker, cfg.broker.output_topic),
        parallelism=cfg.topology.sink_parallelism,
    ).shuffle_grouping("inference-bolt")
    tb.set_bolt(
        "dlq-bolt",
        _make_sink(cfg, broker, cfg.broker.dead_letter_topic),
        parallelism=1,
    ).shuffle_grouping("inference-bolt", stream="dead_letter")
    return tb.build()


def build_null_engine_topology(cfg: Config, broker):
    """The standard DAG with a :class:`NullEngine` in the inference slot.

    No device work, no XLA compile: predictions are a uniform distribution
    computed instantly, so everything measured is framework cost — spout
    decode, routing, ledger, the inter-worker wire. This is the
    framework-ceiling topology the wire bench (``bench.py --wire-compare``)
    submits; registered as builder name ``"null"`` so dist workers can
    rebuild it from the recipe.
    """
    from storm_tpu.connectors import BrokerSpout
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.infer.engine import NullEngine
    from storm_tpu.runtime import TopologyBuilder

    qos = cfg.qos if cfg.qos.enabled else None
    cascade = cfg.cascade if cfg.cascade.enabled else None
    engine = NullEngine(cfg.model.input_shape, cfg.model.num_classes)
    tb = TopologyBuilder()
    tb.set_spout(
        "kafka-spout",
        BrokerSpout(broker, cfg.broker.input_topic, cfg.offsets,
                    chunk=cfg.topology.spout_chunk,
                    scheme=cfg.topology.spout_scheme,
                    qos=qos, frames=cfg.topology.spout_frames),
        parallelism=cfg.topology.spout_parallelism,
    )
    tb.set_bolt(
        "inference-bolt",
        InferenceBolt(cfg.model, cfg.batch, cfg.sharding, engine=engine,
                      warmup=False, qos=qos, cascade=cascade,
                      passthrough=("qos_lane",) if qos else ()),
        parallelism=cfg.topology.inference_parallelism,
    ).shuffle_grouping("kafka-spout")
    tb.set_bolt(
        "kafka-bolt",
        _make_sink(cfg, broker, cfg.broker.output_topic),
        parallelism=cfg.topology.sink_parallelism,
    ).shuffle_grouping("inference-bolt")
    tb.set_bolt(
        "dlq-bolt",
        _make_sink(cfg, broker, cfg.broker.dead_letter_topic),
        parallelism=1,
    ).shuffle_grouping("inference-bolt", stream="dead_letter")
    return tb.build()


def build_multi_model_topology(cfg: Config, broker):
    """One spout -> inference -> sink chain per ``cfg.pipelines`` entry, all
    inside a single topology sharing one process and one TPU slice
    (BASELINE.json config 5). Each pipeline has its own model/batch/sharding
    and topics; component ids are namespaced by pipeline name. Engines are
    cached per model by :func:`storm_tpu.infer.engine.shared_engine`, so two
    pipelines running the same model share params in HBM while different
    models are co-resident."""
    from storm_tpu.connectors import BrokerSink, BrokerSpout
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import TopologyBuilder

    if not cfg.pipelines:
        raise ValueError("build_multi_model_topology needs cfg.pipelines")
    qos = cfg.qos if cfg.qos.enabled else None  # shared across pipelines
    cascade = cfg.cascade if cfg.cascade.enabled else None
    tb = TopologyBuilder()
    for p in cfg.pipelines:
        spout_id = f"{p.name}-spout"
        infer_id = f"{p.name}-inference"
        tb.set_spout(
            spout_id,
            BrokerSpout(broker, p.input_topic, p.offsets,
                        chunk=p.spout_chunk or cfg.topology.spout_chunk,
                        scheme=p.spout_scheme or cfg.topology.spout_scheme,
                        qos=qos,
                        frames=(cfg.topology.spout_frames
                                and (p.spout_scheme
                                     or cfg.topology.spout_scheme) == "raw")),
            parallelism=p.spout_parallelism,
        )
        tb.set_bolt(
            infer_id,
            InferenceBolt(p.model, p.batch, p.sharding, qos=qos,
                          cascade=cascade,
                          passthrough=("qos_lane",) if qos else ()),
            parallelism=p.inference_parallelism,
        ).shuffle_grouping(spout_id)
        tb.set_bolt(
            f"{p.name}-sink",
            BrokerSink(broker, p.output_topic, cfg.sink),
            parallelism=p.sink_parallelism,
        ).shuffle_grouping(infer_id)
        tb.set_bolt(
            f"{p.name}-dlq",
            BrokerSink(broker, p.dead_letter_topic, cfg.sink),
            parallelism=1,
        ).shuffle_grouping(infer_id, stream="dead_letter")
    return tb.build()


def _make_broker(cfg: Config):
    if cfg.broker.kind == "memory":
        from storm_tpu.connectors import MemoryBroker

        return MemoryBroker(default_partitions=cfg.broker.partitions)
    if cfg.broker.kind == "kafka":
        # Pure-Python wire-protocol client — no client library required.
        from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

        return KafkaWireBroker(cfg.broker.bootstrap,
                               message_format=cfg.broker.message_format,
                               compression=cfg.broker.compression,
                               idempotent=cfg.broker.idempotent,
                               isolation=cfg.broker.isolation,
                               security=cfg.broker.security_dict())
    raise ValueError(f"unknown broker kind {cfg.broker.kind!r}")


def _load_config(args) -> Config:
    cfg = Config.load(args.config) if args.config else Config()
    if args.set:
        cfg.apply_overrides(args.set)
    return cfg


async def _run_daemon(name: str, cfg: Config, duration: float,
                      autoscale_target_ms: float = 0.0,
                      ui_port: int = -1,
                      metrics_file: str = "",
                      metrics_interval_s: float = 10.0,
                      topology_file: str = "") -> None:
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    broker = _make_broker(cfg)
    if topology_file:
        from storm_tpu.flux import load_topology

        topo = load_topology(topology_file, resources={"broker": broker})
        desc = f"flux:{topology_file}"
    elif cfg.pipelines:
        topo = build_multi_model_topology(cfg, broker)
        desc = "+".join(p.model.name for p in cfg.pipelines)
    else:
        topo = build_standard_topology(cfg, broker)
        desc = cfg.model.name
    cluster = AsyncLocalCluster()
    rt = await cluster.submit(name, cfg, topo)
    if metrics_file:
        from storm_tpu.runtime.metrics import JsonLinesConsumer

        rt.add_metrics_consumer(JsonLinesConsumer(metrics_file),
                                interval_s=metrics_interval_s)
    # One control pair per inference/sink chain: the standard topology has
    # one; a multi-model topology has one per pipeline.
    pairs = (
        [(f"{p.name}-inference", f"{p.name}-sink") for p in cfg.pipelines]
        if cfg.pipelines
        else [("inference-bolt", "kafka-bolt")]
    )
    shedders = []
    if cfg.qos.enabled and not topology_file:
        from storm_tpu.qos import LoadShedController, ShedPolicy

        # The shed loop runs faster than the autoscaler (1 s vs 5 s
        # default) and is handed to it below: shed first, scale second.
        shedders = [
            LoadShedController(
                rt, ShedPolicy.from_qos(cfg.qos, infer_id, sink_id)).start()
            for infer_id, sink_id in pairs
        ]
    observatory = None
    if cfg.obs.enabled and not topology_file:
        from storm_tpu.obs import Observatory

        # Burn is computed over ALL sink components (one per pipeline);
        # the trip feeds every shedder as an extra hot signal.
        observatory = Observatory(
            rt, cfg.obs,
            sink_components=tuple(sink_id for _, sink_id in pairs)).start()
        for shedder in shedders:
            shedder.burn = observatory.burn
        if cfg.plan.enabled:
            from storm_tpu.plan import PlanCorrector

            # Online half of the planner: stepped by the Observatory
            # loop, consumes this topology's verdict + burn state, and
            # (below) makes the autoscalers defer their global scale-up.
            observatory.corrector = PlanCorrector(
                rt, cfg.plan, attributor=observatory.bottleneck,
                burn=observatory.burn)
    scalers = []
    if autoscale_target_ms > 0:
        from storm_tpu.runtime.autoscale import (
            ACCEL_MAX_PARALLELISM,
            Autoscaler,
            AutoscalePolicy,
        )

        # The inference operator fronts a batching accelerator, so ITS
        # policy carries the measured inversion cap (not the global
        # dataclass default).
        scalers = [
            Autoscaler(
                rt,
                AutoscalePolicy(
                    component=infer_id,
                    latency_source=sink_id,
                    high_ms=autoscale_target_ms,
                    low_ms=autoscale_target_ms / 4,
                    max_parallelism=ACCEL_MAX_PARALLELISM,
                ),
                shedder=shedders[i] if shedders else None,
            ).start()
            for i, (infer_id, sink_id) in enumerate(pairs)
        ]
        if observatory is not None:
            # Bottleneck verdicts become a scale-up signal: a scaler
            # whose component is the NAMED bottleneck at capacity goes
            # hot even before the latency policy trips.
            for scaler in scalers:
                scaler.bottleneck = observatory.bottleneck
                scaler.corrector = observatory.corrector
    ui = None
    if ui_port >= 0:
        from storm_tpu.runtime.ui import UIServer

        # remote submission gets the daemon's broker as $broker
        ui = await UIServer(cluster, port=ui_port,
                            resources={"broker": broker},
                            auth_token=cfg.control.resolve_token()).start()
    print(f"topology {name!r} running "
          f"(model={desc}, broker={cfg.broker.kind}"
          f"{', qos' if shedders else ''}"
          f"{', obs' if observatory else ''}"
          f"{', plan' if observatory and observatory.corrector else ''}"
          f"{', autoscaling' if scalers else ''}"
          f"{f', ui http://127.0.0.1:{ui.port}' if ui else ''})",
          file=sys.stderr)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    if duration > 0:
        loop.call_later(duration, stop.set)
    await stop.wait()

    print("draining...", file=sys.stderr)
    if ui is not None:
        await ui.stop()
    for scaler in scalers:
        await scaler.stop()
    if observatory is not None:
        await observatory.stop()
    for shedder in shedders:
        await shedder.stop()
    await rt.deactivate()
    await rt.drain(timeout_s=30)
    snap = rt.metrics.snapshot()
    await cluster.kill(name, wait_secs=0)
    print(json.dumps(snap, default=str), file=sys.stderr)


def _ctl(args) -> int:
    """Drive a running daemon's UI HTTP API from the command line."""
    import os
    import urllib.error
    import urllib.parse
    import urllib.request

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(getattr(args, "topology", ""), safe="")
    # Admin auth (control.auth_token on the daemon): --token wins, else
    # the shared control-plane env fallback.
    from storm_tpu.config import env_control_token

    token = getattr(args, "token", None) or env_control_token()

    def call(method, path, body=None, timeout=30, headers=None):
        req = urllib.request.Request(
            base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return 0, json.loads(r.read())
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                return 1, json.loads(raw)
            except ValueError:
                # not our daemon (proxy error page etc.): show what came back
                return 1, {"error": f"HTTP {e.code} from {base}",
                           "body": raw[:500].decode("utf-8", "replace")}
        except urllib.error.URLError as e:
            print(f"cannot reach {base}: {e}", file=sys.stderr)
            raise SystemExit(2)

    cmd = args.ctl_cmd
    if cmd == "list":
        rc, out = call("GET", "/api/v1/topology/summary")
    elif cmd == "status":
        rc, out = call("GET", f"/api/v1/topology/{topo}")
    elif cmd in ("metrics", "graph", "errors"):
        rc, out = call("GET", f"/api/v1/topology/{topo}/{cmd}")
    elif cmd == "component":
        import urllib.parse as _up

        rc, out = call("GET", f"/api/v1/topology/{topo}/component/"
                              f"{_up.quote(args.component, safe='')}")
    elif cmd in ("activate", "deactivate"):
        rc, out = call("POST", f"/api/v1/topology/{topo}/{cmd}")
    elif cmd == "drain":
        # client timeout comfortably beyond the server's drain wait, or a
        # slow drain would look like a connectivity failure
        rc, out = call("POST", f"/api/v1/topology/{topo}/drain",
                       {"timeout_s": 30.0}, timeout=60)
    elif cmd == "kill":
        rc, out = call("POST", f"/api/v1/topology/{topo}/kill",
                       {"wait_secs": args.wait_secs})
    elif cmd == "rebalance":
        rc, out = call("POST", f"/api/v1/topology/{topo}/rebalance",
                       {"component": args.component,
                        "parallelism": args.parallelism})
    elif cmd == "seek":
        from storm_tpu.connectors.spout import parse_seek_position

        try:
            pos = parse_seek_position(args.position)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        rc, out = call("POST", f"/api/v1/topology/{topo}/seek",
                       {"component": args.component, "position": pos})
    elif cmd == "profile":
        rc, out = call("POST", f"/api/v1/topology/{topo}/profile",
                       {"log_dir": args.log_dir, "seconds": args.seconds,
                        "worker": args.worker})
    elif cmd == "swap-model":
        overrides = {}
        for kv in args.set:
            if "=" not in kv:
                print(f"--set needs key=value, got {kv!r}", file=sys.stderr)
                return 2
            k, v = kv.split("=", 1)
            try:
                overrides[k] = json.loads(v)  # numbers/bools/lists/null
            except ValueError:
                overrides[k] = v  # bare string (checkpoint paths etc.)
        # Engine warmup happens inside this call; give it compile time.
        body = {"component": args.component, "model": overrides}
        if args.task:
            body["tasks"] = args.task
        rc, out = call("POST", f"/api/v1/topology/{topo}/swap_model",
                       body, timeout=600)
    elif cmd == "logs":
        rc, out = call(
            "GET",
            f"/api/v1/topology/{topo}/logs"
            f"?worker={args.worker}&bytes={args.bytes}")
        if rc == 0:
            print(out.get("log", ""))
            return 0
    elif cmd == "submit":
        from storm_tpu.flux import _load_spec

        rc, out = call("POST", "/api/v1/topology/submit",
                       {"name": args.topology,
                        "definition": _load_spec(args.definition)},
                       headers={"X-Storm-Tpu-Submit": "1"})
    print(json.dumps(out, indent=2, default=str))
    return rc


def _traces(args) -> int:
    """Dump slowest-N traces / flight-recorder tail from a running
    topology's UI endpoint (storm_tpu traces <topology>)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(args.topology, safe="")
    action = "flight" if args.flight else "traces"
    req = urllib.request.Request(
        f"{base}/api/v1/topology/{topo}/{action}?n={args.n}")
    token = args.token or env_control_token()
    if token:  # read route is open; header is harmless if unneeded
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8", "replace"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    if args.flight:
        for ev in out.get("flight", []):
            extra = {k: v for k, v in ev.items() if k not in ("ts", "kind")}
            print(f"{ev.get('ts')} {ev.get('kind'):<18} "
                  + " ".join(f"{k}={v}" for k, v in extra.items()))
        return 0
    order = "recent" if args.recent else "slowest"
    for rec in out.get(order, []):
        print(f"trace {rec['trace_id']}  "
              f"duration={rec.get('duration_ms')}ms  "
              f"opened_at={rec.get('opened_at')}")
        for s in rec.get("spans", []):
            attrs = s.get("attrs") or {}
            links = s.get("links") or []
            parts = [f"  +{s.get('offset_ms'):>9}ms {s['name']:<15} "
                     f"{s.get('duration_ms'):>9}ms  {s.get('component', '')}"]
            if attrs:
                parts.append(" " + " ".join(f"{k}={v}"
                                            for k, v in attrs.items()))
            if links:
                parts.append(f" links={len(links)}")
            print("".join(parts))
    stats = out.get("stats")
    if stats:
        print(f"store: {json.dumps(stats, default=str)}", file=sys.stderr)
    return 0


def _profile_cmd(args) -> int:
    """Dump the live cost model (per-engine per-bucket stage curves,
    compile costs, SLO burn, occupancy) from a running topology's UI
    endpoint (storm_tpu profile <topology>) — the queryable face of
    storm_tpu/obs, mirroring the traces/flight CLI."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(args.topology, safe="")
    req = urllib.request.Request(f"{base}/api/v1/topology/{topo}/profile")
    token = args.token or env_control_token()
    if token:  # read route is open; header is harmless if unneeded
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8", "replace"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    engines = out.get("profile", {}).get("engines", {})
    if not engines:
        print("no profiled batches yet (profiler records on dispatch; "
              "send traffic first)")
    for key, eng in engines.items():
        print(f"engine {key}")
        for bucket, row in eng.get("buckets", {}).items():
            st = row.get("stages", {})
            dev = st.get("device_ms", {})
            parts = [f"  bucket {bucket:>6}: batches={row['batches']:<6}"
                     f" rows={row['rows']:<8}"
                     f" device p50={dev.get('p50')}ms p95={dev.get('p95')}ms"
                     f" ms/row={row.get('ms_per_row')}"
                     f" thr={row.get('throughput_rows_s')} rows/s"]
            print("".join(parts))
        for shape, c in eng.get("compiles", {}).items():
            print(f"  compile bucket {shape}: n={c['count']} "
                  f"last={round(c['last_ms'], 1)}ms")
    slo = out.get("slo")
    if slo:
        print(f"slo: fast_burn={slo.get('fast_burn')} "
              f"slow_burn={slo.get('slow_burn')} "
              f"tripped={slo.get('tripped')} trips={slo.get('trips')}")
    for row in out.get("occupancy", []) or []:
        print(f"occupancy {row['engine']}: "
              f"ring {row['ring_inflight']}/{row['ring_capacity']} "
              f"staging {row['staging_in_use']}/{row['staging_allocated']} "
              f"queue depth={row['queue_depth']} "
              f"oldest={row['queue_oldest_ms']}ms")
    regs = out.get("regressions") or []
    for r in regs:
        print(f"REGRESSION {r['engine']} bucket {r['bucket']} {r['stage']}: "
              f"{r['live_ms']}ms vs baseline {r['baseline_ms']}ms "
              f"(x{r['ratio']})")
    return 0


def _scorecard_cmd(args) -> int:
    """Render the fleet scenario-matrix scorecard (storm_tpu/loadgen):
    one row per (scenario, traffic pattern) cell with goodput, protected-
    lane p99, burn, shed fraction, the bottleneck verdict, and the
    declared-target pass/fail. Offline mode (``--file``) renders a
    committed SCORECARD_*.json; online mode queries the /scorecard route
    the fleet driver attaches mid-run."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token
    from storm_tpu.loadgen.scorecard import render_table

    if args.file:
        with open(args.file) as f:
            out = json.load(f)
    else:
        if not args.topology:
            print("scorecard: give a topology name or --file "
                  "SCORECARD_*.json", file=sys.stderr)
            return 2
        base = args.url.rstrip("/")
        topo = urllib.parse.quote(args.topology, safe="")
        req = urllib.request.Request(
            f"{base}/api/v1/topology/{topo}/scorecard")
        token = args.token or env_control_token()
        if token:  # read route is open; header is harmless if unneeded
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.loads(r.read())
        except urllib.error.HTTPError as e:
            print(e.read().decode("utf-8", "replace"), file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"cannot reach {base}: {e}", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    print(render_table(out))
    if out.get("in_progress"):
        print("(matrix still running: cells land as they are scored)")
    return 0


def _bottleneck_cmd(args) -> int:
    """Render the bottleneck observatory's verdict from a running
    topology's UI endpoint (storm-tpu bottleneck <topology>): ranked
    per-component capacity table, edge lag watermarks, and the
    critical-path latency decomposition. Against a dist UI the table is
    the controller-merged per-worker utilization (no attributor runs
    cross-worker)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(args.topology, safe="")
    req = urllib.request.Request(f"{base}/api/v1/topology/{topo}/bottleneck")
    token = args.token or env_control_token()
    if token:  # read route is open; header is harmless if unneeded
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8", "replace"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    verdict = out.get("bottleneck") or {}
    leader = verdict.get("leader")
    print(f"bottleneck: {leader if leader else '(none above threshold)'}")
    ranked = verdict.get("ranked") or []
    util = out.get("utilization") or {}
    if ranked:
        print(f"{'component':<24} {'score':>6} {'cap':>6} {'busy':>6} "
              f"{'wait':>6} {'inflow':>8}  reasons")
        for row in ranked:
            print(f"{row['component']:<24} {row['score']:>6} "
                  f"{_fmt(row.get('capacity')):>6} "
                  f"{_fmt(row.get('busy_frac')):>6} "
                  f"{_fmt(row.get('wait_frac')):>6} "
                  f"{_fmt(row.get('inflow_growth_per_s')):>8}  "
                  f"{','.join(row.get('reasons') or []) or '-'}")
    elif util:
        # dist view (or local before the first Observatory tick): plain
        # merged utilization table, no scores
        print(f"{'component':<24} {'cap':>6} {'busy':>6} {'wait':>6} "
              f"{'flush':>6} {'tasks':>5}  workers")
        for comp, row in util.items():
            print(f"{comp:<24} {_fmt(row.get('capacity')):>6} "
                  f"{_fmt(row.get('busy_frac')):>6} "
                  f"{_fmt(row.get('wait_frac')):>6} "
                  f"{_fmt(row.get('flush_frac')):>6} "
                  f"{row.get('tasks', '?'):>5}  "
                  f"{row.get('workers', '-')}")
    else:
        print("no utilization window yet (obs enabled? traffic flowing?)")
    for row in verdict.get("edges") or []:
        print(f"edge {row['edge']:<30} depth={row['depth']:<6} "
              f"growth={_fmt(row['growth_per_s'])}/s")
    for row in verdict.get("ingress") or []:
        print(f"ingress {row['component']}[{row['task']}]: "
              f"behind={row['records_behind']} "
              f"partitions={row['partitions']}")
    cp = verdict.get("critical_path") or {}
    stages = cp.get("stages") or {}
    if stages:
        print(f"critical path (e2e mean={cp.get('e2e_mean_ms')}ms "
              f"p95={cp.get('e2e_p95_ms')}ms, n={cp.get('records')}):")
        for name, st in stages.items():
            sub = st.get("substages_ms")
            extra = f"  {sub}" if sub else ""
            print(f"  {name:<26} {_fmt(st.get('mean_ms')):>9}ms "
                  f"frac={_fmt(st.get('frac_of_e2e'))}{extra}")
    return 0


def _copies_cmd(args) -> int:
    """Render the data-plane copy ledger from a running topology's UI
    endpoint (storm-tpu copies <topology>): per-stage bytes/record and
    copies/record ranked by bytes moved, plus the derived copy
    amplification ratio (bytes moved / payload bytes ingested). Against
    a dist UI the tree is the controller-merged per-worker window."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(args.topology, safe="")
    req = urllib.request.Request(f"{base}/api/v1/topology/{topo}/copies")
    token = args.token or env_control_token()
    if token:  # read route is open; header is harmless if unneeded
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8", "replace"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    # Dist route ships the merged window as "copies"; the local route
    # ships cumulative totals (always populated) + the Observatory's
    # latest window.
    tree = out.get("copies") or out.get("cumulative") or {}
    stages = tree.get("stages") or {}
    if not stages:
        print("no copy-ledger rows yet (record path idle? ledger "
              "disabled via set_enabled(False)?)")
        return 0
    amp = tree.get("copy_amplification")
    totals = tree.get("totals") or {}
    print(f"copy amplification: {amp if amp is not None else '-'} "
          f"(moved {_fmt(totals.get('bytes'))}B / ingested "
          f"{_fmt(totals.get('ingest_bytes'))}B over "
          f"{totals.get('ingest_records', 0)} records)")
    print(f"{'stage':<16} {'B/rec':>10} {'copies/rec':>10} "
          f"{'bytes':>12} {'copies':>8} {'allocs':>8} {'records':>9}  "
          f"engines")
    ranked = sorted(
        stages.items(),
        key=lambda kv: -(kv[1].get("bytes") or 0.0))
    for stage, row in ranked:
        engines = ",".join(sorted(row.get("engines") or {})) or "-"
        print(f"{stage:<16} {_fmt(row.get('bytes_per_record')):>10} "
              f"{_fmt(row.get('copies_per_record')):>10} "
              f"{_fmt(row.get('bytes')):>12} {row.get('copies', 0):>8} "
              f"{row.get('allocs', 0):>8} {row.get('records', 0):>9}  "
              f"{engines}")
    win = out.get("window") or {}
    wamp = win.get("copy_amplification")
    if wamp is not None:
        print(f"window: amplification={wamp} over {win.get('dt_s')}s "
              f"(obs step loop)")
    ceiling = out.get("amp_ceiling")
    if ceiling:
        print(f"ceiling: copy_amplification_high fires past "
              f"{ceiling} (obs.copy_amp_ceiling)")
    workers = out.get("workers") or {}
    if workers:
        for idx in sorted(workers, key=str):
            t = workers[idx].get("totals") or {}
            print(f"worker {idx}: moved {_fmt(t.get('bytes'))}B "
                  f"ingested {_fmt(t.get('ingest_bytes'))}B "
                  f"amp={workers[idx].get('copy_amplification')}")
    return 0


def _render_solve(out: dict) -> int:
    """Human view of one solver result (shared by the online and offline
    ``storm-tpu plan`` paths)."""
    cov = out.get("coverage") or {}
    if not out.get("feasible"):
        if "feasible" in out:
            print("INFEASIBLE:", out.get("why") or "no reason reported")
            if out.get("binding_stage"):
                print(f"binding stage: {out['binding_stage']}")
            best = out.get("best_infeasible") or {}
            if best.get("capacity_rows_s") is not None:
                print(f"closest candidate: {best.get('candidate')} -> "
                      f"capacity {best['capacity_rows_s']} rows/s, "
                      f"p99 {best.get('p99_ms')} ms")
        else:
            print(out.get("note", "no target given"))
        for eng, row in cov.items():
            cells = ", ".join(
                f"{b}:{c['status']}({c['samples']})"
                for b, c in row.get("buckets", {}).items()) or "(none)"
            print(f"coverage {eng}: {cells}")
        return 1 if "feasible" in out else 0
    plan = out["plan"]
    pred = plan.get("prediction", {})
    print(f"PLAN engine={plan['engine']} bucket={plan['bucket']} "
          f"deadline={plan['deadline_ms']}ms "
          f"parallelism={plan['parallelism']} "
          f"continuous={plan['continuous']} "
          f"pipeline_depth={plan['pipeline_depth']} "
          f"max_inflight={plan['max_inflight']} "
          f"(replica cost {plan['replica_cost']})")
    print(f"predicted: p99={pred.get('p99_ms')}ms "
          f"capacity={pred.get('capacity_rows_s')} rows/s "
          f"util={pred.get('util')} "
          f"cold={pred.get('cold')}")
    for stage, ms in (pred.get("stages") or {}).items():
        print(f"  {stage:<16} {ms:>9}ms")
    if pred.get("queue_ms") is not None:
        print(f"  {'queue_ms':<16} {pred['queue_ms']:>9}ms")
    print("apply with: storm-tpu run ... " +
          " ".join(f"--set {a}" for a in plan.get("override_args", [])))
    for risk in out.get("framework_risks") or []:
        print(f"risk: {risk['note']}")
    corr = out.get("corrector")
    if corr is not None:
        print(f"corrector: enabled={corr.get('enabled')} "
              f"corrections={corr.get('corrections')}")
    return 0


def _plan_cmd(args) -> int:
    """``storm-tpu plan``: solve for the cheapest config meeting a
    (rate, p99 SLO) target. Online against a running topology's UI
    endpoint (live curves + corrector state), or offline from a
    committed ``PROFILE_*.json`` via ``--baseline`` — no daemon needed."""
    if args.baseline:
        from storm_tpu.plan import Target, solve

        with open(args.baseline) as fh:
            snap = json.load(fh)
        if not (args.rate > 0 and args.slo_ms > 0):
            print("offline solve needs --rate and --slo-ms", file=sys.stderr)
            return 2
        res = solve(snap, Target(args.rate, args.slo_ms,
                                 headroom=args.headroom),
                    engine=args.engine)
        out = res.to_dict()
        if args.json:
            print(json.dumps(out, indent=2, default=str))
            return 0 if res.feasible else 1
        return _render_solve(out)

    import urllib.error
    import urllib.parse
    import urllib.request

    from storm_tpu.config import env_control_token

    base = args.url.rstrip("/")
    topo = urllib.parse.quote(args.topology, safe="")
    q = {}
    if args.rate > 0:
        q["rate"] = args.rate
    if args.slo_ms > 0:
        q["slo_ms"] = args.slo_ms
    if args.engine:
        q["engine"] = args.engine
    q["headroom"] = args.headroom
    qs = urllib.parse.urlencode(q)
    req = urllib.request.Request(
        f"{base}/api/v1/topology/{topo}/plan?{qs}")
    token = args.token or env_control_token()
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8", "replace"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return 0
    return _render_solve(out)


def _fmt(v):
    return "-" if v is None else v


def _lint_cmd(args) -> int:
    """``storm-tpu lint``: the invariant analyzer (storm_tpu/analysis/)."""
    from storm_tpu.analysis import (
        RULES,
        filter_new,
        load_baseline,
        load_config,
        run_lint,
        write_baseline,
    )

    if args.rules:
        for rule in sorted(RULES):
            print(f"{rule}  {RULES[rule]}")
        return 0

    root = os.path.abspath(args.root)
    paths = args.paths or ["storm_tpu"]
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            print(f"lint: no such path: {p}", file=sys.stderr)
            return 2

    if args.regen_metric_registry or args.regen_protocol_registry:
        from storm_tpu.analysis.core import iter_python_files, parse_source

        files = []
        for rel in iter_python_files(["storm_tpu"], root):
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    sf = parse_source(f.read(), rel)
            except OSError:
                sf = None
            if sf is not None:
                files.append(sf)
        regens = []
        if args.regen_metric_registry:
            from storm_tpu.analysis.observability import generate_registry
            regens.append(("metric_names.py", generate_registry))
        if args.regen_protocol_registry:
            from storm_tpu.analysis.protocol import (
                generate_registry as gen_protocol,
            )
            regens.append(("protocol_names.py", gen_protocol))
        for fname, gen in regens:
            out = os.path.join(root, "storm_tpu", "analysis", fname)
            with open(out, "w", encoding="utf-8") as f:
                f.write(gen(files))
            print(f"wrote {os.path.relpath(out, root)}", file=sys.stderr)
        return 0

    config = load_config(root)
    timings = {} if args.profile else None
    findings = run_lint(paths, root, config, timings=timings)
    if timings is not None:
        for k in sorted(timings):
            v = timings[k]
            v = f"{v:.3f}" if isinstance(v, float) else v
            print(f"lint profile: {k:<14} {v}", file=sys.stderr)
    baseline_path = os.path.join(root, "storm_tpu", "analysis",
                                 "baseline.json")
    baseline = load_baseline(baseline_path)

    if args.update_baseline:
        write_baseline(baseline_path, findings, prior=baseline)
        print(f"baseline: {len(findings)} finding(s) -> "
              f"{os.path.relpath(baseline_path, root)} (fill in the 'why' "
              "for each new entry)", file=sys.stderr)
        return 0

    new = findings if args.no_baseline else filter_new(findings, baseline)
    n_baselined = len(findings) - len(filter_new(findings, baseline))
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "total": len(findings),
            "baselined": n_baselined,
            "new": len(new),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        print(f"lint: {len(findings)} finding(s), {n_baselined} baselined, "
              f"{len(new)} new", file=sys.stderr)
    return 1 if new else 0


def main(argv=None) -> int:
    setup_logging()
    ap = argparse.ArgumentParser(prog="storm_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="run a topology daemon")
    runp.add_argument("name")
    runp.add_argument("input_topic")
    runp.add_argument("output_topic")
    runp.add_argument("--config", help="TOML/JSON config file")
    runp.add_argument("--set", action="append", default=[],
                      metavar="section.key=value")
    runp.add_argument("--duration", type=float, default=0.0,
                      help="run window in seconds (0 = until signal); the "
                           "reference hard-killed after 3600s")
    runp.add_argument("--autoscale-target-ms", type=float, default=0.0,
                      help="autoscale inference parallelism to keep e2e p50 "
                           "under this latency (0 = off); the runtime "
                           "equivalent of the reference's rebuild-with-more-"
                           "bolts scaling thesis (README.md:13-14)")
    runp.add_argument("--ui-port", type=int, default=-1,
                      help="serve the Storm-UI-equivalent HTTP status/admin "
                           "API on this port (0 = ephemeral, -1 = off)")
    runp.add_argument("--metrics-file", default="",
                      help="append a JSON-lines metrics snapshot to this "
                           "file every --metrics-interval seconds")
    runp.add_argument("--metrics-interval", type=float, default=10.0)
    runp.add_argument("--topology-file", default="",
                      help="declarative topology definition (TOML/JSON, the "
                           "Storm Flux equivalent) instead of the standard "
                           "spout->inference->sink shape; the configured "
                           "broker is available as the $broker resource")

    distp = sub.add_parser(
        "dist-run",
        help="run a topology across worker processes (gRPC tuple transport)")
    distp.add_argument("name")
    distp.add_argument("input_topic")
    distp.add_argument("output_topic")
    distp.add_argument("--config", help="TOML/JSON config file")
    distp.add_argument("--set", action="append", default=[],
                       metavar="section.key=value")
    distp.add_argument("--workers", type=int, default=3,
                       help="local worker processes to spawn")
    distp.add_argument("--attach", action="append", default=[],
                       metavar="host:port",
                       help="attach to pre-started workers instead of "
                            "spawning (multi-host)")
    distp.add_argument("--duration", type=float, default=0.0)
    distp.add_argument("--ui-port", type=int, default=-1,
                       help="serve the Storm-UI HTTP API over the dist "
                            "controller (0 = ephemeral, -1 = off)")
    distp.add_argument("--journal-dir", default="",
                       help="controller write-ahead journal directory "
                            "(overrides control.journal_dir): a restarted "
                            "controller replays it and reattaches to live "
                            "workers instead of rebuilding them")

    servep = sub.add_parser("serve", help="run the gRPC TPU inference worker")
    servep.add_argument("--config", help="TOML/JSON config file")
    servep.add_argument("--set", action="append", default=[])
    servep.add_argument("--model", default=None, help="model registry name")
    servep.add_argument("--port", type=int, default=50051)
    servep.add_argument("--cross-batch-ms", type=float, default=0.0,
                        help="coalesce concurrent Predict RPCs into one "
                             "device dispatch within this window (0 = off)")

    sub.add_parser("info", help="print devices and registered models")

    ctlp = sub.add_parser(
        "ctl", help="control a running daemon over its UI HTTP API "
                    "(the storm kill/activate/deactivate/rebalance CLI)")
    ctlp.add_argument("--url", default="http://127.0.0.1:8080",
                      help="base URL of the daemon's --ui-port server")
    ctlp.add_argument("--token", default=None,
                      help="bearer token for daemons running with "
                           "control.auth_token (default: "
                           "$STORM_TPU_CONTROL_TOKEN)")
    ctlsub = ctlp.add_subparsers(dest="ctl_cmd", required=True)
    for cmd in ("list", "status", "metrics", "graph", "errors"):
        c = ctlsub.add_parser(cmd)
        if cmd != "list":
            c.add_argument("topology")
    for cmd in ("activate", "deactivate", "drain"):
        c = ctlsub.add_parser(cmd)
        c.add_argument("topology")
    c = ctlsub.add_parser("kill")
    c.add_argument("topology")
    c.add_argument("--wait-secs", type=float, default=0.0)
    c = ctlsub.add_parser("rebalance")
    c.add_argument("topology")
    c.add_argument("component")
    c.add_argument("parallelism", type=int)
    c = ctlsub.add_parser(
        "component",
        help="per-executor stats table for one component (Storm UI's "
             "executor rows)")
    c.add_argument("topology")
    c.add_argument("component")
    c = ctlsub.add_parser(
        "seek",
        help="reposition a spout's consumption: earliest|latest|<offset>|"
             "-<records-behind-latest> (live replay/backfill)")
    c.add_argument("topology")
    c.add_argument("component")
    c.add_argument("position")
    c = ctlsub.add_parser(
        "profile",
        help="capture a jax profiler trace (device+host timelines, "
             "TensorBoard-readable) on the daemon for N seconds")
    c.add_argument("topology")
    c.add_argument("log_dir")
    c.add_argument("--seconds", type=float, default=5.0)
    c.add_argument("--worker", type=int, default=0,
                   help="dist mode: worker index to capture on")
    c = ctlsub.add_parser(
        "swap-model",
        help="live model swap: apply ModelConfig field overrides to a "
             "running inference component (zero-downtime rollout/rollback)")
    c.add_argument("topology")
    c.add_argument("component")
    c.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="ModelConfig field override, repeatable "
                        "(e.g. --set checkpoint=/models/v2)")
    c.add_argument("--task", action="append", type=int, default=[],
                   metavar="N",
                   help="canary: swap only these task indexes (repeatable); "
                        "compare with `ctl component`, then swap the rest "
                        "or roll back")
    c = ctlsub.add_parser("logs")
    c.add_argument("topology")
    c.add_argument("--worker", type=int, default=0)
    c.add_argument("--bytes", type=int, default=16384)
    c = ctlsub.add_parser(
        "submit", help="submit a Flux topology definition to the daemon")
    c.add_argument("topology")
    c.add_argument("definition", help="TOML/JSON topology file")

    tracesp = sub.add_parser(
        "traces",
        help="dump the slowest traces (or the flight-recorder tail) from a "
             "running topology's UI endpoint; needs tracing.sample_rate > 0 "
             "on the daemon for span data")
    tracesp.add_argument("topology")
    tracesp.add_argument("--url", default="http://127.0.0.1:8080",
                         help="base URL of the daemon's --ui-port server")
    tracesp.add_argument("--token", default=None,
                         help="bearer token (default: "
                              "$STORM_TPU_CONTROL_TOKEN)")
    tracesp.add_argument("-n", type=int, default=10,
                         help="how many traces/events to show")
    tracesp.add_argument("--recent", action="store_true",
                         help="most recent traces instead of slowest")
    tracesp.add_argument("--flight", action="store_true",
                         help="flight-recorder events only")
    tracesp.add_argument("--json", action="store_true",
                         help="raw JSON instead of the rendered view")

    profp = sub.add_parser(
        "profile",
        help="dump the live cost model (per-engine/bucket stage curves, "
             "compile costs, SLO burn, occupancy) from a running "
             "topology's UI endpoint; enable [obs] on the daemon for "
             "burn/occupancy state")
    profp.add_argument("topology")
    profp.add_argument("--url", default="http://127.0.0.1:8080",
                       help="base URL of the daemon's --ui-port server")
    profp.add_argument("--token", default=None,
                       help="bearer token (default: "
                            "$STORM_TPU_CONTROL_TOKEN)")
    profp.add_argument("--json", action="store_true",
                       help="raw JSON instead of the rendered view")

    bottp = sub.add_parser(
        "bottleneck",
        help="show where a running topology is limited: ranked "
             "per-component capacity, edge lag watermarks, and the "
             "critical-path latency decomposition (needs [obs] enabled "
             "on the daemon; dist UIs answer with merged per-worker "
             "utilization)")
    bottp.add_argument("topology")
    bottp.add_argument("--url", default="http://127.0.0.1:8080",
                       help="base URL of the daemon's --ui-port server")
    bottp.add_argument("--token", default=None,
                       help="bearer token (default: "
                            "$STORM_TPU_CONTROL_TOKEN)")
    bottp.add_argument("--json", action="store_true",
                       help="raw JSON instead of the rendered view")

    copiesp = sub.add_parser(
        "copies",
        help="show the data-plane copy ledger for a running topology: "
             "per-stage bytes/record + copies/record ranked by bytes "
             "moved, and the copy amplification ratio (dist UIs answer "
             "with the controller-merged per-worker window)")
    copiesp.add_argument("topology")
    copiesp.add_argument("--url", default="http://127.0.0.1:8080",
                         help="base URL of the daemon's --ui-port server")
    copiesp.add_argument("--token", default=None,
                         help="bearer token (default: "
                              "$STORM_TPU_CONTROL_TOKEN)")
    copiesp.add_argument("--json", action="store_true",
                         help="raw JSON instead of the rendered view")

    planp = sub.add_parser(
        "plan",
        help="solve for the cheapest config meeting a (rate, p99 SLO) "
             "target over the profile curves: online against a running "
             "topology's /plan route, or offline from a committed "
             "PROFILE_*.json via --baseline (no daemon needed); prints "
             "the plan as ready-to-paste --set overrides")
    planp.add_argument("topology", nargs="?", default="inference-topology")
    planp.add_argument("--rate", type=float, default=0.0,
                       help="target offered rate, rows/s")
    planp.add_argument("--slo-ms", type=float, default=0.0, dest="slo_ms",
                       help="target end-to-end p99 SLO, ms")
    planp.add_argument("--engine", default=None,
                       help="engine/model key to plan for (default: the "
                            "cheapest profiled engine)")
    planp.add_argument("--headroom", type=float, default=0.8,
                       help="max predicted device utilization a feasible "
                            "plan may run at")
    planp.add_argument("--baseline", default=None,
                       help="solve offline over this PROFILE_*.json "
                            "instead of a running topology")
    planp.add_argument("--url", default="http://127.0.0.1:8080",
                       help="base URL of the daemon's --ui-port server")
    planp.add_argument("--token", default=None,
                       help="bearer token (default: "
                            "$STORM_TPU_CONTROL_TOKEN)")
    planp.add_argument("--json", action="store_true",
                       help="raw JSON instead of the rendered view")

    scorep = sub.add_parser(
        "scorecard",
        help="render the fleet scenario-matrix scorecard as a table: "
             "live from a running topology's /scorecard route (attached "
             "mid-run by bench.py --fleet), or offline from a committed "
             "SCORECARD_*.json via --file")
    scorep.add_argument("topology", nargs="?", default=None,
                        help="topology to query (omit with --file)")
    scorep.add_argument("--file", default=None,
                        help="render this SCORECARD_*.json instead of "
                             "querying a running topology")
    scorep.add_argument("--url", default="http://127.0.0.1:8080",
                        help="base URL of the daemon's --ui-port server")
    scorep.add_argument("--token", default=None,
                        help="bearer token (default: "
                             "$STORM_TPU_CONTROL_TOKEN)")
    scorep.add_argument("--json", action="store_true",
                        help="raw JSON instead of the rendered table")

    lintp = sub.add_parser(
        "lint",
        help="run the project's invariant analyzer (lock discipline, "
             "exactly-once, jit hygiene, observability) over the tree; "
             "exit 1 on non-baselined findings (docs/OPERATIONS.md "
             "'Static analysis')")
    lintp.add_argument("paths", nargs="*", default=[],
                       help="files/dirs to lint (default: storm_tpu/)")
    lintp.add_argument("--root", default=".",
                       help="repo root (pyproject.toml + baseline live here)")
    lintp.add_argument("--json", action="store_true", dest="as_json",
                       help="machine-readable findings on stdout")
    lintp.add_argument("--no-baseline", action="store_true",
                       help="report every finding, including baselined ones")
    lintp.add_argument("--update-baseline", action="store_true",
                       help="accept the current findings into "
                            "analysis/baseline.json (then edit in the "
                            "per-finding justifications)")
    lintp.add_argument("--rules", action="store_true",
                       help="list rule ids and exit")
    lintp.add_argument("--regen-metric-registry", action="store_true",
                       help="regenerate storm_tpu/analysis/metric_names.py "
                            "from the tree's metric call sites")
    lintp.add_argument("--regen-protocol-registry", action="store_true",
                       help="regenerate storm_tpu/analysis/protocol_names.py "
                            "from the tree's control/journal/flight-event "
                            "sites")
    lintp.add_argument("--profile", action="store_true",
                       help="print per-phase lint timings (file load, "
                            "call-graph build, each cross-file pass) to "
                            "stderr")

    args = ap.parse_args(argv)

    if args.cmd == "lint":
        return _lint_cmd(args)

    if args.cmd == "run":
        cfg = _load_config(args)
        cfg.broker.input_topic = args.input_topic
        cfg.broker.output_topic = args.output_topic
        if cfg.pipelines:
            print(
                "note: multi-model config — per-pipeline topics are used; the "
                f"positional topics {args.input_topic!r}/{args.output_topic!r} "
                "are ignored",
                file=sys.stderr,
            )
        asyncio.run(_run_daemon(args.name, cfg, args.duration,
                                args.autoscale_target_ms, args.ui_port,
                                args.metrics_file, args.metrics_interval,
                                args.topology_file))
        return 0

    if args.cmd == "ctl":
        return _ctl(args)

    if args.cmd == "traces":
        return _traces(args)

    if args.cmd == "profile":
        return _profile_cmd(args)

    if args.cmd == "bottleneck":
        return _bottleneck_cmd(args)

    if args.cmd == "copies":
        return _copies_cmd(args)

    if args.cmd == "plan":
        return _plan_cmd(args)

    if args.cmd == "scorecard":
        return _scorecard_cmd(args)

    if args.cmd == "dist-run":
        cfg = _load_config(args)
        cfg.broker.input_topic = args.input_topic
        cfg.broker.output_topic = args.output_topic
        if cfg.broker.kind != "kafka":
            print("dist-run needs broker.kind=kafka (workers are separate "
                  "processes; a memory broker cannot be shared)", file=sys.stderr)
            return 2
        # Dist-run default scheme is "raw" (+ record frames) since r19:
        # the binary wire (already the default) carries bytes natively,
        # so the bytes->str->bytes round trip and per-record routing only
        # survive when the user pins scheme="string" — or pins
        # wire_format="json", which cannot carry bytes and therefore
        # keeps the string scheme (the submit-time check would reject
        # raw+json loudly). See TopologyConfig.spout_scheme deprecation
        # note.
        if (not getattr(cfg.topology, "_scheme_pinned", False)
                and cfg.topology.wire_format != "json"):
            cfg.topology.spout_scheme = "raw"
            cfg.topology.spout_frames = True
        from storm_tpu.dist import DistCluster

        builder = "multi" if cfg.pipelines else "standard"
        # One resolution for BOTH the gRPC plane and the dist UI (config
        # wins, else the shared env fallback inside resolve_token) — the
        # UI must never stay open in a posture where the workers think
        # the cluster is locked (review r5).
        control_token = cfg.control.resolve_token()
        if args.journal_dir:
            cfg.control.journal_dir = args.journal_dir
        # The drill thread below may REPLACE the controller mid-run
        # (abandon + journal reattach), so everything after this point
        # reads the live handle through `holder` instead of a binding
        # frozen at construction time.
        cluster = DistCluster(
            n_workers=args.workers, addrs=args.attach or None,
            auth_token=control_token,
            journal_dir=cfg.control.journal_dir or None,
            reattach=cfg.control.reattach,
            journal_snapshot_every=cfg.control.journal_snapshot_every,
        )
        holder = {"cluster": cluster}
        try:
            if cluster.reattached:
                running = (cluster._recipe or {}).get("name", args.name)
                print(f"controller reattached to {len(cluster.clients)} "
                      f"workers from journal {cfg.control.journal_dir!r}; "
                      f"topology {running!r} kept running", file=sys.stderr)
            else:
                placement = cluster.submit(args.name, cfg, builder=builder)
                print(f"topology {args.name!r} across {len(cluster.clients)} "
                      f"workers: {placement}", file=sys.stderr)
            ui = ui_loop = None
            if args.ui_port >= 0:
                # The dist controller is synchronous; the UI server runs on
                # its own loop in a daemon thread, calling the controller
                # off-loop through the DistRuntimeView adapter.
                import threading

                from storm_tpu.dist.ui import start_dist_ui

                ui_loop = asyncio.new_event_loop()
                threading.Thread(target=ui_loop.run_forever, daemon=True).start()
                ui = asyncio.run_coroutine_threadsafe(
                    start_dist_ui(cluster, args.name, args.ui_port,
                                  auth_token=control_token),
                    ui_loop,
                ).result(timeout=10)
                print(f"ui http://127.0.0.1:{ui.port}", file=sys.stderr)
            chaos_thread = None
            if cfg.chaos.enabled and cfg.chaos.kill_worker_s > 0:
                # Chaos drill ([chaos] kill_worker_s): SIGKILL a random
                # non-controller worker every interval; the heartbeat
                # monitor detects and recovers it. Wire/corruption knobs
                # already rode the submit recipe into every worker.
                import random as _random
                import threading

                cluster.start_monitor()
                stop_chaos = threading.Event()
                rng = _random.Random(cfg.chaos.seed)

                def kill_loop() -> None:
                    while not stop_chaos.wait(cfg.chaos.kill_worker_s):
                        c = holder["cluster"]
                        live = [i for i, p in enumerate(c.procs)
                                if p is not None and p.poll() is None]
                        if len(live) < 2:
                            continue  # never kill the last worker standing
                        victim = rng.choice(live[1:])  # spare the spout host
                        print(f"chaos: SIGKILL worker {victim}",
                              file=sys.stderr)
                        c.flight.event("chaos_injection",
                                       target="worker_kill",
                                       worker=victim)
                        c.procs[victim].kill()

                chaos_thread = threading.Thread(
                    target=kill_loop, name="chaos-kill", daemon=True)
                chaos_thread.start()
            ctl_thread = None
            stop_ctl = None
            if (cfg.chaos.enabled and cfg.chaos.kill_controller_s > 0
                    and cfg.control.journal_dir):
                # Controller-crash drill ([chaos] kill_controller_s):
                # abandon the controller mid-run — drop every client and
                # process handle, workers untouched — then build a fresh
                # one from the journal and prove it reattaches without a
                # recompile storm. One-shot, gated through the injector's
                # controller_crash_next budget so it logs like any other
                # injection.
                import threading

                from storm_tpu.resilience.chaos import get_injector

                inj = get_injector()
                inj.bind_flight(cluster.flight)
                inj.configure(controller_crash_next=1)
                stop_ctl = threading.Event()

                def ctl_crash_loop() -> None:
                    if stop_ctl.wait(cfg.chaos.kill_controller_s):
                        return
                    if not inj.take_controller_crash():
                        return
                    old = holder["cluster"]
                    monitored = old._monitor is not None
                    print("chaos: abandoning controller (workers keep "
                          "serving)", file=sys.stderr)
                    old.abandon()
                    t0 = time.monotonic()
                    fresh = DistCluster(
                        n_workers=args.workers,
                        auth_token=control_token,
                        journal_dir=cfg.control.journal_dir,
                        reattach=True,
                        journal_snapshot_every=(
                            cfg.control.journal_snapshot_every),
                    )
                    holder["cluster"] = fresh
                    if monitored:
                        fresh.start_monitor()
                    print(f"chaos: controller restarted in "
                          f"{time.monotonic() - t0:.2f}s "
                          f"(reattached={fresh.reattached})",
                          file=sys.stderr)

                ctl_thread = threading.Thread(
                    target=ctl_crash_loop, name="chaos-ctl-crash",
                    daemon=True)
                ctl_thread.start()
            try:
                if args.duration > 0:
                    time.sleep(args.duration)
                else:
                    signal.sigwait({signal.SIGINT, signal.SIGTERM})
            except KeyboardInterrupt:
                pass
            if ctl_thread is not None:
                stop_ctl.set()
                ctl_thread.join(timeout=60)
            if chaos_thread is not None:
                stop_chaos.set()
                chaos_thread.join(timeout=5)
                holder["cluster"].stop_monitor()
            if ui is not None:
                asyncio.run_coroutine_threadsafe(ui.stop(), ui_loop).result(timeout=10)
                ui_loop.call_soon_threadsafe(ui_loop.stop)
            print("draining...", file=sys.stderr)
            holder["cluster"].drain(timeout_s=30)
            print(json.dumps(holder["cluster"].metrics(), default=str),
                  file=sys.stderr)
            holder["cluster"].kill()
        finally:
            holder["cluster"].shutdown()
        return 0

    if args.cmd == "serve":
        cfg = _load_config(args)
        if args.model:
            cfg.model.name = args.model
        from storm_tpu.serve import InferenceWorker

        worker = InferenceWorker(cfg.model, cfg.sharding, cfg.batch, port=args.port,
                                 cross_batch_ms=args.cross_batch_ms)
        worker.start()
        print(f"serving {cfg.model.name} on port {worker.port}", file=sys.stderr)
        try:
            worker.wait()
        except KeyboardInterrupt:
            worker.stop()
        return 0

    if args.cmd == "info":
        import jax

        from storm_tpu.models import registry_names

        dev = jax.devices()[0]
        mem = None
        try:
            mem = dev.memory_stats()
        except Exception:
            pass
        print(json.dumps({
            "devices": [str(d) for d in jax.devices()],
            "memory_stats": mem,
            "models": registry_names(),
            "version": __import__("storm_tpu").__version__,
        }, indent=2))
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
