"""RemoteInferenceBolt: inference operator that dispatches to the gRPC
worker instead of an in-process engine — the in-tree realization of the
north-star split (BASELINE.json): a front-end runtime (here our own; in the
reference architecture a JVM Storm bolt) keeps tuple-ack semantics while
batches cross a localhost gRPC + Arrow boundary to the TPU worker process.

Identical streaming behavior to :class:`storm_tpu.infer.InferenceBolt`
(micro-batching, deferred acks, dead-lettering); only the engine call is
remote."""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Set

from storm_tpu.api.schema import DeadLetter, SchemaError, decode_instances, encode_predictions
from storm_tpu.config import BatchConfig
from storm_tpu.infer.batcher import Batch, MicroBatcher
from storm_tpu.infer.operator import InferenceBolt
from storm_tpu.runtime.base import TopologyContext, OutputCollector
from storm_tpu.serve.client import InferenceClient


class RemoteInferenceBolt(InferenceBolt):
    def __init__(
        self,
        target: str = "localhost:50051",
        batch: Optional[BatchConfig] = None,
        warmup: bool = False,
        qos=None,
        passthrough=(),
    ) -> None:
        # qos/passthrough forward unchanged: EDF lane formation and the
        # qos_lane ride-through happen in the batcher/operator layer,
        # which is identical on both sides of the gRPC boundary — the
        # fleet scorecard's serve-path cells need per-lane e2e histograms
        # from a remote topology too.
        super().__init__(batch=batch, warmup=warmup, qos=qos,
                         passthrough=passthrough)
        self.target = target

    def clone(self) -> "RemoteInferenceBolt":
        return RemoteInferenceBolt(self.target, self.batch_cfg, self._warmup,
                                   self.qos, self.passthrough)

    def prepare(self, context: TopologyContext, collector: OutputCollector) -> None:
        # Skip the in-process engine entirely; resolve shape from the worker.
        self.client = InferenceClient(self.target)
        info = self.client.info()
        self._input_shape = tuple(info["input_shape"])

        class _RemoteEngine:
            """Engine facade: predict() over gRPC; shape from Info."""

            input_shape = self._input_shape
            client = self.client

            def predict(self_inner, x):
                return self.client.predict(x)

            def warmup(self_inner):
                pass

        self._engine = _RemoteEngine()
        super().prepare(context, collector)

    def cleanup(self) -> None:
        super().cleanup()
        self.client.close()
