"""gRPC inference worker: the co-located TPU service (north-star boundary).

Replaces the reference's in-process JNI engine (layer 4) with a service any
front-end — including a JVM Storm ``InferenceBolt`` — can dispatch batches
to over localhost gRPC, preserving tuple-ack semantics on the caller side
(BASELINE.json north star; SURVEY.md §7 step 7).

Methods (raw-bytes gRPC, no protoc codegen needed):

- ``/storm_tpu.Inference/Predict``  — Arrow IPC tensor in (N, H, W, C),
  Arrow IPC tensor out (N, K). Zero-copy marshalling both ways
  (:mod:`storm_tpu.serve.marshal`).
- ``/storm_tpu.Inference/PredictJson`` — the ``{"instances": ...}`` /
  ``{"predictions": ...}`` wire contract for HTTP-era clients.
- ``/storm_tpu.Inference/Info`` — model metadata JSON (name, input shape,
  classes, mesh) — replacing the reference's hard-coded tensor names
  (InferenceBolt.java:83-86) with discoverable metadata.

Errors map to gRPC status codes: malformed payloads -> INVALID_ARGUMENT,
engine failures -> INTERNAL.
"""

from __future__ import annotations

import json
import logging
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from storm_tpu.api.schema import SchemaError, decode_instances, encode_predictions
from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
from storm_tpu.infer.engine import InferenceEngine, shared_engine
from storm_tpu.serve.marshal import decode_tensor, encode_tensor

log = logging.getLogger("storm_tpu.serve")

_SERVICE = "storm_tpu.Inference"


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, worker: "InferenceWorker") -> None:
        self._worker = worker
        self._methods = {
            f"/{_SERVICE}/Predict": worker._predict,
            f"/{_SERVICE}/PredictJson": worker._predict_json,
            f"/{_SERVICE}/Info": worker._info,
        }

    def service(self, call_details):
        fn = self._methods.get(call_details.method)
        if fn is None:
            return None
        return grpc.unary_unary_rpc_method_handler(fn)


class InferenceWorker:
    def __init__(
        self,
        model: Optional[ModelConfig] = None,
        sharding: Optional[ShardingConfig] = None,
        batch: Optional[BatchConfig] = None,
        engine: Optional[InferenceEngine] = None,
        port: int = 50051,
        max_workers: int = 8,
        cross_batch_ms: float = 0.0,
    ) -> None:
        self.model_cfg = model or ModelConfig()
        self.engine = engine or shared_engine(
            self.model_cfg, sharding or ShardingConfig(), batch or BatchConfig()
        )
        # cross_batch_ms > 0: coalesce concurrent Predict RPCs from different
        # callers into one device dispatch (serve/batcher.py). Off by default
        # — single-caller deployments shouldn't pay the window latency.
        # batch.continuous routes RPCs into the engine's shared continuous
        # queue instead, where they co-batch with topology traffic on the
        # same slot schedule (no leader window at all).
        self._batcher = None
        bc = batch or BatchConfig()
        if getattr(bc, "continuous", False):
            from storm_tpu.serve.batcher import CrossCallerBatcher

            self._batcher = CrossCallerBatcher(
                self.engine, continuous=True, batch_cfg=bc)
        elif cross_batch_ms > 0:
            from storm_tpu.serve.batcher import CrossCallerBatcher

            self._batcher = CrossCallerBatcher(self.engine, window_ms=cross_batch_ms)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ],
        )
        self._server.add_generic_rpc_handlers((_Handler(self),))
        self.port = self._server.add_insecure_port(f"[::]:{port}")

    # ---- methods -------------------------------------------------------------

    def _predict(self, request: bytes, context: grpc.ServicerContext) -> bytes:
        try:
            x = decode_tensor(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad tensor: {e}")
        if tuple(x.shape[1:]) != self.engine.input_shape:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"instance shape {tuple(x.shape[1:])} != model input "
                f"{self.engine.input_shape}",
            )
        try:
            out = self._run_predict(np.asarray(x, np.float32))
        except Exception as e:  # pragma: no cover - engine failure
            log.exception("predict failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return encode_tensor(out)

    def _run_predict(self, x: np.ndarray) -> np.ndarray:
        if self._batcher is not None:
            return self._batcher.predict(x)
        return self.engine.predict(x)

    def _predict_json(self, request: bytes, context: grpc.ServicerContext) -> bytes:
        try:
            inst = decode_instances(request)
            if tuple(inst.data.shape[1:]) != self.engine.input_shape:
                raise SchemaError(
                    f"instance shape {tuple(inst.data.shape[1:])} != model "
                    f"input {self.engine.input_shape}"
                )
        except SchemaError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        try:
            out = self._run_predict(inst.data)
        except Exception as e:  # pragma: no cover
            log.exception("predict failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return encode_predictions(out).encode("utf-8")

    def _info(self, request: bytes, context: grpc.ServicerContext) -> bytes:
        return json.dumps(
            {
                "model": self.model_cfg.name,
                "input_shape": list(self.engine.input_shape),
                "num_classes": self.model_cfg.num_classes,
                "dtype": self.model_cfg.dtype,
                "mesh": dict(self.engine.mesh.shape),
                "buckets": list(self.engine.batch_cfg.buckets),
            }
        ).encode("utf-8")

    # ---- lifecycle -----------------------------------------------------------

    def start(self) -> "InferenceWorker":
        self._server.start()
        log.info("inference worker on port %d (model=%s)", self.port, self.model_cfg.name)
        return self

    def stop(self, grace: float = 5.0) -> None:
        self._server.stop(grace).wait()

    def wait(self) -> None:  # pragma: no cover - daemon mode
        self._server.wait_for_termination()
