"""Arrow zero-copy tensor marshalling for the gRPC boundary.

The reference's host<->engine boundary is two JNI float-array copies per
tuple (InferenceBolt.java:80, :86). Here the boundary is Arrow IPC tensors,
marshalled by the **C++ layer** (storm_tpu/native/arrow_tensor.cpp — the
SURVEY.md §2.2 obligation: native marshalling, not a Python stand-in):
``encode_tensor`` writes the flatbuffer metadata + body with no element-wise
conversion, and ``decode_tensor`` returns a NumPy view over the received
buffer (zero-copy on the read side) ready for ``jax.device_put``. This is
the marshalling path a JVM/Storm front-end would use to hand batches to the
co-located TPU worker (BASELINE.json north star).

When the native library is not built, both directions fall back to pyarrow
(wire-identical — the C++ marshaller is round-trip tested against pyarrow
in tests/test_native.py).

Besides the serve boundary, these are also the ndarray slot codec of the
binary dist wire (storm_tpu/dist/wire.py): tensors cross worker
boundaries as Arrow IPC messages inside CRC-protected frames.
``decode_tensor`` therefore accepts any buffer object — the dist receiver
hands it a ``memoryview`` slice of the gRPC payload and the returned
array stays a zero-copy view over that slice.
"""

from __future__ import annotations

import numpy as np

from storm_tpu.native import decode_tensor_native, encode_tensor_native


def encode_tensor(x: np.ndarray) -> bytes:
    """NumPy array -> Arrow IPC tensor message bytes (C++ fast path)."""
    x = np.ascontiguousarray(x)
    out = encode_tensor_native(x)
    if out is not None:
        return out
    import pyarrow as pa

    tensor = pa.Tensor.from_numpy(x)
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(tensor, sink)
    return sink.getvalue().to_pybytes()


def decode_tensor(buf) -> np.ndarray:
    """Arrow IPC tensor bytes -> NumPy view (zero-copy over the buffer).

    ``buf`` may be ``bytes`` or any buffer object (``memoryview``,
    ``bytearray``); the view keeps it alive via the array's base chain."""
    out = decode_tensor_native(buf)
    if out is not None:
        return out
    import pyarrow as pa

    tensor = pa.ipc.read_tensor(pa.py_buffer(buf))
    return tensor.to_numpy()
