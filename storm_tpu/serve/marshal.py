"""Arrow zero-copy tensor marshalling for the gRPC boundary.

The reference's host<->engine boundary is two JNI float-array copies per
tuple (InferenceBolt.java:80, :86). Here the boundary is Arrow IPC tensors,
marshalled by the **C++ layer** (storm_tpu/native/arrow_tensor.cpp — the
SURVEY.md §2.2 obligation: native marshalling, not a Python stand-in):
``encode_tensor`` writes the flatbuffer metadata + body with no element-wise
conversion, and ``decode_tensor`` returns a NumPy view over the received
buffer (zero-copy on the read side) ready for ``jax.device_put``. This is
the marshalling path a JVM/Storm front-end would use to hand batches to the
co-located TPU worker (BASELINE.json north star).

When the native library is not built, both directions fall back to pyarrow
(wire-identical — the C++ marshaller is round-trip tested against pyarrow
in tests/test_native.py).

Besides the serve boundary, these are also the ndarray slot codec of the
binary dist wire (storm_tpu/dist/wire.py): tensors cross worker
boundaries as Arrow IPC messages inside CRC-protected frames.
``decode_tensor`` therefore accepts any buffer object — the dist receiver
hands it a ``memoryview`` slice of the gRPC payload and the returned
array stays a zero-copy view over that slice.
"""

from __future__ import annotations

import numpy as np

from storm_tpu.native import decode_tensor_native, encode_tensor_native
from storm_tpu.obs import copyledger as _copyledger


def _records_of(arr: np.ndarray) -> int:
    """Batch-axis length as the ledger's record count (scalars/rank-0: 1)."""
    return int(arr.shape[0]) if arr.ndim else 1


def encode_tensor(x: np.ndarray) -> bytes:
    """NumPy array -> Arrow IPC tensor message bytes (C++ fast path)."""
    c = np.ascontiguousarray(x)
    out = encode_tensor_native(c)
    if out is None:
        import pyarrow as pa

        tensor = pa.Tensor.from_numpy(c)
        sink = pa.BufferOutputStream()
        pa.ipc.write_tensor(tensor, sink)
        out = sink.getvalue().to_pybytes()
    # Copy ledger: the IPC body write is one copy; a non-contiguous
    # input pays a second (the ascontiguousarray materialization).
    _copyledger.record("marshal_encode", len(out),
                       copies=1 if c is x else 2, allocs=1,
                       records=_records_of(c))
    return out


def decode_tensor(buf) -> np.ndarray:
    """Arrow IPC tensor bytes -> NumPy view (zero-copy over the buffer).

    ``buf`` may be ``bytes`` or any buffer object (``memoryview``,
    ``bytearray``); the view keeps it alive via the array's base chain."""
    arr = decode_tensor_native(buf)
    if arr is None:
        import pyarrow as pa

        tensor = pa.ipc.read_tensor(pa.py_buffer(buf))
        arr = tensor.to_numpy()
    # Copy ledger: the decode is a zero-copy view, so it moves ZERO bytes
    # — same convention as the other view hops (batch_route, wire_decode
    # over shm): bytes=0, copies=0, with ``records`` proving engagement.
    # The measurement must not copy either — no ``len(bytes(buf))`` round
    # trip that would materialize the frame slice it measures.
    _copyledger.record("marshal_decode", 0, copies=0, allocs=0,
                       records=_records_of(arr))
    return arr
