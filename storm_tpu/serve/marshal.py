"""Arrow zero-copy tensor marshalling for the gRPC boundary.

The reference's host<->engine boundary is two JNI float-array copies per
tuple (InferenceBolt.java:80, :86). Here the boundary is Arrow IPC tensors:
``encode_tensor`` writes the C-contiguous buffer with no element-wise
conversion, and ``decode_tensor`` returns a NumPy view over the received
buffer (zero-copy on the read side) ready for ``jax.device_put``. This is
the marshalling path a JVM/Storm front-end would use to hand batches to the
co-located TPU worker (BASELINE.json north star).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa


def encode_tensor(x: np.ndarray) -> bytes:
    """NumPy array -> Arrow IPC tensor bytes."""
    x = np.ascontiguousarray(x)
    tensor = pa.Tensor.from_numpy(x)
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(tensor, sink)
    return sink.getvalue().to_pybytes()


def decode_tensor(buf: bytes) -> np.ndarray:
    """Arrow IPC tensor bytes -> NumPy view (zero-copy over the buffer)."""
    tensor = pa.ipc.read_tensor(pa.py_buffer(buf))
    return tensor.to_numpy()
