"""Client for the gRPC inference worker (what a JVM InferenceBolt's
``execute`` would call instead of JNI -> libtensorflow)."""

from __future__ import annotations

import json
from typing import Optional

import grpc
import numpy as np

from storm_tpu.serve.marshal import decode_tensor, encode_tensor

_SERVICE = "storm_tpu.Inference"


class InferenceClient:
    def __init__(self, target: str = "localhost:50051") -> None:
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ],
        )
        self._predict = self._channel.unary_unary(f"/{_SERVICE}/Predict")
        self._predict_json = self._channel.unary_unary(f"/{_SERVICE}/PredictJson")
        self._info = self._channel.unary_unary(f"/{_SERVICE}/Info")

    def predict(self, x: np.ndarray, timeout: Optional[float] = 60.0) -> np.ndarray:
        """Arrow-tensor round trip: (N, ...) batch in, (N, K) scores out."""
        return decode_tensor(self._predict(encode_tensor(x), timeout=timeout))

    def predict_json(self, payload: str | bytes, timeout: Optional[float] = 60.0) -> str:
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        return self._predict_json(payload, timeout=timeout).decode("utf-8")

    def info(self, timeout: Optional[float] = 10.0) -> dict:
        return json.loads(self._info(b"", timeout=timeout))

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "InferenceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
