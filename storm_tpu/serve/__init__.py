from storm_tpu.serve.worker import InferenceWorker
from storm_tpu.serve.client import InferenceClient

__all__ = ["InferenceWorker", "InferenceClient"]
