"""Cross-caller micro-batching for the gRPC worker.

The topology path batches inside one InferenceBolt; the serving path gets
its batching here instead: concurrent Predict RPCs from *different* callers
(e.g. several JVM Storm executors dispatching to one co-located TPU worker,
the north-star deployment) are coalesced into one device dispatch.

Leader-based window: the first request to arrive in an empty window becomes
the leader, sleeps ``window_ms`` while followers queue up, then runs ONE
``engine.predict`` over the concatenated batch and distributes the row
slices back. Followers block on an event. While the leader is on-device, the
next arrival starts a new window — windows pipeline behind the device queue.

This is the server-side analogue of the reference's missing batching
(one ``session.run`` per tuple, InferenceBolt.java:80-86, SURVEY.md §3.3).

With ``continuous=True`` the leader-window machinery is bypassed entirely:
each RPC submits its rows straight into the engine's shared continuous
queue (:mod:`storm_tpu.infer.continuous`), where they coalesce with
topology replicas and cascade residues — serve and streaming traffic
co-batch on the same device slot schedule.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


class _Req:
    __slots__ = ("x", "event", "out", "err")

    def __init__(self, x: np.ndarray) -> None:
        self.x = x
        self.event = threading.Event()
        self.out: Optional[np.ndarray] = None
        self.err: Optional[Exception] = None


class CrossCallerBatcher:
    def __init__(self, engine, window_ms: float = 2.0,
                 max_batch: Optional[int] = None,
                 continuous: bool = False, batch_cfg=None,
                 qos=None) -> None:
        self.engine = engine
        self.window_s = window_ms / 1000.0
        cfg = batch_cfg or getattr(engine, "batch_cfg", None)
        self.max_batch = max_batch or getattr(cfg, "max_batch", None) or 8
        self._lock = threading.Lock()
        self._pending: List[_Req] = []
        self._leader_active = False
        self.dispatches = 0  # instrumentation: device dispatch count
        self._cb = None
        if continuous:
            from storm_tpu.infer.continuous import continuous_for

            if cfg is None:
                from storm_tpu.config import BatchConfig

                cfg = BatchConfig()
            self._cb = continuous_for(engine, cfg, qos)

    def predict(self, x: np.ndarray, lane: Optional[str] = None,
                tenant: Optional[str] = None) -> np.ndarray:
        if self._cb is not None:
            # Continuous path: the shared per-engine queue owns window
            # timing and coalescing (across RPCs AND topology sources);
            # this thread just blocks on its own row slice.
            sub = self._cb.submit(x, lane=lane, tenant=tenant,
                                  source="serve")
            out = sub.future.result()
            self.dispatches = self._cb.batches
            return out
        req = _Req(x)
        with self._lock:
            self._pending.append(req)
            is_leader = not self._leader_active
            if is_leader:
                self._leader_active = True
        if is_leader:
            time.sleep(self.window_s)
            with self._lock:
                batch = self._pending
                self._pending = []
                self._leader_active = False
            self._run(batch)
        else:
            req.event.wait()
        if req.err is not None:
            raise req.err
        assert req.out is not None
        return req.out

    def _run(self, batch: List[_Req]) -> None:
        try:
            xs = np.concatenate([r.x for r in batch]) if len(batch) > 1 else batch[0].x
            outs = []
            # Chunk if concurrent callers exceed the engine's largest bucket.
            for i in range(0, xs.shape[0], self.max_batch):
                outs.append(self.engine.predict(xs[i : i + self.max_batch]))
                self.dispatches += 1
            out = np.concatenate(outs) if len(outs) > 1 else outs[0]
            off = 0
            for r in batch:
                n = r.x.shape[0]
                r.out = out[off : off + n]
                off += n
        except Exception as e:
            for r in batch:
                r.err = e
        finally:
            for r in batch:
                r.event.set()
