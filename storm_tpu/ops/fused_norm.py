"""Pallas TPU fused residual-add + LayerNorm.

Why: every ViT/Mixer encoder block computes ``y = x + f(x); out = LN(y)``
(models/vit.py `_block`, models/mixer.py). Unfused, the (tokens, dim)
activation makes an extra HBM round trip between the add and the norm;
this kernel reads x and the branch output once, does add + mean/var +
scale/shift in VMEM, and writes the residual sum and the normed tensor.
The reference has no transformer at all (SURVEY.md §5.7) — this serves
the beyond-parity ViT/Mixer configs in BASELINE.json.

Autodiff: ``pallas_call`` has no automatic VJP, and the same block code
runs under ``jax.grad`` in the training path (parallel/train.py,
pipeline dryruns). The op is wrapped in ``jax.custom_vjp``: forward is
the Pallas kernel (jnp reference off-TPU), backward is the standard
LayerNorm gradient in plain jnp (XLA fuses it fine; training peak HBM is
dominated elsewhere).

Layout: inputs flatten to (rows, dim); grid over row blocks, full dim per
program (dim <= a few thousand for the zoo). Rows pad to the block, dim
pads to the 128-lane tile; padded columns are masked out of mean/var and
the ln output (they'd otherwise contribute (0-mean)^2 to the variance).

CPU/tests: ``interpret=True`` runs the kernel under the Pallas
interpreter; forward + grads are cross-checked against jnp in
tests/test_ops.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128


def _kernel(x_ref, r_ref, g_ref, b_ref, y_ref, o_ref, *, d_valid, eps):
    x = x_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)
    y = x + r
    mask = lax.broadcasted_iota(jnp.int32, y.shape, 1) < d_valid
    ym = jnp.where(mask, y, 0.0)
    mean = ym.sum(axis=1, keepdims=True) / d_valid
    var = (jnp.where(mask, y - mean, 0.0) ** 2).sum(axis=1, keepdims=True) / d_valid
    rstd = lax.rsqrt(var + eps)
    normed = (y - mean) * rstd * g_ref[0] + b_ref[0]
    y_ref[...] = jnp.where(mask, y, 0.0).astype(y_ref.dtype)
    o_ref[...] = jnp.where(mask, normed, 0.0).astype(o_ref.dtype)


def _pad2(a, rows, cols):
    pr = (-a.shape[0]) % rows
    pc = (-a.shape[1]) % cols
    if pr or pc:
        a = jnp.pad(a, ((0, pr), (0, pc)))
    return a


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def _fused_fwd_pallas(x2, r2, g, b, *, eps, block_rows=256, interpret=False):
    rows, d = x2.shape
    # Row block: round rows up to the 8-sublane tile, capped at block_rows
    # only when that cap does not force a near-empty trailing block (e.g.
    # rows=300 with a 256 cap would pad to 512 and norm 212 garbage rows).
    r8 = ((max(8, rows) + 7) // 8) * 8
    br = r8 if r8 <= 2 * block_rows else block_rows
    xp = _pad2(x2, br, _LANE)
    rp = _pad2(r2, br, _LANE)
    dp = xp.shape[1]
    gp = jnp.pad(g.astype(jnp.float32), (0, dp - d)).reshape(1, dp)
    bp = jnp.pad(b.astype(jnp.float32), (0, dp - d)).reshape(1, dp)
    grid = (xp.shape[0] // br,)
    y, out = pl.pallas_call(
        functools.partial(_kernel, d_valid=d, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, dp), lambda i: (i, 0)),
            pl.BlockSpec((br, dp), lambda i: (i, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, dp), lambda i: (i, 0)),
            pl.BlockSpec((br, dp), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(xp.shape, x2.dtype),
            jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        ],
        interpret=interpret,
    )(xp, rp, gp, bp)
    return y[:rows, :d], out[:rows, :d]


def _reference(x2, r2, g, b, eps):
    from storm_tpu.ops.layers import layernorm

    # Delegate to the canonical unfused LN so the off-TPU forward and the
    # custom_vjp backward can never numerically diverge from the blocks
    # this kernel replaces.
    y = x2 + r2
    return y, layernorm({"scale": g, "bias": b}, y, eps)


def _force_pallas_norm() -> bool:
    """The Pallas residual+LN kernel is OFF by default: measured on-chip
    (BENCH_NOTES.md round 2 A/B) XLA's own elementwise fusion wins —
    vit_b16 19.3ms/step XLA vs 20.4ms with the kernel, mixer_tiny 0.33ms
    vs 0.60ms. XLA already emits one fused pass for add+LN; the hand
    kernel only adds pipeline barriers. ``STORM_TPU_FUSED_NORM=1``
    re-enables it (e.g. to re-measure on a future XLA/TPU generation)."""
    import os

    return os.environ.get("STORM_TPU_FUSED_NORM", "") not in (
        "", "0", "false", "False")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(x2, r2, g, b, eps):
    from storm_tpu.ops.platform import use_pallas

    if use_pallas() and _force_pallas_norm():
        return _fused_fwd_pallas(x2, r2, g, b, eps=eps)
    return _reference(x2, r2, g, b, eps)


def _fused_fwd(x2, r2, g, b, eps):
    y, out = _fused(x2, r2, g, b, eps)
    return (y, out), (x2, r2, g, b)


def _fused_bwd(eps, res, cots):
    # Backward = jax's own vjp of the unfused reference. Writing the LN
    # gradient by hand is easy to get numerically right but WRONG under
    # shard_map's varying-axis tracking: autodiff of the unfused op
    # transposes the implicit param broadcast (pvary) into a psum over the
    # data axes, which a hand-rolled sum cannot know to do. Recomputing
    # the cheap forward here costs one fused elementwise pass.
    x2, r2, g, b = res
    _, vjp = jax.vjp(lambda *a: _reference(*a, eps), x2, r2, g, b)
    return vjp(cots)


_fused.defvjp(_fused_fwd, _fused_bwd)


def residual_layernorm(p: dict, branch: jnp.ndarray, x: jnp.ndarray,
                       eps: float = 1e-6):
    """``y = x + branch; out = LayerNorm_p(y)`` — fused on TPU.

    Returns ``(y, out)`` so the caller keeps the residual stream.
    ``p`` is the `layernorm_init` dict ({"scale", "bias"})."""
    *lead, d = x.shape
    x2 = x.reshape(-1, d)
    b2 = branch.reshape(-1, d)
    y, out = _fused(b2, x2, p["scale"], p["bias"], eps)
    return y.reshape(*lead, d), out.reshape(*lead, d)
