"""Pallas TPU flash attention (fused scores/softmax/value contraction).

Why: the naive path materializes the (S, S) score matrix in HBM twice per
layer; this kernel keeps the whole online-softmax accumulation in VMEM, so
HBM traffic is just q/k/v in and o out. Dispatch is shape-aware
(ops/attention.py): below ~1024 tokens XLA's own fused attention is
faster on-chip and serves (e.g. ViT-B/16's S=197); at and above it this
kernel wins 2-3x (measured — BENCH_NOTES.md round 2). The ring-attention
sequence-parallel path computes its per-shard partials with its own
online-softmax math (parallel/ring_attention.py), not this kernel.

Layout: inputs (B, H, S, D) are flattened to (B*H, S, D); the grid is
(B*H, Sq_blocks); each program owns one (block_q, D) query tile and loops
KV chunks of ``block_k`` with the standard online-softmax carry
(running max m, denominator l, accumulator acc — all f32 in registers/VMEM).

Shapes are padded: D to the 128-lane tile, S to block multiples; padded key
positions are masked with a large negative before the softmax, padded query
rows are sliced off on return. Masking uses -1e30 (not -inf: a fully-masked
chunk would produce exp(-inf - -inf) = NaN in the carry).

CPU/tests: ``interpret=True`` runs the same kernel under the Pallas
interpreter — cross-checked against the jnp reference in tests/test_ops.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128
_NEG = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, s_valid, block_k):
    q = q_ref[0]  # (BQ, Dp)
    bq = q.shape[0]
    sp = k_ref.shape[1]
    nk = sp // block_k

    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :]  # (BK, Dp)
        v = v_ref[0, pl.ds(i * block_k, block_k), :]
        # (BQ, BK) scores, f32 accumulation on the MXU.
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        idx = lax.broadcasted_iota(jnp.int32, s.shape, 1) + i * block_k
        s = jnp.where(idx < s_valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 2048,
    interpret: bool = False,
) -> jnp.ndarray:
    """softmax(q k^T * scale) v for (B, H, S, D) inputs, fused on TPU.

    Block defaults are the measured-fastest on v5e (BENCH_NOTES.md round
    2 block sweep: bq=512/bk=2048 runs S=2048 in 0.52 ms vs 0.91 ms with
    the round-1 128/512 tiles — 3.25x XLA's fused attention); both clamp
    to the padded sequence so direct short-shape callers (tests, sweeps,
    future kernels built on this one) never pad q 8x just to fill a tile.
    (The serving dispatch, ops/attention.py, only routes here at
    S >= _flash_min_seq; ring attention uses its own per-shard math.)"""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = d**-0.5

    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)

    # Tile padding: D -> lane width; Sq -> block_q; Sk -> block_k, with
    # both block sizes clamped to the (pow2-padded) sequence lengths.
    block_q = min(block_q, max(_LANE, 1 << (sq - 1).bit_length()))
    qf = _pad_to(_pad_to(qf, 2, _LANE), 1, block_q)
    bk = min(block_k, max(_LANE, 1 << (sk - 1).bit_length()))
    kf = _pad_to(_pad_to(kf, 2, _LANE), 1, bk)
    vf = _pad_to(_pad_to(vf, 2, _LANE), 1, bk)
    sq_p, d_p = qf.shape[1], qf.shape[2]
    sk_p = kf.shape[1]

    grid = (b * h, sq_p // block_q)
    out = pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale, s_valid=sk, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, sk_p, d_p), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, sk_p, d_p), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_p), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :sq, :d].reshape(b, h, sq, d)
