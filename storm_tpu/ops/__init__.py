from storm_tpu.ops import layers
from storm_tpu.ops.attention import multi_head_attention

__all__ = ["layers", "multi_head_attention"]
