"""Functional NN layers: pure jnp/lax functions + param initializers.

The compute vocabulary for the model zoo (:mod:`storm_tpu.models`), written
TPU-first: NHWC layouts (XLA's preferred conv layout on TPU), matmul-shaped
ops that tile onto the MXU, static shapes everywhere, and no Python control
flow inside traced code. Replaces the reference's opaque frozen-graph blob
(``SavedModelBundle.load``, InferenceBolt.java:57) with transparent param
pytrees.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---- initializers ------------------------------------------------------------


def he_normal(rng, shape, fan_in: int, dtype=jnp.float32):
    return jax.random.normal(rng, shape, dtype) * np.sqrt(2.0 / fan_in)


def lecun_normal(rng, shape, fan_in: int, dtype=jnp.float32):
    return jax.random.normal(rng, shape, dtype) * np.sqrt(1.0 / fan_in)


def trunc_normal(rng, shape, std: float = 0.02, dtype=jnp.float32):
    return jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype) * std


# ---- dense -------------------------------------------------------------------


def dense_init(rng, in_dim: int, out_dim: int, dtype=jnp.float32) -> dict:
    kw, _ = jax.random.split(rng)
    return {
        "w": lecun_normal(kw, (in_dim, out_dim), in_dim, dtype),
        "b": jnp.zeros((out_dim,), dtype),
    }


def dense(p: dict, x: jnp.ndarray) -> jnp.ndarray:
    if isinstance(p["w"], dict) and "__q" in p["w"]:
        # Weight left int8 by the engine's "int8_fused" mode: run the
        # Pallas fused dequant-matmul so only int8 bytes leave HBM.
        from storm_tpu.ops.quant_matmul import qdense

        return qdense(p, x)
    # Accumulate matmuls in f32 on the MXU even for bf16 inputs.
    return jnp.dot(x, p["w"], preferred_element_type=jnp.float32).astype(x.dtype) + p["b"]


# ---- conv --------------------------------------------------------------------


def conv_init(
    rng, kh: int, kw: int, cin: int, cout: int, dtype=jnp.float32, bias: bool = True
) -> dict:
    kr, _ = jax.random.split(rng)
    p = {"w": he_normal(kr, (kh, kw, cin, cout), kh * kw * cin, dtype)}
    if bias:
        p["b"] = jnp.zeros((cout,), dtype)
    return p


def conv2d(
    p: dict,
    x: jnp.ndarray,
    stride: int | Tuple[int, int] = 1,
    padding: str | Sequence[Tuple[int, int]] = "SAME",
) -> jnp.ndarray:
    """NHWC x HWIO -> NHWC convolution (MXU path)."""
    if isinstance(stride, int):
        stride = (stride, stride)
    out = lax.conv_general_dilated(
        x,
        p["w"].astype(x.dtype),
        window_strides=stride,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if "b" in p:
        out = out + p["b"].astype(x.dtype)
    return out


# ---- pooling -----------------------------------------------------------------


def max_pool(x: jnp.ndarray, window: int = 2, stride: int = 2) -> jnp.ndarray:
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        "VALID",
    )


def avg_pool(x: jnp.ndarray, window: int = 2, stride: int = 2) -> jnp.ndarray:
    s = lax.reduce_window(
        x, 0.0, lax.add, (1, window, window, 1), (1, stride, stride, 1), "VALID"
    )
    return s / (window * window)


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(x, axis=(1, 2))


# ---- normalization -----------------------------------------------------------


def batchnorm_init(dim: int, dtype=jnp.float32) -> Tuple[dict, dict]:
    """Returns (params, state): scale/bias are learned; mean/var are running
    statistics threaded functionally (state in, state out)."""
    params = {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}
    state = {"mean": jnp.zeros((dim,), jnp.float32), "var": jnp.ones((dim,), jnp.float32)}
    return params, state


def batchnorm(
    p: dict,
    s: dict,
    x: jnp.ndarray,
    train: bool = False,
    momentum: float = 0.9,
    eps: float = 1e-5,
) -> Tuple[jnp.ndarray, dict]:
    """BatchNorm over all but the channel (last) axis. Returns (y, new_state)."""
    if train:
        axes = tuple(range(x.ndim - 1))
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + eps) * p["scale"].astype(jnp.float32)
    y = (x.astype(jnp.float32) - mean) * inv + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new_s


def layernorm_init(dim: int, dtype=jnp.float32) -> dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layernorm(p: dict, x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


# ---- activations -------------------------------------------------------------

relu = jax.nn.relu
gelu = jax.nn.gelu
softmax = jax.nn.softmax


def relu6(x: jnp.ndarray) -> jnp.ndarray:
    """min(max(x, 0), 6) — MobileNet's quantization-friendly activation."""
    return jnp.clip(x, 0.0, 6.0)


def depthwise_conv_init(rng, kh: int, kw: int, c: int, dtype=jnp.float32) -> dict:
    """Per-channel (depthwise) kernel: HWIO with I=1, grouped over channels."""
    return {"w": he_normal(rng, (kh, kw, 1, c), kh * kw, dtype)}


def depthwise_conv2d(
    p: dict,
    x: jnp.ndarray,
    stride: int | Tuple[int, int] = 1,
    padding: str | Sequence[Tuple[int, int]] = "SAME",
) -> jnp.ndarray:
    """NHWC depthwise convolution (feature_group_count = channels)."""
    if isinstance(stride, int):
        stride = (stride, stride)
    c = x.shape[-1]
    return lax.conv_general_dilated(
        x,
        p["w"].astype(x.dtype),
        window_strides=stride,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
