"""Multi-head attention with a Pallas flash-attention fast path.

The reference has no attention (CNN workloads only, SURVEY.md §5.7); the
ViT-B/16 config in BASELINE.json adds it. On TPU the score/softmax/value
contraction runs as a fused Pallas kernel (:mod:`storm_tpu.ops.flash_attention`)
so the (S, S) score matrix never round-trips to HBM; on CPU (tests) and for
shapes the kernel doesn't cover, a plain jnp reference path is used — both
paths are numerically cross-checked in tests/test_ops.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


from storm_tpu.ops.platform import use_pallas as _use_pallas


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: Optional[float] = None
) -> jnp.ndarray:
    """Plain softmax(q k^T / sqrt(d)) v. Shapes: (B, H, S, D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


def _flash_min_seq() -> int:
    """Sequence length above which the Pallas flash kernel dispatches.

    Below it, XLA's own fused attention is FASTER on TPU (measured on-chip:
    vit_b16 S=197 runs 20.3ms/step via XLA vs 29.1ms via flash,
    BENCH_NOTES.md round 2) — the S^2 score tensor is small enough that
    fusion beats tiling, so flash only pays off where it was designed to:
    long sequences whose S^2 intermediates would blow HBM traffic/VMEM
    (and the ring-attention SP path, which calls it directly)."""
    import os

    return int(os.environ.get("STORM_TPU_FLASH_MIN_SEQ", "1024"))


def scaled_dot_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: Optional[float] = None
) -> jnp.ndarray:
    """Dispatch: Pallas flash attention on TPU for long sequences, XLA's
    fused attention otherwise (shape-aware — see :func:`_flash_min_seq`)."""
    if _use_pallas() and q.shape[-2] >= _flash_min_seq():
        from storm_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, scale=scale)
    return attention_reference(q, k, v, scale=scale)


def mha_init(rng, dim: int, num_heads: int, dtype=jnp.float32) -> dict:
    from storm_tpu.ops.layers import dense_init

    ks = jax.random.split(rng, 4)
    return {
        "q": dense_init(ks[0], dim, dim, dtype),
        "k": dense_init(ks[1], dim, dim, dtype),
        "v": dense_init(ks[2], dim, dim, dtype),
        "o": dense_init(ks[3], dim, dim, dtype),
    }


def multi_head_attention(p: dict, x: jnp.ndarray, num_heads: int) -> jnp.ndarray:
    """Self-attention over (B, S, C) activations."""
    from storm_tpu.ops.layers import dense

    b, s, c = x.shape
    d = c // num_heads

    def split(y):
        return y.reshape(b, s, num_heads, d).transpose(0, 2, 1, 3)

    q, k, v = split(dense(p["q"], x)), split(dense(p["k"], x)), split(dense(p["v"], x))
    out = scaled_dot_attention(q, k, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, c)
    return dense(p["o"], out)
