"""Pallas TPU fused w8a16 dequant-matmul.

Why: the baseline w8a16 path (`storm_tpu.infer.engine.dequantize_params`)
dequantizes int8 weights inside jit and relies on XLA to fuse the
int8→bf16 convert+scale into each weight's consumer. When XLA instead
materializes the dequantized matrix, the HBM read per matmul doubles —
exactly the traffic weight-only quantization exists to avoid. This kernel
*guarantees* the int8 bytes are what leaves HBM: each program reads an
(int8 K×bn weight tile + bm×K activation tile) into VMEM, upcasts in
registers, accumulates f32 on the MXU, and applies the per-output-channel
scale once to the accumulator (valid because quantization is symmetric
per last axis: ``x @ (q * s) == (x @ q) * s``).

Reference parity note: the reference has no quantization at all (its
engine is TF-Java float32, InferenceBolt.java:80-86); this is part of the
beyond-parity serving path (`ModelConfig.weights = "int8_fused"`).

Layout: ``x (..., K) @ q (K, N) * s (N,) -> (..., N)`` in x.dtype. Leading
dims flatten to M. Grid is (M/bm, N/bn); K lives fully in VMEM per program
(K ≤ a few thousand for every model in the zoo) and is consumed in
``block_k`` chunks with zero-padding — zeros contribute nothing to the
accumulator, so no masking is needed. M/N are padded to block multiples
and sliced off on return.

CPU/tests: ``interpret=True`` runs the same kernel under the Pallas
interpreter — cross-checked against the jnp dequant reference in
tests/test_ops.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, *, block_k):
    kp = x_ref.shape[1]
    nk = kp // block_k

    acc0 = jnp.zeros((x_ref.shape[0], o_ref.shape[1]), jnp.float32)

    def body(i, acc):
        xb = x_ref[:, pl.ds(i * block_k, block_k)]  # (BM, BK) activations
        qb = q_ref[pl.ds(i * block_k, block_k), :].astype(xb.dtype)
        return acc + lax.dot_general(
            xb, qb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    acc = lax.fori_loop(0, nk, body, acc0)
    o_ref[...] = (acc * s_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def _pad_to(a, axis, mult):
    n = a.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret")
)
def w8a16_matmul(
    x: jnp.ndarray,
    q: jnp.ndarray,
    s: jnp.ndarray,
    *,
    block_m: int = 512,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x (..., K) @ (q (K, N) int8 * s (N,)) -> (..., N)`` in x.dtype.

    Block defaults from the round-2 on-chip sweep (BENCH_NOTES.md): the
    round-1 128/128/256 tiles ran the vit_b16 mlp_in shape at 1.39 ms vs
    0.60-0.67 ms with 512-wide tiles (~2.2x). Even tuned, XLA's own
    dequant+matmul fusion remains faster at the zoo's compute-bound
    shapes — ``weights="int8"`` is the recommended w8a16 mode; this
    kernel's guarantee (int8 bytes are all that leaves HBM) matters in
    weight-bandwidth-bound regimes (very large K x N, small M)."""
    *lead, k = x.shape
    kq, n = q.shape
    assert k == kq, f"contraction mismatch: x K={k}, q K={kq}"
    assert s.shape == (n,), f"scale must be ({n},), got {s.shape}"

    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    # Mosaic wants (8, 128)-aligned f32 tiles: round the row block up to a
    # multiple of 8 rather than using a small M verbatim.
    bm = min(block_m, ((max(8, m) + 7) // 8) * 8)
    x2 = _pad_to(_pad_to(x2, 1, block_k), 0, bm)
    qp = _pad_to(_pad_to(q, 0, block_k), 1, block_n)
    sp = _pad_to(s.astype(jnp.float32).reshape(1, n), 1, block_n)
    mp, kp = x2.shape
    np_ = qp.shape[1]

    out = pl.pallas_call(
        functools.partial(_qmm_kernel, block_k=block_k),
        grid=(mp // bm, np_ // block_n),
        in_specs=[
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=interpret,
    )(x2, qp, sp)
    return out[:m, :n].reshape(*lead, n)


def qdense(p: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Dense layer over quantized weights ``{"__q", "__s"}`` (the
    `quantize_params` leaf format), Pallas-fused on TPU."""
    from storm_tpu.ops.platform import use_pallas

    w = p["w"]
    if use_pallas():
        y = w8a16_matmul(x, w["__q"], w["__s"])
    else:
        wd = (w["__q"].astype(x.dtype) * w["__s"].astype(x.dtype))
        y = jnp.dot(x, wd, preferred_element_type=jnp.float32).astype(x.dtype)
    return y + p["b"]
