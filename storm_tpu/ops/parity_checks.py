"""Compiled-kernel parity checks: every Pallas kernel vs its jnp reference.

Why this module exists: the interpret-mode tests in tests/test_ops.py prove
the *kernel math* but run under the Pallas interpreter on CPU — a Mosaic
compilation bug (tiling, layout, masking) would be invisible to them. These
checks run the SAME kernels compiled (``interpret=False``) and compare
against the jnp references to tight tolerances; they are the "correct
softmax out of the serving path" obligation the reference carries in its
engine (InferenceBolt.java:81-86), applied to the TPU fast paths.

Two consumers share these functions so the suite and the artifact can never
check different things:
  - tests/test_tpu_kernels.py — pytest wrappers, skipped (not passed)
    off-TPU;
  - tpu_kernel_parity.py (repo root) — runs on the real chip and writes
    KERNEL_TPU_r{N}.json for the round record.
"""

from __future__ import annotations

from typing import List

import numpy as np


def _row(kernel: str, case: str, dtype: str, got, want,
         rel_tol: float = None, abs_tol: float = None) -> dict:
    """Error row. Matmul kernels compare RELATIVE to the reference's max
    magnitude (TPU MXU multiplies f32 at bf16 precision by default, so a
    K-independent absolute bound would be meaningless across shapes);
    elementwise kernels use absolute error. The reference is computed at
    precision=highest so the measured error is the kernel's own."""
    abs_err = float(np.abs(got - want).max())
    scale = float(np.abs(want).max())
    rel_err = abs_err / scale if scale else abs_err
    if rel_tol is not None:
        ok, tol, metric = rel_err <= rel_tol, rel_tol, "rel"
    else:
        ok, tol, metric = abs_err <= abs_tol, abs_tol, "abs"
    return {"kernel": kernel, "case": case, "dtype": dtype,
            "max_abs_err": round(abs_err, 8),
            "max_rel_err": round(rel_err, 8),
            "metric": metric, "tol": tol, "pass": bool(ok)}


def check_flash_attention(interpret: bool = False) -> List[dict]:
    """Compiled flash attention vs the jnp reference path.

    Cases: the long-context flagship shape (S=2048, the regime the kernel
    exists for — multi-query-block grid, full online-softmax carry), a
    non-pow2 padded shape, and bf16 at S=2048 (the serving dtype). Error
    is measured in f32 against an f32 reference; bf16 tolerance reflects
    one output rounding step (~8-bit mantissa), not accumulated error —
    the kernel's carry is f32 throughout."""
    import jax
    import jax.numpy as jnp

    from storm_tpu.ops.attention import attention_reference
    from storm_tpu.ops.flash_attention import flash_attention

    rows = []
    # Two certifications per f32 case (measured on-chip, round 5):
    #   @highest — kernel traced under precision=highest: isolates Mosaic
    #     compilation (tiling/masking/layout) from MXU multiply precision;
    #     measured 4.6e-7 rel on S=2048, so 1e-5 is a real bug detector.
    #   @default — the serving configuration (MXU multiplies f32 at bf16
    #     precision): measured ~3.5e-3 rel, bounded at 5e-3.
    cases = [
        ("S2048", (1, 2, 2048, 64), jnp.float32),
        ("S2048_bf16", (1, 2, 2048, 64), jnp.bfloat16),
        ("S4096_multiblock", (1, 1, 4096, 128), jnp.float32),
        ("S600_padded", (1, 1, 600, 64), jnp.float32),
    ]
    for case, (b, h, s, d), dt in cases:
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
            .astype(dt) for i in range(3))
        # Reference sees the SAME (possibly bf16-rounded) inputs upcast to
        # f32 at highest matmul precision, so the measured error is the
        # kernel's own — accumulation order, MXU multiply precision, and
        # output rounding — not the input cast.
        with jax.default_matmul_precision("highest"):
            want = np.asarray(attention_reference(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32)), np.float32)
            if dt == jnp.float32:
                got_hi = np.asarray(
                    flash_attention(q, k, v, interpret=interpret), np.float32)
                rows.append(_row("flash_attention", f"{case}@highest",
                                 np.dtype(dt).name, got_hi, want,
                                 rel_tol=1e-5))
        got = np.asarray(flash_attention(q, k, v, interpret=interpret),
                         np.float32)
        rel_tol = 1e-2 if dt == jnp.bfloat16 else 5e-3
        rows.append(_row("flash_attention", f"{case}@default",
                         np.dtype(dt).name, got, want, rel_tol=rel_tol))
    return rows


def check_fused_norm(interpret: bool = False) -> List[dict]:
    """Compiled fused residual-add+LayerNorm vs the unfused jnp reference.

    Covers lane padding (d=100), multi-row-block grids, and the ViT dim.
    Both outputs (residual stream y and the normed tensor) are checked."""
    import jax.numpy as jnp
    import numpy as np_mod

    from storm_tpu.ops.fused_norm import _fused_fwd_pallas, _reference

    rng = np_mod.random.RandomState(0)
    rows = []
    for rows_n, d in [(6, 64), (300, 100), (1024, 768)]:
        x = jnp.asarray(rng.randn(rows_n, d), jnp.float32)
        r = jnp.asarray(rng.randn(rows_n, d), jnp.float32)
        g = jnp.asarray(rng.randn(d), jnp.float32)
        b = jnp.asarray(rng.randn(d), jnp.float32)
        wy, wo = _reference(x, r, g, b, 1e-6)
        gy, go = _fused_fwd_pallas(x, r, g, b, eps=1e-6, interpret=interpret)
        rows.append(_row("fused_norm.y", f"{rows_n}x{d}", "float32",
                         np.asarray(gy), np.asarray(wy), abs_tol=1e-5))
        rows.append(_row("fused_norm.ln", f"{rows_n}x{d}", "float32",
                         np.asarray(go), np.asarray(wo), abs_tol=1e-4))
    return rows


def check_w8a16(interpret: bool = False) -> List[dict]:
    """Compiled fused w8a16 dequant-matmul vs explicit dequantize-then-dot.

    Shapes exercise M/N/K padding, the multi-chunk K loop, 3-D (token)
    activations, and bf16 activations (the serving dtype for
    weights="int8_fused")."""
    import jax.numpy as jnp

    from storm_tpu.infer.engine import quantize_params
    from storm_tpu.ops.quant_matmul import w8a16_matmul

    import jax

    rng = np.random.RandomState(0)
    rows = []
    # Same two-row scheme as flash attention: @highest isolates Mosaic
    # compilation (tight 1e-5), @default certifies the serving precision
    # (bf16 MXU multiply, measured ~2e-3 rel, bounded at 5e-3).
    cases = [
        ("4x64@64x128", (4, 64), 64, 128, jnp.float32),
        ("5x100@100x70_padded", (5, 100), 100, 70, jnp.float32),
        ("2x9x48@48x200_tokens", (2, 9, 48), 48, 200, jnp.float32),
        ("1x700@700x10_multichunk", (1, 700), 700, 10, jnp.float32),
        ("64x768@768x3072_bf16", (64, 768), 768, 3072, jnp.bfloat16),
    ]
    for case, xshape, k, n, dt in cases:
        x = jnp.asarray(rng.randn(*xshape), jnp.float32).astype(dt)
        w = jnp.asarray(rng.randn(k, n), jnp.float32)
        q = quantize_params({"w": w})["w"]
        # Same-input reference (dtype-rounded x upcast to f32) at highest
        # matmul precision: measures the kernel's accumulation + output
        # rounding, not the input cast.
        with jax.default_matmul_precision("highest"):
            want = np.asarray(
                jnp.matmul(x.astype(jnp.float32),
                           q["__q"].astype(jnp.float32) * q["__s"]),
                np.float32)
            if dt == jnp.float32:
                got_hi = np.asarray(
                    w8a16_matmul(x, q["__q"], q["__s"], interpret=interpret),
                    np.float32)
                rows.append(_row("w8a16_matmul", f"{case}@highest",
                                 np.dtype(dt).name, got_hi, want,
                                 rel_tol=1e-5))
        got = np.asarray(
            w8a16_matmul(x, q["__q"], q["__s"], interpret=interpret),
            np.float32)
        rel_tol = 2e-2 if dt == jnp.bfloat16 else 5e-3
        rows.append(_row("w8a16_matmul", f"{case}@default",
                         np.dtype(dt).name, got, want, rel_tol=rel_tol))
    return rows


def run_all(interpret: bool = False) -> List[dict]:
    return (check_flash_attention(interpret)
            + check_fused_norm(interpret)
            + check_w8a16(interpret))
