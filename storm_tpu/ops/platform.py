"""Shared Pallas-vs-reference dispatch predicate for the ops package.

Kernels (flash attention, w8a16 dequant-matmul) run as Pallas on TPU and
fall back to jnp reference paths elsewhere (CPU tests, unsupported
shapes). ``STORM_TPU_NO_PALLAS`` forces the reference paths everywhere —
the escape hatch for debugging numeric diffs.
"""

from __future__ import annotations

import os

import jax


def use_pallas() -> bool:
    if os.environ.get("STORM_TPU_NO_PALLAS"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False
