"""Child-side multilang helper — the ``storm.py`` equivalent.

Import this INSIDE a shell component subprocess (see
:class:`storm_tpu.runtime.shell.ShellBolt` for the host side). The
protocol is newline-JSON messages terminated by a line ``end`` on
stdin/stdout, identical framing to Storm's multilang so components are
portable between the two.

A complete bolt::

    from storm_tpu.multilang import ShellComponent

    class Doubler(ShellComponent):
        def process(self, tup):
            self.emit([tup["tuple"][0] * 2], anchors=[tup["id"]])
            self.ack(tup["id"])

    Doubler().run()
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional


class ShellComponent:
    """Base class: handshake on construction, ``run()`` loops forever."""

    def __init__(self) -> None:
        # Keep the REAL stdout for the protocol and point sys.stdout at
        # stderr, exactly like Storm's storm.py: a user print() in
        # process() must never corrupt the newline-JSON framing.
        self._out = sys.stdout
        sys.stdout = sys.stderr
        self.setup = self._read()  # {"conf", "context", "pidDir"}
        self.conf = self.setup.get("conf", {})
        self.context = self.setup.get("context", {})
        self._send({"pid": os.getpid()})

    # ---- framing -------------------------------------------------------------

    def _read(self) -> Dict[str, Any]:
        lines: List[str] = []
        while True:
            line = sys.stdin.readline()
            if not line:
                sys.exit(0)  # host closed stdin: clean shutdown
            if line.strip() == "end":
                break
            lines.append(line)
        return json.loads("".join(lines))

    def _send(self, obj: Dict[str, Any]) -> None:
        self._out.write(json.dumps(obj) + "\nend\n")
        self._out.flush()

    # ---- component surface ---------------------------------------------------

    def emit(self, values: List[Any], anchors: Optional[List[str]] = None,
             stream: Optional[str] = None, id: Optional[str] = None) -> None:
        msg: Dict[str, Any] = {
            "command": "emit",
            "tuple": list(values),
            "need_task_ids": False,  # we don't route; skip the reply round trip
        }
        if anchors:
            msg["anchors"] = list(anchors)
        if stream:
            msg["stream"] = stream
        if id is not None:
            msg["id"] = id  # spout emits: at-least-once tracking id
        self._send(msg)

    def ack(self, tuple_id: str) -> None:
        self._send({"command": "ack", "id": tuple_id})

    def fail(self, tuple_id: str) -> None:
        self._send({"command": "fail", "id": tuple_id})

    def log(self, msg: str) -> None:
        self._send({"command": "log", "msg": str(msg)})

    # ---- lifecycle -----------------------------------------------------------

    def process(self, tup: Dict[str, Any]) -> None:
        raise NotImplementedError

    def run(self) -> None:
        while True:
            tup = self._read()
            if isinstance(tup, list):
                continue  # bare task-ids reply to an emit (Storm framing)
            if tup.get("stream") == "__heartbeat__":
                self._send({"command": "sync"})
                continue
            self.process(tup)


class ShellSpoutComponent(ShellComponent):
    """Child-side SOURCE: override ``next`` (emit zero or more tuples with
    ids), ``on_ack``/``on_fail`` for replay policy. The host drives the
    next/ack/fail cycle; each cycle ends with the automatic ``sync``."""

    def next(self) -> None:
        raise NotImplementedError

    def on_ack(self, tuple_id: str) -> None:
        pass

    def on_fail(self, tuple_id: str) -> None:
        pass

    def run(self) -> None:
        while True:
            msg = self._read()
            if isinstance(msg, list):
                continue  # bare task-ids reply
            cmd = msg.get("command")
            if cmd == "next":
                self.next()
            elif cmd == "ack":
                self.on_ack(msg.get("id"))
            elif cmd == "fail":
                self.on_fail(msg.get("id"))
            self._send({"command": "sync"})
