"""Cascade runtime: one shared engine + batcher per tier.

The :class:`CascadeRouter` owns the per-tier state the inference operator
drives: tier engines (built through the process-level ``shared_engine``
cache, so two bolts cascading over the same models share params in HBM),
per-tier micro-batchers for the escalated residue, the accept/escalate
decision (confidence math from :mod:`storm_tpu.cascade.policy`), and the
escalation-budget window.

Division of labor with the operator: the operator keeps owning tasks,
the dispatch semaphore (``max_inflight`` backpressure now bounds device
round trips ACROSS tiers), deferred acks, and replay — the router never
touches a tuple's lifecycle. A record's original payload (runtime tuple or
chunk handle) rides every tier inside an :class:`Escalated` wrapper that
ack/fail unwrap, so exactly-once semantics are identical to the
single-engine path: a tier failure fails the original tuples -> replay
from tier 0.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from storm_tpu.cascade.policy import CascadeConfig, uncertainty
from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
from storm_tpu.infer.batcher import Batch, MicroBatcher


class Escalated:
    """A record's batch payload while it rides an escalation tier.

    ``payload`` is the ORIGINAL payload (runtime tuple or chunk handle) —
    completion always targets it, whatever tier it lands on. ``link_span``
    carries the device span id of the tier that escalated it, so the next
    tier's queue_wait span links back and the trace shows the full
    tier-to-tier journey of a hard record.

    Escalation granularity is the ROW: a multi-instance record's
    confident rows accept where they are and only the uncertain residue
    rides up, so ``partial`` holds the already-accepted rows (full
    (n_rows, K) buffer in original row order) and ``row_idx`` the
    original positions of the rows still undecided. Both stay ``None``
    while the record escalates whole. The record's single output message
    is merged from ``partial`` when its last row decides — the ack tree
    never sees a partially-answered record."""

    __slots__ = ("payload", "link_span", "partial", "row_idx")

    def __init__(self, payload, link_span: Optional[str] = None) -> None:
        self.payload = payload
        self.link_span = link_span
        self.partial = None
        self.row_idx = None


class _Residue:
    """The escalated rows of one record, shaped like a BatchItem for the
    next tier's ``batcher.add`` (payload/data/ts/lane)."""

    __slots__ = ("payload", "data", "ts", "lane")

    def __init__(self, payload, data, ts, lane) -> None:
        self.payload = payload
        self.data = data
        self.ts = ts
        self.lane = lane


class _Tier:
    __slots__ = ("index", "model_cfg", "engine", "batcher", "m_device",
                 "m_accepted")

    def __init__(self, index: int, model_cfg: ModelConfig) -> None:
        self.index = index
        self.model_cfg = model_cfg
        self.engine = None
        self.batcher = None
        self.m_device = None
        self.m_accepted = None

    @property
    def name(self) -> str:
        return self.model_cfg.name


class CascadeRouter:
    def __init__(self, cfg: CascadeConfig, qos=None) -> None:
        self.cfg = cfg
        self.qos = qos if (qos is not None and qos.enabled) else None
        self.tiers: List[_Tier] = [
            _Tier(i, None) for i in range(len(cfg.tiers))]
        # Sliding escalation-budget window (tier-0 decisions): halved in
        # place at budget_window so the rate tracks recent traffic without
        # per-record history.
        self._win_total = 0
        self._win_escalated = 0
        self._m = None

    # ---- construction --------------------------------------------------------

    def tier_model(self, i: int, base: ModelConfig) -> ModelConfig:
        """The tier's ModelConfig: the operator's config with the tier's
        registry name + checkpoint swapped in (dtype/shape/wire knobs are
        shared — every tier must accept the same decoded records)."""
        name = self.cfg.tiers[i]
        if self.cfg.checkpoints:
            ckpt = self.cfg.checkpoints[i] or None
        else:
            ckpt = base.checkpoint if name == base.name else None
        if name == base.name and ckpt == base.checkpoint:
            return base
        return dataclasses.replace(base, name=name, checkpoint=ckpt)

    def build(self, base: ModelConfig, sharding: ShardingConfig,
              batch_cfg: BatchConfig, build_engine, flagship=None,
              warmup: bool = False) -> None:
        """Build/fetch one engine per tier via ``build_engine`` (the
        operator's ``shared_engine`` closure) plus one batcher per tier
        for escalated residue. ``flagship`` (the operator's already-built
        engine) is reused for the tier whose config matches it — injected
        test/bench engines included."""
        for tier in self.tiers:
            mc = self.tier_model(tier.index, base)
            tier.model_cfg = mc
            if flagship is not None and mc is base:
                tier.engine = flagship
            else:
                tier.engine = build_engine(mc)
                if warmup:
                    tier.engine.warmup()
            if self.qos is not None:
                from storm_tpu.qos.lanes import LaneBatcher

                tier.batcher = LaneBatcher(batch_cfg, self.qos)
            else:
                tier.batcher = MicroBatcher(batch_cfg)
        shapes = {tuple(t.engine.input_shape) for t in self.tiers}
        if len(shapes) > 1:
            raise ValueError(
                f"cascade tiers disagree on input_shape: "
                f"{ {t.name: tuple(t.engine.input_shape) for t in self.tiers} }"
                " — every tier sees the same decoded records")

    def bind_metrics(self, metrics, component_id: str) -> None:
        self._m = metrics
        self._cid = component_id
        for tier in self.tiers:
            tier.m_device = metrics.histogram(
                component_id, f"tier{tier.index}_device_ms")
            tier.m_accepted = metrics.counter(
                component_id, f"cascade_accepted_tier{tier.index}")
        self._m_escalations = metrics.counter(
            component_id, "cascade_escalations")
        self._m_capped = metrics.counter(
            component_id, "cascade_budget_capped")
        self._m_pinned = metrics.counter(
            component_id, "cascade_shed_pinned")
        self._g_rate = metrics.gauge("cascade", "escalation_rate")

    # ---- routing -------------------------------------------------------------

    @property
    def last_tier(self) -> int:
        return len(self.tiers) - 1

    def entry_tier(self, lane: Optional[str], shed_level: int) -> int:
        return self.cfg.entry_tier(lane, shed_level, self.qos)

    def escalation_rate(self) -> float:
        return (self._win_escalated / self._win_total
                if self._win_total else 0.0)

    def _budget_allows(self) -> bool:
        if self.cfg.escalation_budget >= 1.0:
            return True
        if self.cfg.escalation_budget <= 0.0:
            return False
        return (self._win_escalated + 1) <= (
            self.cfg.escalation_budget * (self._win_total + 1))

    @staticmethod
    def _merge(wrapper, preds):
        """The record's final output: its partial buffer with the rows
        just decided filled in, or the tier output as-is for records that
        never split."""
        if wrapper is None or wrapper.partial is None:
            return preds
        wrapper.partial[wrapper.row_idx] = preds
        return wrapper.partial

    def decide_item(self, payload, data, preds, lane, tier_idx: int,
                    shed_level: int, ts=None):
        """Accept-or-escalate ONE record's tier output.

        Returns ``(merged_preds_or_None, residue_or_None, info)``: when
        the record (or its last undecided rows) accepts here,
        ``merged_preds`` is the full output in original row order and
        ``residue`` is None; when any rows escalate, ``merged_preds`` is
        None and ``residue`` is the :class:`_Residue` for tier
        ``tier_idx + 1`` (data sliced to the uncertain rows, lane/ts
        preserved). ``info`` carries this record's row counts
        (accepted/escalated/pinned/budget_capped).

        Decision granularity is the ROW: each row accepts where its own
        uncertainty clears the tier's threshold, and only the uncertain
        residue escalates — a multi-instance record with one hard image
        sends ONE row up, not all of them (record-level worst-row gating
        collapses to flagship-only as record width grows: P(all n rows
        confident) -> 0). Accepted rows park in the record's
        :class:`Escalated` partial buffer; the record emits once, merged
        in original row order, when its last row decides. Pinned (shed)
        and budget-capped records accept all remaining rows at this
        tier. Counters (``cascade_accepted_tier{i}``,
        ``cascade_escalations``, lane counters, the budget window) all
        count ROWS, which for single-instance records is identical to
        counting records. This is the unit both dispatch paths share:
        the batch path (:meth:`decide`) loops it over a fetched batch;
        the continuous path calls it per resolved submission."""
        tier = self.tiers[tier_idx]
        n = int(data.shape[0])
        wrapper = payload if isinstance(payload, Escalated) else None
        pinned = capped = 0
        if tier_idx == self.last_tier:
            esc_mask = np.zeros(n, dtype=bool)
        elif self.cfg.pinned(lane, shed_level, self.qos):
            pinned = n
            esc_mask = np.zeros(n, dtype=bool)
            for _ in range(n):
                self._charge(tier_idx, escalate=False)
        else:
            row_u = uncertainty(preds, self.cfg.metric, self.cfg.temperature)
            thr = self.cfg.threshold_for(tier_idx, lane, shed_level)
            esc_mask = np.asarray(row_u >= thr).reshape(-1).copy()
            # Row-order budget walk, window charges interleaved with
            # decisions exactly as record-level gating charged them.
            for j in range(n):
                if esc_mask[j] and not self._budget_allows():
                    esc_mask[j] = False
                    capped += 1
                self._charge(tier_idx, escalate=bool(esc_mask[j]))
        n_esc = int(esc_mask.sum())
        if n_esc == 0:
            merged, residue = self._merge(wrapper, preds), None
        else:
            if wrapper is None:
                wrapper = Escalated(payload)
            if n_esc < n:
                cur_idx = wrapper.row_idx if wrapper.row_idx is not None \
                    else np.arange(n)
                if wrapper.partial is None:
                    wrapper.partial = np.zeros(
                        (n, preds.shape[-1]), dtype=preds.dtype)
                keep = ~esc_mask
                wrapper.partial[cur_idx[keep]] = preds[keep]
                wrapper.row_idx = cur_idx[esc_mask]
                residue = _Residue(wrapper, data[esc_mask], ts, lane)
            else:
                residue = _Residue(wrapper, data, ts, lane)
            merged = None
        rows_accepted = n - n_esc
        if self._m is not None:
            lane_key = lane or "default"
            self._m.counter(
                self._cid, f"cascade_decided_lane_{lane_key}").inc(n)
            if n_esc:
                self._m.counter(
                    self._cid, f"cascade_escalated_lane_{lane_key}").inc(
                    n_esc)
            if rows_accepted:
                tier.m_accepted.inc(rows_accepted)
            if n_esc:
                self._m_escalations.inc(n_esc)
            if capped:
                self._m_capped.inc(capped)
            if pinned:
                self._m_pinned.inc(pinned)
            self._g_rate.set(self.escalation_rate())
        info = {"accepted": rows_accepted, "escalated": n_esc,
                "pinned": pinned, "budget_capped": capped}
        return merged, residue, info

    def decide(self, batch: Batch, out, tier_idx: int, shed_level: int):
        """Split one fetched tier output into accepts and escalations.

        Returns ``(accepted, escalated, info)``: ``accepted`` is
        ``[(payload, merged_preds)]`` ready for the operator's emit+ack
        loop, ``escalated`` the per-record residue items (original
        data/ts/lane preserved, data sliced to the uncertain rows) to
        re-batch into tier ``tier_idx + 1``, and ``info`` the decision
        stats for the flight-recorder event. Each record's decision is
        one :meth:`decide_item` call — the same unit the continuous
        batcher drives per resolved submission."""
        accepted, escalated = [], []
        agg = {"accepted": 0, "escalated": 0, "pinned": 0,
               "budget_capped": 0}
        ofs = 0
        for it in batch.items:
            n = it.data.shape[0]
            preds = out[ofs:ofs + n]
            ofs += n
            merged, residue, info = self.decide_item(
                it.payload, it.data, preds, it.lane, tier_idx, shed_level,
                ts=it.ts)
            if residue is None:
                accepted.append((it.payload, merged))
            else:
                escalated.append(residue)
            for k in agg:
                agg[k] += info[k]
        info = {"tier": tier_idx, "model": self.tiers[tier_idx].name,
                **agg, "escalation_rate": round(self.escalation_rate(), 4)}
        return accepted, escalated, info

    def _charge(self, tier_idx: int, escalate: bool) -> None:
        # Budget window counts TIER-0 decisions only: the budget caps how
        # much of the ingress stream may leave tier 0; records already
        # past the gate aren't re-charged at later tiers.
        if tier_idx != 0:
            return
        self._win_total += 1
        if escalate:
            self._win_escalated += 1
        if self._win_total >= max(1, int(self.cfg.budget_window)):
            self._win_total //= 2
            self._win_escalated //= 2

    # ---- observability -------------------------------------------------------

    def inventory(self) -> list:
        """Per-tier engine attribution for the UI ``cascade`` route: which
        model serves each tier, its gate, the HBM its params occupy, and
        the tier's LIVE measured cost — so a multi-engine bolt reads as N
        sized tiers, not one opaque blob (ISSUE 5 satellite).

        ``cost`` is the cost profiler's per-row device cost for the
        tier's engine (storm_tpu/obs/profile.py), measured from this
        process's own traffic — the cheapest-first tier ordering the
        cascade config asserts is auditable here as numbers, not a
        doc note. None until the tier has served a batch."""
        from storm_tpu.obs.profile import profile_store

        store = profile_store()
        rows = []
        for tier in self.tiers:
            eng = tier.engine
            row = {
                "tier": tier.index,
                "model": tier.name,
                "checkpoint": tier.model_cfg.checkpoint,
                "threshold": (None if tier.index == self.last_tier
                              else self.cfg.thresholds[tier.index]),
                "pending_records": len(tier.batcher)
                if tier.batcher is not None else 0,
                "cost": store.cost_of(
                    getattr(eng, "profile_key", tier.name)),
            }
            for attr in ("param_bytes", "param_bytes_per_device"):
                fn = getattr(eng, attr, None)
                row[attr] = int(fn()) if callable(fn) else None
            rows.append(row)
        return rows
