"""Confidence-gated model cascade (ISSUE 5 tentpole).

``policy`` holds :class:`CascadeConfig` + the uncertainty math (import-light:
``Config`` embeds it); ``router`` holds the runtime that drives one shared
engine per tier through the operator's dispatch/fetch pipeline. The router
is exposed lazily so importing ``storm_tpu.config`` never drags the engine
stack in.
"""

from storm_tpu.cascade.policy import (  # noqa: F401
    CONFIDENCE_METRICS, CascadeConfig, fit_temperature, uncertainty)

__all__ = ["CONFIDENCE_METRICS", "CascadeConfig", "CascadeRouter",
           "Escalated", "fit_temperature", "uncertainty"]


def __getattr__(name):
    if name in ("CascadeRouter", "Escalated"):
        from storm_tpu.cascade import router

        return getattr(router, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
