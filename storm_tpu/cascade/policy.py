"""Cascade policy: tier configuration + confidence math.

A confidence-gated model cascade (InferLine; Divide-and-Conquer — PAPERS.md)
routes every record through an ordered list of model tiers, cheapest first.
A record is ACCEPTED at the first tier whose prediction it can trust and
only the hard residue escalates to the next (more expensive) tier, so the
flagship model sees a fraction of the traffic at matched accuracy.

Trust is an *uncertainty* test: each metric maps a softmax row to an
uncertainty score in [0, 1] (0 = certain, 1 = clueless), and a record
accepts at tier *i* when its worst row's uncertainty is strictly below
``thresholds[i]``. The identities follow directly:

* ``threshold = 0``  — nothing is ever certain enough: every record
  escalates to the flagship (flagship-only).
* ``threshold = 1``  — everything is trusted: every record accepts at
  tier 0 (tier-0-only).

Metrics (``p`` a softmax row over K classes, optionally re-tempered):

* ``max_softmax`` — ``1 - max(p)``
* ``margin``      — ``1 - (top1(p) - top2(p))``
* ``entropy``     — ``H(p) / log(K)`` (normalized Shannon entropy)

``temperature`` re-calibrates the probabilities before scoring
(``softmax(log p / T)``): converged models are over-confident, and a fitted
T > 1 spreads the scores so thresholds discriminate (fit it with
``accuracy_harness.py --cascade-sweep``).

This module is import-light on purpose (stdlib + numpy only): ``Config``
embeds :class:`CascadeConfig`, so nothing here may import back into
``storm_tpu.config`` or the engine/runtime layers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

CONFIDENCE_METRICS = ("max_softmax", "margin", "entropy")


@dataclass
class CascadeConfig:
    """Confidence-gated model cascade for the inference operator.

    Off by default: ``enabled=False`` leaves the single-engine operator
    untouched. TOML section ``[cascade]`` on the top-level :class:`Config`.
    """

    enabled: bool = False
    # Model registry names, cheapest tier first. A record enters at tier 0
    # and escalates until a tier accepts it; the last tier always accepts.
    # The ordering claim is auditable at runtime: the /cascade UI route's
    # per-tier ``cost`` rows carry each tier's LIVE measured ms/row from
    # the cost profiler (storm_tpu/obs/profile.py).
    tiers: tuple = ()
    # Per-tier checkpoint dirs aligned with ``tiers``. "" = inherit the
    # operator's model checkpoint when the tier name matches its model,
    # else random init. Empty tuple = apply that rule to every tier.
    checkpoints: tuple = ()
    # Per-tier uncertainty thresholds for every NON-final tier (the last
    # tier always accepts, so len == len(tiers) - 1). A record accepts at
    # tier i when its uncertainty < thresholds[i]; see the module
    # docstring for the 0/1 identities.
    thresholds: tuple = ()
    # Uncertainty metric: max_softmax | margin | entropy.
    metric: str = "max_softmax"
    # Softmax re-calibration temperature applied before scoring (> 0;
    # 1.0 = raw probabilities). Fit via accuracy_harness --cascade-sweep.
    temperature: float = 1.0
    # Per-QoS-lane threshold overrides: {"lane": (t0, t1, ...)} with the
    # same length as ``thresholds``. A latency-critical lane can run a
    # looser tier-0 gate (accept more, escalate less) than best-effort.
    lane_thresholds: dict = field(default_factory=dict)
    # Escalation-budget cap: the fraction of records allowed PAST tier 0
    # over a sliding window of ``budget_window`` decisions. When the
    # budget is exhausted, records accept at tier 0 regardless of
    # uncertainty (bounded flagship load under confidence collapse).
    # 1.0 = uncapped, 0.0 = never escalate (tier-0-only).
    escalation_budget: float = 1.0
    budget_window: int = 512
    # QoS coupling: each raised shed level multiplies the remaining
    # escalation strictness by this factor — effective threshold moves
    # toward 1 (accept-everything) as ``1 - (1 - t) * shed_tighten**level``
    # — and shed-ELIGIBLE lanes pin to tier 0 outright (no escalation).
    shed_tighten: float = 0.5
    # Degrade-compat mode (synthesized from qos.degrade_model): normal
    # traffic enters at the LAST tier (the flagship serves it directly)
    # and only shed-eligible records enter pinned at tier 0. A regular
    # cascade enters everything at tier 0.
    shed_only: bool = False

    def __post_init__(self) -> None:
        self.tiers = tuple(str(t) for t in self.tiers)
        self.checkpoints = tuple(str(c) for c in self.checkpoints)
        self.thresholds = tuple(float(t) for t in self.thresholds)
        self.lane_thresholds = {
            str(k): tuple(float(x) for x in v)
            for k, v in dict(self.lane_thresholds).items()}
        if not self.enabled:
            return
        if len(self.tiers) < 2:
            raise ValueError(
                "cascade.tiers needs >= 2 models (cheapest first); a "
                "single-model 'cascade' is just the plain operator")
        if self.checkpoints and len(self.checkpoints) != len(self.tiers):
            raise ValueError(
                f"cascade.checkpoints has {len(self.checkpoints)} entries "
                f"for {len(self.tiers)} tiers")
        if len(self.thresholds) != len(self.tiers) - 1:
            raise ValueError(
                f"cascade.thresholds needs one entry per non-final tier "
                f"({len(self.tiers) - 1}), got {len(self.thresholds)}")
        for t in self.thresholds:
            if not 0.0 <= t <= 1.0:
                raise ValueError(
                    f"cascade thresholds are uncertainty bounds in [0, 1], "
                    f"got {t!r}")
        if self.metric not in CONFIDENCE_METRICS:
            raise ValueError(
                f"cascade.metric must be one of {CONFIDENCE_METRICS}, "
                f"got {self.metric!r}")
        if float(self.temperature) <= 0.0:
            raise ValueError(
                f"cascade.temperature must be > 0, got {self.temperature!r}")
        if not 0.0 <= float(self.escalation_budget) <= 1.0:
            raise ValueError(
                "cascade.escalation_budget is a fraction in [0, 1], "
                f"got {self.escalation_budget!r}")
        if int(self.budget_window) < 1:
            raise ValueError(
                f"cascade.budget_window must be >= 1, got {self.budget_window!r}")
        if not 0.0 <= float(self.shed_tighten) <= 1.0:
            raise ValueError(
                f"cascade.shed_tighten must be in [0, 1], got {self.shed_tighten!r}")
        for lane, thr in self.lane_thresholds.items():
            if len(thr) != len(self.thresholds):
                raise ValueError(
                    f"cascade.lane_thresholds[{lane!r}] has {len(thr)} "
                    f"entries, expected {len(self.thresholds)}")
            for t in thr:
                if not 0.0 <= t <= 1.0:
                    raise ValueError(
                        f"cascade.lane_thresholds[{lane!r}] values must be "
                        f"in [0, 1], got {t!r}")

    # ---- routing policy ------------------------------------------------------

    @property
    def last_tier(self) -> int:
        return len(self.tiers) - 1

    def entry_tier(self, lane: Optional[str], shed_level: int, qos) -> int:
        """Which tier a fresh record enters at. Regular cascades start
        everything at tier 0; degrade-compat (``shed_only``) sends normal
        traffic straight to the flagship and only shed-eligible records
        into tier 0."""
        if not self.shed_only:
            return 0
        if shed_level > 0 and qos is not None \
                and qos.shed_eligible(lane, shed_level):
            return 0
        return self.last_tier

    def pinned(self, lane: Optional[str], shed_level: int, qos) -> bool:
        """Shed pins eligible lanes to their current tier: the record
        accepts where it is instead of escalating (the cascade IS the
        degrade path — satellite of ISSUE 5)."""
        return (shed_level > 0 and qos is not None
                and qos.shed_eligible(lane, shed_level))

    def threshold_for(self, tier: int, lane: Optional[str],
                      shed_level: int) -> float:
        """Effective uncertainty threshold for ``tier``: the per-lane
        override when one exists, widened toward accept-everything by the
        shed level (each level scales the remaining strictness ``1 - t``
        by ``shed_tighten``)."""
        base = self.lane_thresholds.get(lane, self.thresholds)[tier]
        if shed_level > 0:
            base = 1.0 - (1.0 - base) * (self.shed_tighten ** int(shed_level))
        return base


def uncertainty(probs: np.ndarray, metric: str = "max_softmax",
                temperature: float = 1.0) -> np.ndarray:
    """Per-row uncertainty scores in [0, 1] for a (n, K) batch of softmax
    probabilities (0 = certain). Shared by the router's accept/escalate
    split and the accuracy harness's threshold sweep — one definition, so
    an offline-tuned threshold means the same thing online."""
    p = np.asarray(probs, dtype=np.float64)
    if p.ndim == 1:
        p = p[None, :]
    p = np.clip(p, 1e-12, None)
    p = p / p.sum(axis=-1, keepdims=True)
    if temperature != 1.0:
        # Re-temper in log space: softmax(log p / T). T > 1 flattens the
        # over-confident converged distribution so scores discriminate.
        logp = np.log(p) / float(temperature)
        logp -= logp.max(axis=-1, keepdims=True)
        p = np.exp(logp)
        p = p / p.sum(axis=-1, keepdims=True)
    if metric == "max_softmax":
        return 1.0 - p.max(axis=-1)
    if metric == "margin":
        top2 = np.partition(p, -2, axis=-1)[..., -2:]
        return 1.0 - (top2[..., 1] - top2[..., 0])
    if metric == "entropy":
        k = p.shape[-1]
        if k < 2:
            return np.zeros(p.shape[0])
        h = -(p * np.log(p)).sum(axis=-1)
        return h / math.log(k)
    raise ValueError(f"unknown cascade metric {metric!r}")


def fit_temperature(probs: np.ndarray, labels: np.ndarray,
                    grid=None) -> dict:
    """Grid-fit a calibration temperature minimizing NLL of ``labels``
    under re-tempered ``probs`` (softmax(log p / T)) — the classic
    single-parameter post-hoc calibration. Returns the fit plus per-T
    NLL so the harness artifact shows the curve, not just the argmin."""
    if grid is None:
        grid = (0.5, 0.75, 1.0, 1.25, 1.5, 2.0, 2.5, 3.0, 4.0)
    p = np.clip(np.asarray(probs, np.float64), 1e-12, None)
    p = p / p.sum(axis=-1, keepdims=True)
    logp = np.log(p)
    rows = np.arange(len(labels))
    curve = []
    for t in grid:
        z = logp / float(t)
        z -= z.max(axis=-1, keepdims=True)
        q = np.exp(z)
        q = q / q.sum(axis=-1, keepdims=True)
        nll = float(-np.log(np.clip(q[rows, labels], 1e-12, None)).mean())
        curve.append({"temperature": float(t), "nll": round(nll, 5)})
    best = min(curve, key=lambda r: r["nll"])
    return {"temperature": best["temperature"], "nll": best["nll"],
            "curve": curve}
