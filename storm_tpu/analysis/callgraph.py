"""Project-wide call graph with blocking and lock summaries.

The PR 9 checkers are intraprocedural: LCK001 only sees a blocking call
written *directly* under the ``with lock:``, so ``recover_worker()``
holding the controller lock while calling ``self._reship()`` — which five
lines later issues a blocking control RPC — passes clean. This module is
the interprocedural tier the depth-N rules (LCK003/LCK004, THR001/THR002)
are built on:

* **Resolution.** Intra-project calls are resolved by name: module
  functions, imported functions/classes (absolute and relative imports),
  ``self.``/``cls.`` methods (with base-class walk), ``self.attr.meth()``
  through attribute types inferred from ``self.attr = Cls(...)``
  assignments, and ``var.meth()`` through function-local ``var = Cls(...)``
  assignments. Anything dynamic stays unresolved — the graph is
  deliberately under-approximate, so every edge it reports is real.

* **Blocking summaries.** Seeded from the LCK blocking table (plus
  ``[tool.storm-tpu.lint] blocking_methods``), propagated to a fixed point
  over the call graph by BFS from the directly-blocking functions — so
  each function carries a *shortest witness chain* down to the concrete
  blocking call (``recover_worker -> _reship -> client.control``), which
  LCK003 prints in its finding detail.

* **Lock summaries.** The set of lock keys a function may acquire,
  directly or transitively.  Combined with the per-call held-lock context
  recorded by the LCK walker, this yields the *interprocedural*
  acquisition edges (caller holds A, callee eventually takes B) that
  LCK004 feeds into full cycle detection.

Like every checker here this is a pure AST pass: nothing in the checked
tree is imported or executed.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis import locks
from storm_tpu.analysis.core import (
    LintConfig,
    SourceFile,
    dotted_name,
)


def module_of(path: str) -> str:
    """Dotted module name for a repo-relative path (packages collapse:
    ``storm_tpu/analysis/__init__.py`` -> ``storm_tpu.analysis``)."""
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


@dataclass
class FunctionNode:
    """One function/method (or the module top level, scope ``<module>``)."""

    qual: str  # "storm_tpu.dist.worker:PeerSender._flush"
    module: str
    scope: str
    path: str
    line: int = 0
    calls: List[locks.CallRecord] = field(default_factory=list)
    local_types: Dict[str, str] = field(default_factory=dict)
    resolved: List[str] = field(default_factory=list)  # callee quals
    call_raw: Dict[str, str] = field(default_factory=dict)  # qual -> raw text
    acquires: Set[str] = field(default_factory=set)
    blocking: List[Tuple[str, int]] = field(default_factory=list)
    may_block: bool = False
    block_via: Optional[str] = None  # next hop toward the blocking call
    block_reason: str = ""  # direct reason when this node is the seed
    trans_acquires: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.scope.rsplit(".", 1)[-1]


@dataclass
class ClassNode:
    qual: str  # "storm_tpu.dist.worker:PeerSender"
    module: str
    name: str
    path: str
    bases: List[str] = field(default_factory=list)  # raw dotted names
    methods: Dict[str, str] = field(default_factory=dict)  # name -> func qual
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> raw ctor


class LockedCall:
    """One call site executed while at least one lock is held."""

    __slots__ = ("path", "module", "scope", "raw", "line", "held", "reason")

    def __init__(self, path: str, module: str, scope: str, raw: str,
                 line: int, held: Tuple[str, ...],
                 reason: Optional[str]) -> None:
        self.path = path
        self.module = module
        self.scope = scope
        self.raw = raw
        self.line = line
        self.held = held
        self.reason = reason  # LCK001 reason, if the call blocks directly


#: function names that count as externally-driven lifecycle entry points
#: for THR001's "join must be reachable from a shutdown path" check.
_LIFECYCLE = re.compile(
    r"close|shutdown|stop|kill|drain|exit|finali[sz]e|join|serve|atexit"
    r"|teardown|cleanup|main|wait|__del__|reap", re.I)

_MAX_MRO_DEPTH = 8


class CallGraph:
    """Build once per lint run from the already-parsed ``SourceFile``s."""

    def __init__(self, files: Sequence[SourceFile],
                 config: Optional[LintConfig] = None) -> None:
        self.config = config or LintConfig()
        self.functions: Dict[str, FunctionNode] = {}
        self.classes: Dict[str, ClassNode] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        self.modules: Set[str] = set()
        self.locked_calls: List[LockedCall] = []
        #: syntactic same-function acquisition edges from the LCK walker
        self.lock_edges: List[Tuple[str, str, str, int, str]] = []
        self._lifecycle_reach: Optional[Set[str]] = None
        for sf in files:
            self._index_defs(sf)
        for sf in files:
            self._attach_records(sf)
        self._resolve_all()
        self._summarize()

    # -- indexing ---------------------------------------------------------

    def _index_defs(self, sf: SourceFile) -> None:
        module = module_of(sf.path)
        self.modules.add(module)
        imp = self.imports.setdefault(module, {})
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        imp[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        imp.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # level=1 -> current package; module names collapse
                    # __init__, so a module's package is itself minus the
                    # last segment (a package's package is itself).
                    pkg = module.split(".")
                    if not sf.path.endswith("/__init__.py"):
                        pkg = pkg[:-1]
                    pkg = pkg[: len(pkg) - (node.level - 1)]
                    base = ".".join(pkg + ([node.module] if node.module
                                           else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    imp[a.asname or a.name] = target
        self._walk_defs(sf, sf.tree.body, [], None, module, direct=False)
        self._ensure_func(module, "<module>", sf.path, 0)

    def _walk_defs(self, sf: SourceFile, body, scope_parts: List[str],
                   owner: Optional[ClassNode], module: str,
                   direct: bool) -> None:
        for st in body:
            if isinstance(st, ast.ClassDef):
                cname = ".".join(scope_parts + [st.name])
                cn = ClassNode(
                    qual=f"{module}:{cname}", module=module, name=cname,
                    path=sf.path,
                    bases=[dotted_name(b) for b in st.bases
                           if dotted_name(b)])
                self.classes[cn.qual] = cn
                self._walk_defs(sf, st.body, scope_parts + [st.name], cn,
                                module, direct=True)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = ".".join(scope_parts + [st.name])
                fn = self._ensure_func(module, scope, sf.path, st.lineno)
                if owner is not None and direct:
                    owner.methods.setdefault(st.name, fn.qual)
                self._collect_types(st, fn, owner)
                self._walk_defs(sf, st.body, scope_parts + [st.name],
                                owner, module, direct=False)

    def _collect_types(self, func, fn: FunctionNode,
                       owner: Optional[ClassNode]) -> None:
        for node in ast.walk(func):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            raw = dotted_name(node.value.func)
            if not raw:
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                fn.local_types.setdefault(tgt.id, raw)
            elif (owner is not None and isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == "self"):
                owner.attr_types.setdefault(tgt.attr, raw)

    def _ensure_func(self, module: str, scope: str, path: str,
                     line: int) -> FunctionNode:
        qual = f"{module}:{scope}"
        fn = self.functions.get(qual)
        if fn is None:
            fn = FunctionNode(qual=qual, module=module, scope=scope,
                              path=path, line=line)
            self.functions[qual] = fn
        elif line and not fn.line:
            fn.line = line
        return fn

    # -- walker records ---------------------------------------------------

    def _attach_records(self, sf: SourceFile) -> None:
        module = module_of(sf.path)
        w = locks._LockWalker(sf, self.config)
        w.run()
        self.lock_edges.extend(w.edges)
        for scope, key, _line in w.acquisitions:
            self._ensure_func(module, scope, sf.path, 0).acquires.add(key)
        for rec in w.calls:
            fn = self._ensure_func(module, rec.scope, sf.path, 0)
            fn.calls.append(rec)
            if rec.summary_reason:
                fn.blocking.append((rec.summary_reason, rec.line))
            if rec.held:
                self.locked_calls.append(LockedCall(
                    sf.path, module, rec.scope, rec.raw, rec.line,
                    rec.held, rec.reason))

    # -- resolution -------------------------------------------------------

    def _owning_class(self, module: str, scope: str) -> Optional[ClassNode]:
        best: Optional[ClassNode] = None
        name = ""
        for p in scope.split("."):
            name = f"{name}.{p}" if name else p
            cn = self.classes.get(f"{module}:{name}")
            if cn is None:
                break
            best = cn
        return best

    def _class_from_raw(self, module: str,
                        raw: str, depth: int = 0) -> Optional[ClassNode]:
        if not raw or depth > _MAX_MRO_DEPTH:
            return None
        cn = self.classes.get(f"{module}:{raw}")
        if cn is not None:
            return cn
        imp = self.imports.get(module, {})
        parts = raw.split(".")
        target = imp.get(parts[0])
        if target is None:
            return None
        if len(parts) == 1:
            head, _, tail = target.rpartition(".")
            return self.classes.get(f"{head}:{tail}")
        # "mod.Cls" through an imported module
        if target in self.modules:
            return self.classes.get(f"{target}:{'.'.join(parts[1:])}")
        return None

    def _method(self, cls: Optional[ClassNode], name: str,
                depth: int = 0) -> Optional[str]:
        if cls is None or depth > _MAX_MRO_DEPTH:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for braw in cls.bases:
            q = self._method(self._class_from_raw(cls.module, braw, depth + 1),
                             name, depth + 1)
            if q:
                return q
        return None

    def _attr_class(self, cls: Optional[ClassNode], attr: str,
                    depth: int = 0) -> Optional[ClassNode]:
        if cls is None or depth > _MAX_MRO_DEPTH:
            return None
        raw = cls.attr_types.get(attr)
        if raw:
            return self._class_from_raw(cls.module, raw)
        for braw in cls.bases:
            k = self._attr_class(
                self._class_from_raw(cls.module, braw, depth + 1), attr,
                depth + 1)
            if k is not None:
                return k
        return None

    def resolve(self, module: str, scope: str, raw: str,
                fn: Optional[FunctionNode] = None) -> Optional[str]:
        """Qual of the project function ``raw`` calls from ``scope``, or
        None when the target is dynamic or outside the project."""
        if not raw:
            return None
        parts = raw.split(".")
        imp = self.imports.get(module, {})
        if parts[0] in ("self", "cls"):
            owner = self._owning_class(module, scope)
            if owner is None:
                return None
            if len(parts) == 2:
                return self._method(owner, parts[1])
            if len(parts) == 3:
                return self._method(self._attr_class(owner, parts[1]),
                                    parts[2])
            return None
        if len(parts) == 1:
            name = parts[0]
            if scope != "<module>":
                nested = self.functions.get(f"{module}:{scope}.{name}")
                if nested is not None:
                    return nested.qual
            q = f"{module}:{name}"
            if q in self.functions:
                return q
            if q in self.classes:
                return self._method(self.classes[q], "__init__")
            target = imp.get(name)
            if target:
                return self._resolve_target(target)
            return None
        # var.meth() through a function-local constructor assignment
        if fn is not None and parts[0] in fn.local_types and len(parts) == 2:
            k = self._class_from_raw(module, fn.local_types[parts[0]])
            if k is not None:
                m = self._method(k, parts[1])
                if m:
                    return m
        target = imp.get(parts[0])
        if target:
            if target in self.modules:
                q = f"{target}:{'.'.join(parts[1:])}"
                if q in self.functions:
                    return q
                if len(parts) == 2 and q in self.classes:
                    return self._method(self.classes[q], "__init__")
                if len(parts) == 3:
                    return self._method(
                        self.classes.get(f"{target}:{parts[1]}"), parts[2])
            else:
                head, _, tail = target.rpartition(".")
                cn = self.classes.get(f"{head}:{tail}")
                if cn is not None and len(parts) == 2:
                    return self._method(cn, parts[1])
            return None
        # fully-dotted module path: storm_tpu.dist.wire.encode(...)
        mod_guess = ".".join(parts[:-1])
        if mod_guess in self.modules:
            q = f"{mod_guess}:{parts[-1]}"
            if q in self.functions:
                return q
        return None

    def _resolve_target(self, target: str) -> Optional[str]:
        head, _, tail = target.rpartition(".")
        if head in self.modules:
            q = f"{head}:{tail}"
            if q in self.functions:
                return q
            if q in self.classes:
                return self._method(self.classes[q], "__init__")
        return None

    def _resolve_all(self) -> None:
        for fn in self.functions.values():
            seen: Set[str] = set()
            for rec in fn.calls:
                q = self.resolve(fn.module, fn.scope, rec.raw, fn)
                if q and q != fn.qual and q not in seen:
                    seen.add(q)
                    fn.resolved.append(q)
                    fn.call_raw[q] = rec.raw

    # -- summaries --------------------------------------------------------

    def _summarize(self) -> None:
        rev: Dict[str, List[str]] = defaultdict(list)
        for q, fn in self.functions.items():
            for c in fn.resolved:
                rev[c].append(q)
        dist: Dict[str, int] = {}
        queue: deque = deque()
        for q in sorted(self.functions):
            fn = self.functions[q]
            if fn.blocking:
                fn.blocking.sort(key=lambda t: t[1])
                fn.may_block = True
                fn.block_reason = fn.blocking[0][0]
                dist[q] = 0
                queue.append(q)
        while queue:
            q = queue.popleft()
            for caller in sorted(rev[q]):
                if caller in dist:
                    continue
                dist[caller] = dist[q] + 1
                cf = self.functions[caller]
                cf.may_block = True
                cf.block_via = q
                queue.append(caller)
        # transitive lock acquisition closure
        for fn in self.functions.values():
            fn.trans_acquires = set(fn.acquires)
        changed = True
        while changed:
            changed = False
            for fn in self.functions.values():
                before = len(fn.trans_acquires)
                for c in fn.resolved:
                    fn.trans_acquires |= self.functions[c].trans_acquires
                if len(fn.trans_acquires) != before:
                    changed = True

    def short(self, qual: str) -> str:
        module, _, scope = qual.partition(":")
        return f"{module.rsplit('.', 1)[-1]}.{scope}"

    def block_chain(self, qual: str) -> List[str]:
        """Shortest witness chain from ``qual`` down to the concrete
        blocking call, e.g. ``['controller.DistCluster.recover_worker',
        'controller.DistCluster._reship', 'client.control']``."""
        out: List[str] = []
        q: Optional[str] = qual
        for _ in range(64):
            if q is None or q not in self.functions:
                break
            fn = self.functions[q]
            out.append(self.short(q))
            if fn.block_via is None:
                out.append(fn.block_reason or "?")
                break
            q = fn.block_via
        return out

    def lifecycle_reachable(self) -> Set[str]:
        """Functions reachable (forward) from a lifecycle-named entry point
        or from module level — the set a thread's ``join()`` site must live
        in for the thread to be reaped on shutdown (THR001)."""
        if self._lifecycle_reach is not None:
            return self._lifecycle_reach
        roots = [q for q, fn in self.functions.items()
                 if fn.scope == "<module>" or _LIFECYCLE.search(fn.name)]
        seen: Set[str] = set(roots)
        stack = list(roots)
        while stack:
            q = stack.pop()
            for c in self.functions[q].resolved:
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        self._lifecycle_reach = seen
        return seen
