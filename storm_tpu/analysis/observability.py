"""OBS001-003: observability hygiene.

The dashboards, the autoscaler, the shed controller, and the SLO burn
monitor all read metrics *by name* (``e2e_latency_ms``, ``batch_wait_ms``,
``slo_breaches``...). A typo in a writer site doesn't error — it creates a
parallel, never-read series while the reader sees a flatline, which is the
one failure mode a dashboard cannot display. So:

* **OBS001** — every literal metric name in a ``counter``/``gauge``/
  ``histogram`` call must appear in the generated registry
  (``storm_tpu/analysis/metric_names.py``); f-string names must match one
  of the registry's wildcard patterns. The registry is *generated from the
  call sites themselves* (``storm-tpu lint --regen-metric-registry``), so
  the check is "this name was seen when the registry was last reviewed",
  i.e. new names show up as findings until the regen is committed.
* **OBS002** — ``jax.profiler.start_trace`` without a ``stop_trace`` in
  the same function leaks a device trace session (the sanctioned shape is
  ``device_trace()``'s try/finally).
* **OBS003** — (whole-tree) one metric name used as conflicting kinds
  (counter in one module, histogram in another): the prometheus renderer
  would emit the same family with two types.

Name-variable call sites (``m.histogram(comp, key)`` with ``key`` looping
over a dict) are skipped statically; the runtime registry warn-once in
``runtime/metrics.py`` covers those.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    ScopedVisitor,
    SourceFile,
    dotted_name,
)

_KINDS = ("counter", "gauge", "histogram")

#: Minimum literal characters for a wildcard pattern to be used when
#: validating *literal* names: f"{what}_{tenant}"-style sites generate
#: patterns like ``*_*`` that would vacuously accept near-typos.
_STRICT_PATTERN_MIN_LITERAL = 3


def _name_arg(call: ast.Call) -> Optional[ast.AST]:
    """The metric-name argument of a registry call, or None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    if call.func.attr not in _KINDS:
        return None
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _pattern_of(js: ast.JoinedStr) -> str:
    """fnmatch pattern for an f-string name: literal chunks joined by *."""
    parts: List[str] = []
    for v in js.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    pat = "".join(parts)
    while "**" in pat:
        pat = pat.replace("**", "*")
    return pat


#: (kind, name_or_pattern, is_pattern, line, scope)
Site = Tuple[str, str, bool, int, str]


def collect_sites(sf: SourceFile) -> List[Site]:
    sites: List[Site] = []

    class V(ScopedVisitor):
        def visit_Call(self, call: ast.Call) -> None:
            arg = _name_arg(call)
            if arg is not None:
                kind = call.func.attr  # type: ignore[union-attr]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    sites.append((kind, arg.value, False, call.lineno,
                                  self.scope))
                elif isinstance(arg, ast.JoinedStr):
                    sites.append((kind, _pattern_of(arg), True, call.lineno,
                                  self.scope))
                # Name/other: dynamic, runtime warn-once covers it
            self.generic_visit(call)

    V().visit(sf.tree)
    return sites


def _registry():
    try:
        from storm_tpu.analysis import metric_names
        return metric_names
    except ImportError:  # registry not generated yet: OBS001 is inert
        return None


def check(sf: SourceFile, config: LintConfig) -> List[Finding]:
    import fnmatch

    findings: List[Finding] = []
    reg = _registry()
    if reg is not None and sf.path != "storm_tpu/analysis/metric_names.py":
        known: Set[str] = set(getattr(reg, "METRIC_NAMES", ()))
        patterns: Sequence[str] = tuple(getattr(reg, "METRIC_PATTERNS", ()))
        strict = [p for p in patterns
                  if len(p.replace("*", "")) >= _STRICT_PATTERN_MIN_LITERAL]
        for kind, name, is_pattern, line, scope in collect_sites(sf):
            if is_pattern:
                ok = name in patterns
            else:
                ok = name in known or any(
                    fnmatch.fnmatchcase(name, p) for p in strict)
            if not ok:
                findings.append(Finding(
                    rule="OBS001", path=sf.path, line=line, scope=scope,
                    message=(f"metric name {name!r} ({kind}) is not in the "
                             "generated registry"),
                    hint=("typo? fix the name; new metric? run `storm-tpu "
                          "lint --regen-metric-registry` and commit "
                          "metric_names.py with the change"),
                    detail=f"{kind}:{name}"))
    findings.extend(_check_trace_balance(sf))
    return findings


def _check_trace_balance(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        starts: List[ast.Call] = []
        stops = 0
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                continue
            if isinstance(sub, ast.Call):
                name = dotted_name(sub.func)
                tail = name.rsplit(".", 1)[-1]
                if tail == "start_trace":
                    starts.append(sub)
                elif tail == "stop_trace":
                    stops += 1
        if starts and stops == 0:
            findings.append(Finding(
                rule="OBS002", path=sf.path, line=starts[0].lineno,
                scope=node.name,
                message=(f"start_trace in {node.name} has no stop_trace "
                         "on any path"),
                hint=("wrap in try/finally (see tracing.device_trace) so "
                      "the device trace session always closes"),
                detail="start_trace"))
    return findings


def check_kinds(files: Iterable[SourceFile],
                config: LintConfig) -> List[Finding]:
    """OBS003: one literal name used as more than one metric kind."""
    first: Dict[str, Tuple[str, str, int, str]] = {}  # name -> kind,site
    findings: List[Finding] = []
    reported: Set[str] = set()
    for sf in files:
        for kind, name, is_pattern, line, scope in collect_sites(sf):
            if is_pattern:
                continue
            if name not in first:
                first[name] = (kind, sf.path, line, scope)
                continue
            kind0, path0, line0, _ = first[name]
            if kind != kind0 and name not in reported:
                reported.add(name)
                findings.append(Finding(
                    rule="OBS003", path=sf.path, line=line, scope=scope,
                    message=(f"metric {name!r} used as {kind} here but as "
                             f"{kind0} at {path0}:{line0}"),
                    hint=("pick one kind per name; the prometheus family "
                          "can only have one type"),
                    detail=f"{name}:{'/'.join(sorted((kind, kind0)))}"))
    return findings


# ---------------------------------------------------------------------------
# Registry generation
# ---------------------------------------------------------------------------

_HEADER = '''"""Metric-name registry — GENERATED, do not edit by hand.

Regenerate after adding/renaming a metric:

    storm-tpu lint --regen-metric-registry

Generated from every ``counter``/``gauge``/``histogram`` call site in the
tree. Literal names land in ``METRIC_NAMES``; f-string sites contribute a
wildcard pattern to ``METRIC_PATTERNS`` (literal chunks joined by ``*``).
``storm_tpu/analysis/observability.py`` (OBS001) checks call sites against
this file statically; ``runtime/metrics.py`` warns once at runtime for any
name that matches neither — together they catch the write-side typo whose
only other symptom is a flatlined dashboard panel.
"""

from __future__ import annotations

import fnmatch
'''


def generate_registry(files: Sequence[SourceFile]) -> str:
    names: Set[str] = set()
    patterns: Set[str] = set()
    kinds: Dict[str, Set[str]] = {}
    for sf in files:
        if sf.path == "storm_tpu/analysis/metric_names.py":
            continue
        for kind, name, is_pattern, _line, _scope in collect_sites(sf):
            if is_pattern:
                patterns.add(name)
            else:
                names.add(name)
                kinds.setdefault(name, set()).add(kind)
    lines = [_HEADER]
    lines.append("METRIC_NAMES = frozenset({")
    for n in sorted(names):
        lines.append(f"    {n!r},")
    lines.append("})")
    lines.append("")
    lines.append("METRIC_PATTERNS = (")
    for p in sorted(patterns):
        lines.append(f"    {p!r},")
    lines.append(")")
    lines.append("")
    lines.append("#: literal name -> kinds seen at generation time")
    lines.append("METRIC_KINDS = {")
    for n in sorted(kinds):
        lines.append(f"    {n!r}: {tuple(sorted(kinds[n]))!r},")
    lines.append("}")
    lines.append("")
    lines.append("")
    lines.append("def is_known(name: str) -> bool:")
    lines.append("    if name in METRIC_NAMES:")
    lines.append("        return True")
    lines.append("    return any(fnmatch.fnmatchcase(name, p)")
    lines.append("               for p in METRIC_PATTERNS)")
    lines.append("")
    return "\n".join(lines)
