"""storm-tpu's project-specific static analyzer (``storm-tpu lint``).

Rule families over the package's own AST, gated in tier-1 against the
committed ``analysis/baseline.json``:

* lock discipline — direct (LCK001/LCK002) and interprocedural
  (LCK003 transitive blocking, LCK004 full lock-order cycles), built on
  the project call graph (``analysis/callgraph.py``);
* thread/executor lifecycle (THR001/THR002);
* protocol conformance (PRT001-003) against the generated
  ``analysis/protocol_names.py`` registry;
* exactly-once tuple handling (XO001), jit tracer hygiene (JIT001-004),
  and observability hygiene (OBS001-003).

See docs/ARCHITECTURE.md "Statically checked invariants" and the
docs/OPERATIONS.md runbook.

Kept import-light: ``runtime/metrics.py`` imports
``storm_tpu.analysis.metric_names`` on the hot path at registry-creation
time, so this module must not pull in the checkers.
"""

from storm_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintConfig,
    RULES,
    filter_new,
    lint_source,
    load_baseline,
    load_config,
    run_lint,
    write_baseline,
)
