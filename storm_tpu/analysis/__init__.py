"""storm-tpu's project-specific static analyzer (``storm-tpu lint``).

Four invariant checkers over the package's own AST — lock discipline
(LCK001/LCK002), exactly-once tuple handling (XO001), jit tracer hygiene
(JIT001-004), and observability hygiene (OBS001-003) — gated in tier-1
against the committed ``analysis/baseline.json``. See
docs/ARCHITECTURE.md "Statically checked invariants" and the
docs/OPERATIONS.md runbook.

Kept import-light: ``runtime/metrics.py`` imports
``storm_tpu.analysis.metric_names`` on the hot path at registry-creation
time, so this module must not pull in the checkers.
"""

from storm_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintConfig,
    RULES,
    filter_new,
    lint_source,
    load_baseline,
    load_config,
    run_lint,
    write_baseline,
)
