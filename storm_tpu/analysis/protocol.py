"""PRT001-003: control-plane protocol conformance.

Three of the mesh's wire surfaces are bare string protocols, exactly like
the metric names were before the generated registry caught two real gaps:

* control commands — ``client.control("drain", ...)`` on the controller
  side vs the ``cmd == "drain"`` chain in ``dist/worker.py:_control``;
* journal record kinds — ``self._jappend("rebalance", ...)`` vs the
  ``kind == "rebalance"`` fold arms in ``ControlPlaneState.apply``
  (unknown-kind *replay* is deliberately a no-op for forward
  compatibility, but *emitting* a kind nothing folds is lost state);
* flight-recorder event names — ``flight.event("dist_circuit_open", ...)``
  read back by dashboards, the fleet scorecard, and chaos drills.

A typo on either side of any of these doesn't error; it silently drops
the command, the journal record, or the dashboard row. So:

* **PRT001** — every control command sent must have a handler, and every
  handler must have an in-tree sender (externally-driven commands are
  baselined with a why). When the linted file set lacks the handler (or
  sender) side, the generated registry stands in for it.
* **PRT002** — every journal kind emitted must have an ``apply`` fold arm.
* **PRT003** — every literal flight-event name must be in the generated
  registry (``storm_tpu/analysis/protocol_names.py``) and carry that
  event's required fields (the fields every registered site provides);
  f-string names must match a registered wildcard pattern. The registry is
  generated from the call sites (``storm-tpu lint
  --regen-protocol-registry``) and freshness-gated in tier-1, same as
  ``metric_names.py``; ``runtime/tracing.py`` warns once at runtime for
  dynamic names the AST pass can't see.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    ScopedVisitor,
    SourceFile,
    dotted_name,
    last_segment,
)
from storm_tpu.analysis.observability import (
    _STRICT_PATTERN_MIN_LITERAL,
    _pattern_of,
)

_REGISTRY_PATH = "storm_tpu/analysis/protocol_names.py"

#: (name, path, line, scope)
Site = Tuple[str, str, int, str]


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# Collection
# ---------------------------------------------------------------------------


def collect_control(files: Iterable[SourceFile]
                    ) -> Tuple[Dict[str, List[Site]], Dict[str, List[Site]]]:
    """(sent, handled): literal commands passed to ``.control()``/
    ``.probe()`` vs literal ``cmd == "..."`` arms inside ``_control``."""
    sent: Dict[str, List[Site]] = {}
    handled: Dict[str, List[Site]] = {}
    for sf in files:
        if sf.path == _REGISTRY_PATH:
            continue

        class V(ScopedVisitor):
            def visit_Call(self, call: ast.Call) -> None:
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr in ("control", "probe") \
                        and call.args:
                    cmd = _const_str(call.args[0])
                    if cmd is not None:
                        sent.setdefault(cmd, []).append(
                            (cmd, sf.path, call.lineno, self.scope))
                self.generic_visit(call)

            def visit_Compare(self, node: ast.Compare) -> None:
                if "_control" in self.scope.split(".") \
                        and isinstance(node.left, ast.Name) \
                        and node.left.id == "cmd" \
                        and len(node.ops) == 1:
                    if isinstance(node.ops[0], ast.Eq):
                        cmd = _const_str(node.comparators[0])
                        if cmd is not None:
                            handled.setdefault(cmd, []).append(
                                (cmd, sf.path, node.lineno, self.scope))
                    elif isinstance(node.ops[0], ast.In) and isinstance(
                            node.comparators[0], (ast.Tuple, ast.List,
                                                  ast.Set)):
                        for el in node.comparators[0].elts:
                            cmd = _const_str(el)
                            if cmd is not None:
                                handled.setdefault(cmd, []).append(
                                    (cmd, sf.path, node.lineno, self.scope))
                self.generic_visit(node)

        V().visit(sf.tree)
    return sent, handled


def collect_journal(files: Iterable[SourceFile]
                    ) -> Tuple[Dict[str, List[Site]], Dict[str, List[Site]]]:
    """(emitted, folded): kinds appended to the controller journal vs the
    ``kind == "..."`` fold arms inside an ``apply`` function."""
    emitted: Dict[str, List[Site]] = {}
    folded: Dict[str, List[Site]] = {}
    for sf in files:
        if sf.path == _REGISTRY_PATH:
            continue

        class V(ScopedVisitor):
            def visit_Call(self, call: ast.Call) -> None:
                if isinstance(call.func, ast.Attribute) and call.args:
                    attr = call.func.attr
                    base = last_segment(dotted_name(call.func.value)).lower()
                    if attr == "_jappend" or (
                            attr == "append" and "journal" in base):
                        kind = _const_str(call.args[0])
                        if kind is not None:
                            emitted.setdefault(kind, []).append(
                                (kind, sf.path, call.lineno, self.scope))
                self.generic_visit(call)

            def visit_Compare(self, node: ast.Compare) -> None:
                if "apply" in self.scope.split(".") \
                        and isinstance(node.left, ast.Name) \
                        and node.left.id == "kind" \
                        and len(node.ops) == 1 \
                        and isinstance(node.ops[0], ast.Eq):
                    kind = _const_str(node.comparators[0])
                    if kind is not None:
                        folded.setdefault(kind, []).append(
                            (kind, sf.path, node.lineno, self.scope))
                self.generic_visit(node)

        V().visit(sf.tree)
    return emitted, folded


def _flightish(base: str) -> bool:
    seg = last_segment(base).lower().lstrip("_")
    return "flight" in seg or seg in ("fl", "recorder")


#: (name_or_pattern, is_pattern, fields-or-None, path, line, scope)
FlightSite = Tuple[str, bool, Optional[frozenset], str, int, str]


def collect_flight(files: Iterable[SourceFile]) -> List[FlightSite]:
    sites: List[FlightSite] = []
    for sf in files:
        if sf.path == _REGISTRY_PATH:
            continue

        class V(ScopedVisitor):
            def visit_Call(self, call: ast.Call) -> None:
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr == "event" and call.args \
                        and _flightish(dotted_name(call.func.value)):
                    fields: Optional[frozenset] = frozenset(
                        k.arg for k in call.keywords
                        if k.arg not in (None, "throttle_s"))
                    if any(k.arg is None for k in call.keywords):
                        fields = None  # **kwargs: field set unknowable
                    arg = call.args[0]
                    name = _const_str(arg)
                    if name is not None:
                        sites.append((name, False, fields, sf.path,
                                      call.lineno, self.scope))
                    elif isinstance(arg, ast.JoinedStr):
                        sites.append((_pattern_of(arg), True, fields,
                                      sf.path, call.lineno, self.scope))
                self.generic_visit(call)

        V().visit(sf.tree)
    return sites


# ---------------------------------------------------------------------------
# Checks
# ---------------------------------------------------------------------------


def _registry():
    try:
        from storm_tpu.analysis import protocol_names
        return protocol_names
    except ImportError:  # not generated yet: registry-backed checks inert
        return None


def _first(sites: List[Site]) -> Site:
    return sorted(sites, key=lambda s: (s[1], s[3], s[2]))[0]


def check_protocols(files: Sequence[SourceFile],
                    config: LintConfig) -> List[Finding]:
    reg = _registry()
    findings: List[Finding] = []
    findings.extend(_check_control(files, reg))
    findings.extend(_check_journal(files, reg))
    findings.extend(_check_flight(files, reg))
    return findings


def _check_control(files: Sequence[SourceFile], reg) -> List[Finding]:
    sent, handled = collect_control(files)
    out: List[Finding] = []
    handled_names: Set[str] = set(handled)
    if not handled_names and reg is not None:
        handled_names = set(getattr(reg, "CONTROL_COMMANDS", ()))
    sent_names: Set[str] = set(sent)
    if not sent_names and reg is not None:
        sent_names = set(getattr(reg, "CONTROL_SENT", ()))
    if handled_names:
        for cmd in sorted(set(sent) - handled_names):
            _name, path, line, scope = _first(sent[cmd])
            out.append(Finding(
                rule="PRT001", path=path, line=line, scope=scope,
                message=(f"control command {cmd!r} is sent here but no "
                         "worker `cmd ==` arm handles it"),
                hint=("typo, or add the handler to dist/worker.py "
                      "_control (the worker raises `unknown control cmd` "
                      "at runtime)"),
                detail=f"unhandled:{cmd}"))
    if sent_names:
        for cmd in sorted(set(handled) - sent_names):
            _name, path, line, scope = _first(handled[cmd])
            out.append(Finding(
                rule="PRT001", path=path, line=line, scope=scope,
                message=(f"control command {cmd!r} has a handler but "
                         "nothing in the tree sends it"),
                hint=("dead protocol arm, or an externally-driven command "
                      "(bench/ops tooling) — baseline those with a why"),
                detail=f"unsent:{cmd}"))
    return out


def _check_journal(files: Sequence[SourceFile], reg) -> List[Finding]:
    emitted, folded = collect_journal(files)
    folded_names: Set[str] = set(folded)
    if not folded_names and reg is not None:
        folded_names = set(getattr(reg, "JOURNAL_KINDS", ()))
    out: List[Finding] = []
    if not folded_names:
        return out
    for kind in sorted(set(emitted) - folded_names):
        _name, path, line, scope = _first(emitted[kind])
        out.append(Finding(
            rule="PRT002", path=path, line=line, scope=scope,
            message=(f"journal kind {kind!r} is appended here but "
                     "ControlPlaneState.apply has no fold arm for it — "
                     "replay silently drops it"),
            hint=("add the `kind == ...` arm to dist/journal.py apply() "
                  "(unknown-kind replay staying a no-op is the forward-"
                  "compat contract for *old* binaries, not new emitters)"),
            detail=f"unfolded:{kind}"))
    return out


def _check_flight(files: Sequence[SourceFile], reg) -> List[Finding]:
    out: List[Finding] = []
    if reg is None:
        return out
    known: Dict[str, tuple] = dict(getattr(reg, "FLIGHT_EVENTS", {}))
    patterns: Sequence[str] = tuple(getattr(reg, "FLIGHT_EVENT_PATTERNS", ()))
    strict = [p for p in patterns
              if len(p.replace("*", "")) >= _STRICT_PATTERN_MIN_LITERAL]
    for name, is_pattern, fields, path, line, scope in collect_flight(files):
        if is_pattern:
            if name not in patterns:
                out.append(Finding(
                    rule="PRT003", path=path, line=line, scope=scope,
                    message=(f"flight event pattern {name!r} is not in the "
                             "generated protocol registry"),
                    hint=("run `storm-tpu lint --regen-protocol-registry` "
                          "and commit protocol_names.py with the change"),
                    detail=f"event:{name}"))
            continue
        if name not in known:
            if any(fnmatch.fnmatchcase(name, p) for p in strict):
                continue
            out.append(Finding(
                rule="PRT003", path=path, line=line, scope=scope,
                message=(f"flight event {name!r} is not in the generated "
                         "protocol registry"),
                hint=("typo? fix the name; new event? run `storm-tpu lint "
                      "--regen-protocol-registry` and commit "
                      "protocol_names.py"),
                detail=f"event:{name}"))
            continue
        if fields is not None:
            missing = sorted(set(known[name]) - fields)
            if missing:
                out.append(Finding(
                    rule="PRT003", path=path, line=line, scope=scope,
                    message=(f"flight event {name!r} omits required "
                             f"field(s) {', '.join(missing)} that every "
                             "registered site provides"),
                    hint=("readers key on those fields; pass them, or "
                          "regen the registry if the contract changed"),
                    detail=f"fields:{name}:{','.join(missing)}"))
    return out


# ---------------------------------------------------------------------------
# Registry generation
# ---------------------------------------------------------------------------

_HEADER = '''"""Control-plane protocol registry — GENERATED, do not edit by hand.

Regenerate after adding a control command, journal kind, or flight event:

    storm-tpu lint --regen-protocol-registry

Generated from the tree's own call sites: ``.control()``/``.probe()``
sends and ``cmd ==`` handler arms, journal ``_jappend``/fold arms, and
every literal ``flight.event(...)`` name with the fields common to all of
its sites. ``storm_tpu/analysis/protocol.py`` (PRT001-003) checks call
sites against this file statically; ``runtime/tracing.py`` warns once at
runtime for event names built from variables — together they catch the
drift whose only other symptom is a command that bounces, a journal record
replay silently drops, or a dashboard row that never appears.
"""

from __future__ import annotations

import fnmatch
'''


def generate_registry(files: Sequence[SourceFile]) -> str:
    sent, handled = collect_control(files)
    emitted, folded = collect_journal(files)
    flight = collect_flight(files)
    names: Dict[str, Optional[Set[str]]] = {}
    patterns: Set[str] = set()
    for name, is_pattern, fields, _path, _line, _scope in flight:
        if is_pattern:
            patterns.add(name)
            continue
        if name not in names:
            names[name] = None if fields is None else set(fields)
        elif fields is not None:
            cur = names[name]
            names[name] = set(fields) if cur is None else (cur & fields)
    lines = [_HEADER]

    def _emit_set(title: str, var: str, values: Iterable[str]) -> None:
        lines.append(f"#: {title}")
        lines.append(f"{var} = frozenset({{")
        for v in sorted(values):
            lines.append(f"    {v!r},")
        lines.append("})")
        lines.append("")

    _emit_set("commands with a `cmd ==` handler arm (dist/worker.py)",
              "CONTROL_COMMANDS", handled)
    _emit_set("commands sent via .control()/.probe() in the tree",
              "CONTROL_SENT", sent)
    _emit_set("journal kinds with an apply() fold arm (dist/journal.py)",
              "JOURNAL_KINDS", folded)
    _emit_set("journal kinds appended in the tree", "JOURNAL_EMITTED",
              emitted)
    lines.append("#: literal flight-event name -> fields every site provides")
    lines.append("FLIGHT_EVENTS = {")
    for n in sorted(names):
        req = tuple(sorted(names[n] or ()))
        lines.append(f"    {n!r}: {req!r},")
    lines.append("}")
    lines.append("")
    lines.append("FLIGHT_EVENT_PATTERNS = (")
    for p in sorted(patterns):
        lines.append(f"    {p!r},")
    lines.append(")")
    lines.append("")
    lines.append("")
    lines.append("def is_known_event(name: str) -> bool:")
    lines.append("    if name in FLIGHT_EVENTS:")
    lines.append("        return True")
    lines.append("    return any(fnmatch.fnmatchcase(name, p)")
    lines.append("               for p in FLIGHT_EVENT_PATTERNS)")
    lines.append("")
    return "\n".join(lines)
