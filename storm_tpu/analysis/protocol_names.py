"""Control-plane protocol registry — GENERATED, do not edit by hand.

Regenerate after adding a control command, journal kind, or flight event:

    storm-tpu lint --regen-protocol-registry

Generated from the tree's own call sites: ``.control()``/``.probe()``
sends and ``cmd ==`` handler arms, journal ``_jappend``/fold arms, and
every literal ``flight.event(...)`` name with the fields common to all of
its sites. ``storm_tpu/analysis/protocol.py`` (PRT001-003) checks call
sites against this file statically; ``runtime/tracing.py`` warns once at
runtime for event names built from variables — together they catch the
drift whose only other symptom is a command that bounces, a journal record
replay silently drops, or a dashboard row that never appears.
"""

from __future__ import annotations

import fnmatch

#: commands with a `cmd ==` handler arm (dist/worker.py)
CONTROL_COMMANDS = frozenset({
    'activate',
    'chaos',
    'component_stats',
    'copies',
    'deactivate',
    'decode_sessions',
    'drain',
    'drain_worker',
    'health',
    'kill',
    'metrics',
    'parallelism',
    'ping',
    'profile',
    'rebalance',
    'seek',
    'shutdown',
    'start_bolts',
    'start_spouts',
    'state_report',
    'submit',
    'swap_model',
    'traces',
    'update_peer',
    'utilization',
})

#: commands sent via .control()/.probe() in the tree
CONTROL_SENT = frozenset({
    'activate',
    'component_stats',
    'copies',
    'deactivate',
    'decode_sessions',
    'drain',
    'drain_worker',
    'health',
    'kill',
    'metrics',
    'parallelism',
    'ping',
    'profile',
    'rebalance',
    'seek',
    'shutdown',
    'start_bolts',
    'start_spouts',
    'state_report',
    'submit',
    'swap_model',
    'traces',
    'update_peer',
    'utilization',
})

#: journal kinds with an apply() fold arm (dist/journal.py)
JOURNAL_KINDS = frozenset({
    'activation',
    'kill',
    'peer_update',
    'rebalance',
    'submit',
    'swap_model',
    'workers',
})

#: journal kinds appended in the tree
JOURNAL_EMITTED = frozenset({
    'activation',
    'kill',
    'peer_update',
    'rebalance',
    'submit',
    'swap_model',
    'workers',
})

#: literal flight-event name -> fields every site provides
FLIGHT_EVENTS = {
    'autoscale_decision': ('bottleneck', 'capacity', 'component', 'direction', 'inbox_frac', 'p50_ms', 'parallelism'),
    'batch_formed': ('component', 'continuous', 'device_ms', 'fill', 'records', 'size', 'sources'),
    'bottleneck_shift': ('capacity', 'component', 'device_frac', 'e2e_p95_ms', 'inflow_growth_per_s', 'previous', 'reasons', 'score'),
    'cascade_escalation': (),
    'chaos_injection': ('target',),
    'copy_amplification_high': ('amplification', 'ceiling', 'ingest_bytes', 'top_bytes_per_record', 'top_stage'),
    'decode_session_evicted': ('cached_rows', 'session'),
    'decode_session_migrated': ('cached_rows', 'committed', 'session'),
    'decode_session_started': ('max_new_tokens', 'prompt_len', 'restored', 'session'),
    'dist_circuit_close': ('peer',),
    'dist_circuit_open': ('opens', 'peer'),
    'dist_heartbeat_miss': ('consecutive', 'error', 'worker'),
    'dist_peer_replaced': ('addr', 'idx'),
    'dist_reattached': ('dead', 'reattach_s', 'reconciled', 'replayed', 'survivors'),
    'dist_worker_draining': ('worker',),
    'dist_worker_recovered': ('worker',),
    'dist_worker_restarted': ('drained', 'restart_s', 'worker'),
    'engine_quarantined': ('component', 'model', 'trips'),
    'engine_replaced': ('component', 'model'),
    'executor_restart': ('component', 'error', 'task', 'topology'),
    'plan_correction': ('action', 'burn', 'component', 'parallelism', 'score'),
    'profile_regression': ('baseline_ms', 'bucket', 'engine', 'live_ms', 'ratio', 'stage'),
    'ring_handoff': ('component', 'remapped_fraction'),
    'scenario_phase': (),
    'shed_decision': ('breach_rate', 'burn_rate', 'component', 'direction', 'inbox_frac', 'level', 'wait_p95_ms'),
    'shed_degrade': ('component', 'lane', 'level', 'records'),
    'shed_reject': ('component', 'lane', 'level', 'records'),
    'slo_breach': ('component', 'e2e_ms', 'slo_ms', 'trace_id'),
    'slo_burn': ('breaches', 'budget', 'delivered', 'fast_burn', 'slow_burn', 'threshold'),
    'tree_timeout': ('topology', 'trees'),
    'wire_error': ('error', 'nbytes'),
    'worker_drained': ('checkpoints', 'flushed', 'worker'),
    'worker_draining': ('worker',),
    'xla_compile': ('batch_shape', 'compile_ms', 'component'),
}

FLIGHT_EVENT_PATTERNS = (
)


def is_known_event(name: str) -> bool:
    if name in FLIGHT_EVENTS:
        return True
    return any(fnmatch.fnmatchcase(name, p)
               for p in FLIGHT_EVENT_PATTERNS)
