"""THR001/THR002: thread and executor lifecycle discipline.

The runtime's thread population keeps growing (engine fetch thread,
continuous-batching dispatcher, observatory loop, dist heartbeat monitor,
peer senders, chaos drivers, profile capture), and a single non-daemon
thread with no join path turns every clean shutdown into a hang — the
interpreter waits on it forever, which in a worker process means the
controller's drain times out and the restart escalates to SIGKILL.

* **THR001** — every ``threading.Thread`` created in the tree must be
  ``daemon=True``, handed to ``weakref.finalize``, or *joined from a
  lifecycle path*: the ``join()`` site's function must be reachable (via
  the project call graph) from a ``close``/``shutdown``/``stop``-style
  entry point or module level. A join buried in a helper nobody calls on
  shutdown is still a leak.
* **THR002** — every ``ThreadPoolExecutor``/``ProcessPoolExecutor`` must
  be context-managed, have ``.shutdown()`` called on it in the owning
  scope, or be handed off whole as an argument (``grpc.server(pool)``
  transfers ownership to the server).

Both checks are deliberately alias-aware but shallow: ``t = self._thread``
then ``t.join()`` counts, ``for t in self._threads: t.join()`` counts;
anything more dynamic should either be daemonized or baselined with a why.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    SourceFile,
    dotted_name,
    last_segment,
)
from storm_tpu.analysis.callgraph import _LIFECYCLE, CallGraph, module_of

_EXECUTORS = ("ThreadPoolExecutor", "ProcessPoolExecutor")


def _is_thread_ctor(name: str) -> bool:
    return name == "threading.Thread" or name == "Thread" \
        or name.endswith(".Thread")


def _is_executor_ctor(name: str) -> bool:
    return last_segment(name) in _EXECUTORS


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for ch in ast.iter_child_nodes(node):
            out[ch] = node
    return out


def _context(node: ast.AST, parents: Dict[ast.AST, ast.AST]
             ) -> Tuple[str, Optional[ast.AST], Optional[ast.ClassDef]]:
    """(scope string, enclosing function node, enclosing class node)."""
    names: List[str] = []
    func: Optional[ast.AST] = None
    cls: Optional[ast.ClassDef] = None
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(cur.name)
            if func is None:
                func = cur
        elif isinstance(cur, ast.ClassDef):
            names.append(cur.name)
            if cls is None:
                cls = cur
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>", func, cls


def _binding(call: ast.Call, parents: Dict[ast.AST, ast.AST]
             ) -> Tuple[str, str]:
    """How the constructed object is captured.

    Returns one of ``("attr", name)`` for ``self.name = ...`` (or
    ``self.name.append(...)``), ``("local", name)``, ``("handoff", text)``
    when passed whole into another call, ``("with", "")`` for a context
    manager, or ``("inline", "")`` for ``Thread(...).start()``-style
    fire-and-forget."""
    cur: ast.AST = call
    parent = parents.get(cur)
    while parent is not None:
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            tgt = parent.targets[0] if isinstance(parent, ast.Assign) \
                else parent.target
            if isinstance(tgt, ast.Name):
                return "local", tgt.id
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                return "attr", tgt.attr
            return "inline", ""
        if isinstance(parent, ast.Call) and cur in parent.args:
            func = parent.func
            if isinstance(func, ast.Attribute) and func.attr == "append":
                base = dotted_name(func.value)
                if base.startswith("self."):
                    return "attr", base[5:]
                if base:
                    return "local", base
            return "handoff", dotted_name(func) or "?"
        if isinstance(parent, ast.withitem):
            return "with", ""
        if isinstance(parent, ast.Attribute):
            # Thread(...).start() — never bound anywhere
            return "inline", ""
        if isinstance(parent, ast.stmt):
            return "inline", ""
        cur = parent
        parent = parents.get(cur)
    return "inline", ""


def _aliases(scope_node: ast.AST, root_expr: str) -> Set[str]:
    """Names that alias ``root_expr`` (e.g. ``self._t`` or ``threads``)
    via plain assignment or ``for v in <root>`` loops, to a fixed point."""
    exprs = {root_expr}
    names: Set[str] = set()
    if "." not in root_expr:
        names.add(root_expr)
    for _ in range(3):
        grew = False
        for node in ast.walk(scope_node):
            src = None
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                src = dotted_name(node.value)
                tgt = node.targets[0].id
            elif isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.target, ast.Name):
                src = dotted_name(node.iter)
                tgt = node.target.id
            if src and tgt and src in exprs and tgt not in names:
                names.add(tgt)
                exprs.add(tgt)
                grew = True
        if not grew:
            break
    return names


def _has_call_on(scope_node: ast.AST, attr: str, root_expr: str) -> \
        Optional[ast.Call]:
    """First ``<alias>.<attr>(...)`` call on the bound object in scope."""
    names = _aliases(scope_node, root_expr)
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)\
                and node.func.attr == attr:
            base = dotted_name(node.func.value)
            if base == root_expr or base in names:
                return node
    return None


def _finalized(scope_node: ast.AST, root_expr: str) -> bool:
    names = _aliases(scope_node, root_expr)
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Call) \
                and dotted_name(node.func).endswith("finalize"):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                nm = dotted_name(arg)
                if nm == root_expr or nm in names:
                    return True
    return False


def _daemon_ok(call: ast.Call) -> Optional[bool]:
    """True: daemon=True constant; False: absent or constant False;
    None: daemon=<expr> (can't prove, give the benefit of the doubt)."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return None
    return False


def check_lifecycles(files: Iterable[SourceFile], config: LintConfig,
                     graph: Optional[CallGraph] = None) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        findings.extend(_check_file(sf, config, graph))
    return findings


def _check_file(sf: SourceFile, config: LintConfig,
                graph: Optional[CallGraph]) -> List[Finding]:
    parents = _parents(sf.tree)
    module = module_of(sf.path)
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if _is_thread_ctor(name):
            out.extend(_check_thread(sf, node, parents, module, graph))
        elif _is_executor_ctor(name):
            out.extend(_check_executor(sf, node, parents))
    return out


def _search_scope(sf: SourceFile, kind: str,
                  func: Optional[ast.AST],
                  cls: Optional[ast.ClassDef]) -> ast.AST:
    if kind == "attr":
        return cls if cls is not None else sf.tree
    return func if func is not None else sf.tree


def _check_thread(sf: SourceFile, call: ast.Call,
                  parents: Dict[ast.AST, ast.AST], module: str,
                  graph: Optional[CallGraph]) -> List[Finding]:
    daemon = _daemon_ok(call)
    if daemon is True or daemon is None:
        return []
    scope, func, cls = _context(call, parents)
    kind, name = _binding(call, parents)
    tag = f"self.{name}" if kind == "attr" else (name or "<inline>")
    if kind == "handoff":
        return []  # ownership transferred whole; the callee's problem
    if kind != "inline":
        where = _search_scope(sf, kind, func, cls)
        root = f"self.{name}" if kind == "attr" else name
        if _finalized(where, root):
            return []
        join = _has_call_on(where, "join", root)
        if join is not None:
            if kind == "local":
                return []  # joined before the creating function returns
            jscope, _jf, _jc = _context(join, parents)
            if graph is None:
                return []
            jqual = f"{module}:{jscope}"
            if jqual in graph.lifecycle_reachable():
                return []
            return [_thr001(sf, call, scope, tag,
                            f"joined only in {jscope}(), which no "
                            "close/shutdown/stop path reaches")]
    return [_thr001(sf, call, scope, tag,
                    "no daemon flag, no finalizer, and no join on any "
                    "shutdown path")]


def _thr001(sf: SourceFile, call: ast.Call, scope: str, tag: str,
            why: str) -> Finding:
    return Finding(
        rule="THR001", path=sf.path, line=call.lineno, scope=scope,
        message=f"non-daemon thread {tag} leaks: {why}",
        hint=("pass daemon=True, register weakref.finalize, or join it "
              "from close()/shutdown()/stop() so process exit cannot hang "
              "on it"),
        detail=f"thread:{tag}")


def _check_executor(sf: SourceFile, call: ast.Call,
                    parents: Dict[ast.AST, ast.AST]) -> List[Finding]:
    scope, func, cls = _context(call, parents)
    kind, name = _binding(call, parents)
    if kind in ("handoff", "with"):
        return []
    tag = f"self.{name}" if kind == "attr" else (name or "<inline>")
    if kind != "inline":
        where = _search_scope(sf, kind, func, cls)
        root = f"self.{name}" if kind == "attr" else name
        if _has_call_on(where, "shutdown", root) is not None:
            return []
    return [Finding(
        rule="THR002", path=sf.path, line=call.lineno, scope=scope,
        message=(f"executor {tag} is never shut down (and not "
                 "context-managed or handed off)"),
        hint=("use `with ThreadPoolExecutor(...) as pool:`, call "
              ".shutdown() from the owner's close path, or pass it whole "
              "to the component that owns its lifecycle"),
        detail=f"executor:{tag}")]
