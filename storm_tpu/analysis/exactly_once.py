"""XO001: exactly-once tuple discipline.

Every tuple that enters a bolt/router/operator ``execute`` path must leave
it **owned by someone**: acked, failed, handed to a deferral registry
(pending batch, residue buffer, replay queue), emitted as an anchor, or
raised through to the executor (``BoltExecutor._run`` catches execute
exceptions and calls ``collector.fail(t)`` — so a raise IS a handled path).
A tuple that simply falls off the end of a control-flow path is a tuple
the ledger will wait on forever — exactly the silent-drop class the
cascade/continuous replay code re-implements deferral to avoid.

The checker walks the method body as a small path-sensitive CFG:

* "handled" events: ``*.ack(t)`` / ``*.fail(t)``; ``t`` passed to any
  non-predicate call (ownership transfer — ``self._pending.append(t)``,
  ``self.emit(row, anchor=t)``, ``registry.defer(t)``); ``t`` stored into
  an attribute or container; ``return t``.
* calls in **test position** (``if is_tick(t):``) do NOT count — reading a
  tuple is not owning it. Neither do attribute reads (``t.values``).
* ``raise`` ends a path as handled (executor fails the tuple).
* ``try/finally`` is finally-aware: a ``finally`` block that always
  handles the tuple rescues every path through the try, including early
  returns and exception edges. ``except`` handlers enter with the state
  from try *entry* (the conservative choice — the handler can run before
  any try-body handling happened).

Only methods named ``execute``/``process``/``drain`` on classes whose name
matches ``[tool.storm-tpu.lint] tuple_classes`` are checked; abstract
bodies (docstring/pass/ellipsis only) are skipped.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    SourceFile,
    dotted_name,
    last_segment,
)

_METHODS = ("execute", "process", "drain")

#: call names (last segment) that merely *read* the tuple — passing t to
#: these is not an ownership transfer
_PREDICATES = {"is_tick", "isinstance", "len", "repr", "str", "id", "type",
               "bool", "hash", "getattr", "hasattr", "print", "format"}


def _is_abstract(body: Sequence[ast.stmt]) -> bool:
    for st in body:
        if isinstance(st, ast.Pass):
            continue
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            continue  # docstring / Ellipsis
        if isinstance(st, ast.Raise):
            continue  # raise NotImplementedError
        return False
    return True


def _mentions(node: ast.AST, var: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == var
               for n in ast.walk(node))


def _call_handles(call: ast.Call, var: str) -> bool:
    """Does this call take ownership of ``var``?"""
    if not _mentions(call, var):
        return False
    fn = last_segment(dotted_name(call.func))
    if fn in ("ack", "fail"):
        return True
    if fn in _PREDICATES or fn.startswith(("is_", "has_")):
        return False
    return True


def _expr_handles(node: ast.AST, var: str) -> bool:
    """Any ownership-transfer event for ``var`` inside ``node`` (which must
    not be a test-position expression — callers exclude those)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False  # deferred execution; too clever — don't credit
        if isinstance(sub, ast.Call) and _call_handles(sub, var):
            return True
        if isinstance(sub, ast.Assign):
            if isinstance(sub.value, ast.Name) and sub.value.id == var:
                for tgt in sub.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        return True
            # self.x = (t, meta) / buf[k] = [t, ...]
            elif _mentions(sub.value, var):
                for tgt in sub.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        return True
    return False


class _Flow:
    """Path walk over one method body.

    States are booleans ("tuple handled so far"); a statement list maps an
    in-state set to a fall-through state set, recording every exit
    (return / implicit end) that can happen while unhandled."""

    def __init__(self, var: str) -> None:
        self.var = var
        #: (line, kind) of unhandled exits
        self.bad: List[Tuple[int, str]] = []

    def walk(self, stmts: Sequence[ast.stmt],
             states: Set[bool]) -> Set[bool]:
        cur = set(states)
        for st in stmts:
            if not cur:
                break  # unreachable after return/raise on all paths
            cur = self._stmt(st, cur)
        return cur

    def _stmt(self, st: ast.stmt, states: Set[bool]) -> Set[bool]:
        v = self.var
        if isinstance(st, ast.Return):
            if st.value is not None and (
                    (isinstance(st.value, ast.Name) and st.value.id == v)
                    or _expr_handles(st.value, v)):
                return set()  # return t / return self._defer(t)
            if False in states:
                self.bad.append((st.lineno, "return"))
            return set()
        if isinstance(st, ast.Raise):
            return set()  # executor fails the tuple
        if isinstance(st, ast.If):
            # test position never handles
            out = self.walk(st.body, states)
            out |= self.walk(st.orelse, states)
            return out
        if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            body_out = self.walk(st.body, states)
            out = set(states) | body_out  # zero or more iterations
            out |= self.walk(st.orelse, out)
            return out
        if isinstance(st, (ast.With, ast.AsyncWith)):
            entry = set(states)
            for item in st.items:
                if _expr_handles(item.context_expr, v):
                    entry = {True}
            return self.walk(st.body, entry)
        if isinstance(st, ast.Try):
            return self._try(st, states)
        if isinstance(st, ast.Match):
            out: Set[bool] = set()
            exhaustive = False
            for case in st.cases:
                out |= self.walk(case.body, states)
                if isinstance(case.pattern, ast.MatchAs) \
                        and case.pattern.pattern is None:
                    exhaustive = True  # case _:
            if not exhaustive:
                out |= states
            return out
        if isinstance(st, (ast.Break, ast.Continue)):
            # approximate: treat as falling through with current state —
            # the loop join above already unions body states in
            return states
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return states
        # simple statement
        if _expr_handles(st, v):
            return {True}
        return states

    def _try(self, st: ast.Try, states: Set[bool]) -> Set[bool]:
        v = self.var
        # Does finally unconditionally handle? Then it rescues everything
        # that happens inside the try: exception edges, early returns, and
        # plain falls all pass through it.
        rescued = False
        if st.finalbody:
            probe = _Flow(v)
            if probe.walk(st.finalbody, {False}) == {True} and not probe.bad:
                rescued = True
        if rescued:
            # run sub-walks only for nested findings *outside* this try's
            # responsibility — everything tuple-related is rescued, so
            # discard their bad exits.
            sub = _Flow(v)
            sub.walk(st.body, states)
            for h in st.handlers:
                sub.walk(h.body, states)
            sub.walk(st.orelse, {True})
            return {True}
        body_out = self.walk(st.body, states)
        out = set(body_out)
        for h in st.handlers:
            # conservative: the handler may run before any try-body
            # handling happened
            out |= self.walk(h.body, states)
        if st.orelse:
            out = self.walk(st.orelse, body_out) | (out - body_out)
        if st.finalbody:
            out = self.walk(st.finalbody, out or states)
        return out


def check(sf: SourceFile, config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if not any(tag in cls.name for tag in config.tuple_classes):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in _METHODS:
                continue
            args = fn.args.args
            if len(args) < 2:
                continue  # no tuple parameter (tick-style)
            if _is_abstract(fn.body):
                continue
            var = args[1].arg
            flow = _Flow(var)
            fall = flow.walk(fn.body, {False})
            exits = list(flow.bad)
            if False in fall:
                exits.append((fn.body[-1].end_lineno or fn.lineno, "end"))
            for i, (line, kind) in enumerate(exits):
                where = ("falls off the end of" if kind == "end"
                         else "returns from")
                findings.append(Finding(
                    rule="XO001",
                    path=sf.path,
                    line=line,
                    scope=f"{cls.name}.{fn.name}",
                    message=(f"tuple '{var}' can reach this point "
                             f"unhandled ({where} {fn.name} without "
                             "ack/fail/defer)"),
                    hint=("ack/fail the tuple, hand it to a deferral "
                          "registry, or raise — on every path including "
                          "except edges; a finally that always defers "
                          "also satisfies the contract"),
                    detail=f"{var}:{kind}:{i}",
                ))
    return findings
