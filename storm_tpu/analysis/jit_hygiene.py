"""JIT001-004: tracer hygiene for jit-compiled functions.

Functions handed to ``jax.jit``/``pjit`` are *traced*: their Python body
runs once with abstract values, and anything that escapes the tracer —
``np.*`` on a traced array, ``if`` on a tracer, a clock read, a host sync —
either raises ``TracerError`` at first dispatch or, worse, silently bakes
a trace-time constant into the compiled graph (the clock/RNG case). The
engine's dispatch path stays async only because the jitted forward never
blocks on the host; these rules keep it that way.

Jit targets are found three ways: ``@jax.jit`` / ``@pjit`` decorators,
``@functools.partial(jax.jit, static_argnames=...)`` decorators, and
``jax.jit(fn, ...)`` call sites where ``fn`` resolves to a def in the same
file (the engine's closure-built ``fwd``). Params named in
``static_argnames``/``static_argnums`` are concrete at trace time and are
exempt from taint.

* **JIT001** — ``np.*``/``numpy.*`` applied to a traced argument (use
  ``jnp``; numpy forces a host round-trip or a TracerError).
* **JIT002** — ``if``/``while``/ternary/assert branching on a tracer (use
  ``jnp.where`` / ``lax.cond``; Python control flow burns the branch into
  the trace).
* **JIT003** — clock or RNG read (``time.*``, ``random.*``,
  ``np.random.*``, ``datetime.now``) anywhere in a jitted body: the value
  freezes at trace time, so every later call replays it.
* **JIT004** — host sync (``.block_until_ready()``, ``.item()``,
  ``jax.device_get``, ``float()/int()/bool()`` of a tracer) inside the
  traced body.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    SourceFile,
    dotted_name,
    last_segment,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}

_CLOCKS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "time.monotonic_ns",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
}

_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")

_SYNC_METHODS = {"block_until_ready", "item", "tolist"}

#: attributes of a traced array that are *concrete* at trace time — values
#: derived from them are ordinary Python scalars, so branching on them or
#: asserting about them is fine (shape polymorphism is not in play here)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding"}


def _is_jit_expr(node: ast.AST) -> Tuple[bool, Set[str], Set[int]]:
    """Is ``node`` a jit/pjit (possibly partial-wrapped) expression?
    Returns (is_jit, static_argnames, static_argnums)."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        return dotted_name(node) in _JIT_NAMES, set(), set()
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in _JIT_NAMES:
            return True, _static_names(node), _static_nums(node)
        if last_segment(fn) == "partial" and node.args:
            inner = dotted_name(node.args[0])
            if inner in _JIT_NAMES:
                return True, _static_names(node), _static_nums(node)
    return False, set(), set()


def _static_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
    return out


def _static_nums(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    out.add(n.value)
    return out


def _collect_targets(sf: SourceFile):
    """Yield (funcdef, static_names, static_nums, class_scope) for every
    jit-compiled function in the file."""
    # index every def by name within its immediate parent, for resolving
    # jax.jit(fwd) call sites
    defs_by_name: Dict[str, List[ast.AST]] = {}
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(sf.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    def scope_of(node: ast.AST) -> str:
        chain: List[str] = []
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.ClassDef, ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                chain.append(cur.name)
            cur = parents.get(cur)
        return ".".join(reversed(chain))

    seen: Set[ast.AST] = set()
    # decorator form
    for name, defs in defs_by_name.items():
        for fd in defs:
            for dec in getattr(fd, "decorator_list", []):
                is_jit, snames, snums = _is_jit_expr(dec)
                if is_jit and fd not in seen:
                    seen.add(fd)
                    yield fd, snames, snums, scope_of(fd)
    # call form: jax.jit(fn, ...)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn_name = dotted_name(node.func)
        if fn_name not in _JIT_NAMES or not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name) and target.id in defs_by_name:
            for fd in defs_by_name[target.id]:
                if fd not in seen:
                    seen.add(fd)
                    yield (fd, _static_names(node), _static_nums(node),
                           scope_of(fd))


def _tainted_params(fd, static_names: Set[str],
                    static_nums: Set[int]) -> Set[str]:
    params = [a.arg for a in fd.args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
        offset = 1
    else:
        offset = 0
    out = set()
    for i, p in enumerate(params):
        if p in static_names or (i + offset) in static_nums or i in static_nums:
            continue
        out.add(p)
    return out


def check(sf: SourceFile, config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for fd, snames, snums, cls_scope in _collect_targets(sf):
        scope = f"{cls_scope}.{fd.name}" if cls_scope else fd.name
        tainted = _tainted_params(fd, snames, snums)
        findings.extend(_check_body(sf, fd, tainted, scope))
    return findings


def _check_body(sf: SourceFile, fd, tainted: Set[str],
                scope: str) -> List[Finding]:
    findings: List[Finding] = []
    taint = set(tainted)

    def is_tainted(node: ast.AST) -> bool:
        # prune subtrees that are concrete at trace time: x.shape, x.dtype,
        # len(x) — a value computed from those is a Python scalar
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call) and \
                last_segment(dotted_name(node.func)) == "len":
            return False
        if isinstance(node, ast.Name):
            return node.id in taint
        return any(is_tainted(c) for c in ast.iter_child_nodes(node))

    def add(rule: str, node: ast.AST, message: str, hint: str,
            detail: str) -> None:
        findings.append(Finding(
            rule=rule, path=sf.path, line=node.lineno, scope=scope,
            message=message, hint=hint, detail=detail))

    for node in ast.walk(fd):
        # taint propagation through straight-line assignment (order of
        # ast.walk is pre-order, good enough for the simple bodies here)
        if isinstance(node, ast.Assign) and is_tainted(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        taint.add(n.id)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.startswith(("np.", "numpy.")) and \
                    not name.startswith(_RNG_PREFIXES):
                if any(is_tainted(a) for a in node.args) or \
                        any(is_tainted(k.value) for k in node.keywords):
                    add("JIT001", node,
                        f"{name}() applied to traced argument inside "
                        f"jit-compiled {fd.name}",
                        "use the jnp equivalent; numpy on a tracer raises "
                        "or forces a host transfer", name)
            if name in _CLOCKS or name.startswith(_RNG_PREFIXES):
                add("JIT003", node,
                    f"{name}() inside jit-compiled {fd.name} freezes its "
                    "value at trace time",
                    "pass the value in as an argument, or use "
                    "jax.random with an explicit key", name)
            if name == "jax.device_get":
                add("JIT004", node,
                    f"jax.device_get inside jit-compiled {fd.name}",
                    "return the array and fetch it outside the jitted "
                    "function", name)
            if name in ("float", "int", "bool") and node.args and \
                    is_tainted(node.args[0]):
                add("JIT004", node,
                    f"{name}() of a traced value inside jit-compiled "
                    f"{fd.name} forces a host sync",
                    "keep the value on-device (jnp ops) or hoist the "
                    "conversion out of the jitted function", name)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS and \
                    is_tainted(node.func.value):
                add("JIT004", node,
                    f".{node.func.attr}() on a traced value inside "
                    f"jit-compiled {fd.name}",
                    "host syncs belong outside the traced body",
                    node.func.attr)
        if isinstance(node, (ast.If, ast.While)) and is_tainted(node.test):
            add("JIT002", node,
                f"Python control flow branches on a traced value in "
                f"jit-compiled {fd.name}",
                "use jnp.where or jax.lax.cond/switch; Python if/while "
                "bakes one branch into the trace",
                "branch")
        if isinstance(node, ast.IfExp) and is_tainted(node.test):
            add("JIT002", node,
                f"conditional expression tests a traced value in "
                f"jit-compiled {fd.name}",
                "use jnp.where(cond, a, b)", "ifexp")
        if isinstance(node, ast.Assert) and is_tainted(node.test):
            add("JIT002", node,
                f"assert on a traced value in jit-compiled {fd.name}",
                "use checkify or move the check outside the trace",
                "assert")
    return findings
