"""LCK001/LCK002: lock-discipline checkers.

The runtime mixes asyncio executors with real threads (engine fetch thread,
continuous-batching dispatcher, dist transport, Kafka wire client), all
coordinated by ``threading.Lock``/``Condition``. Two bug classes keep
reappearing in review:

* **LCK001 — blocking call under a lock.** A thread sleeping, joining,
  waiting on a Future, doing socket I/O, or forcing a device sync while it
  holds a lock stalls every other thread that needs that lock; under the
  client-wide locks (KafkaWireClient, shared_engine) that is a global stall.
  Condition ``wait``/``wait_for`` on the *held* condition is exempt — it
  releases the lock while sleeping, which is the whole point of a Condition.

* **LCK002 — lock-order inversion.** Two sites that acquire the same pair
  of locks in opposite orders can deadlock. The checker builds an
  acquisition graph over the whole tree (lock identities are
  ``module:Class.attr`` for instance locks, ``module:NAME`` for globals)
  and flags every 2-cycle.

Both are heuristic AST passes: lock-ness is inferred from names
(``*lock*``, ``*cond*``, ``mutex``, ``*sem*``) plus ``.acquire()`` calls,
and blocking-ness from a call table extended by ``[tool.storm-tpu.lint]
blocking_methods``. Intentional holds (e.g. the engine's device dispatch
under ``_lock`` to preserve collective ordering) go in the baseline with a
justification, not in code-level suppressions.
"""

from __future__ import annotations

import ast
from typing import (
    Dict,
    Iterable,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

from storm_tpu.analysis.core import (
    Finding,
    LintConfig,
    SourceFile,
    dotted_name,
    last_segment,
)

#: fully-dotted callables that block the calling thread
BLOCKING_FUNCS = {
    "time.sleep",
    "select.select",
    "socket.create_connection",
    "jax.device_put",
    "jax.device_get",
    "subprocess.run",
    "subprocess.check_output",
}

#: method names that block regardless of receiver
BLOCKING_METHODS = {
    "recv", "recv_into", "accept", "connect", "sendall", "makefile",
    "block_until_ready", "result",
}

#: base-name fragments that mark a receiver as a queue (so zero-positional
#: ``.get()`` / ``.put(...)`` mean the blocking queue protocol, not dict.get)
_QUEUEISH = ("queue", "inbox", "outbox", "mailbox")

#: schedulers that take a coroutine *object*: ``create_task(proc.wait())``
#: queues the wrapped call for later, so it never blocks at this site
_SCHEDULERS = ("create_task", "ensure_future", "run_coroutine_threadsafe")


def _module_of(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    return mod.replace("/", ".")


def _is_lockish(name: str) -> bool:
    seg = last_segment(name).lower()
    return bool(seg) and ("lock" in seg or "cond" in seg or seg == "mutex"
                          or "sem" in seg)


def _queueish(base: str) -> bool:
    seg = last_segment(base).lower()
    return (any(q in seg for q in _QUEUEISH) or seg in ("q",)
            or seg.endswith("_q"))


class _Region:
    """One lock-held region: identity key + acquisition site."""

    __slots__ = ("key", "line")

    def __init__(self, key: str, line: int) -> None:
        self.key = key
        self.line = line


class CallRecord(NamedTuple):
    """Every call the walker sees, with its lock context — the substrate
    the interprocedural passes (analysis/callgraph.py) are built on.

    ``reason`` is the LCK001 blocking reason (held-aware: Condition.wait
    on a held lock is exempt); ``summary_reason`` ignores that exemption,
    because a callee that parks on its own condition still sleeps while
    the *caller's* locks stay held — that is exactly what a transitive
    blocking summary must propagate."""

    scope: str
    raw: str  # dotted callee text ('' for dynamic calls)
    line: int
    held: Tuple[str, ...]  # lock keys held at the call site, outer->inner
    reason: Optional[str]
    summary_reason: Optional[str]


class _LockWalker:
    """Per-file walk producing LCK001 findings and acquisition edges."""

    def __init__(self, sf: SourceFile, config: LintConfig) -> None:
        self.sf = sf
        self.config = config
        self.module = _module_of(sf.path)
        self.findings: List[Finding] = []
        #: (outer_key, inner_key, path, line, scope)
        self.edges: List[Tuple[str, str, str, int, str]] = []
        #: every call with its lock context (callgraph substrate)
        self.calls: List[CallRecord] = []
        #: every lock acquisition: (scope, key, line)
        self.acquisitions: List[Tuple[str, str, int]] = []
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []

    # -- identity ---------------------------------------------------------

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        name = dotted_name(expr)
        if not name or not _is_lockish(name):
            return None
        if name.startswith("self."):
            cls = self._class_stack[-1] if self._class_stack else "?"
            return f"{self.module}:{cls}.{name[5:]}"
        if "." not in name:
            # module global (typically ALL_CAPS) unifies across functions;
            # a function-local lock object is scoped to its function.
            if name.isupper() or not self._func_stack:
                return f"{self.module}:{name}"
            return f"{self.module}:{'.'.join(self._func_stack)}#{name}"
        return f"{self.module}:{name}"

    @property
    def scope(self) -> str:
        return ".".join(self._class_stack + self._func_stack) or "<module>"

    # -- traversal --------------------------------------------------------

    def run(self) -> None:
        self._walk_body(self.sf.tree.body, [])

    def _walk_body(self, stmts: Sequence[ast.stmt],
                   held: List[_Region]) -> None:
        i = 0
        n = len(stmts)
        while i < n:
            st = stmts[i]
            key = self._acquire_stmt(st)
            if key is not None:
                # linear-scan region: from this .acquire() to the matching
                # .release() at the same nesting level (or end of body).
                j = i + 1
                while j < n and self._release_stmt(stmts[j]) != key:
                    j += 1
                self._enter(key, st.lineno, held)
                region = _Region(key, st.lineno)
                self._walk_body(list(stmts[i + 1:j]), held + [region])
                i = j + 1
                continue
            self._walk_stmt(st, held)
            i += 1

    def _acquire_stmt(self, st: ast.stmt) -> Optional[str]:
        if not (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)):
            return None
        call = st.value
        if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
            return self._lock_key(call.func.value)
        return None

    def _release_stmt(self, st: ast.stmt) -> Optional[str]:
        if not (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)):
            return None
        call = st.value
        if isinstance(call.func, ast.Attribute) and call.func.attr == "release":
            return self._lock_key(call.func.value)
        return None

    def _enter(self, key: str, line: int, held: List[_Region]) -> None:
        self.acquisitions.append((self.scope, key, line))
        for outer in held:
            if outer.key != key:
                self.edges.append(
                    (outer.key, key, self.sf.path, line, self.scope))

    def _walk_stmt(self, st: ast.stmt, held: List[_Region]) -> None:
        if isinstance(st, ast.ClassDef):
            self._class_stack.append(st.name)
            self._walk_body(st.body, held)
            self._class_stack.pop()
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs later, not under the current locks
            self._func_stack.append(st.name)
            self._walk_body(st.body, [])
            self._func_stack.pop()
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            add: List[_Region] = []
            for item in st.items:
                expr = item.context_expr
                key = None if isinstance(expr, ast.Call) \
                    else self._lock_key(expr)
                if key is not None:
                    self._enter(key, st.lineno, held + add)
                    add.append(_Region(key, st.lineno))
                else:
                    # with sock.makefile() as f: — a blocking item is a
                    # blocking call like any other
                    self._scan_expr(expr, held + add)
            self._walk_body(st.body, held + add)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._scan_expr(st.test, held)
            self._walk_body(st.body, list(held))
            self._walk_body(st.orelse, list(held))
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter, held)
            self._walk_body(st.body, list(held))
            self._walk_body(st.orelse, list(held))
            return
        if isinstance(st, ast.Try):
            self._walk_body(st.body, list(held))
            for handler in st.handlers:
                self._walk_body(handler.body, list(held))
            self._walk_body(st.orelse, list(held))
            self._walk_body(st.finalbody, list(held))
            return
        # simple statement: scan the whole thing
        self._scan_expr(st, held)

    # -- blocking-call detection ------------------------------------------

    def _scan_expr(self, node: ast.AST, held: List[_Region]) -> None:
        scheduled = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and last_segment(dotted_name(sub.func)) in _SCHEDULERS:
                for a in sub.args:
                    if isinstance(a, ast.Call):
                        scheduled.add(a)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                continue  # runs later
            if isinstance(sub, ast.Call) and sub not in scheduled:
                self._check_call(sub, held)

    def _check_call(self, call: ast.Call, held: List[_Region]) -> None:
        summary = self._blocking_reason(call, [])
        reason = self._blocking_reason(call, held) if summary else None
        self.calls.append(CallRecord(
            scope=self.scope, raw=dotted_name(call.func), line=call.lineno,
            held=tuple(r.key for r in held),
            reason=reason if held else None, summary_reason=summary))
        if not held or reason is None:
            return
        innermost = held[-1]
        self.findings.append(Finding(
            rule="LCK001",
            path=self.sf.path,
            line=call.lineno,
            scope=self.scope,
            message=(f"blocking call {reason}() while holding "
                     f"{innermost.key.split(':')[-1]} "
                     f"(acquired line {innermost.line})"),
            hint=("move the blocking call outside the lock (snapshot under "
                  "the lock, act after releasing), or baseline with a "
                  "justification if the hold is intentional"),
            detail=reason,
        ))

    def _blocking_reason(self, call: ast.Call,
                         held: List[_Region]) -> Optional[str]:
        name = dotted_name(call.func)
        if name in BLOCKING_FUNCS:
            return name
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        base = dotted_name(call.func.value)
        if meth in ("wait", "wait_for"):
            # Condition.wait on a lock we hold RELEASES it — the sanctioned
            # sleep-under-lock. Any other .wait (Event, Process, foreign
            # condition) sleeps while still holding ours.
            key = self._lock_key(call.func.value)
            if key is not None and any(r.key == key for r in held):
                return None
            return f"{base or '?'}.{meth}"
        if meth in BLOCKING_METHODS:
            # .result() is the Future protocol everywhere in this tree;
            # recv/sendall/accept/connect only appear on sockets.
            return f"{base or '?'}.{meth}"
        if meth == "join":
            # zero-arg join is Thread/Process.join; sep.join(parts) and
            # os.path.join always take arguments.
            if not call.args and not call.keywords:
                return f"{base or '?'}.join"
            return None
        if meth == "get":
            kw = {k.arg for k in call.keywords}
            if "timeout" in kw or "block" in kw:
                return f"{base or '?'}.get"
            if not call.args and _queueish(base):
                return f"{base}.get"
            return None
        if meth == "put":
            if _queueish(base):
                for k in call.keywords:
                    if k.arg == "block" and isinstance(k.value, ast.Constant) \
                            and k.value.value is False:
                        return None
                return f"{base}.put"
            return None
        if meth == "acquire":
            # acquiring a second lock is an LCK002 edge, not LCK001 —
            # except semaphores, which can sleep indefinitely and are not
            # part of an ordering discipline.
            if "sem" in last_segment(base).lower():
                return f"{base}.acquire"
            return None
        if meth in self.config.blocking_methods:
            return f"{base or '?'}.{meth}"
        return None


def check(sf: SourceFile, config: LintConfig) -> List[Finding]:
    w = _LockWalker(sf, config)
    w.run()
    return w.findings


def collect_edges(sf: SourceFile, config: LintConfig):
    w = _LockWalker(sf, config)
    w.run()
    return w.edges


def check_ordering(files: Iterable[SourceFile], config: LintConfig,
                   edges_in: Optional[Sequence[Tuple[str, str, str, int, str]]]
                   = None) -> List[Finding]:
    """LCK002: find 2-cycles in the whole-tree lock-acquisition graph.

    ``edges_in`` lets the driver reuse the walker output already collected
    for the call graph instead of re-walking every file."""
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    if edges_in is None:
        edges_in = [e for sf in files for e in collect_edges(sf, config)]
    for outer, inner, path, line, scope in edges_in:
        edges.setdefault((outer, inner), (path, line, scope))
    findings: List[Finding] = []
    seen = set()
    for (a, b), (path, line, scope) in sorted(edges.items()):
        if (b, a) not in edges or frozenset((a, b)) in seen:
            continue
        seen.add(frozenset((a, b)))
        other_path, other_line, _ = edges[(b, a)]
        findings.append(Finding(
            rule="LCK002",
            path=path,
            line=line,
            scope=scope,
            message=(f"lock-order inversion: {a.split(':')[-1]} -> "
                     f"{b.split(':')[-1]} here, but "
                     f"{other_path}:{other_line} acquires them in the "
                     "opposite order"),
            hint=("pick one global order for this lock pair and make both "
                  "sites follow it, or split the critical sections so "
                  "neither nests"),
            detail="<->".join(sorted((a, b))),
        ))
    return findings


# ---------------------------------------------------------------------------
# Interprocedural tier (LCK003/LCK004) — built on analysis/callgraph.py
# ---------------------------------------------------------------------------


def check_transitive(graph, config: LintConfig) -> List[Finding]:
    """LCK003: a call under a held lock whose *callee* may block, any
    number of frames down — the depth-N upgrade of LCK001. Direct blocking
    calls are LCK001's job and are skipped here; the finding prints the
    shortest witness chain down to the concrete blocking call."""
    findings: List[Finding] = []
    emitted = set()
    for lc in graph.locked_calls:
        if lc.reason is not None:
            continue  # directly blocking: LCK001 already covers it
        caller_q = f"{lc.module}:{lc.scope}"
        target = graph.resolve(lc.module, lc.scope, lc.raw,
                               graph.functions.get(caller_q))
        if target is None or target == caller_q:
            continue
        fn = graph.functions[target]
        if not fn.may_block:
            continue
        chain = graph.block_chain(target)
        detail = f"{lc.raw}->{chain[-1]}"
        dkey = (lc.path, lc.scope, detail)
        if dkey in emitted:
            continue
        emitted.add(dkey)
        innermost = lc.held[-1]
        findings.append(Finding(
            rule="LCK003",
            path=lc.path,
            line=lc.line,
            scope=lc.scope,
            message=(f"{lc.raw}() may block while holding "
                     f"{innermost.split(':')[-1]}: "
                     f"{' -> '.join(chain)}"),
            hint=("the callee (or something it calls) blocks; snapshot "
                  "under the lock and call after releasing, or baseline "
                  "with a justification if the hold is intentional"),
            detail=detail,
            chain=chain,
        ))
    return findings


_MAX_CYCLE_LEN = 6
_MAX_CYCLES = 64


def check_cycles(graph, config: LintConfig) -> List[Finding]:
    """LCK004: full lock-order cycle detection over the acquisition graph
    (SCC-style bounded DFS), replacing LCK002's 2-cycle special case for
    anything longer — and extending the edge set *interprocedurally*: a
    call made while holding A into a function whose lock summary says it
    may take B contributes an A->B edge even though no single function
    nests the two acquisitions. Syntactic 2-cycles stay LCK002's report."""
    # edge -> (path, line, scope, how)
    edges: Dict[Tuple[str, str], Tuple[str, int, str, str]] = {}
    syntactic = set()
    for outer, inner, path, line, scope in graph.lock_edges:
        if outer == inner:
            continue
        edges.setdefault((outer, inner), (path, line, scope, "nested here"))
        syntactic.add((outer, inner))
    for lc in graph.locked_calls:
        caller_q = f"{lc.module}:{lc.scope}"
        target = graph.resolve(lc.module, lc.scope, lc.raw,
                               graph.functions.get(caller_q))
        if target is None:
            continue
        for dest in graph.functions[target].trans_acquires:
            for held in lc.held:
                if held != dest:
                    edges.setdefault(
                        (held, dest),
                        (lc.path, lc.line, lc.scope, f"via {lc.raw}()"))
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    for a in adj:
        adj[a].sort()
    cycles: List[Tuple[str, ...]] = []

    def _dfs(start: str, node: str, path: List[str],
             on_path: set) -> None:
        if len(cycles) >= _MAX_CYCLES or len(path) > _MAX_CYCLE_LEN:
            return
        for nxt in adj.get(node, ()):
            if nxt < start:
                continue  # each cycle enumerated from its min node only
            if nxt == start:
                if len(path) >= 2:
                    cycles.append(tuple(path))
            elif nxt not in on_path:
                on_path.add(nxt)
                _dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for start in sorted(adj):
        _dfs(start, start, [start], {start})
    findings: List[Finding] = []
    for cyc in sorted(cycles):
        pairs = [(cyc[i], cyc[(i + 1) % len(cyc)]) for i in range(len(cyc))]
        if len(cyc) == 2 and all(p in syntactic for p in pairs):
            continue  # LCK002 reports syntactic 2-cycles
        path, line, scope, how = edges[pairs[0]]
        shorts = [k.split(":")[-1] for k in cyc]
        findings.append(Finding(
            rule="LCK004",
            path=path,
            line=line,
            scope=scope,
            message=(f"lock-order cycle {' -> '.join(shorts)} -> "
                     f"{shorts[0]} (first edge {how})"),
            hint=("impose one global acquisition order over these locks, "
                  "or break the chain by moving a call out of the held "
                  "region"),
            detail="->".join(cyc),
            chain=list(cyc),
        ))
    return findings
