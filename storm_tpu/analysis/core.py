"""Shared machinery for the ``storm-tpu lint`` invariant analyzer.

The analyzer is project-specific by design: instead of a generic linter's
style rules, each checker encodes one invariant the runtime's correctness
actually rests on (lock discipline, the exactly-once ack contract, jit
tracer hygiene, metric-name/span integrity — see docs/ARCHITECTURE.md
"Statically checked invariants"). Checkers are pure AST passes: no imports
of the checked code, so linting never executes device or network paths.

Findings are gated against a committed ``baseline.json`` of reviewed-and-
accepted findings (each with a one-line justification), so the tier-1 gate
is "no NEW findings" — the analyzer can be adopted on a living tree without
first refactoring every intentional lock-hold. Baseline keys deliberately
exclude line numbers: editing an unrelated part of a file must not churn
the baseline.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Rule id -> short description (the CLI's --rules help and the docs table
#: derive from this; checkers register themselves via CHECKERS below).
RULES: Dict[str, str] = {
    "LCK001": "blocking call while a lock is held",
    "LCK002": "lock-order inversion between acquisition sites",
    "LCK003": "call that may block transitively while a lock is held",
    "LCK004": "lock-order cycle in the cross-file acquisition graph",
    "THR001": "thread without daemon flag, finalizer, or shutdown join",
    "THR002": "executor without shutdown or ownership hand-off",
    "PRT001": "control command sent/handled on only one side of the wire",
    "PRT002": "journal kind emitted without an apply fold arm",
    "PRT003": "flight event not in the generated protocol registry",
    "XO001": "tuple can leave execute() without ack/fail/deferral",
    "JIT001": "np.* applied to a traced argument inside jit",
    "JIT002": "Python control flow branches on a tracer value",
    "JIT003": "clock/RNG read inside a jit-compiled function",
    "JIT004": "host sync (block_until_ready/.item) inside jit",
    "OBS001": "metric name not in the generated registry",
    "OBS002": "unbalanced span/trace capture (start without stop)",
    "OBS003": "metric name used as conflicting kinds",
}


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    scope: str  # dotted: Class.method or function or <module>
    message: str
    hint: str = ""
    #: Stable detail token for baseline keying (e.g. the offending call
    #: text) — survives line drift from unrelated edits.
    detail: str = ""
    #: Witness chain for interprocedural findings (LCK003's call chain
    #: down to the blocking call, LCK004's lock cycle); empty otherwise.
    chain: List[str] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.scope}:{self.detail}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "description": RULES.get(self.rule, ""),
            "path": self.path,
            "line": self.line,
            "scope": self.scope,
            "message": self.message,
            "hint": self.hint,
            "key": self.key(),
            "chain": list(self.chain),
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} [{self.scope}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class LintConfig:
    """Knobs from ``[tool.storm-tpu.lint]`` in pyproject.toml.

    ``exclude`` patterns are fnmatch globs against the repo-relative path;
    per-rule excludes live in ``rule_exclude`` ({rule: [globs]}).
    ``blocking_methods`` extends the built-in blocking-call table with
    project-specific method names (e.g. the gRPC control-plane verbs) —
    the attr name alone matches, so keep the list specific."""

    enable: List[str] = field(default_factory=lambda: sorted(RULES))
    exclude: List[str] = field(default_factory=list)
    rule_exclude: Dict[str, List[str]] = field(default_factory=dict)
    blocking_methods: List[str] = field(default_factory=list)
    #: substrings identifying tuple-handling classes for the XO checker
    tuple_classes: List[str] = field(
        default_factory=lambda: ["Bolt", "Spout", "Sink", "Router",
                                 "Operator"])

    def rule_enabled(self, rule: str) -> bool:
        return rule in self.enable

    def excluded(self, rule: str, path: str) -> bool:
        pats = list(self.exclude) + list(self.rule_exclude.get(rule, []))
        return any(fnmatch.fnmatch(path, p) for p in pats)


def _read_lint_section(path: str) -> dict:
    """``[tool.storm-tpu.lint]`` as a dict. Uses tomllib when available
    (3.11+); otherwise a minimal fallback that understands the subset this
    section uses (string-list and string values), since the container's
    3.10 has no TOML parser in the stdlib."""
    try:
        import tomllib  # type: ignore[import-not-found]
    except ImportError:
        tomllib = None
    if tomllib is not None:
        try:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        except (OSError, ValueError):
            return {}
        sec = data.get("tool", {}).get("storm-tpu", {}).get("lint", {})
        return sec if isinstance(sec, dict) else {}
    import re

    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return {}
    m = re.search(r"^\[tool\.(?:\"storm-tpu\"|storm-tpu)\.lint\]\s*$(.*?)"
                  r"(?=^\[|\Z)", text, re.M | re.S)
    if not m:
        return {}
    body = m.group(1)
    out: dict = {}
    # join multiline arrays, then parse `key = value` pairs
    body = re.sub(r",\s*\n", ", ", body)
    body = re.sub(r"\[\s*\n", "[", body)
    body = re.sub(r"\n\s*\]", "]", body)
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("["):
            out[key] = re.findall(r"\"([^\"]*)\"|'([^']*)'", val)
            out[key] = [a or b for a, b in out[key]]
        elif val[:1] in ("\"", "'"):
            out[key] = val[1:-1]
    return out


def load_config(root: str) -> LintConfig:
    """Read ``[tool.storm-tpu.lint]`` from ``<root>/pyproject.toml``;
    missing file or section yields the defaults."""
    cfg = LintConfig()
    sec = _read_lint_section(os.path.join(root, "pyproject.toml"))
    if not sec:
        return cfg
    if isinstance(sec.get("enable"), list):
        cfg.enable = [str(r) for r in sec["enable"]]
    if isinstance(sec.get("disable"), list):
        cfg.enable = [r for r in cfg.enable
                      if r not in {str(x) for x in sec["disable"]}]
    if isinstance(sec.get("exclude"), list):
        cfg.exclude = [str(p) for p in sec["exclude"]]
    if isinstance(sec.get("blocking_methods"), list):
        cfg.blocking_methods = [str(m) for m in sec["blocking_methods"]]
    if isinstance(sec.get("tuple_classes"), list):
        cfg.tuple_classes = [str(c) for c in sec["tuple_classes"]]
    for rule in RULES:
        key = f"exclude_{rule}"
        if isinstance(sec.get(key), list):
            cfg.rule_exclude[rule] = [str(p) for p in sec[key]]
    return cfg


# ---------------------------------------------------------------------------
# Source model: one parsed file handed to every checker
# ---------------------------------------------------------------------------


@dataclass
class SourceFile:
    path: str  # repo-relative, forward slashes
    tree: ast.Module
    source: str

    def text_of(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:  # pragma: no cover - malformed positions
            return ""


def parse_source(source: str, path: str) -> Optional[SourceFile]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    return SourceFile(path=path.replace(os.sep, "/"), tree=tree,
                      source=source)


def iter_python_files(paths: Sequence[str], root: str) -> Iterable[str]:
    """Yield .py files under ``paths`` (files or directories), sorted,
    skipping caches. Paths are returned repo-relative to ``root``."""
    seen = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            seen.append(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    seen.append(os.path.join(dirpath, fn))
    for ap in sorted(seen):
        yield os.path.relpath(ap, root).replace(os.sep, "/")


# ---------------------------------------------------------------------------
# AST helpers shared by checkers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains; '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_segment(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the dotted Class.method scope for findings."""

    def __init__(self) -> None:
        self._scope: List[str] = []

    @property
    def scope(self) -> str:
        return ".".join(self._scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_func(self, node) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, str]:
    """``{finding key: justification}``. Accepts the committed schema
    ({"findings": [{"key": ..., "why": ...}]}) and a bare key->why map."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if isinstance(data, dict) and isinstance(data.get("findings"), list):
        out = {}
        for row in data["findings"]:
            if isinstance(row, dict) and row.get("key"):
                out[str(row["key"])] = str(row.get("why", ""))
        return out
    if isinstance(data, dict):
        return {str(k): str(v) for k, v in data.items()}
    return {}


def write_baseline(path: str, findings: Sequence[Finding],
                   why: str = "accepted via --update-baseline",
                   prior: Optional[Dict[str, str]] = None) -> None:
    """Write the committed baseline, preserving prior justifications for
    keys that survive."""
    prior = prior or {}
    rows = []
    seen = set()
    for f in sorted(findings, key=lambda f: f.key()):
        if f.key() in seen:
            continue  # several lines can share one key (same call, same
        seen.add(f.key())  # scope); one entry suppresses them all
        rows.append({
            "key": f.key(),
            "rule": f.rule,
            "path": f.path,
            "scope": f.scope,
            "why": prior.get(f.key(), why),
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"findings": rows}, fh, indent=2, sort_keys=False)
        fh.write("\n")


def filter_new(findings: Sequence[Finding],
               baseline: Dict[str, str]) -> List[Finding]:
    return [f for f in findings if f.key() not in baseline]


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                config: Optional[LintConfig] = None) -> List[Finding]:
    """Lint one source string (the unit-test entry point)."""
    sf = parse_source(source, path)
    if sf is None:
        return [Finding(rule="PARSE", path=path, line=1, scope="<module>",
                        message="file does not parse", detail="syntax")]
    return _check_file(sf, config or LintConfig())


def _load_files(paths: Sequence[str], root: str
                ) -> Tuple[List[SourceFile], List[Finding]]:
    files: List[SourceFile] = []
    findings: List[Finding] = []
    for rel in iter_python_files(paths, root):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        sf = parse_source(src, rel)
        if sf is None:
            findings.append(Finding(
                rule="PARSE", path=rel, line=1, scope="<module>",
                message="file does not parse", detail="syntax"))
            continue
        files.append(sf)
    return files, findings


def _check_file(sf: SourceFile, config: LintConfig) -> List[Finding]:
    # Imported here so each checker module can use core helpers freely.
    from storm_tpu.analysis import exactly_once, jit_hygiene, locks
    from storm_tpu.analysis import observability

    out: List[Finding] = []
    for checker in (locks.check, exactly_once.check, jit_hygiene.check,
                    observability.check):
        for f in checker(sf, config):
            if config.rule_enabled(f.rule) and not config.excluded(
                    f.rule, f.path):
                out.append(f)
    return out


def cross_file_findings(files: Sequence[SourceFile], config: LintConfig,
                        timings: Optional[Dict[str, float]] = None
                        ) -> List[Finding]:
    """Whole-tree passes that need every file at once: the call graph and
    the interprocedural rules built on it (LCK002-004, THR, PRT), plus
    metric kind conflicts (OBS003). ``timings`` (from ``--profile``) is
    filled with per-phase wall-clock seconds."""
    import time as _time

    from storm_tpu.analysis import (
        callgraph,
        locks,
        observability,
        protocol,
        threads,
    )

    t0 = _time.perf_counter()
    graph = callgraph.CallGraph(files, config)
    if timings is not None:
        timings["callgraph_s"] = _time.perf_counter() - t0
    passes = (
        ("lck002_s", lambda: locks.check_ordering(
            files, config, edges_in=graph.lock_edges)),
        ("lck003_s", lambda: locks.check_transitive(graph, config)),
        ("lck004_s", lambda: locks.check_cycles(graph, config)),
        ("thr_s", lambda: threads.check_lifecycles(files, config, graph)),
        ("prt_s", lambda: protocol.check_protocols(files, config)),
        ("obs003_s", lambda: observability.check_kinds(files, config)),
    )
    out: List[Finding] = []
    for label, run in passes:
        t0 = _time.perf_counter()
        for f in run():
            if config.rule_enabled(f.rule) and not config.excluded(
                    f.rule, f.path):
                out.append(f)
        if timings is not None:
            timings[label] = _time.perf_counter() - t0
    return out


def run_lint(paths: Sequence[str], root: str,
             config: Optional[LintConfig] = None,
             timings: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Full run: per-file checkers plus the cross-file graph passes."""
    import time as _time

    t_start = _time.perf_counter()
    config = config or load_config(root)
    files, findings = _load_files(paths, root)
    if timings is not None:
        timings["load_s"] = _time.perf_counter() - t_start
        timings["files"] = len(files)
    t0 = _time.perf_counter()
    for sf in files:
        findings.extend(_check_file(sf, config))
    if timings is not None:
        timings["per_file_s"] = _time.perf_counter() - t0
    findings.extend(cross_file_findings(files, config, timings))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if timings is not None:
        timings["total_s"] = _time.perf_counter() - t_start
    return findings
