"""Metric-name registry — GENERATED, do not edit by hand.

Regenerate after adding/renaming a metric:

    storm-tpu lint --regen-metric-registry

Generated from every ``counter``/``gauge``/``histogram`` call site in the
tree. Literal names land in ``METRIC_NAMES``; f-string sites contribute a
wildcard pattern to ``METRIC_PATTERNS`` (literal chunks joined by ``*``).
``storm_tpu/analysis/observability.py`` (OBS001) checks call sites against
this file statically; ``runtime/metrics.py`` warns once at runtime for any
name that matches neither — together they catch the write-side typo whose
only other symptom is a flatlined dashboard panel.
"""

from __future__ import annotations

import fnmatch

METRIC_NAMES = frozenset({
    'ack_rate',
    'acked',
    'batch_fill',
    'batch_size',
    'batch_wait_ms',
    'burn_rate',
    'burn_rate_slow',
    'cascade_budget_capped',
    'cascade_escalations',
    'cascade_shed_pinned',
    'checkpoints',
    'coalesced_sources',
    'copies_amplification',
    'dead_lettered',
    'delivered',
    'device_ms',
    'dispatch_wait_ms',
    'dist_circuit_opens',
    'dist_heartbeat_miss',
    'dist_journal_appends',
    'dist_journal_replayed',
    'dist_journal_snapshots',
    'dist_parked_batches',
    'dist_replay_throttle_ms',
    'dist_replay_throttled',
    'dist_rerouted',
    'dist_ring_remapped',
    'dist_send_failures',
    'dist_send_retries',
    'dist_shm_batches',
    'dist_wire_errors',
    'dropped_stale',
    'e2e_latency_ms',
    'emitted',
    'engine_quarantined',
    'errors',
    'escalation_rate',
    'execute_ms',
    'execute_rate',
    'executed',
    'executor_restarts',
    'failed',
    'inbox_depth',
    'ingest_lag_ms',
    'instances_inferred',
    'offered_records',
    'plan_active',
    'plan_corrections',
    'produce_ms',
    'profile_regressions',
    'shed_decisions',
    'shed_degraded',
    'shed_level',
    'shed_rejected',
    'slo_breaches',
    'tree_acked',
    'tree_failed',
    'tripped',
    'txn_aborts',
    'txn_commits',
    'txn_offsets_deferred',
    'watchdog_trips',
    'worker_draining',
})

METRIC_PATTERNS = (
    '*_ms',
    'admitted_*',
    'admitted_lane_*',
    'cascade_accepted_tier*',
    'cascade_decided_lane_*',
    'cascade_escalated_lane_*',
    'copies_bytes_per_rec_*',
    'copies_per_rec_*',
    'dist_circuit_open_w*',
    'e2e_latency_ms_*',
    'fair_rows_*_*',
    'fair_starved_*_*',
    'offered_lane_*',
    'shed_*',
    'shed_lane_*',
    'throttled_*',
    'throttled_lane_*',
    'tier*_device_ms',
)

#: literal name -> kinds seen at generation time
METRIC_KINDS = {
    'ack_rate': ('gauge',),
    'acked': ('counter',),
    'batch_fill': ('histogram',),
    'batch_size': ('histogram',),
    'batch_wait_ms': ('histogram',),
    'burn_rate': ('gauge',),
    'burn_rate_slow': ('gauge',),
    'cascade_budget_capped': ('counter',),
    'cascade_escalations': ('counter',),
    'cascade_shed_pinned': ('counter',),
    'checkpoints': ('counter',),
    'coalesced_sources': ('counter',),
    'copies_amplification': ('gauge',),
    'dead_lettered': ('counter',),
    'delivered': ('counter',),
    'device_ms': ('histogram',),
    'dispatch_wait_ms': ('histogram',),
    'dist_circuit_opens': ('counter',),
    'dist_heartbeat_miss': ('counter',),
    'dist_journal_appends': ('counter',),
    'dist_journal_replayed': ('counter',),
    'dist_journal_snapshots': ('counter',),
    'dist_parked_batches': ('counter',),
    'dist_replay_throttle_ms': ('histogram',),
    'dist_replay_throttled': ('counter',),
    'dist_rerouted': ('counter',),
    'dist_ring_remapped': ('counter',),
    'dist_send_failures': ('counter',),
    'dist_send_retries': ('counter',),
    'dist_shm_batches': ('counter',),
    'dist_wire_errors': ('counter',),
    'dropped_stale': ('counter',),
    'e2e_latency_ms': ('histogram',),
    'emitted': ('counter',),
    'engine_quarantined': ('gauge',),
    'errors': ('counter',),
    'escalation_rate': ('gauge',),
    'execute_ms': ('histogram',),
    'execute_rate': ('gauge',),
    'executed': ('counter',),
    'executor_restarts': ('counter',),
    'failed': ('counter',),
    'inbox_depth': ('gauge',),
    'ingest_lag_ms': ('histogram',),
    'instances_inferred': ('counter',),
    'offered_records': ('counter',),
    'plan_active': ('gauge',),
    'plan_corrections': ('counter',),
    'produce_ms': ('histogram',),
    'profile_regressions': ('counter',),
    'shed_decisions': ('counter',),
    'shed_degraded': ('counter',),
    'shed_level': ('gauge',),
    'shed_rejected': ('counter',),
    'slo_breaches': ('counter',),
    'tree_acked': ('counter',),
    'tree_failed': ('counter',),
    'tripped': ('gauge',),
    'txn_aborts': ('counter',),
    'txn_commits': ('counter',),
    'txn_offsets_deferred': ('counter',),
    'watchdog_trips': ('counter',),
    'worker_draining': ('gauge',),
}


def is_known(name: str) -> bool:
    if name in METRIC_NAMES:
        return True
    return any(fnmatch.fnmatchcase(name, p)
               for p in METRIC_PATTERNS)
