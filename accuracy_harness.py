#!/usr/bin/env python
"""End-to-end trained-model accuracy harness (VERDICT r3, missing #1).

Proves the system computes CORRECT predictions, not just fast ones: train
real models to convergence on a real dataset (scikit-learn's handwritten
digits — genuine 8x8 scans, the MNIST task at offline-available scale),
save orbax checkpoints, then serve the held-out test set through the FULL
product path — Kafka record -> {"instances"} JSON -> spout -> batcher ->
engine -> {"predictions"} JSON -> sink — for every fast-path mode that
could silently destroy task accuracy:

  bf16 compute, uint8 wire transfer, int8 weights (w8a16), int8_fused,
  and sharded serving (dp over the mesh; tp for attention models; ep for
  MoE) on an 8-device mesh.

For each mode it reports task accuracy measured AT THE OUTPUT TOPIC vs the
device-resident float32 accuracy, plus an ordering proof: every e2e output
row must be nearest-neighbor matched to its own index's device-resident
prediction (a bijection), so positional accuracy is sound without a
correlation id (the wire contract, like the reference's, has none —
InstObj.java:8, PredObj.java:9).

Run (CPU mesh, the suite-reproducible configuration):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python accuracy_harness.py --out ACCURACY_r04.json

On the real TPU chip (single-device modes):
  python accuracy_harness.py --models lenet5 --skip-sharded --out -
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CKPT_ROOT = os.path.join(REPO, "checkpoints")

# (model, builder kwargs, input_shape, channels, train epochs, modes)
MODEL_SPECS = {
    "lenet5": dict(input_shape=(32, 32, 1),
                   modes=["bf16", "uint8_wire", "int8", "int8_fused", "dp8"]),
    "resnet20": dict(input_shape=(32, 32, 3),
                     modes=["bf16", "uint8_wire", "int8", "dp8"]),
    "vit_tiny": dict(input_shape=(32, 32, 3),
                     modes=["bf16", "uint8_wire", "int8", "tp2"]),
    "moe_vit_tiny": dict(input_shape=(32, 32, 3),
                         modes=["bf16", "ep4"]),
}

# |acc_e2e - acc_float_device| bounds, stated up front. 8-bit quantization
# is lossy by design; bf16/sharding must be within argmax-flip noise.
EPSILON = {"bf16": 0.01, "dp8": 0.01, "tp2": 0.01, "ep4": 0.01,
           "uint8_wire": 0.02, "int8": 0.02, "int8_fused": 0.02}

# Transport-faithfulness bound: L-inf between each e2e output row and the
# SAME-mode engine-direct prediction at the same index. Within a mode the
# only legitimate differences are batch-composition effects (uint8 wire
# quantizes per transfer batch; bf16 reductions retile per bucket; MoE
# capacity overflow drops different tokens under different batch shapes),
# so the proof is row-fraction-based: >= MIN_ROW_MATCH of rows must agree
# within tolerance AND argmax agreement must be near-total. An
# out-of-order pipeline fails both catastrophically (most rows carry a
# different image's near-one-hot prediction), while batch-composition
# noise touches only the affected rows.
TRANSPORT_TOL = {"bf16": 0.05, "dp8": 0.05, "tp2": 0.05, "ep4": 0.05,
                 "uint8_wire": 0.15, "int8": 0.05, "int8_fused": 0.05}
MIN_ROW_MATCH = 0.90
MIN_ARGMAX_AGREE = 0.97


def log(msg: str) -> None:
    print(f"[accuracy] {msg}", flush=True)


def train_or_load(name: str, input_shape, max_epochs: int, seed: int = 0,
                  ckpt_tag: str = None):
    """Train to convergence once; later runs (and the test suite) reuse the
    committed checkpoint. Returns (ckpt_path, model, float_test_acc,
    x_test, y_test, history_tail). ``ckpt_tag`` names the checkpoint dir
    when one registry model is trained at a non-default shape (the cascade
    retrains lenet5 at 3 channels as ``lenet5_rgb``)."""
    import jax
    import jax.numpy as jnp

    from storm_tpu.data import load_digits_nhwc, train_to_convergence
    from storm_tpu.models.registry import (
        build_model,
        load_or_init,
        save_checkpoint,
    )

    x_tr, y_tr, x_te, y_te = load_digits_nhwc(input_shape, seed=seed)
    model = build_model(name, input_shape=input_shape)
    path = os.path.join(CKPT_ROOT, f"{ckpt_tag or name}_digits")
    if not os.path.exists(path):
        log(f"training {name} on digits ({len(x_tr)} train / {len(x_te)} test)")
        t0 = time.time()
        params, state, hist = train_to_convergence(
            model, x_tr, y_tr, x_te, y_te, max_epochs=max_epochs, seed=seed)
        log(f"{name}: {len(hist)} epochs in {time.time() - t0:.0f}s, "
            f"best val_acc={max(h['val_acc'] for h in hist):.4f}")
        save_checkpoint(path, params, state, model=model)
    params, state = load_or_init(model, path)

    @jax.jit
    def fwd(x):
        return model.apply(params, state, x, train=False)[0]

    preds = np.concatenate([
        np.asarray(fwd(jnp.asarray(x_te[i:i + 128])))
        for i in range(0, len(x_te), 128)])
    float_acc = float((preds.argmax(-1) == y_te).mean())
    log(f"{name}: device-resident float32 accuracy {float_acc:.4f}")
    return path, model, float_acc, x_te, y_te, preds


def mode_configs(mode: str, ckpt: str, name: str, input_shape):
    from storm_tpu.config import ModelConfig, ShardingConfig

    mc = dict(name=name, checkpoint=ckpt, input_shape=input_shape,
              num_classes=10)
    sc = dict()
    if mode == "bf16":
        pass
    elif mode == "uint8_wire":
        mc["transfer_dtype"] = "uint8"
    elif mode == "int8":
        mc["weights"] = "int8"
    elif mode == "int8_fused":
        mc["weights"] = "int8_fused"
    elif mode == "dp8":
        sc["data_parallel"] = 8
    elif mode == "tp2":
        sc["data_parallel"] = 4
        sc["tensor_parallel"] = 2
    elif mode == "ep4":
        sc["data_parallel"] = 2
        sc["expert_parallel"] = 4
    else:
        raise ValueError(mode)
    return ModelConfig(**mc), ShardingConfig(**sc)


def engine_accuracy(model_cfg, sharding_cfg, x_te, y_te):
    """Device-resident accuracy THROUGH the serving engine (same mode),
    separating engine-introduced error from transport-introduced error."""
    from storm_tpu.config import BatchConfig
    from storm_tpu.infer.engine import InferenceEngine

    eng = InferenceEngine(model_cfg, sharding_cfg,
                          BatchConfig(max_batch=64, buckets=(64,)))
    preds = np.concatenate([
        eng.predict(x_te[i:i + 64].astype(np.float32))
        for i in range(0, len(x_te), 64)])
    return float((preds.argmax(-1) == y_te).mean()), preds


def e2e_run(model_cfg, sharding_cfg, x_te, y_te, engine_preds, mode,
            timeout_s: float = 420.0, wire: bool = False):
    """Serve the test set through the full topology; returns the e2e row.

    One image per record on ONE partition with spout/infer/sink
    parallelism 1 and max_inflight 1 — the ordering-deterministic
    configuration — then PROVES ordering + faithful transport by
    positional L-inf agreement with the same-mode engine-direct
    predictions (see TRANSPORT_TOL) before positional accuracy is
    trusted. Nearest-neighbor matching cannot serve as the proof here:
    converged softmax outputs saturate to near-one-hot, so different
    images of the same class are mutually nearest."""
    from storm_tpu.api.schema import decode_predictions
    from storm_tpu.config import BatchConfig, Config
    from storm_tpu.connectors import MemoryBroker
    from storm_tpu.main import build_standard_topology
    from storm_tpu.runtime import LocalCluster

    cfg = Config()
    cfg.model = model_cfg
    cfg.sharding = sharding_cfg
    cfg.batch = BatchConfig(max_batch=32, max_wait_ms=5.0, buckets=(8, 32),
                            max_inflight=1)
    cfg.topology.spout_parallelism = 1
    cfg.topology.inference_parallelism = 1
    cfg.topology.sink_parallelism = 1
    # sync sends: async mode races concurrent produces (worker threads on
    # a network broker), scrambling arrival order — the positional proof
    # needs one in-order send at a time.
    cfg.sink.mode = "sync"
    cfg.offsets.policy = "earliest"
    cfg.offsets.max_behind = None

    if wire:
        # --wire: the REAL Kafka wire protocol over sockets (stub broker)
        # instead of the in-process MemoryBroker — proves the accuracy
        # path through record-batch encode/decode + fetch/produce framing.
        from tests.kafka_stub import KafkaStubBroker
        from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

        stub = KafkaStubBroker(partitions=1)
        broker = KafkaWireBroker(f"127.0.0.1:{stub.port}")
    else:
        stub = None
        broker = MemoryBroker(default_partitions=1)
    n = len(x_te)
    topo = build_standard_topology(cfg, broker)
    size_of = (stub.topic_size if stub is not None else broker.topic_size)
    try:
        with LocalCluster() as cluster:
            cluster.submit_topology("accuracy", cfg, topo)
            t0 = time.time()
            for img in x_te:
                broker.produce(cfg.broker.input_topic, json.dumps(
                    {"instances": [img.tolist()]}), partition=0)
            while time.time() - t0 < timeout_s:
                if size_of(cfg.broker.output_topic) >= n:
                    break
                time.sleep(0.25)
            produced = size_of(cfg.broker.output_topic)
            dead = size_of(cfg.broker.dead_letter_topic)

        if produced < n:
            return {"error": f"only {produced}/{n} outputs after "
                             f"{timeout_s}s ({dead} dead-lettered)"}
        recs = []
        while len(recs) < n:  # brokers cap records per fetch; page through
            batch = broker.fetch(cfg.broker.output_topic, 0, len(recs),
                                 max_records=n - len(recs))
            if not batch:
                break
            recs.extend(batch)
        if len(recs) < n:
            return {"error": f"fetch pages dried up at {len(recs)}/{n}"}
        outs = np.concatenate(
            [decode_predictions(r.value).data for r in recs[:n]])
    finally:
        if stub is not None:
            broker.close()
            stub.close()

    row_diff = np.abs(outs - engine_preds).max(axis=1)
    row_match = float((row_diff <= TRANSPORT_TOL[mode]).mean())
    argmax_agree = float(
        (outs.argmax(-1) == engine_preds.argmax(-1)).mean())
    transport_ok = (row_match >= MIN_ROW_MATCH
                    and argmax_agree >= MIN_ARGMAX_AGREE)
    acc = float((outs.argmax(-1) == y_te).mean())
    return {"acc_e2e": acc, "n_out": int(produced), "dead_lettered": dead,
            "max_abs_diff_vs_engine": round(float(row_diff.max()), 5),
            "row_match_frac": round(row_match, 4),
            "argmax_agree_vs_engine": round(argmax_agree, 4),
            "transport_faithful": bool(transport_ok),
            "wall_s": round(time.time() - t0, 1)}


# ---------------------------------------------------------------------------
# Confidence-gated cascade (storm_tpu/cascade/): offline threshold sweep +
# lock-step e2e serving of the tiered operator.

CASCADE_SHAPE = (32, 32, 3)
# (registry name, checkpoint tag) cheapest-first BY MEASURED COST ON THE
# SERVING PLATFORM, not by parameter count: on the CPU CI host convs are
# the expensive path (measured ms per 32-batch: vit_tiny 3.4, lenet5
# 17.7, resnet20 85.0 — small transformer matmuls hit BLAS, conv loops
# do not), so the chain runs vit_tiny -> lenet5 -> resnet20. On digits
# this order is also accuracy-ascending (0.920 / 0.989 / 0.993), the
# textbook cascade shape: weak-cheap gate first, strong-expensive
# flagship last. All tiers must share one input shape (the router
# re-batches escalated residue through the same transfer path), so
# lenet5 is retrained at 3 input channels under the ``lenet5_rgb`` tag;
# resnet20/vit_tiny reuse their committed checkpoints.
CASCADE_TIERS = (("vit_tiny", None), ("lenet5", "lenet5_rgb"),
                 ("resnet20", None))
CASCADE_GRID = (0.02, 0.05, 0.1, 0.15, 0.2, 0.3, 0.4, 0.5)
# Accuracy budget for the cascade claim: e2e cascade accuracy must land
# within this of e2e flagship accuracy on the held-back eval split.
CASCADE_EPSILON = 0.005


def _softmax(z):
    """train_or_load returns raw LOGITS (its jit forward has no head);
    the serving engine emits softmax rows. The sweep must score what the
    router will actually see, so tier predictions are softmaxed before
    any uncertainty math."""
    z = np.asarray(z, np.float64)
    z = z - z.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def simulate_cascade(tier_probs, thresholds, metric, temperature, y):
    """Offline replay of the router's accept/escalate rule (uncertainty
    strictly below the tier threshold accepts; the last tier always
    accepts) over per-tier softmax predictions for the SAME records.
    Returns (accuracy, per-tier acceptance fractions, per-tier PURITY —
    the accuracy of each tier's accepted subset, None where a tier
    accepted nothing). Uses the same ``uncertainty`` the router calls,
    so a threshold tuned here means the same thing online."""
    from storm_tpu.cascade.policy import uncertainty

    n = len(y)
    decided = np.full(n, -1, dtype=np.int64)
    preds = np.zeros_like(tier_probs[0])
    remaining = np.arange(n)
    purity = []
    for i, probs in enumerate(tier_probs):
        if not len(remaining):
            purity.append(None)
            continue
        last = i == len(tier_probs) - 1
        if last:
            take = np.ones(len(remaining), dtype=bool)
        else:
            u = uncertainty(probs[remaining], metric, temperature)
            take = u < thresholds[i]
        idx = remaining[take]
        preds[idx] = probs[idx]
        decided[idx] = i
        remaining = remaining[~take]
        purity.append(round(float((probs[idx].argmax(-1) == y[idx]).mean()),
                            4) if len(idx) else None)
    acc = float((preds.argmax(-1) == y).mean())
    fracs = [round(float((decided == i).mean()), 4)
             for i in range(len(tier_probs))]
    return acc, fracs, purity


def cascade_sweep(tier_probs, y, temperature):
    """Grid-sweep (metric, t0, t1) on the calibration split. Returns the
    sweep rows (every point, so OPERATIONS.md's tuning guide can show the
    whole surface) sorted by flagship traffic at matched accuracy."""
    from storm_tpu.cascade.policy import CONFIDENCE_METRICS

    rows = []
    for metric in CONFIDENCE_METRICS:
        for t0 in CASCADE_GRID:
            for t1 in CASCADE_GRID:
                acc, fracs, purity = simulate_cascade(
                    tier_probs, (t0, t1), metric, temperature, y)
                rows.append({"metric": metric, "thresholds": [t0, t1],
                             "sim_acc": round(acc, 4), "tier_fracs": fracs,
                             "tier_purity": purity,
                             "flagship_frac": fracs[-1]})
    return rows


# Relative forward cost per tier (vit_tiny : lenet5 : resnet20) from the
# measured per-image CPU forward times (0.106 / 0.553 / 2.66 ms). Only
# used to break ties between equally-accurate sweep points; the real
# cost claim is measured end-to-end by ``bench.py --cascade-compare``.
CASCADE_TIER_COST = (1.0, 5.2, 25.0)


def pick_operating_point(sweep, flagship_cal_acc):
    """Three-constraint pick: hold calibration accuracy (>= flagship -
    half the budget) AND tier PURITY (every early tier's accepted subset
    must itself be at least flagship-accurate on cal — early exits may
    not cost accuracy), then take the MOST accurate candidates and,
    among those, the cheapest under the measured tier-cost model (an
    escalated record pays every tier it visited). The purity constraint
    is what makes the pick generalize: without it the cost tiebreak
    drifts to the loosest gate that still ties on cal accuracy, and a
    loose gate's confidently-wrong accepts are exactly the overfit that
    falls apart on the held-back eval split (measured: -2.2 points
    without purity, ±0.0 with)."""
    def pure(r):
        return all(p is None or p >= flagship_cal_acc
                   for p in r.get("tier_purity", [])[:-1])

    ok = [r for r in sweep
          if r["sim_acc"] >= flagship_cal_acc - CASCADE_EPSILON / 2
          and pure(r)]
    if not ok:
        ok = [r for r in sweep
              if r["sim_acc"] >= flagship_cal_acc - CASCADE_EPSILON / 2]
    if not ok:
        ok = sweep
    top = max(r["sim_acc"] for r in ok)
    best = [r for r in ok if r["sim_acc"] >= top - 1e-9]

    def cost(r):
        c, cum = 0.0, 0.0
        for frac, tier_c in zip(r["tier_fracs"], CASCADE_TIER_COST):
            cum += tier_c
            c += frac * cum
        return c

    return min(best, key=cost)


def cascade_run_cfg(ckpts, point=None, temperature=1.0):
    """Serving config for the lock-step e2e phase. ``point=None`` builds
    the flagship-only reference (cascade disabled, same flagship model/
    checkpoint/batching); otherwise the cascade at the swept operating
    point. ``max_batch=1`` flushes every add immediately — lock-step
    serving would otherwise pay ``max_wait_ms`` per tier per record."""
    from storm_tpu.cascade.policy import CascadeConfig
    from storm_tpu.config import BatchConfig, Config, ModelConfig

    flagship = CASCADE_TIERS[-1][0]
    cfg = Config()
    cfg.model = ModelConfig(name=flagship, checkpoint=ckpts[flagship],
                            input_shape=CASCADE_SHAPE, num_classes=10)
    cfg.batch = BatchConfig(max_batch=1, max_wait_ms=5.0, buckets=(1,),
                            max_inflight=2)
    cfg.topology.spout_parallelism = 1
    cfg.topology.inference_parallelism = 1
    cfg.topology.sink_parallelism = 1
    cfg.sink.mode = "sync"
    cfg.offsets.policy = "earliest"
    cfg.offsets.max_behind = None
    if point is not None:
        cfg.cascade = CascadeConfig(
            enabled=True,
            tiers=tuple(name for name, _ in CASCADE_TIERS),
            checkpoints=tuple(ckpts[name] for name, _ in CASCADE_TIERS),
            thresholds=tuple(point["thresholds"]),
            metric=point["metric"],
            temperature=temperature)
    return cfg


def cascade_e2e_run(cfg, x, timeout_per_record_s: float = 60.0):
    """Serve ``x`` through the FULL topology one record in flight at a
    time; returns (softmax outputs aligned with ``x``, metrics snapshot,
    wall seconds).

    Lock-step, not backlog: escalated records re-enter a later tier's
    batcher and complete out of order under load, so ``e2e_run``'s
    positional transport proof is unsound for a cascade. Producing record
    i+1 only after output i arrives restores exact correlation while
    still exercising the whole spout -> batcher -> router -> per-tier
    engines -> escalation re-batch -> encode -> sink path. Transport
    faithfulness itself is proven by the main harness modes; this
    phase's job is cascade ACCURACY."""
    from storm_tpu.api.schema import decode_predictions
    from storm_tpu.connectors import MemoryBroker
    from storm_tpu.main import build_standard_topology
    from storm_tpu.runtime import LocalCluster

    broker = MemoryBroker(default_partitions=1)
    topo = build_standard_topology(cfg, broker)
    n = len(x)
    t0 = time.time()
    with LocalCluster() as cluster:
        cluster.submit_topology("cascade-acc", cfg, topo)
        for i, img in enumerate(x):
            broker.produce(cfg.broker.input_topic, json.dumps(
                {"instances": [img.tolist()]}), partition=0)
            deadline = time.time() + timeout_per_record_s
            while broker.topic_size(cfg.broker.output_topic) <= i:
                if time.time() > deadline:
                    dead = broker.topic_size(cfg.broker.dead_letter_topic)
                    raise RuntimeError(
                        f"cascade e2e: record {i}/{n} produced no output in "
                        f"{timeout_per_record_s}s ({dead} dead-lettered)")
                time.sleep(0.001)
        snap = cluster.metrics("cascade-acc")
        recs = []
        while len(recs) < n:  # brokers cap records per fetch; page through
            batch = broker.fetch(cfg.broker.output_topic, 0, len(recs),
                                 max_records=n - len(recs))
            if not batch:
                break
            recs.extend(batch)
    if len(recs) < n:
        raise RuntimeError(f"cascade e2e: fetch dried up at {len(recs)}/{n}")
    outs = np.concatenate([decode_predictions(r.value).data
                           for r in recs[:n]])
    return outs, snap, time.time() - t0


def _cascade_counters(snap):
    """Pull the router's evidence out of a metrics snapshot: every
    ``cascade_*`` counter plus the escalation-rate gauge."""
    out = {}
    for comp, metrics_ in snap.items():
        for k, v in metrics_.items():
            if k.startswith("cascade_") and isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
        if comp == "cascade" and "escalation_rate" in metrics_:
            out["escalation_rate"] = round(float(metrics_["escalation_rate"]),
                                           4)
    return out


def cascade_main(args) -> int:
    """``--cascade`` / ``--cascade-sweep``: train the tier checkpoints,
    fit the calibration temperature and sweep thresholds on HALF the test
    split, then (``--cascade``) serve the held-back half e2e through both
    the flagship-only and cascade topologies and write the accuracy
    artifact. The calibration/eval split (even/odd indices) means the
    served accuracy claim is made on records the thresholds never saw."""
    import jax

    from storm_tpu.cascade.policy import fit_temperature

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    log(f"platform={platform} devices={n_dev}")

    tiers, ckpts = [], {}
    x_te = y_te = None
    for name, tag in CASCADE_TIERS:
        ckpt, _, facc, x_te, y_te, preds = train_or_load(
            name, CASCADE_SHAPE, args.max_epochs, ckpt_tag=tag)
        ckpts[name] = ckpt
        tiers.append({"model": name, "checkpoint": os.path.basename(ckpt),
                      "float_acc": round(facc, 4),
                      "_preds": _softmax(preds)})
    if args.n_test:
        x_te, y_te = x_te[:args.n_test], y_te[:args.n_test]
        for t in tiers:
            t["_preds"] = t["_preds"][:args.n_test]

    cal, ev = slice(0, None, 2), slice(1, None, 2)
    y_cal, y_ev = y_te[cal], y_te[ev]
    cal_probs = [t["_preds"][cal] for t in tiers]
    fit = fit_temperature(cal_probs[0], y_cal)
    temperature = fit["temperature"]
    log(f"tier-0 calibration: T={temperature} nll={fit['nll']:.4f}")

    sweep = cascade_sweep(cal_probs, y_cal, temperature)
    flagship_cal = float((cal_probs[-1].argmax(-1) == y_cal).mean())
    point = pick_operating_point(sweep, flagship_cal)
    log(f"operating point: metric={point['metric']} "
        f"thresholds={point['thresholds']} sim_acc={point['sim_acc']:.4f} "
        f"(flagship cal acc {flagship_cal:.4f}) "
        f"tier_fracs={point['tier_fracs']}")

    artifact = {
        "platform": platform, "n_devices": n_dev,
        "dataset": "sklearn digits (1797 real 8x8 handwritten scans), "
                   "upscaled to 32x32x3, 25% held-out test; even test "
                   "indices calibrate thresholds, odd indices are served",
        "tiers": [{k: v for k, v in t.items() if not k.startswith("_")}
                  for t in tiers],
        "metric": point["metric"],
        "thresholds": point["thresholds"],
        "temperature": temperature,
        "temperature_fit": fit,
        "calibration": {"n": int(len(y_cal)),
                        "flagship_acc": round(flagship_cal, 4),
                        "sim_acc": point["sim_acc"],
                        "tier_fracs": point["tier_fracs"]},
        "sweep": sorted(sweep, key=lambda r: (r["flagship_frac"],
                                              -r["sim_acc"]))[:20]
                 if not args.cascade_sweep else sweep,
    }

    if args.cascade_sweep and not args.cascade:
        out = json.dumps(artifact, indent=1)
        if args.out == "-":
            print(out)
        else:
            path = args.out if args.out != "ACCURACY_r04.json" \
                else "CASCADE_SWEEP.json"
            with open(os.path.join(REPO, path), "w") as f:
                f.write(out + "\n")
            log(f"wrote {path} ({len(sweep)} sweep points)")
        return 0

    x_ev = x_te[ev]
    log(f"--- e2e flagship-only ({len(x_ev)} eval records, lock-step)")
    outs_f, _, wall_f = cascade_e2e_run(cascade_run_cfg(ckpts), x_ev)
    acc_f = float((outs_f.argmax(-1) == y_ev).mean())
    log(f"flagship e2e acc {acc_f:.4f} in {wall_f:.1f}s")

    log(f"--- e2e cascade ({len(x_ev)} eval records, lock-step)")
    outs_c, snap_c, wall_c = cascade_e2e_run(
        cascade_run_cfg(ckpts, point, temperature), x_ev)
    acc_c = float((outs_c.argmax(-1) == y_ev).mean())
    counters = _cascade_counters(snap_c)
    log(f"cascade e2e acc {acc_c:.4f} in {wall_c:.1f}s counters={counters}")

    n_ev = len(x_ev)
    served_fracs = [counters.get(f"cascade_accepted_tier{i}", 0) / n_ev
                    for i in range(len(tiers))]
    delta = acc_c - acc_f
    artifact["eval"] = {
        "n": n_ev,
        "flagship": {"acc_e2e": round(acc_f, 4), "wall_s": round(wall_f, 1)},
        "cascade": {"acc_e2e": round(acc_c, 4), "wall_s": round(wall_c, 1),
                    "served_tier_fracs": [round(f, 4) for f in served_fracs],
                    "router_counters": counters},
    }
    artifact["acc_delta_vs_flagship"] = round(delta, 4)
    artifact["epsilon"] = CASCADE_EPSILON
    # One-sided bound: the cascade may not COST more than epsilon vs the
    # flagship; beating the flagship (possible when an early tier is
    # right where the flagship is wrong) passes.
    artifact["bound"] = "one-sided: acc_cascade >= acc_flagship - epsilon"
    # Pass = accuracy held AND the cascade actually gated (tier 0 served a
    # real share; all-escalate would match flagship accuracy trivially).
    artifact["pass"] = bool(delta >= -CASCADE_EPSILON
                            and served_fracs[0] >= 0.25
                            and sum(served_fracs) >= 0.999)
    out = json.dumps(artifact, indent=1)
    if args.out == "-":
        print(out)
    else:
        path = args.out if args.out != "ACCURACY_r04.json" \
            else "ACCURACY_CASCADE_r09.json"
        with open(os.path.join(REPO, path), "w") as f:
            f.write(out + "\n")
        log(f"wrote {path}: pass={artifact['pass']} "
            f"delta={delta:+.4f} (budget {CASCADE_EPSILON})")
    return 0 if artifact["pass"] else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="lenet5,resnet20,vit_tiny,moe_vit_tiny")
    ap.add_argument("--out", default="ACCURACY_r04.json")
    ap.add_argument("--max-epochs", type=int, default=60)
    ap.add_argument("--n-test", type=int, default=0,
                    help="cap test set size (0 = all)")
    ap.add_argument("--skip-sharded", action="store_true",
                    help="single-device modes only (real-TPU runs)")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "default"],
                    help="'cpu' forces the host backend + an 8-device "
                         "virtual mesh (env vars alone are overridden by "
                         "the TPU plugin's sitecustomize); 'default' keeps "
                         "whatever jax.devices() resolves (the real chip)")
    ap.add_argument("--wire", action="store_true",
                    help="serve the e2e phase over the REAL Kafka wire "
                         "protocol (socket stub broker) instead of the "
                         "in-process MemoryBroker")
    ap.add_argument("--cascade", action="store_true",
                    help="confidence-gated cascade: sweep thresholds on a "
                         "calibration split, then serve the eval split e2e "
                         "through flagship-only AND cascade topologies -> "
                         "ACCURACY_CASCADE_r09.json")
    ap.add_argument("--cascade-sweep", action="store_true",
                    help="cascade threshold sweep only (no e2e serving): "
                         "the operator-facing tuning surface -> "
                         "CASCADE_SWEEP.json (see docs/OPERATIONS.md)")
    args = ap.parse_args()

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    if args.cascade or args.cascade_sweep:
        return cascade_main(args)

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    log(f"platform={platform} devices={n_dev}")

    results = []
    for name in args.models.split(","):
        spec = MODEL_SPECS[name]
        ckpt, model, float_acc, x_te, y_te, float_preds = train_or_load(
            name, spec["input_shape"], args.max_epochs)
        if args.n_test:
            x_te, y_te = x_te[:args.n_test], y_te[:args.n_test]
            float_preds = float_preds[:args.n_test]
            # the accuracy anchor must cover the same subset being served
            float_acc = float((float_preds.argmax(-1) == y_te).mean())
        for mode in spec["modes"]:
            if args.skip_sharded and mode in ("dp8", "tp2", "ep4"):
                continue
            mc, sc = mode_configs(mode, ckpt, name, spec["input_shape"])
            log(f"--- {name} / {mode}")
            acc_eng, engine_preds = engine_accuracy(mc, sc, x_te, y_te)
            # Per-row dataset label (VERDICT r4 weak #3): the model NAMES
            # come from the bench zoo (resnet20 etc.) but the accuracy
            # workload is the offline-available digits stand-in, stated on
            # every row so no row can be quoted as a CIFAR-10 result.
            row = {"model": name, "mode": mode,
                   "dataset": f"sklearn-digits upscaled to "
                              f"{'x'.join(map(str, spec['input_shape']))}"
                              " (NOT cifar10)",
                   "n_test": len(x_te),
                   "acc_float_device": round(float_acc, 4),
                   "acc_engine_device": round(acc_eng, 4),
                   "epsilon": EPSILON[mode]}
            row.update(e2e_run(mc, sc, x_te, y_te, engine_preds, mode,
                               wire=args.wire))
            if "acc_e2e" in row:
                row["pass"] = bool(
                    abs(row["acc_e2e"] - float_acc) <= row["epsilon"]
                    and row["transport_faithful"])
                log(f"{name}/{mode}: e2e={row['acc_e2e']:.4f} "
                    f"engine={acc_eng:.4f} float={float_acc:.4f} "
                    f"rows={row['row_match_frac']:.3f} "
                    f"argmax={row['argmax_agree_vs_engine']:.3f}"
                    f" -> {'PASS' if row['pass'] else 'FAIL'}")
            else:
                row["pass"] = False
                log(f"{name}/{mode}: {row['error']}")
            results.append(row)

    artifact = {
        "platform": platform, "n_devices": n_dev,
        "dataset": "sklearn digits (1797 real 8x8 handwritten scans), "
                   "upscaled to model input shape, 25% held-out test",
        "ordering_note": "no correlation id on the wire (reference parity);"
                         " ordering + faithful transport proven per run by"
                         " positional L-inf agreement with same-mode"
                         " engine-direct predictions (TRANSPORT_TOL)",
        "all_pass": all(r["pass"] for r in results),
        "results": results,
    }
    out = json.dumps(artifact, indent=1)
    if args.out == "-":
        print(out)
    else:
        with open(os.path.join(REPO, args.out), "w") as f:
            f.write(out + "\n")
        log(f"wrote {args.out}: all_pass={artifact['all_pass']}")
    return 0 if artifact["all_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
