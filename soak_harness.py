#!/usr/bin/env python
"""Full-surface production soak (VERDICT r4 missing #2 / next-round #2).

Every production subsystem AT ONCE, for >= 10 minutes, on the real chip:

  - transport: SASL_SSL (TLS + SCRAM-SHA-256) to a 2-node wire-protocol
    stub broker — every connection in the run is encrypted+authenticated;
  - delivery: end-to-end exactly-once (offsets.policy='txn' spout +
    whole-tree transactional sink committing consumed offsets inside the
    producer transaction, read_committed audit);
  - churn: periodic LEADER moves and COORDINATOR moves while transactions
    and group state are live;
  - elasticity: one live rebalance (prewarmed replica) mid-run;
  - ops: one live model swap mid-run (engine rebuild under traffic);
  - failure: chaos kills of the inference and echo executors (tree replay
    through the exactly-once machinery);
  - the real device path: trained LeNet-5 serving on jax.devices()[0].

Topology (product components, unmodified):

    spout(txn) ──> infer(InferenceBolt, real chip) ──┐
         │                                           ├──> txn sink ──> soak-out
         └──> echo(identity: sha256 of the record) ──┘
                                 infer dead_letter ────> dlq sink ──> soak-dlq

Each input record's tuple tree = {1 prediction + 1 echo}; the sink parks
the whole tree and commits it with the record's offset in ONE transaction.
The audit (read_committed) then proves, for EVERY consumed offset:
  - its echo hash appears EXACTLY once (identity-level exactly-once —
    catches loss+dupe pairs that count-based audits cancel out);
  - prediction count == input count, every prediction a valid softmax row
    (tree atomicity extends the echo lane's exactly-once to the
    prediction lane);
  - committed group offsets cover the whole input log;
  - zero dead-letters.
Any violation is a release blocker (exit 1). Reference analog: the
1-hour run-and-watch integration test (MainTopology.java:69-77) — this
is shorter but audited, not watched.

Run (real chip):  python soak_harness.py --seconds 660 --rate 30
CPU smoke:        STORM_TPU_PLATFORM=cpu python soak_harness.py \
                      --seconds 60 --rate 20 --out -
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

GROUP = "soak-group"
IN, OUT, DLQ = "soak-in", "soak-out", "soak-dlq"


def log(msg: str) -> None:
    print(f"[soak] {msg}", file=sys.stderr, flush=True)


def make_certs(d: str):
    crt, key = os.path.join(d, "broker.crt"), os.path.join(d, "broker.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", "2", "-subj",
         "/CN=127.0.0.1", "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return crt, key


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=660.0,
                    help="feed duration (events are scheduled across it)")
    ap.add_argument("--rate", type=float, default=30.0, help="records/sec")
    ap.add_argument("--trace", default=None,
                    help="replay a storm_tpu.loadgen trace file as the "
                         "feed source (event schedule + tenant:lane keys) "
                         "instead of fixed-interval pacing; loops until "
                         "--seconds elapse")
    ap.add_argument("--trace-speed", type=float, default=1.0,
                    help="time-compression factor for --trace replay")
    ap.add_argument("--out", default="SOAK_r05.json")
    ap.add_argument("--slo-ms", type=float, default=1000.0,
                    help="per-window sink p50 target for the SLO timeline")
    ap.add_argument("--chaos", action="store_true",
                    help="add a dist-grade chaos phase: engine-hang "
                         "injections under a live watchdog "
                         "(batch.watchdog_ms) driving a quarantine + "
                         "engine replacement mid-soak")
    ap.add_argument("--drain-drill", action="store_true",
                    help="add two graceful-drain cycles mid-soak "
                         "(deactivate -> flush inflight -> activate), the "
                         "per-worker step of a rolling restart, proving "
                         "intake pause + resume preserves exactly-once")
    args = ap.parse_args()

    plat = os.environ.get("STORM_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    import jax

    device = jax.devices()[0]
    log(f"device: {device.device_kind} ({device.platform})")

    import ssl

    from tests.kafka_stub import KafkaStubBroker

    from storm_tpu.config import (BatchConfig, Config, ModelConfig,
                                  OffsetsConfig, ShardingConfig, SinkConfig)
    from storm_tpu.connectors import BrokerSink, BrokerSpout, \
        TransactionalBrokerSink
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import Bolt, TopologyBuilder, Values
    from storm_tpu.runtime.chaos import ChaosMonkey
    from storm_tpu.runtime.cluster import LocalCluster

    tmp = tempfile.mkdtemp(prefix="soak-certs-")
    crt, key = make_certs(tmp)
    P = 16  # txn policy gates ONE open tree per partition; the tunneled
    # device RTT (~0.3 s) makes per-partition tree rate ~3/s, so the
    # partition count IS the in-flight parallelism of the soak
    stub = KafkaStubBroker(partitions=P, nodes=2)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(crt, key)
    stub.ssl_context = ctx
    stub.sasl = ("soak-svc", "soak-pw")
    stub.sasl_mechanism = "SCRAM-SHA-256"
    security = {"protocol": "SASL_SSL", "sasl_mechanism": "SCRAM-SHA-256",
                "sasl_username": "soak-svc", "sasl_password": "soak-pw",
                "ssl_cafile": crt, "ssl_check_hostname": False}

    def wire():
        return KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                               security=security)

    class EchoBolt(Bolt):
        """Identity lane: the record's content hash, anchored to the same
        tree as its prediction, so the transactional sink commits both
        (or neither) with the offset."""

        async def execute(self, t):
            h = hashlib.sha256(t.get("message").encode()).hexdigest()[:24]
            await self.collector.emit(Values([f"h:{h}"]), anchors=[t])
            self.collector.ack(t)

    ckpt = os.path.join(REPO, "checkpoints", "lenet5_digits")
    model_cfg = ModelConfig(name="lenet5", checkpoint=ckpt,
                            input_shape=(32, 32, 1), num_classes=10)
    batch_cfg = BatchConfig(max_batch=64, max_wait_ms=20.0, buckets=(8, 64),
                            max_inflight=2,
                            # chaos phase: a 2.5s injected hang against a
                            # 500ms fetch deadline trips the watchdog; two
                            # consecutive trips quarantine the engine and
                            # the operator swaps in a fresh one mid-soak.
                            watchdog_ms=500.0 if args.chaos else 0.0,
                            watchdog_trips=2)
    run_cfg = Config()
    run_cfg.topology.message_timeout_s = 120.0
    if args.drain_drill:
        # A drain cycle lands ~2s after a chaos executor kill, and a tree
        # stranded by that kill stays in the ledger for the FULL message
        # timeout — 120s would wedge every drain. 15s bounds the stall
        # (legit trees settle in <1s even through the device tunnel)
        # without changing the replay mechanism under audit.
        run_cfg.topology.message_timeout_s = 15.0

    broker = wire()
    tb = TopologyBuilder()
    tb.set_spout(
        "spout",
        BrokerSpout(broker, IN,
                    OffsetsConfig(policy="txn", group_id=GROUP,
                                  max_behind=None)),
        parallelism=1)
    tb.set_bolt("infer",
                InferenceBolt(model_cfg, batch_cfg,
                              ShardingConfig(data_parallel=0)),
                parallelism=1).shuffle_grouping("spout")
    tb.set_bolt("echo", EchoBolt(), parallelism=1).shuffle_grouping("spout")
    tb.set_bolt(
        "sink",
        TransactionalBrokerSink(
            broker, OUT,
            SinkConfig(mode="transactional", txn_batch=64, txn_ms=250.0,
                       offsets_group=GROUP)),
        parallelism=1)\
        .shuffle_grouping("infer").shuffle_grouping("echo")
    tb.set_bolt("dlq", BrokerSink(broker, DLQ, run_cfg.sink), parallelism=1)\
        .shuffle_grouping("infer", stream="dead_letter")

    rng = np.random.RandomState(7)
    produced_hashes = []
    feeder = wire()
    stop_feed = threading.Event()
    fed = [0]

    def _produce_one(key=None):
        payload = json.dumps(
            {"instances": rng.rand(1, 32, 32, 1).round(4).tolist()})
        produced_hashes.append(
            hashlib.sha256(payload.encode()).hexdigest()[:24])
        feeder.produce(IN, payload, key=key, partition=fed[0] % P)
        fed[0] += 1

    def feed():
        if args.trace:
            # Trace-driven soak source (storm_tpu.loadgen): the recorded
            # arrival schedule paces production and each record carries
            # its tenant:lane key, so the soak sees fleet-shaped traffic
            # (bursts, tenant skew) instead of a metronome. The trace
            # loops until the run ends; the identity audit is unchanged —
            # it counts records, not pacing.
            from storm_tpu.loadgen import load_trace, replay

            tr = load_trace(args.trace)
            while not stop_feed.is_set():
                replay(tr, lambda ev: _produce_one(key=ev.key()),
                       speed=args.trace_speed,
                       stop=stop_feed.is_set)
            return
        interval = 1.0 / args.rate
        nxt = time.perf_counter()
        while not stop_feed.is_set():
            now = time.perf_counter()
            if now < nxt:
                time.sleep(min(0.01, nxt - now))
                continue
            _produce_one()
            nxt += interval

    events = []  # (t_s, name, detail)
    timeline = []  # (t_s, sink_p50_ms, windows' delivered count)

    def mark(name, detail=""):
        events.append((round(time.perf_counter() - t0, 1), name, detail))
        log(f"EVENT {name} {detail}")

    cluster = LocalCluster()
    t0 = time.perf_counter()
    wd_stats = None
    try:
        cluster.submit_topology("soak", run_cfg, tb.build())
        log("topology up; starting feed")

        rt = None

        async def _rt():
            return cluster._cluster.runtime("soak")

        rt = cluster._run(_rt())
        chaos = ChaosMonkey(rt)

        feeder_thread = threading.Thread(target=feed, daemon=True)
        feeder_thread.start()

        # events spread across the run (fractions of --seconds)
        dur = args.seconds
        plan = [
            (0.10, "move_leader", lambda: stub.move_leader(OUT, 0, 1)),
            (0.20, "move_coordinator", lambda: stub.move_coordinator(1)),
            (0.30, "chaos_kill_infer", lambda: chaos.crash_bolt("infer", 0)),
            (0.40, "rebalance_infer_2",
             lambda: cluster._run(rt.rebalance("infer", 2))),
            (0.55, "swap_model_f32",
             lambda: cluster._run(rt.swap_model(
                 "infer", {"dtype": "float32"}))),
            (0.70, "move_leader_in", lambda: stub.move_leader(IN, 1, 0)),
            (0.78, "chaos_kill_echo", lambda: chaos.crash_bolt("echo", 0)),
            (0.86, "move_coordinator_back",
             lambda: stub.move_coordinator(0)),
            (0.93, "chaos_kill_infer_2",
             lambda: chaos.crash_bolt("infer", 1)),
        ]
        if args.chaos:
            from storm_tpu.resilience import get_injector

            def arm_engine_hang():
                inj = get_injector()
                inj.bind_flight(rt.flight)
                # Two consecutive hung batches = watchdog_trips, so this
                # single injection drives the full quarantine->replace arc.
                inj.configure(engine_hang_ms=2500.0, engine_hang_next=2)

            plan.insert(4, (0.48, "chaos_engine_hang", arm_engine_hang))
        if args.drain_drill:
            # The per-worker step of a rolling restart, run against the
            # live runtime: stop intake, flush every in-flight tree, then
            # resume. Two cycles — one on each side of the rebalance/swap
            # block — so the audit proves a drain preserves exactly-once
            # both on the original mesh shape and on the reshaped one.
            def drain_cycle():
                cluster._run(rt.deactivate())
                flushed = cluster._run(rt.drain(timeout_s=60.0))
                cluster._run(rt.activate())
                if not flushed:
                    raise RuntimeError("drain did not flush within 60s")

            drill = [(0.35, "drain_drill_1", drain_cycle),
                     (0.65, "drain_drill_2", drain_cycle)]
            plan = sorted(plan + drill, key=lambda e: e[0])
        next_plan = 0
        window_s = 10.0
        next_window = time.perf_counter() + window_s
        end = time.perf_counter() + dur
        last_out = 0
        while time.perf_counter() < end:
            now = time.perf_counter()
            frac = (now - t0) / dur
            if next_plan < len(plan) and frac >= plan[next_plan][0]:
                name = plan[next_plan][1]
                try:
                    plan[next_plan][2]()
                    mark(name)
                except Exception as e:  # an event must not end the soak
                    mark(name + "_FAILED", repr(e))
                next_plan += 1
            if now >= next_window:
                next_window = now + window_s
                lat = cluster.metrics("soak")["sink"]["e2e_latency_ms"]
                p50 = lat["p50"]
                cluster.reset_histogram("soak", "sink", "e2e_latency_ms")
                out_n = stub.topic_size(OUT)
                timeline.append((round(now - t0, 1),
                                 None if p50 is None else round(p50, 1),
                                 out_n - last_out))
                last_out = out_n
                log(f"t={now - t0:6.1f}s p50="
                    f"{'stalled' if p50 is None else f'{p50:.0f}ms'} "
                    f"out+={timeline[-1][2]} fed={fed[0]}")
            time.sleep(0.2)

        stop_feed.set()
        feeder_thread.join(timeout=10)
        # A feeder still alive past the join timeout is wedged mid-produce:
        # fed[0] may keep moving under the audit below, so the exactly-once
        # accounting would compare against a moving target. Flag it and
        # fail the run rather than report a vacuous pass.
        feeder_stuck = feeder_thread.is_alive()
        if feeder_stuck:
            log("WARNING: feeder thread still alive after join timeout; "
                "exactly-once accounting is unreliable")
        n = fed[0]
        log(f"feed done: {n} records; draining")
        deadline = time.time() + 300
        while time.time() < deadline:
            if stub.topic_size(OUT) >= 2 * n:
                break
            time.sleep(0.5)
        drained = stub.topic_size(OUT) >= 2 * n
        log(f"drained={drained} out={stub.topic_size(OUT)}/{2 * n}")
        if args.chaos:
            infer_m = cluster.metrics("soak").get("infer", {})
            wd_stats = {k: infer_m.get(k)
                        for k in ("watchdog_trips", "engine_quarantined")}
            # The quarantine->replace arc as flight events: the drained
            # audit above already proves the REPLACEMENT engine served
            # (the injection lands mid-soak), these make it explicit.
            wd_stats["flight"] = [
                {k: v for k, v in ev.items() if k != "ts"}
                for ev in rt.flight.tail(400)
                if ev.get("kind") in ("engine_quarantined",
                                      "engine_replaced")]
    finally:
        try:
            cluster.shutdown()
        except Exception as e:
            log(f"shutdown: {e!r}")

    # ---- audit (read_committed) ---------------------------------------------
    n = fed[0]
    rc = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                         isolation="read_committed", security=security)
    out_records = []
    for p in range(P):
        off = 0
        while True:
            batch = rc.fetch(OUT, p, off, max_records=2000)
            if not batch:
                break
            out_records.extend(batch)
            off = batch[-1].offset + 1
    committed = {p: feeder.committed(GROUP, IN, p) for p in range(P)}
    produced_per_part = {p: (n - p + P - 1) // P for p in range(P)}
    dlq_n = stub.topic_size(DLQ)
    rc.close()
    feeder.close()
    broker.close()
    stub.close()

    echoes, preds, bad_preds = [], 0, 0
    for r in out_records:
        v = r.value.decode()
        if v.startswith("h:"):
            echoes.append(v[2:])
        else:
            preds += 1
            try:
                row = json.loads(v)["predictions"][0]
                if len(row) != 10 or abs(sum(row) - 1.0) > 1e-2:
                    bad_preds += 1
            except Exception:
                bad_preds += 1

    from collections import Counter

    want, got = Counter(produced_hashes), Counter(echoes)
    missing = sum((want - got).values())
    duplicated = sum((got - want).values())
    offsets_ok = committed == produced_per_part
    stalled_windows = sum(1 for w in timeline if w[1] is None and w[2] == 0)
    p50s = [w[1] for w in timeline if w[1] is not None]
    met = [p for p in p50s if p <= args.slo_ms]

    exactly_once = (missing == 0 and duplicated == 0 and preds == n
                    and bad_preds == 0 and offsets_ok and dlq_n == 0
                    and drained and not feeder_stuck)
    artifact = {
        "platform": device.platform,
        "device_kind": device.device_kind,
        "duration_s": round(args.seconds, 1),
        "offered_rate_msg_s": args.rate if not args.trace else None,
        "trace_source": (os.path.basename(args.trace) if args.trace
                         else None),
        "trace_speed": args.trace_speed if args.trace else None,
        "records_in": n,
        "records_out": len(out_records),
        "transport": "SASL_SSL + SCRAM-SHA-256 (2-node stub, "
                     "wire protocol over TLS sockets)",
        "exactly_once": exactly_once,
        "audit": {
            "echo_missing": missing,
            "echo_duplicated": duplicated,
            "predictions": preds,
            "predictions_expected": n,
            "invalid_predictions": bad_preds,
            "committed_offsets": committed,
            "committed_offsets_expected": produced_per_part,
            "dead_letters": dlq_n,
            "drained": drained,
            "feeder_stuck": feeder_stuck,
        },
        "slo": {
            "target_p50_ms": args.slo_ms,
            "windows_met": f"{len(met)}/{len(p50s)}",
            "stalled_windows": stalled_windows,
            "worst_window_p50_ms": max(p50s, default=None),
            "median_window_p50_ms": (sorted(p50s)[len(p50s) // 2]
                                     if p50s else None),
        },
        "events": events,
        "timeline": timeline,
        "chaos": None,
        "note": "echo lane = sha256 of each record, committed in the SAME "
                "transaction (same tuple tree) as its prediction and its "
                "offset; identity-level exactly-once on the echo lane + "
                "tree atomicity + count equality extends the proof to the "
                "prediction lane (the product wire contract carries no "
                "correlation id, reference parity)",
    }
    if args.chaos:
        from storm_tpu.resilience import get_injector

        snap = get_injector().snapshot()
        artifact["chaos"] = {
            "enabled": True,
            "injections": sum(snap["counts"].values()),
            "counts": snap["counts"],
            "watchdog": wd_stats,
        }
    out = json.dumps(artifact, indent=1)
    if args.out == "-":
        print(out)
    else:
        with open(os.path.join(REPO, args.out), "w") as f:
            f.write(out + "\n")
        log(f"wrote {args.out}")
    log(f"exactly_once={exactly_once} "
        f"(missing={missing} dup={duplicated} preds={preds}/{n} "
        f"bad={bad_preds} offsets_ok={offsets_ok} dlq={dlq_n} "
        f"feeder_stuck={feeder_stuck})")
    return 0 if exactly_once else 1


if __name__ == "__main__":
    sys.exit(main())
