"""Connector tests: memory broker semantics, spout offset policies,
sink ack modes (reference KafkaSpout config MainTopology.java:95-106 and
KafkaBolt.java:116-166)."""

import asyncio

import pytest

from storm_tpu.config import Config, OffsetsConfig, SinkConfig
from storm_tpu.connectors import BrokerSink, BrokerSpout, MemoryBroker
from storm_tpu.connectors.sink import Producer
from storm_tpu.runtime import TopologyBuilder
from storm_tpu.runtime.cluster import AsyncLocalCluster


# ---- broker ------------------------------------------------------------------


def test_broker_produce_fetch_offsets():
    b = MemoryBroker(default_partitions=2)
    for i in range(10):
        b.produce("t", f"v{i}")
    assert b.topic_size("t") == 10
    total = sum(len(b.fetch("t", p, 0, 100)) for p in range(2))
    assert total == 10
    assert b.latest_offset("t", 0) + b.latest_offset("t", 1) == 10


def test_broker_key_partition_affinity():
    b = MemoryBroker(default_partitions=4)
    parts = {b.produce("t", "v", key="samekey")[0] for _ in range(10)}
    assert len(parts) == 1


def test_broker_commit_roundtrip():
    b = MemoryBroker()
    assert b.committed("g", "t", 0) is None
    b.commit("g", "t", 0, 7)
    assert b.committed("g", "t", 0) == 7


# ---- spout policies ----------------------------------------------------------


async def _spout_run(broker, offsets, produce_before, produce_after, wait=1.0):
    from tests.test_runtime import CaptureBolt

    CaptureBolt.seen = None
    for v in produce_before:
        broker.produce("in", v)
    cluster = AsyncLocalCluster()
    tb = TopologyBuilder()
    tb.set_spout("spout", BrokerSpout(broker, "in", offsets), 2)
    tb.set_bolt("cap", CaptureBolt(), 2).shuffle_grouping("spout")
    rt = await cluster.submit("t", Config(), tb.build())
    await asyncio.sleep(0.1)
    for v in produce_after:
        broker.produce("in", v)
    deadline = asyncio.get_event_loop().time() + wait
    while asyncio.get_event_loop().time() < deadline:
        if CaptureBolt.seen and len(CaptureBolt.seen) >= len(produce_after) + len(
            produce_before
        ):
            break
        await asyncio.sleep(0.02)
    await rt.drain(timeout_s=5)
    seen = sorted(m for _, m in (CaptureBolt.seen or []))
    await cluster.shutdown()
    return seen


def test_latest_policy_skips_backlog(run):
    """Reference semantics: start at log end — backlog invisible
    (MainTopology.java:101-103)."""
    broker = MemoryBroker(default_partitions=2)
    seen = run(
        _spout_run(
            broker,
            OffsetsConfig(policy="latest", max_behind=0),
            produce_before=["old1", "old2"],
            produce_after=["new1", "new2", "new3"],
        )
    )
    assert seen == ["new1", "new2", "new3"]


def test_earliest_policy_replays_backlog(run):
    broker = MemoryBroker(default_partitions=2)
    seen = run(
        _spout_run(
            broker,
            OffsetsConfig(policy="earliest", max_behind=None),
            produce_before=["a", "b"],
            produce_after=["c"],
        )
    )
    assert seen == ["a", "b", "c"]


def test_resume_policy_commits_and_resumes(run):
    broker = MemoryBroker(default_partitions=1)
    offsets = OffsetsConfig(policy="resume", max_behind=None, group_id="g1")
    seen1 = run(
        _spout_run(broker, offsets, produce_before=["a", "b"], produce_after=[])
    )
    assert seen1 == ["a", "b"]
    # Second run with same group resumes after committed offset.
    seen2 = run(
        _spout_run(broker, offsets, produce_before=[], produce_after=["c", "d"])
    )
    assert seen2 == ["c", "d"]
    assert broker.committed("g1", "in", 0) == 4


# ---- sink ack modes ----------------------------------------------------------


class FlakyProducer(Producer):
    """Fails the first N sends."""

    def __init__(self, broker, fail_first=0):
        self.broker = broker
        self.fail_first = fail_first
        self.sent = 0

    async def send(self, topic, value, key):
        if self.sent < self.fail_first:
            self.sent += 1
            raise IOError("delivery failed")
        self.sent += 1
        self.broker.produce(topic, value, key)


def _sink_with(broker, mode, fail_first=0):
    class TestSink(BrokerSink):
        def make_producer(self):  # the mkProducer test seam
            return FlakyProducer(broker, fail_first)

    return TestSink(broker, "out", SinkConfig(mode=mode))


async def _sink_run(broker, sink, items):
    from tests.test_runtime import ListSpout

    cluster = AsyncLocalCluster()
    tb = TopologyBuilder()
    spout = ListSpout(items)
    tb.set_spout("s", spout, 1)
    tb.set_bolt("sink", sink, 1).shuffle_grouping("s")
    rt = await cluster.submit("t", Config(), tb.build())
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        live = rt.spout_execs["s"][0].spout
        if len(live.acked) + len(live.failed) >= len(items):
            break
        await asyncio.sleep(0.01)
    await asyncio.sleep(0.05)  # let async send tasks settle
    live = rt.spout_execs["s"][0].spout
    res = (list(live.acked), list(live.failed))
    await cluster.shutdown()
    return res


@pytest.mark.parametrize("mode", ["async", "sync"])
def test_sink_delivery_ack(run, mode):
    broker = MemoryBroker()
    acked, failed = run(_sink_run(broker, _sink_with(broker, mode), ["a", "b"]))
    assert sorted(acked) == ["a", "b"] and failed == []
    assert broker.topic_size("out") == 2


@pytest.mark.parametrize("mode", ["async", "sync"])
def test_sink_delivery_failure_fails_tuple(run, mode):
    """Producer error -> tuple failed -> spout replay (KafkaBolt.java:137)."""
    broker = MemoryBroker()
    acked, failed = run(
        _sink_run(broker, _sink_with(broker, mode, fail_first=1), ["a"])
    )
    assert failed == ["a"] and acked == []
    assert broker.topic_size("out") == 0


def test_sink_fire_and_forget_acks_despite_failure(run):
    """fire-and-forget acks immediately, errors dropped (KafkaBolt.java:153-155)."""
    broker = MemoryBroker()
    acked, failed = run(
        _sink_run(broker, _sink_with(broker, "fire_and_forget", fail_first=1), ["a"])
    )
    assert acked == ["a"] and failed == []


def test_sink_null_topic_warns_and_acks(run):
    """None topic -> ack without send (KafkaBolt.java:156-159)."""
    broker = MemoryBroker()
    sink = BrokerSink(broker, None, SinkConfig(mode="sync"))
    acked, failed = run(_sink_run(broker, sink, ["a"]))
    assert acked == ["a"] and failed == []
    assert broker.topic_size("out") == 0


# ---- fail-path at-least-once invariants (blocking brokers) -------------------


class _SlowLatestBroker(MemoryBroker):
    """Blocking broker whose latest_offset waits on an event (simulating a
    network round-trip) or raises (simulating broker downtime)."""

    blocking = True

    def __init__(self):
        super().__init__(default_partitions=1)
        self.gate = asyncio.Event()
        self.raise_on_latest = False
        self._loop = None

    def latest_offset(self, topic, partition):
        if self.raise_on_latest:
            raise OSError("broker unreachable")
        if self._loop is not None:
            # Called from a to_thread worker: block until the test opens the gate.
            import concurrent.futures
            fut = asyncio.run_coroutine_threadsafe(self.gate.wait(), self._loop)
            fut.result(timeout=5)
        return super().latest_offset(topic, partition)


def _make_failing_spout(broker):
    """A BrokerSpout wired with the minimum context to exercise fail()."""
    from storm_tpu.runtime.metrics import MetricsRegistry

    spout = BrokerSpout(broker, "in", OffsetsConfig(policy="earliest", max_behind=0))

    class Ctx:
        parallelism = 1
        task_index = 0
        component_id = "spout"
        metrics = MetricsRegistry()

    class Coll:
        async def emit(self, *a, **k):
            return 1

    spout.open(Ctx(), Coll())
    return spout


def test_blocking_fail_keeps_record_visible_during_staleness_check(run):
    """While the async staleness check is in flight, the failed record must
    already sit in `replay` so ack()'s low-water commit scan sees it — a
    commit racing past an undecided failure would break at-least-once."""

    async def body():
        broker = _SlowLatestBroker()
        broker.produce("in", "v0")
        spout = _make_failing_spout(broker)
        broker._loop = asyncio.get_running_loop()
        rec = broker.fetch("in", 0, 0, 10)[0]
        spout.pending[(0, rec.offset)] = rec
        spout.fail((0, rec.offset))
        # Verdict still pending (gate closed): record must be in replay NOW.
        assert rec in spout.replay
        broker.produce("in", "fresh")  # makes offset 0 stale (max_behind=0)
        broker.gate.set()
        for _ in range(100):
            if rec not in spout.replay:
                break
            await asyncio.sleep(0.01)
        assert rec not in spout.replay  # stale verdict removed it
        assert spout.dropped == 1

    run(body())


def test_blocking_fail_broker_error_keeps_record_for_replay(run):
    """If the staleness probe raises (broker down), the record must stay
    queued for replay — never silently dropped."""

    async def body():
        broker = _SlowLatestBroker()
        broker.produce("in", "v0")
        spout = _make_failing_spout(broker)
        broker.raise_on_latest = True
        rec = broker.fetch("in", 0, 0, 10)[0]
        spout.pending[(0, rec.offset)] = rec
        spout.fail((0, rec.offset))
        await asyncio.sleep(0.05)  # let the background check run and raise
        assert rec in spout.replay
        assert spout.dropped == 0

    run(body())


# ---- consumer-group-protocol spout mode --------------------------------------


def test_spout_group_protocol_splits_partitions(run):
    """Two spout tasks with offsets.group_protocol=True get their partitions
    from JoinGroup/SyncGroup coordination instead of task-index modulo, and
    together consume everything exactly the static mode would."""
    import json as _json
    import sys as _sys

    _sys.path.insert(0, "tests")
    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker
    from storm_tpu.runtime import Bolt, TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    class Gather(Bolt):
        got = None

        def prepare(self, context, collector):
            super().prepare(context, collector)
            if Gather.got is None:
                Gather.got = []

        async def execute(self, t):
            Gather.got.append(t.get("message"))
            self.collector.ack(t)

    async def go():
        Gather.got = None
        stub = KafkaStubBroker(partitions=4)
        try:
            broker = KafkaWireBroker(f"127.0.0.1:{stub.port}")
            for i in range(12):
                broker.produce("gin", f"m{i}", key=str(i))

            cfg = Config()
            tb = TopologyBuilder()
            tb.set_spout(
                "spout",
                BrokerSpout(broker, "gin",
                            OffsetsConfig(policy="earliest", max_behind=None,
                                          group_id="gspout",
                                          group_protocol=True)),
                parallelism=2,
            )
            tb.set_bolt("gather", Gather(), parallelism=1)\
                .shuffle_grouping("spout")
            cluster = AsyncLocalCluster()
            rt = await cluster.submit("gp", cfg, tb.build())
            spouts = [e.spout for e in rt.spout_execs["spout"]]
            deadline = asyncio.get_event_loop().time() + 60
            while asyncio.get_event_loop().time() < deadline:
                # settle BOTH conditions: the rebalanced 2/2 split (the
                # second join races the first member's initial solo grab)
                # and full consumption
                split = sorted(len(s.my_partitions) for s in spouts)
                if split == [2, 2] and len(Gather.got or []) >= 12:
                    break
                await asyncio.sleep(0.1)
            assert sorted(len(s.my_partitions) for s in spouts) == [2, 2]
            owned = sorted(p for s in spouts for p in s.my_partitions)
            assert owned == [0, 1, 2, 3]
            await cluster.shutdown()
            # at-least-once across the handoff: partitions reassigned mid-run
            # are re-read from 'earliest' by their new owner (duplicates are
            # the correct policy outcome; nothing may be LOST)
            assert set(Gather.got) == {f"m{i}" for i in range(12)}
        finally:
            stub.close()

    run(go(), timeout=120)


def test_topology_over_scram_authenticated_broker(run):
    """Full spout -> bolt -> sink path over a SCRAM-authenticated wire
    broker, with the security dict built from BrokerConfig — the daemon's
    config surface. Every connection (spout fetch, sink produce, metadata)
    authenticates via the RFC 5802 exchange."""
    import sys as _sys

    _sys.path.insert(0, "tests")
    from kafka_stub import KafkaStubBroker
    from storm_tpu.config import BrokerConfig
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker
    from storm_tpu.runtime import Bolt

    class Echo(Bolt):
        async def execute(self, t):
            await self.collector.emit([t.get("message")], anchors=[t])
            self.collector.ack(t)

    async def go():
        stub = KafkaStubBroker(partitions=2)
        stub.sasl = ("svc", "scram-pw")
        stub.sasl_mechanism = "SCRAM-SHA-256"
        try:
            bcfg = BrokerConfig(
                kind="kafka", bootstrap=f"127.0.0.1:{stub.port}",
                security_protocol="SASL_PLAINTEXT",
                sasl_mechanism="SCRAM-SHA-256",
                sasl_username="svc", sasl_password="scram-pw")
            broker = KafkaWireBroker(bcfg.bootstrap,
                                     security=bcfg.security_dict())
            for i in range(6):
                broker.produce("sin", f"r{i}", key=str(i))
            cfg = Config()
            tb = TopologyBuilder()
            tb.set_spout("spout", BrokerSpout(
                broker, "sin",
                OffsetsConfig(policy="earliest", max_behind=None)),
                parallelism=1)
            tb.set_bolt("echo", Echo(), parallelism=1)\
                .shuffle_grouping("spout")
            tb.set_bolt("sink", BrokerSink(broker, "sout", cfg.sink),
                        parallelism=1).shuffle_grouping("echo")
            cluster = AsyncLocalCluster()
            rt = await cluster.submit("scram-topo", cfg, tb.build())
            got = set()
            deadline = asyncio.get_event_loop().time() + 60
            while asyncio.get_event_loop().time() < deadline:
                for p in range(2):
                    for rec in broker.client.fetch("sout", p, 0,
                                                   max_wait_ms=10):
                        got.add(rec.value.decode())
                if len(got) >= 6:
                    break
                await asyncio.sleep(0.1)
            assert got == {f"r{i}" for i in range(6)}
            await rt.drain(timeout_s=20)
            await cluster.shutdown()
        finally:
            stub.close()

    run(go(), timeout=120)


def test_spout_group_protocol_requires_wire_broker():
    from storm_tpu.runtime.base import OutputCollector

    broker = MemoryBroker()
    # group_protocol without a pinned group_id is itself a config error
    with pytest.raises(ValueError, match="group_id"):
        OffsetsConfig(group_protocol=True)
    spout = BrokerSpout(broker, "t",
                        OffsetsConfig(group_protocol=True, group_id="g"))

    class Ctx:
        task_index = 0
        parallelism = 1
        component_id = "s"
        config = None
        metrics = None

    with pytest.raises(ValueError, match="wire-protocol broker"):
        spout.open(Ctx(), None)


def test_spout_seek_replays_and_skips(run):
    """request_seek('earliest') reprocesses the log; seek('latest') skips
    backlog; negative seek replays the last N records."""
    import asyncio
    import json as _json

    from storm_tpu.config import Config
    from storm_tpu.connectors import BrokerSpout, MemoryBroker
    from storm_tpu.runtime import Bolt, TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    broker = MemoryBroker(default_partitions=2)
    for i in range(10):
        broker.produce("t", _json.dumps({"i": i}))

    class Count(Bolt):
        seen = []

        async def execute(self, t):
            Count.seen.append(t.values[0])
            self.collector.ack(t)

    async def settle_at(n, timeout=15.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if len(Count.seen) >= n:
                await asyncio.sleep(0.3)  # let any extras surface
                return
            await asyncio.sleep(0.05)
        raise AssertionError(f"timed out at {len(Count.seen)}/{n}")

    async def go():
        from storm_tpu.connectors.spout import OffsetsConfig

        Count.seen = []
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "t", OffsetsConfig(policy="earliest")), 1)
        tb.set_bolt("c", Count(), 1).shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("seek", Config(), tb.build())
        await settle_at(10)
        assert len(Count.seen) == 10

        # full replay
        n = await rt.seek("s", "earliest")
        assert n == 1
        await settle_at(20)
        assert len(Count.seen) == 20

        # seek latest: new backlog produced BEFORE the seek applies is
        # skipped once the spout repositions
        await rt.seek("s", "latest")
        await asyncio.sleep(0.3)
        before = len(Count.seen)
        broker.produce("t", _json.dumps({"i": 99}))
        await settle_at(before + 1)
        assert len(Count.seen) == before + 1

        # negative: replay ~last 2 per partition
        await rt.seek("s", -2)
        await asyncio.sleep(0.5)
        assert len(Count.seen) > before + 1

        # unknown / non-spout components error
        with pytest.raises(KeyError):
            await rt.seek("nope", "earliest")
        with pytest.raises(KeyError):
            await rt.seek("c", "earliest")
        await cluster.shutdown()

    run(go(), timeout=60)


def test_transactional_sink_commit_and_abort(run):
    """TransactionalSink: a failing commit aborts all-or-nothing (records
    never partially visible) and fails the tuples for replay; the replay
    commits in a new transaction and every record appears exactly once."""
    import asyncio
    import json as _json

    from storm_tpu.config import Config
    from storm_tpu.connectors import MemoryBroker, TransactionalBrokerSink
    from storm_tpu.runtime import TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    class FlakyTxn:
        """Fails the first commit, then delegates (deterministic chaos)."""

        def __init__(self, inner):
            self._inner = inner
            self.fail_next = 1

        def begin(self):
            self._inner.begin()

        def produce(self, *a, **kw):
            self._inner.produce(*a, **kw)

        def commit(self):
            if self.fail_next:
                self.fail_next -= 1
                self._inner.abort()
                raise RuntimeError("injected commit failure")
            self._inner.commit()

        def abort(self):
            self._inner.abort()

    class FlakyBroker(MemoryBroker):
        def txn(self, txn_id):
            return FlakyTxn(super().txn(txn_id))

    from storm_tpu.runtime import Spout, Values

    class ReplaySpout(Spout):
        def open(self, ctx, col):
            super().open(ctx, col)
            self.q = [f"m{i}" for i in range(6)] if ctx.task_index == 0 else []
            self.done = []

        async def next_tuple(self):
            if not self.q:
                return False
            m = self.q.pop(0)
            await self.collector.emit(Values([m]), msg_id=m)
            return True

        def ack(self, msg_id):
            self.done.append(msg_id)

        def fail(self, msg_id):
            self.q.append(msg_id)  # replay

    async def main():
        broker = FlakyBroker()
        tb = TopologyBuilder()
        tb.set_spout("s", ReplaySpout(), 1)
        from storm_tpu.config import SinkConfig

        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=3, txn_ms=30.0)), 1)\
            .shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("txn", Config(), tb.build())
        deadline = asyncio.get_event_loop().time() + 20
        while asyncio.get_event_loop().time() < deadline:
            if broker.topic_size("out") >= 6:
                break
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)
        recs = broker.drain_topic("out")
        vals = sorted(r.value.decode() for r in recs)
        assert vals == [f"m{i}" for i in range(6)], vals  # exactly once
        snap = rt.metrics.snapshot()
        assert snap["sink"]["txn_aborts"] == 1
        assert snap["sink"]["txn_commits"] >= 2
        await cluster.shutdown()

    run(main(), timeout=60)


def test_transactional_sink_rearms_deadline_after_own_flush(run):
    """Tuples that arrive WHILE a deadline-triggered flush is committing
    must get a fresh deadline timer: the flushing task is the deadline task
    itself (`.done()` is False), so the old re-arm check skipped them and
    they sat unacked until tree-timeout replay — the double-commit the
    re-arm exists to prevent. Regression for ADVICE r1 (sink.py:303)."""
    import time as _time

    from storm_tpu.config import Config, SinkConfig
    from storm_tpu.connectors import MemoryBroker, TransactionalBrokerSink
    from storm_tpu.runtime import Spout, TopologyBuilder, Values
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    class SlowTxn:
        def __init__(self, inner):
            self._inner = inner

        def begin(self):
            self._inner.begin()

        def produce(self, *a, **kw):
            self._inner.produce(*a, **kw)

        def commit(self):
            _time.sleep(0.25)  # commit in flight while tuple "b" arrives
            self._inner.commit()

        def abort(self):
            self._inner.abort()

    class SlowBroker(MemoryBroker):
        blocking = True  # sink runs txns on a worker thread

        def txn(self, txn_id):
            return SlowTxn(super().txn(txn_id))

    class TwoPhaseSpout(Spout):
        def open(self, ctx, col):
            super().open(ctx, col)
            self.plan = [("a", 0.0), ("b", 0.1)] if ctx.task_index == 0 else []
            self.t0 = _time.monotonic()
            self.acked, self.failed = [], []

        async def next_tuple(self):
            if not self.plan:
                return False
            m, at = self.plan[0]
            if _time.monotonic() - self.t0 < at:
                return False
            self.plan.pop(0)
            await self.collector.emit(Values([m]), msg_id=m)
            return True

        def ack(self, msg_id):
            self.acked.append(msg_id)

        def fail(self, msg_id):
            self.failed.append(msg_id)

    async def main():
        broker = SlowBroker()
        tb = TopologyBuilder()
        tb.set_spout("s", TwoPhaseSpout(), 1)
        # batch=100 so only the deadline (30ms) ever triggers a flush:
        # t=30ms flush("a") starts, commit blocks 250ms; t=100ms "b" arrives
        # mid-flush; the re-armed deadline must flush "b" ~30ms after.
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=100, txn_ms=30.0)), 1)\
            .shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("txn-rearm", Config(), tb.build())
        spout = rt.spout_execs["s"][0].spout
        deadline = asyncio.get_event_loop().time() + 3.0
        while asyncio.get_event_loop().time() < deadline:
            if len(spout.acked) >= 2:
                break
            await asyncio.sleep(0.02)
        # Well under any tree timeout: both tuples committed+acked promptly.
        assert sorted(spout.acked) == ["a", "b"], (spout.acked, spout.failed)
        assert spout.failed == []
        recs = broker.drain_topic("out")
        assert sorted(r.value.decode() for r in recs) == ["a", "b"]
        await cluster.shutdown()

    run(main(), timeout=30)


def test_append_root_ts_clamps_future_timestamps():
    """A producer with a skewed-forward clock must not yield negative
    latency: the ingress clock clamps record age at 0."""
    import time as _time

    from storm_tpu.connectors.memory import Record
    from storm_tpu.connectors.spout import BrokerSpout

    spout = object.__new__(BrokerSpout)  # _append_root_ts reads no state
    now = _time.perf_counter()
    past = Record("t", 0, 0, None, b"v", _time.time() - 1.5)
    future = Record("t", 0, 1, None, b"v", _time.time() + 60.0)
    ts_past = spout._append_root_ts(past)
    ts_future = spout._append_root_ts(future)
    assert 1.3 <= now - ts_past <= 1.8  # ~1.5s of age preserved
    assert ts_future <= _time.perf_counter()  # clamped, never negative age

    # Kafka baseTimestamp=-1 sentinel (no producer timestamp) decodes to
    # ts<=0; the clock must fall back to age 0, not an epoch-scale age
    # that poisons the e2e histograms.
    sentinel = Record("t", 0, 2, None, b"v", -0.001)
    zero = Record("t", 0, 3, None, b"v", 0.0)
    for rec in (sentinel, zero):
        before = _time.perf_counter()
        ts = spout._append_root_ts(rec)
        assert before <= ts <= _time.perf_counter()  # age ~0


# ---- EOS fan-out: whole tree per transaction (ADVICE r3-high) ----------------


def _eos_fanout_harness(group: str, fan: int, violations: list):
    """Shared fixtures for the EOS fan-out tests: a broker whose
    transactions record, at every commit, (a) duplicate output values and
    (b) any committed source offset not fully covered by its tree's
    outputs in the topic — the two ways a split tree breaks exactly-once —
    plus the 1->fan splitter bolt that creates such trees."""
    from storm_tpu.runtime import Bolt, Values

    class RecTxn:
        def __init__(self, inner, broker):
            self._inner, self._broker = inner, broker

        def begin(self):
            self._inner.begin()

        def produce(self, *a, **kw):
            self._inner.produce(*a, **kw)

        def send_offsets(self, *a, **kw):
            self._inner.send_offsets(*a, **kw)

        def abort(self):
            self._inner.abort()

        def commit(self):
            self._inner.commit()
            out_vals = [r.value.decode()
                        for r in self._broker.drain_topic("out")]
            if len(out_vals) != len(set(out_vals)):
                violations.append(("dupes", sorted(out_vals)))
            uniq = set(out_vals)
            for p in range(2):
                k = self._broker.committed(group, "in", p)
                if k is None:
                    continue
                for rec in self._broker.fetch("in", p, 0, 100)[:k]:
                    v = rec.value.decode()
                    missing = [j for j in range(fan)
                               if f"{v}/{j}" not in uniq]
                    if missing:
                        violations.append((v, missing))

    class RecBroker(MemoryBroker):
        def txn(self, txn_id):
            return RecTxn(super().txn(txn_id), self)

    class SplitBolt(Bolt):
        async def execute(self, t):
            for j in range(fan):
                await self.collector.emit(
                    Values([f'{t.get("message")}/{j}']), anchors=[t])
            self.collector.ack(t)

    return RecBroker, SplitBolt


def test_eos_fanout_whole_tree_single_txn(run):
    """One spout entry fanning out to multiple sink tuples must commit ALL
    its outputs + its source offsets in ONE transaction even when txn_batch
    would split the tree (ADVICE r3-high, sink.py fold-on-first-sight).
    A recording txn asserts, at every commit, that a committed source
    offset is fully covered by its tree's outputs already in the topic —
    never an offset ahead of unproduced siblings."""
    from storm_tpu.connectors import TransactionalBrokerSink
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    G = "eos-fan"
    FAN = 3
    violations = []
    RecBroker, SplitBolt = _eos_fanout_harness(G, FAN, violations)

    async def main():
        broker = RecBroker(default_partitions=2)
        for i in range(8):
            broker.produce("in", f"r{i}", partition=i % 2)
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "in",
            OffsetsConfig(policy="txn", group_id=G, max_behind=None)), 1)
        tb.set_bolt("mid", SplitBolt(), 1).shuffle_grouping("s")
        # txn_batch=2 < FAN: fold-on-first-sight would commit the entry's
        # offset in a transaction holding only part of its tree.
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=2, txn_ms=20.0,
                       offsets_group=G)), 1).shuffle_grouping("mid")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("fan", Config(), tb.build())
        deadline = asyncio.get_event_loop().time() + 25
        while asyncio.get_event_loop().time() < deadline:
            if (broker.topic_size("out") >= 8 * FAN
                    and all(broker.committed(G, "in", p) == 4
                            for p in range(2))):
                break
            await asyncio.sleep(0.05)
        snap = rt.metrics.snapshot()
        await cluster.shutdown()
        assert violations == [], violations
        vals = sorted(r.value.decode() for r in broker.drain_topic("out"))
        assert vals == sorted(
            f"r{i}/{j}" for i in range(8) for j in range(FAN)), vals
        committed = {p: broker.committed(G, "in", p) for p in range(2)}
        assert committed == {0: 4, 1: 4}, committed
        # parking actually engaged (the batch boundary DID split the tree)
        assert snap["sink"]["txn_offsets_deferred"] > 0, snap["sink"]

    run(main(), timeout=60)


def test_eos_offsets_group_rejects_parallel_sink(run):
    """offsets_group + sink parallelism > 1 must fail loudly at prepare: a
    fan-out tree split across sink executors can close in neither (each
    sees live edges held by the other), so parked tuples would replay
    forever."""
    from storm_tpu.connectors import TransactionalBrokerSink
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    async def main():
        broker = MemoryBroker(default_partitions=2)
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "in",
            OffsetsConfig(policy="txn", group_id="g", max_behind=None)), 1)
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", offsets_group="g")),
            2).shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        with pytest.raises(ValueError, match="parallelism 1"):
            await cluster.submit("fan2", Config(), tb.build())
        await cluster.shutdown()

    run(main(), timeout=30)


def test_eos_fanout_sibling_failure_no_partial_commit(run):
    """When one sibling of a fan-out tree fails mid-flight, the sink's
    parked siblings belong to a FAILED tree (ledger entry gone): they must
    be dropped, never produced or offset-committed — the replayed tree
    then commits whole. Guards the outstanding()==0 'gone means failed,
    not closed' distinction in _plan."""
    from storm_tpu.connectors import TransactionalBrokerSink
    from storm_tpu.runtime import Bolt, Values
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    G = "eos-fail"
    FAN = 3
    violations = []
    RecBroker, SplitBolt = _eos_fanout_harness(G, FAN, violations)

    class FlakyPass(Bolt):
        failed = False

        async def execute(self, t):
            v = t.get("message")
            if v.endswith("/1") and not FlakyPass.failed:
                FlakyPass.failed = True
                self.collector.fail(t)  # kills the whole tree
                return
            await self.collector.emit(Values([v]), anchors=[t])
            self.collector.ack(t)

    async def main():
        FlakyPass.failed = False
        broker = RecBroker(default_partitions=2)
        for i in range(4):
            broker.produce("in", f"r{i}", partition=i % 2)
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "in",
            OffsetsConfig(policy="txn", group_id=G, max_behind=None)), 1)
        tb.set_bolt("split", SplitBolt(), 1).shuffle_grouping("s")
        tb.set_bolt("mid", FlakyPass(), 1).shuffle_grouping("split")
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=2, txn_ms=20.0,
                       offsets_group=G)), 1).shuffle_grouping("mid")
        cluster = AsyncLocalCluster()
        await cluster.submit("fanfail", Config(), tb.build())
        deadline = asyncio.get_event_loop().time() + 25
        while asyncio.get_event_loop().time() < deadline:
            if (broker.topic_size("out") >= 4 * FAN
                    and all(broker.committed(G, "in", p) == 2
                            for p in range(2))):
                break
            await asyncio.sleep(0.05)
        await cluster.shutdown()
        assert violations == [], violations
        vals = sorted(r.value.decode() for r in broker.drain_topic("out"))
        assert vals == sorted(
            f"r{i}/{j}" for i in range(4) for j in range(FAN)), vals
        committed = {p: broker.committed(G, "in", p) for p in range(2)}
        assert committed == {0: 2, 1: 2}, committed

    run(main(), timeout=60)


def test_txn_small_chunk_warns(caplog):
    """offsets.policy='txn' below the measured 5x throughput cliff
    (chunk < 64, BENCH_NOTES 'what does exactly-once cost') must warn
    loudly at open — the foot-gun is silent otherwise (VERDICT r3 #8)."""
    import logging

    from storm_tpu.runtime.metrics import MetricsRegistry

    class Ctx:
        parallelism = 1
        task_index = 0
        component_id = "spout"
        metrics = MetricsRegistry()

    class Coll:
        async def emit(self, *a, **k):
            return 1

    broker = MemoryBroker(default_partitions=2)
    with caplog.at_level(logging.WARNING, logger="storm_tpu.spout"):
        s = BrokerSpout(broker, "in",
                        OffsetsConfig(policy="txn", group_id="g",
                                      max_behind=None), chunk=4)
        s.open(Ctx(), Coll())
    assert any("spout_chunk" in r.message and "gated entry" in r.message
               for r in caplog.records), caplog.records

    # at or past the measured-free point: silent (on the spout's own
    # logger — caplog collects every logger's records, filter first)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="storm_tpu.spout"):
        s2 = BrokerSpout(broker, "in2",
                         OffsetsConfig(policy="txn", group_id="g",
                                       max_behind=None), chunk=16)
        s2.open(Ctx(), Coll())
    assert not [r for r in caplog.records if r.name == "storm_tpu.spout"]


def test_eos_rebalance_to_parallel_sink_rolls_back(run):
    """Growing the offsets-committing sink past parallelism 1 must fail
    loudly AND leave the runtime intact: the rejected replica is rolled
    out of bolt_execs (a half-registered executor would swallow routed
    tuples forever) and the pipeline keeps flowing."""
    from storm_tpu.connectors import TransactionalBrokerSink
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    async def main():
        broker = MemoryBroker(default_partitions=2)
        for i in range(3):
            broker.produce("in", f"a{i}", partition=i % 2)
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "in",
            OffsetsConfig(policy="txn", group_id="rb-g",
                          max_behind=None)), 1)
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=2, txn_ms=20.0,
                       offsets_group="rb-g")), 1).shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("rb", Config(), tb.build())
        with pytest.raises(ValueError, match="parallelism 1"):
            await rt.rebalance("sink", 2)
        assert rt.parallelism_of("sink") == 1  # rolled back, not zombie
        for i in range(3, 6):
            broker.produce("in", f"a{i}", partition=i % 2)
        deadline = asyncio.get_event_loop().time() + 20
        while asyncio.get_event_loop().time() < deadline:
            if broker.topic_size("out") >= 6:
                break
            await asyncio.sleep(0.05)
        assert broker.topic_size("out") == 6  # still flowing after the raise
        await cluster.shutdown()

    run(main(), timeout=40)


def test_eos_tree_closure_commits_without_deadline_wait(run):
    """The tree-closure trigger: an entry whose tree is fully held must
    commit IMMEDIATELY, not after txn_ms/txn_batch — with a 30 s deadline
    and a huge batch, three single-record entries still flow in well
    under a second each (before the trigger, each gated entry waited the
    full deadline: measured 60 rec/s at chunk=1 on a 50 ms txn_ms)."""
    from storm_tpu.connectors import TransactionalBrokerSink
    from storm_tpu.runtime.cluster import AsyncLocalCluster
    from tests.test_runtime import PassBolt

    async def main():
        broker = MemoryBroker(default_partitions=1)
        for i in range(3):
            broker.produce("in", f"m{i}", partition=0)
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(
            broker, "in",
            OffsetsConfig(policy="txn", group_id="cl-g",
                          max_behind=None)), 1)
        tb.set_bolt("mid", PassBolt(), 1).shuffle_grouping("s")
        # deadline and batch far beyond the test timeout: only the
        # closure trigger can commit these
        tb.set_bolt("sink", TransactionalBrokerSink(
            broker, "out",
            SinkConfig(mode="transactional", txn_batch=512,
                       txn_ms=30_000.0, offsets_group="cl-g")),
            1).shuffle_grouping("mid")
        cluster = AsyncLocalCluster()
        await cluster.submit("closure", Config(), tb.build())
        t0 = asyncio.get_event_loop().time()
        while asyncio.get_event_loop().time() - t0 < 10:
            if broker.topic_size("out") >= 3:
                break
            await asyncio.sleep(0.05)
        took = asyncio.get_event_loop().time() - t0
        await cluster.shutdown()
        assert broker.topic_size("out") == 3, broker.topic_size("out")
        assert took < 5.0, f"closure trigger too slow: {took:.1f}s"
        assert broker.committed("cl-g", "in", 0) == 3

    run(main(), timeout=40)
