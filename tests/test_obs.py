"""Continuous profiling & SLO-burn observatory (round-11 tentpole).

Unit coverage for the three obs pillars — the ProfileStore's
per-(engine, bucket) cost curves, the multi-window burn-rate tracker,
and the Observatory's regression sentinel — plus the metrics-layer
satellites they lean on (thread-safe Histogram mutation, the windowed-
rate helper). The end-to-end behaviour (burn trips before the shed
level moves under real overload; the /profile route serves live curves)
is captured in BENCH_SLO_BURN_r11.json, not re-measured here.
"""

from __future__ import annotations

import json
import threading
from types import SimpleNamespace

import pytest

from storm_tpu.obs.profile import ProfileStore
from storm_tpu.obs.slo import SloBurnTracker
from storm_tpu.runtime.metrics import Histogram, MetricsRegistry


class FakeFlight:
    def __init__(self) -> None:
        self.events = []

    def event(self, kind, **fields):
        fields.pop("throttle_s", None)
        self.events.append({"kind": kind, **fields})


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---- ProfileStore: curves ----------------------------------------------------


def _feed_linear(store: ProfileStore, key: str, buckets=(16, 64, 256),
                 batches: int = 30, scale: float = 1.0) -> None:
    """Synthetic stage costs that grow linearly with the bucket — the
    shape a real device produces once per-batch overhead amortizes."""
    for padded in buckets:
        for i in range(batches):
            jitter = 1.0 + 0.01 * (i % 5)
            store.record_batch(key, padded, padded, {
                "h2d_ms": scale * 0.02 * padded * jitter,
                "compute_ms": scale * 0.05 * padded * jitter,
                "d2h_ms": scale * 0.01 * padded * jitter,
            })


def test_profile_store_builds_monotone_curves():
    store = ProfileStore()
    _feed_linear(store, "lenet5")
    store.record_compile("lenet5", 16, 120.0)
    store.record_compile("lenet5", 64, 150.0)

    snap = store.snapshot()
    eng = snap["engines"]["lenet5"]
    assert set(eng["buckets"]) == {"16", "64", "256"}
    p50s = [eng["buckets"][b]["stages"]["device_ms"]["p50"]
            for b in ("16", "64", "256")]
    assert p50s == sorted(p50s)  # whole-batch cost grows with the bucket
    row64 = eng["buckets"]["64"]
    assert row64["batches"] == 30 and row64["rows"] == 30 * 64
    # device_ms is the synthetic sum of the three phases
    st = row64["stages"]
    assert st["device_ms"]["mean"] == pytest.approx(
        st["h2d_ms"]["mean"] + st["compute_ms"]["mean"]
        + st["d2h_ms"]["mean"], rel=1e-6)
    assert row64["ms_per_row"] == pytest.approx(
        st["device_ms"]["mean"] / 64, rel=1e-3)
    assert row64["throughput_rows_s"] > 0
    assert eng["compiles"]["16"]["last_ms"] == 120.0
    assert eng["compiles"]["64"]["count"] == 1


def test_profile_cost_of_reads_largest_bucket():
    store = ProfileStore()
    _feed_linear(store, "resnet20")
    cost = store.cost_of("resnet20")
    assert cost["bucket"] == 256
    assert cost["ms_per_row"] == pytest.approx(
        cost["device_ms_mean"] / 256, rel=1e-3)
    assert store.cost_of("never-profiled") is None


def test_profile_partial_timings_skip_missing_stages():
    store = ProfileStore()
    store.record_batch("m", 8, 8, {"compute_ms": 3.0})  # no h2d/d2h
    row = store.snapshot()["engines"]["m"]["buckets"]["8"]
    assert "h2d_ms" not in row["stages"]
    assert row["stages"]["device_ms"]["mean"] == pytest.approx(3.0)
    store.record_batch("m", 8, 8, {})  # empty timings: ignored
    assert store.snapshot()["engines"]["m"]["buckets"]["8"]["batches"] == 1


# ---- ProfileStore: baseline round-trip + sentinel ----------------------------


def test_profile_snapshot_round_trips_as_baseline():
    store = ProfileStore()
    _feed_linear(store, "lenet5")
    snap = json.loads(json.dumps(store.snapshot()))  # the artifact path
    store.load_baseline(snap)
    assert store.baseline is snap
    # Self-comparison is clean at any sample floor: the committed
    # artifact is directly usable as the sentinel's baseline.
    assert store.regressions(factor=1.5, min_samples=1) == []
    with pytest.raises(ValueError):
        store.load_baseline({"not": "a snapshot"})


def test_profile_baseline_accepts_bench_artifact_form():
    # obs.baseline_path points at the committed PROFILE_*.json, whose
    # snapshot lives under the artifact's "profile" key (the top-level
    # "engines" there is a list of names, not the curves mapping).
    store = ProfileStore()
    _feed_linear(store, "lenet5")
    snap = json.loads(json.dumps(store.snapshot()))
    artifact = {"metric": "profile_curves", "engines": ["lenet5"],
                "profile": snap}
    store.load_baseline(artifact)
    assert store.baseline == snap
    assert store.regressions(factor=1.5, min_samples=1) == []
    with pytest.raises(ValueError):
        store.load_baseline({"engines": ["lenet5"]})  # list, no profile


def test_profile_regressions_detect_drift():
    base_store = ProfileStore()
    _feed_linear(base_store, "lenet5")
    live = ProfileStore()
    _feed_linear(live, "lenet5", scale=2.0)  # every stage 2x slower
    live.load_baseline(base_store.snapshot())
    regs = live.regressions(factor=1.5, min_samples=10)
    assert regs  # all (bucket, stage) cells drifted
    assert {r["engine"] for r in regs} == {"lenet5"}
    assert all(1.8 < r["ratio"] < 2.2 for r in regs)
    # Below the sample floor the same drift is NOT reported (cold
    # curves flap; the sentinel waits for evidence).
    assert live.regressions(factor=1.5, min_samples=10_000) == []
    # Without a baseline there is nothing to compare against.
    assert ProfileStore().regressions() == []


def test_observatory_sentinel_records_flight_events():
    from storm_tpu.obs import Observatory
    from storm_tpu.config import ObsConfig
    from storm_tpu.obs.profile import profile_store

    store = profile_store()
    store.reset()
    rt = SimpleNamespace(metrics=MetricsRegistry(), flight=FakeFlight())
    clock = FakeClock()
    obs = Observatory(rt, ObsConfig(enabled=True, min_samples=10),
                      clock=clock)
    assert rt.obs is obs  # exposed for the UI /profile route
    try:
        # Baseline at 1x, live traffic at 3x: drift the sentinel must see.
        base = ProfileStore()
        _feed_linear(base, "drift-model")
        store.load_baseline(base.snapshot())
        _feed_linear(store, "drift-model", scale=3.0)
        regs = obs.sentinel_check()
        assert regs and obs.last_regressions == regs
        kinds = {e["kind"] for e in rt.flight.events}
        assert "profile_regression" in kinds
        ev = next(e for e in rt.flight.events
                  if e["kind"] == "profile_regression")
        assert ev["engine"] == "drift-model" and ev["ratio"] > 1.5
        assert rt.metrics.counter(
            "obs", "profile_regressions").value == len(regs)
        snap = obs.snapshot()
        assert snap["baseline_loaded"] is True
        assert snap["regressions"] == regs
        assert "slo" in snap and "occupancy" in snap
    finally:
        store.reset()


# ---- SloBurnTracker ----------------------------------------------------------


def _mk_burn(**kw):
    reg = MetricsRegistry()
    flight = FakeFlight()
    clock = FakeClock()
    kw.setdefault("objective", 0.99)
    kw.setdefault("fast_window_s", 60.0)
    kw.setdefault("slow_window_s", 600.0)
    tracker = SloBurnTracker(reg, components=("kafka-bolt",), flight=flight,
                             clock=clock, **kw)
    return tracker, reg, flight, clock


def test_burn_trips_on_dual_window_and_untrips():
    tracker, reg, flight, clock = _mk_burn()
    delivered = reg.counter("kafka-bolt", "delivered")
    breaches = reg.counter("kafka-bolt", "slo_breaches")

    out = tracker.step()  # baseline sample, nothing flowing
    assert out == {"fast_burn": 0.0, "slow_burn": 0.0, "tripped": False}

    # 5% breach ratio against a 1% budget => burn 5 in BOTH windows.
    delivered.inc(1000)
    breaches.inc(50)
    clock.t = 1.0
    out = tracker.step()
    assert out["fast_burn"] == pytest.approx(5.0)
    assert out["slow_burn"] == pytest.approx(5.0)
    assert out["tripped"] is True
    assert tracker.trips == 1
    assert reg.gauge("slo", "burn_rate").value == pytest.approx(5.0)
    assert reg.gauge("slo", "tripped").value == 1.0
    (ev,) = flight.events
    assert ev["kind"] == "slo_burn" and ev["fast_burn"] == 5.0

    # Clean traffic beyond both windows: burn decays to 0, gauge untrips,
    # and the flight event is RE-ARMED (a second trip fires again).
    clock.t = 700.0
    delivered.inc(10_000)
    tracker.step()
    assert tracker.tripped is False
    assert reg.gauge("slo", "tripped").value == 0.0
    clock.t = 701.0
    delivered.inc(1000)
    breaches.inc(100)
    tracker.step()
    assert tracker.tripped is True and tracker.trips == 2
    assert len(flight.events) == 2


def test_burn_fast_window_alone_does_not_trip():
    # Old breaches inside the slow window but outside the fast one:
    # slow burn stays hot, fast burn reads clean recent traffic -> no
    # trip (the classic multi-window de-flap, in the recovering
    # direction).
    tracker, reg, flight, clock = _mk_burn(
        fast_window_s=10.0, slow_window_s=600.0)
    delivered = reg.counter("kafka-bolt", "delivered")
    breaches = reg.counter("kafka-bolt", "slo_breaches")
    tracker.step()
    delivered.inc(100)
    breaches.inc(50)  # the incident
    clock.t = 5.0
    assert tracker.step()["tripped"] is True
    clock.t = 100.0  # incident now outside the fast window
    delivered.inc(2000)  # recovery traffic, no new breaches
    out = tracker.step()
    assert out["fast_burn"] == 0.0
    assert out["slow_burn"] > 1.0  # slow window still remembers
    assert out["tripped"] is False
    assert tracker.trips == 1


def test_burn_zero_delivery_counts_as_full_burn():
    tracker, reg, _, clock = _mk_burn()
    breaches = reg.counter("kafka-bolt", "slo_breaches")
    tracker.step()
    breaches.inc(7)  # breaches with NO deliveries: everything failing
    clock.t = 1.0
    out = tracker.step()
    assert out["fast_burn"] == pytest.approx(1.0 / tracker.budget)
    assert out["tripped"] is True


def test_burn_validates_config():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        SloBurnTracker(reg, objective=1.0)
    with pytest.raises(ValueError):
        SloBurnTracker(reg, fast_window_s=60.0, slow_window_s=10.0)


def test_burn_snapshot_shape():
    tracker, reg, _, clock = _mk_burn()
    snap = tracker.snapshot()
    assert snap["components"] == ["kafka-bolt"]
    assert snap["budget"] == pytest.approx(0.01)
    assert snap["tripped"] is False and snap["trips"] == 0


# ---- metrics satellites: thread-safe Histogram + window helper ---------------


def test_histogram_concurrent_observe_reset_hammer():
    """Regression: an unguarded reset racing observe could tear the ring
    indices (negative counts / percentile reading stale rows). Hammer
    observe from 4 threads while the main thread resets and reads."""
    h = Histogram(256)
    stop = threading.Event()
    errors = []

    def writer():
        try:
            while not stop.is_set():
                h.observe(1.0)
        except Exception as e:  # pragma: no cover - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            h.reset()
            p = h.percentile(95)
            assert p != p or p == 1.0  # NaN (empty) or the only value
            snap = h.snapshot()
            assert snap["count"] >= 0
            assert h.count * 1.0 == h.sum  # all observations are 1.0
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    assert 0 <= h._n <= 256 and 0 <= h._i < 256


def test_histogram_snapshot_has_p90_and_max():
    h = Histogram()
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["max"] == 100.0
    assert 89.0 <= snap["p90"] <= 91.0
    assert snap["p50"] == pytest.approx(50.5)
    empty = Histogram().snapshot()
    assert empty["p90"] is None and empty["max"] is None


def test_histogram_window_named_cursors():
    h = Histogram()
    # First read of a cursor is a zero-length window, not a huge delta.
    assert h.window("a")["count"] == 0
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    wa = h.window("a")
    assert wa["count"] == 3 and wa["sum"] == 6.0
    assert wa["mean"] == pytest.approx(2.0)
    # Independent cursor "b" starts fresh and doesn't steal a's delta.
    assert h.window("b")["count"] == 0
    h.observe(10.0)
    assert h.window("a")["count"] == 1
    assert h.window("b")["count"] == 1
    # reset clears the cursors too: next read is zero-length again.
    h.reset()
    assert h.window("a")["count"] == 0
