"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports,
so sharding/mesh tests run without TPU hardware (SURVEY.md §4 build
obligation: fake/CPU backend for multi-device simulation)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# config.update, not the env var: the dev environment pins JAX_PLATFORMS to
# the real TPU platform in a way that survives os.environ edits; tests must
# run on the virtual 8-device CPU backend. STORM_TPU_TEST_PLATFORM=default
# keeps whatever jax resolves (the real chip) so the compiled-on-TPU tests
# (tests/test_tpu_kernels.py) can run un-skipped on hardware.
_plat = os.environ.get("STORM_TPU_TEST_PLATFORM", "cpu")
if _plat not in ("cpu", "default"):
    raise RuntimeError(
        f"STORM_TPU_TEST_PLATFORM={_plat!r}: must be 'cpu' (forced 8-device "
        "CPU mesh, the default) or 'default' (keep whatever jax resolves — "
        "the real chip, for tests/test_tpu_kernels.py)")
if _plat == "cpu":
    jax.config.update("jax_platforms", "cpu")
    assert jax.devices()[0].platform == "cpu", "tests require the CPU backend"
    assert len(jax.devices()) == 8, "tests require 8 virtual CPU devices"

import asyncio
import signal

import pytest


@pytest.fixture(autouse=True)
def _per_test_timeout(request):
    """Per-test wall-clock timeout (VERDICT r1 weak #3): a wedged test must
    FAIL with a traceback pointing at the hang, not stall the whole run.
    Defaults: 120s, 420s for ``slow``-marked tests; override with
    ``@pytest.mark.timeout(seconds)``. SIGALRM only fires on the main
    thread, which is where pytest runs test bodies."""
    if not hasattr(signal, "SIGALRM"):  # pragma: no cover - non-unix
        yield
        return
    limit = 420 if request.node.get_closest_marker("slow") else 120
    m = request.node.get_closest_marker("timeout")
    if m and m.args:
        limit = int(m.args[0])

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"per-test timeout: exceeded {limit}s (tests/conftest.py)")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(limit)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def run():
    """Run a coroutine to completion on a fresh event loop."""

    def _run(coro, timeout=60.0):
        return asyncio.run(asyncio.wait_for(coro, timeout))

    return _run
