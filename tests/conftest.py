"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports,
so sharding/mesh tests run without TPU hardware (SURVEY.md §4 build
obligation: fake/CPU backend for multi-device simulation)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio

import pytest


@pytest.fixture
def run():
    """Run a coroutine to completion on a fresh event loop."""

    def _run(coro, timeout=60.0):
        return asyncio.run(asyncio.wait_for(coro, timeout))

    return _run
