"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4):
mesh construction, tp param placement, sharded train step, ring attention,
and the driver contract's dryrun."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from storm_tpu.models import build_model
from storm_tpu.ops.attention import attention_reference
from storm_tpu.parallel.mesh import make_mesh
from storm_tpu.parallel.ring_attention import ring_attention
from storm_tpu.parallel.sharding import batch_sharding, shard_params_tp
from storm_tpu.parallel.train import init_sharded_training, train_one_step


def test_make_mesh_shapes():
    m = make_mesh()  # all devices on data axis
    assert m.shape["data"] == 8 and m.shape["model"] == 1
    m2 = make_mesh(4, 2)
    assert m2.shape["data"] == 4 and m2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh(8, 3)
    with pytest.raises(ValueError):
        make_mesh(8, 2)  # 16 > 8


def test_tp_param_placement():
    mesh = make_mesh(4, 2)
    model = build_model("vit_tiny")
    params, _ = model.init(jax.random.PRNGKey(0))
    placed = shard_params_tp(mesh, params)
    blk = placed["blocks"][0]
    # column-parallel: output dim sharded on model axis
    q_spec = blk["attn"]["q"]["w"].sharding.spec
    assert q_spec == P(None, "model")
    mlp_in_spec = blk["mlp_in"]["w"].sharding.spec
    assert mlp_in_spec == P(None, "model")
    # row-parallel: input dim sharded
    o_spec = blk["attn"]["o"]["w"].sharding.spec
    assert o_spec == P("model", None)
    # norms replicated
    ln_spec = blk["ln1"]["scale"].sharding.spec
    assert ln_spec == P()


def test_sharded_train_step_matches_single_device():
    """dp x tp sharded step computes the same loss as unsharded."""
    model = build_model("vit_tiny")
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(8,))

    mesh = make_mesh(4, 2)
    step, params, opt_state, state = init_sharded_training(model, mesh, seed=0)
    _, _, _, loss_sharded = train_one_step(step, mesh, params, opt_state, state, x, y)

    mesh1 = make_mesh(1, 1, devices=jax.devices()[:1])
    step1, params1, opt1, state1 = init_sharded_training(model, mesh1, seed=0)
    _, _, _, loss_single = train_one_step(step1, mesh1, params1, opt1, state1, x, y)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single), rtol=1e-4)


def test_train_reduces_loss_over_steps():
    model = build_model("vit_tiny")
    mesh = make_mesh(8, 1)
    step, params, opt_state, state = init_sharded_training(
        model, mesh, seed=0, learning_rate=1e-3
    )
    rng = np.random.RandomState(1)
    x = rng.rand(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(16,))
    losses = []
    for _ in range(5):
        params, opt_state, state, loss = train_one_step(
            step, mesh, params, opt_state, state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("n_shard", [2, 4, 8])
def test_ring_attention_exact(n_shard):
    """Ring attention over an n-way sharded sequence == full attention."""
    mesh = make_mesh(n_shard, 1, devices=jax.devices()[:n_shard])
    b, h, s, d = 1, 2, 16 * n_shard, 32
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
        for i in range(3)
    )
    want = attention_reference(q, k, v)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_attention_rejects_indivisible():
    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    q = jnp.zeros((1, 1, 10, 8))
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh)


def test_dryrun_multichip_contract():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
    g.dryrun_multichip(4)
