"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4):
mesh construction, tp param placement, sharded train step, ring attention,
and the driver contract's dryrun."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from storm_tpu.models import build_model
from storm_tpu.ops.attention import attention_reference
from storm_tpu.parallel.mesh import make_mesh
from storm_tpu.parallel.ring_attention import ring_attention
from storm_tpu.parallel.sharding import batch_sharding, shard_params_tp
from storm_tpu.parallel.train import init_sharded_training, train_one_step


def test_make_mesh_shapes():
    m = make_mesh()  # all devices on data axis
    assert m.shape["data"] == 8 and m.shape["model"] == 1
    m2 = make_mesh(4, 2)
    assert m2.shape["data"] == 4 and m2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh(8, 3)
    with pytest.raises(ValueError):
        make_mesh(8, 2)  # 16 > 8


def test_tp_param_placement():
    mesh = make_mesh(4, 2)
    model = build_model("vit_tiny")
    params, _ = model.init(jax.random.PRNGKey(0))
    placed = shard_params_tp(mesh, params)
    blk = placed["blocks"][0]
    # column-parallel: output dim sharded on model axis
    q_spec = blk["attn"]["q"]["w"].sharding.spec
    assert q_spec == P(None, "model")
    mlp_in_spec = blk["mlp_in"]["w"].sharding.spec
    assert mlp_in_spec == P(None, "model")
    # row-parallel: input dim sharded
    o_spec = blk["attn"]["o"]["w"].sharding.spec
    assert o_spec == P("model", None)
    # norms replicated
    ln_spec = blk["ln1"]["scale"].sharding.spec
    assert ln_spec == P()


@pytest.mark.slow
def test_sharded_train_step_matches_single_device():
    """dp x tp sharded step computes the same loss as unsharded."""
    model = build_model("vit_tiny")
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(8,))

    mesh = make_mesh(4, 2)
    step, params, opt_state, state = init_sharded_training(model, mesh, seed=0)
    _, _, _, loss_sharded = train_one_step(step, mesh, params, opt_state, state, x, y)

    mesh1 = make_mesh(1, 1, devices=jax.devices()[:1])
    step1, params1, opt1, state1 = init_sharded_training(model, mesh1, seed=0)
    _, _, _, loss_single = train_one_step(step1, mesh1, params1, opt1, state1, x, y)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single), rtol=1e-4)


@pytest.mark.slow
def test_train_reduces_loss_over_steps():
    model = build_model("vit_tiny")
    mesh = make_mesh(8, 1)
    step, params, opt_state, state = init_sharded_training(
        model, mesh, seed=0, learning_rate=1e-3
    )
    rng = np.random.RandomState(1)
    x = rng.rand(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(16,))
    losses = []
    for _ in range(5):
        params, opt_state, state, loss = train_one_step(
            step, mesh, params, opt_state, state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("n_shard", [2, 4, 8])
def test_ring_attention_exact(n_shard):
    """Ring attention over an n-way sharded sequence == full attention."""
    mesh = make_mesh(n_shard, 1, devices=jax.devices()[:n_shard])
    b, h, s, d = 1, 2, 16 * n_shard, 32
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
        for i in range(3)
    )
    want = attention_reference(q, k, v)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_attention_rejects_indivisible():
    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    q = jnp.zeros((1, 1, 10, 8))
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh)


@pytest.mark.slow
def test_dryrun_multichip_contract():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
    g.dryrun_multichip(4)


# ---- tensor parallelism in the SERVING engine (VERDICT r1 missing #1) --------


def test_serving_engine_tp_shards_params_per_device():
    """tensor_parallel=2 must actually shard serving params across the
    model axis: each device holds ~total/tp of the attention/MLP kernels
    (plus the replicated small leaves), not a full replica. Round 1
    replicated unconditionally (infer/engine.py:159-161) — a model that
    doesn't fit one chip could not be served."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="vit_tiny", input_shape=(32, 32, 3),
                       dtype="float32")
    bcfg = BatchConfig(max_batch=8, buckets=(8,))
    rep = InferenceEngine(mcfg, ShardingConfig(data_parallel=0), bcfg)
    tp = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=4, tensor_parallel=2), bcfg)

    assert tp.tp == 2 and rep.tp == 1
    total = rep.param_bytes()
    assert rep.param_bytes_per_device() == total  # full replica everywhere
    per_dev = tp.param_bytes_per_device()
    # Sharded kernels dominate vit_tiny: per-device must sit well below a
    # full replica and above total/tp (replicated norms/embeddings remain).
    assert per_dev < 0.75 * total, (per_dev, total)
    assert per_dev >= total / 2 * 0.9

    # Sanity on placement: at least one kernel is split on the model axis.
    import jax
    from jax.sharding import PartitionSpec as P

    specs = {s.spec for s in jax.tree.leaves(
        jax.tree.map(lambda a: a.sharding, tp.params))}
    assert P(None, "model") in specs or P("model", None) in specs


def test_serving_engine_tp_output_matches_replicated():
    """TP-sharded serving must be numerically equivalent to the replicated
    engine (same params via fixed seed): XLA's inserted collectives change
    the schedule, not the math."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="vit_tiny", input_shape=(32, 32, 3),
                       dtype="float32", seed=7)
    bcfg = BatchConfig(max_batch=8, buckets=(8,))
    rep = InferenceEngine(mcfg, ShardingConfig(data_parallel=0), bcfg)
    tp = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=4, tensor_parallel=2), bcfg)
    x = np.random.RandomState(0).rand(8, 32, 32, 3).astype(np.float32)
    want = rep.predict(x)
    got = tp.predict(x)
    assert got.shape == want.shape == (8, 10)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_serving_engine_tp_with_int8_weights():
    """w8a16 + TP compose: quantized kernels ({__q,__s}) shard the same way
    (the __q int8 tensor splits on the model axis; scales stay replicated)."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="vit_tiny", input_shape=(32, 32, 3),
                       dtype="float32", seed=7, weights="int8")
    bcfg = BatchConfig(max_batch=8, buckets=(8,))
    tp = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=4, tensor_parallel=2), bcfg)
    rep = InferenceEngine(mcfg, ShardingConfig(data_parallel=0), bcfg)
    x = np.random.RandomState(1).rand(4, 32, 32, 3).astype(np.float32)
    got, want = tp.predict(x), rep.predict(x)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
    assert tp.param_bytes_per_device() < rep.param_bytes_per_device()


# ---- sequence parallelism in the SERVING engine ------------------------------


def test_serving_engine_sp_matches_dense():
    """sequence_parallel=4: the engine serves the long-context family with
    the S axis sharded over a (data, seq) mesh (ring attention on the
    'ICI'); outputs match the dense single-mesh engine."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="longseq_tiny", dtype="float32",
                       input_shape=(64, 16), seed=3)
    bcfg = BatchConfig(max_batch=4, buckets=(4,))
    dense = InferenceEngine(mcfg, ShardingConfig(data_parallel=0), bcfg)
    sp = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=2, sequence_parallel=4), bcfg)
    assert sp.sp == 4
    assert dict(sp.mesh.shape) == {"data": 2, "seq": 4}

    x = np.random.RandomState(0).rand(4, 64, 16).astype(np.float32)
    want = dense.predict(x)
    got = sp.predict(x)
    assert got.shape == want.shape == (4, 10)
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=1e-4)
    np.testing.assert_allclose(got.sum(-1), np.ones(4), atol=1e-4)


def test_serving_engine_sp_rejects_unsupported():
    """SP serving needs an SP-aware model, sp x tp is rejected, and the
    sequence must divide by sp."""
    import pytest as _pytest

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    bcfg = BatchConfig(max_batch=4, buckets=(4,))
    with _pytest.raises(ValueError, match="apply_sp"):
        InferenceEngine(
            ModelConfig(name="lenet5", dtype="float32",
                        input_shape=(28, 28, 1)),
            ShardingConfig(data_parallel=2, sequence_parallel=4), bcfg)
    with _pytest.raises(ValueError, match="mutually exclusive"):
        InferenceEngine(
            ModelConfig(name="longseq_tiny", dtype="float32",
                        input_shape=(64, 16)),
            ShardingConfig(data_parallel=2, sequence_parallel=2,
                           tensor_parallel=2), bcfg)
    with _pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(
            ModelConfig(name="longseq_tiny", dtype="float32",
                        input_shape=(63, 16)),
            ShardingConfig(data_parallel=1, sequence_parallel=4), bcfg)


# ---- expert parallelism in the SERVING engine --------------------------------


def test_serving_engine_ep_shards_experts_and_matches_dense():
    """expert_parallel=4: MoE expert tensors shard their expert dim over
    the (data, expert) mesh — apply is unchanged, GSPMD inserts the
    all-to-alls — and outputs match the replicated engine."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="moe_vit_tiny", dtype="float32",
                       input_shape=(32, 32, 3), seed=5)
    bcfg = BatchConfig(max_batch=4, buckets=(4,))
    # dp matched between the engines: batch padding changes the token
    # count, and capacity-bounded routing (cap = ceil(n/e * cf)) drops
    # different tail tokens at different n — an inherent property of
    # Switch-style MoE, not a sharding effect.
    dense = InferenceEngine(mcfg, ShardingConfig(data_parallel=2), bcfg)
    ep = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=2, expert_parallel=4), bcfg)
    assert ep.ep == 4
    assert dict(ep.mesh.shape) == {"data": 2, "expert": 4}

    # expert tensors actually sharded; everything else replicated
    specs = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(ep.params)[0]:
        keys = [getattr(k, "key", getattr(k, "idx", None)) for k in path]
        specs[tuple(keys)] = leaf.sharding.spec
    moe_w_in = [s for k, s in specs.items() if "moe" in k and k[-1] == "w_in"]
    assert moe_w_in and all(s == P("expert") for s in moe_w_in)
    gate = [s for k, s in specs.items() if "moe" in k and k[-1] == "gate"]
    assert gate and all(s == P() for s in gate)
    assert ep.param_bytes_per_device() < dense.param_bytes_per_device()

    x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
    want = dense.predict(x)
    got = ep.predict(x)
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=1e-4)


def test_serving_engine_parallelism_knobs_mutually_exclusive():
    import pytest as _pytest

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    with _pytest.raises(ValueError, match="mutually exclusive"):
        InferenceEngine(
            ModelConfig(name="moe_vit_tiny", dtype="float32",
                        input_shape=(32, 32, 3)),
            ShardingConfig(data_parallel=2, expert_parallel=2,
                           tensor_parallel=2),
            BatchConfig(max_batch=4, buckets=(4,)))


def test_serving_engine_ep_with_int8_weights():
    """w8a16 + EP compose: the int8 expert tensors shard their expert dim;
    the 1-D per-channel scales replicate; outputs match the replicated
    int8 engine at matched dp."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    mcfg = ModelConfig(name="moe_vit_tiny", dtype="float32",
                       input_shape=(32, 32, 3), seed=5, weights="int8")
    bcfg = BatchConfig(max_batch=4, buckets=(4,))
    dense = InferenceEngine(mcfg, ShardingConfig(data_parallel=2), bcfg)
    ep = InferenceEngine(
        mcfg, ShardingConfig(data_parallel=2, expert_parallel=4), bcfg)
    for path, leaf in jax.tree_util.tree_flatten_with_path(ep.params)[0]:
        keys = [getattr(k, "key", getattr(k, "idx", None)) for k in path]
        if "moe" in keys and "w_in" in keys:
            want = P() if keys[-1] == "__s" else P("expert")
            assert leaf.sharding.spec == want, (keys, leaf.sharding.spec)
    assert ep.param_bytes_per_device() < dense.param_bytes_per_device()
    x = np.random.RandomState(1).rand(4, 32, 32, 3).astype(np.float32)
    np.testing.assert_allclose(ep.predict(x), dense.predict(x),
                               atol=1e-4, rtol=1e-3)


def test_serving_engine_ep_rejects_non_moe_and_indivisible():
    import pytest as _pytest

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    bcfg = BatchConfig(max_batch=4, buckets=(4,))
    with _pytest.raises(ValueError, match="no MoE params"):
        InferenceEngine(
            ModelConfig(name="resnet20", dtype="float32",
                        input_shape=(32, 32, 3)),
            ShardingConfig(data_parallel=1, expert_parallel=4), bcfg)
    with _pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(
            ModelConfig(name="moe_vit_tiny", dtype="float32",
                        input_shape=(32, 32, 3)),
            ShardingConfig(data_parallel=1, expert_parallel=8), bcfg)
