"""In-process Kafka broker stub speaking the real wire protocol over real
sockets — the test double for KafkaWireClient/KafkaWireBroker (SURVEY.md §4:
fake broker for topology tests without external Kafka).

Implements the exact API subset the client uses: Metadata v0, Produce v2,
Fetch v2, ListOffsets v0, FindCoordinator v0, OffsetCommit v2,
OffsetFetch v1. Single-node, message-format v1, no compression."""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from storm_tpu.connectors.kafka_protocol import (
    Reader,
    Writer,
    decode_message_set,
    encode_message_set,
)


class KafkaStubBroker:
    #: serve fetches as record batches (magic 2) instead of message sets —
    #: exercises the client's v2 decode over a real socket
    serve_batches = False

    def __init__(self, partitions: int = 2) -> None:
        self.partitions = partitions
        self._logs: Dict[Tuple[str, int], List[Tuple[Optional[bytes], bytes, float]]] = {}
        self._topics: Dict[str, int] = {}
        self._commits: Dict[Tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._running = True
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    # ---- plumbing ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                head = self._recv(conn, 4)
                if head is None:
                    return
                size = struct.unpack(">i", head)[0]
                data = self._recv(conn, size)
                if data is None:
                    return
                r = Reader(data)
                api_key = r.i16()
                api_version = r.i16()
                corr = r.i32()
                r.string()  # client id
                body = self._dispatch(api_key, api_version, r)
                resp = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (OSError, Exception):
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            c = conn.recv(n - len(buf))
            if not c:
                return None
            buf += c
        return bytes(buf)

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    # ---- state helpers -------------------------------------------------------

    def _ensure(self, topic: str) -> None:
        if topic not in self._topics:
            self._topics[topic] = self.partitions
            for p in range(self.partitions):
                self._logs[(topic, p)] = []

    def topic_size(self, topic: str) -> int:
        with self._lock:
            self._ensure(topic)
            return sum(len(self._logs[(topic, p)]) for p in range(self.partitions))

    # ---- api dispatch --------------------------------------------------------

    def _dispatch(self, api: int, version: int, r: Reader) -> bytes:
        if api == 3:
            return self._metadata(r)
        if api == 0:
            return self._produce(r, version)
        if api == 1:
            return self._fetch(r)
        if api == 2:
            return self._list_offsets(r)
        if api == 10:
            return self._find_coordinator(r)
        if api == 8:
            return self._offset_commit(r)
        if api == 9:
            return self._offset_fetch(r)
        raise RuntimeError(f"stub does not implement api {api}")

    def _metadata(self, r: Reader) -> bytes:
        n = r.i32()
        topics = [r.string() for _ in range(n)]
        with self._lock:
            for t in topics:
                self._ensure(t)
            listing = {t: self._topics[t] for t in (topics or self._topics)}
        w = Writer()
        w.i32(1)  # one broker
        w.i32(0).string("127.0.0.1").i32(self.port)
        w.i32(len(listing))
        for t, nparts in listing.items():
            w.i16(0).string(t)
            w.i32(nparts)
            for p in range(nparts):
                w.i16(0).i32(p).i32(0)  # leader node 0
                w.i32(1).i32(0)  # replicas
                w.i32(1).i32(0)  # isr
        return bytes(w.buf)

    def _produce(self, r: Reader, version: int = 2) -> bytes:
        if version >= 3:
            r.string()  # transactional_id (KIP-98)
        r.i16()  # acks
        r.i32()  # timeout
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                data = r.bytes_() or b""
                records = decode_message_set(topic, pid, data)
                with self._lock:
                    self._ensure(topic)
                    log = self._logs[(topic, pid)]
                    base = len(log)
                    for rec in records:
                        log.append((rec.key, rec.value, time.time()))
                w.i32(pid).i16(0).i64(base).i64(-1)
        w.i32(0)  # throttle
        return bytes(w.buf)

    def _fetch(self, r: Reader) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        w = Writer()
        w.i32(0)  # throttle
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                offset = r.i64()
                r.i32()  # max bytes
                with self._lock:
                    self._ensure(topic)
                    log = self._logs[(topic, pid)]
                    chunk = log[offset : offset + 256]
                    hw = len(log)
                if self.serve_batches and chunk:
                    from storm_tpu.connectors.kafka_protocol import (
                        encode_record_batch,
                    )

                    msgset = encode_record_batch(
                        [(k, v) for k, v, _ in chunk],
                        int(time.time() * 1e3),
                        base_offset=offset,
                    )
                else:
                    msgset = encode_message_set(
                        [(k, v) for k, v, _ in chunk],
                        int(time.time() * 1e3),
                        offsets=list(range(offset, offset + len(chunk))),
                    )
                w.i32(pid).i16(0).i64(hw)
                w.bytes_(msgset)
        return bytes(w.buf)

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()  # replica
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                ts = r.i64()
                r.i32()  # max offsets
                with self._lock:
                    self._ensure(topic)
                    end = len(self._logs[(topic, pid)])
                off = 0 if ts == -2 else end
                w.i32(pid).i16(0)
                w.i32(1).i64(off)
        return bytes(w.buf)

    def _find_coordinator(self, r: Reader) -> bytes:
        r.string()  # group
        w = Writer()
        w.i16(0)
        w.i32(0).string("127.0.0.1").i32(self.port)
        return bytes(w.buf)

    def _offset_commit(self, r: Reader) -> bytes:
        group = r.string()
        r.i32()  # generation
        r.string()  # member
        r.i64()  # retention
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                off = r.i64()
                r.string()  # metadata
                with self._lock:
                    self._commits[(group, topic, pid)] = off
                w.i32(pid).i16(0)
        return bytes(w.buf)

    def _offset_fetch(self, r: Reader) -> bytes:
        group = r.string()
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                with self._lock:
                    off = self._commits.get((group, topic, pid), -1)
                w.i32(pid).i64(off).string(None).i16(0)
        return bytes(w.buf)
