"""In-process Kafka broker stub speaking the real wire protocol over real
sockets — the test double for KafkaWireClient/KafkaWireBroker (SURVEY.md §4:
fake broker for topology tests without external Kafka).

Implements the exact API subset the client uses: Metadata v0, Produce
v2/v3 (message sets and KIP-98 record batches, gzip included), Fetch v2
(optionally serving magic-2 batches via ``serve_batches``), ListOffsets
v0, FindCoordinator v0, OffsetCommit v2, OffsetFetch v1, and
consumer-group coordination — JoinGroup/SyncGroup/Heartbeat/LeaveGroup v0
with immediate-join semantics and session-timeout expiry of dead members.
Single node."""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from storm_tpu.connectors.kafka_protocol import (
    Reader,
    Writer,
    decode_message_set,
    encode_message_set,
)


class KafkaStubBroker:
    #: serve fetches as record batches (magic 2) instead of message sets —
    #: exercises the client's v2 decode over a real socket
    serve_batches = False

    #: ApiVersions (api 18) behavior: None = advertise every version the
    #: client pins (compatible broker); a dict {api: (min, max)} simulates
    #: a broker with a different surface (e.g. post-KIP-896 removals);
    #: "closed" = hang up on the probe like a pre-0.10 broker.
    api_versions: "dict | str | None" = None

    #: answer idempotent duplicates with DUPLICATE_SEQUENCE_NUMBER (46)
    #: instead of silently acking with the original offset
    duplicate_error = False

    #: SASL/PLAIN: set to ("user", "password") to require the 0.11-era
    #: handshake (Kafka-framed SaslHandshake api 17, then RAW
    #: length-prefixed tokens) before any other API on the connection;
    #: wrong credentials close the socket like a real broker.
    sasl: "tuple | None" = None

    #: SASL mechanisms the stub advertises/accepts: "PLAIN" (default) or
    #: "SCRAM-SHA-256"/"SCRAM-SHA-512" (full RFC 5802 server exchange,
    #: proof verified via StoredKey, server signature returned).
    sasl_mechanism = "PLAIN"

    #: SCRAM PBKDF2 iteration count the stub requests (lower it to test
    #: the client's RFC 7677 downgrade refusal).
    scram_iterations = 4096

    #: SSL: an ssl.SSLContext to wrap accepted connections with (combine
    #: with ``sasl`` for SASL_SSL).
    ssl_context = None

    #: True = REAL-broker transactional log semantics: transactional
    #: records append to the log immediately (tagged with their producer
    #: id) and EndTxn appends a control marker, occupying an offset —
    #: read_uncommitted fetches see everything, Fetch v4 read_committed
    #: clients filter via the aborted_transactions ranges the stub
    #: reports. Default False keeps the simpler buffer-until-commit model
    #: the rest of the suite uses (nothing visible before commit).
    log_transactional = False

    def __init__(self, partitions: int = 2, nodes: int = 1) -> None:
        """``nodes > 1`` runs extra listeners that share ALL state (logs,
        groups, transactions) but have distinct node ids/ports — enough to
        move a partition leader or the coordinator mid-stream and exercise
        the client's election-survival path: a non-leader node answers
        produce/fetch/list_offsets with NOT_LEADER_FOR_PARTITION (6) and a
        non-coordinator node answers group/txn RPCs with NOT_COORDINATOR
        (16), exactly like a real broker after the metadata moved."""
        self.partitions = partitions
        self.nodes = nodes
        #: (topic, partition) -> leader node id (missing = node 0)
        self._leaders: Dict[Tuple[str, int], int] = {}
        #: node answering group + txn coordinator RPCs
        self._coord_node = 0
        self._logs: Dict[Tuple[str, int], List[Tuple[Optional[bytes], bytes, float]]] = {}
        self._topics: Dict[str, int] = {}
        self._commits: Dict[Tuple[str, str, int], int] = {}
        # consumer groups: group -> {"generation", "members": {member_id:
        # metadata}, "leader", "assignments": {member_id: bytes},
        # "stable": set(member ids that joined the current generation)}
        self._groups: Dict[str, dict] = {}
        self._member_seq = 0
        # KIP-98 idempotence: allocated producer ids and, per
        # (pid, topic, partition), the last accepted (base_seq, count,
        # base_offset) for duplicate/out-of-order detection.
        self._next_pid = 1000
        self._pid_state: Dict[Tuple[int, str, int], Tuple[int, int, int]] = {}
        # Transactions (KIP-98): txn_id -> {"pid", "epoch", "pending":
        # [(topic, part, key, value)], "parts": set}. Produced transactional
        # batches buffer in "pending" and append at EndTxn(commit) — i.e.
        # read-committed visibility; abort drops them. Re-InitProducerId on
        # the same txn_id bumps the epoch (zombie fencing).
        self._txns: Dict[str, dict] = {}
        # log_transactional mode: per-(topic, partition) list of
        # (producer_id, first_offset, marker_offset) for ABORTED
        # transactions; Fetch v4 reports (pid, first_offset) for ranges
        # whose ABORT marker lies within/after the fetched region — a
        # range whose marker precedes the fetch offset is history (its
        # aborted data can't appear in the response), and reporting it
        # would wrongly re-activate the producer and drop its later
        # committed records.
        self._aborted: Dict[Tuple[str, int], List[Tuple[int, int, int]]] = {}
        self._lock = threading.Lock()
        self._socks: List[socket.socket] = []
        self.ports: List[int] = []
        self._running = True
        self._threads: List[threading.Thread] = []
        for node in range(nodes):
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("127.0.0.1", 0))
            sock.listen(16)
            self._socks.append(sock)
            self.ports.append(sock.getsockname()[1])
            t = threading.Thread(target=self._accept_loop,
                                 args=(sock, node), daemon=True)
            t.start()
            self._threads.append(t)
        self.port = self.ports[0]

    # ---- leadership / coordinator moves (election simulation) ----------------

    def move_leader(self, topic: str, partition: int, node: int) -> None:
        with self._lock:
            self._ensure(topic)
            self._leaders[(topic, partition)] = node

    def move_coordinator(self, node: int) -> None:
        with self._lock:
            self._coord_node = node

    # ---- plumbing ------------------------------------------------------------

    def _accept_loop(self, sock: socket.socket, node: int) -> None:
        while self._running:
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn, node),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket, node: int = 0) -> None:
        try:
            if self.ssl_context is not None:
                conn = self.ssl_context.wrap_socket(conn, server_side=True)
            authed = self.sasl is None
            while True:
                head = self._recv(conn, 4)
                if head is None:
                    return
                size = struct.unpack(">i", head)[0]
                data = self._recv(conn, size)
                if data is None:
                    return
                r = Reader(data)
                api_key = r.i16()
                api_version = r.i16()
                corr = r.i32()
                r.string()  # client id
                if not authed:
                    if api_key != 17:
                        return  # real brokers drop pre-auth requests
                    mech = r.string()
                    ok = mech == self.sasl_mechanism
                    w = Writer()
                    w.i16(0 if ok else 33)  # UNSUPPORTED_SASL_MECHANISM
                    w.i32(1).string(self.sasl_mechanism)
                    resp = struct.pack(">i", corr) + bytes(w.buf)
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
                    if not ok:
                        return
                    if mech == "PLAIN":
                        # raw (pre-KIP-152) token frame: \0user\0password
                        token = self._recv_token(conn)
                        parts = (token or b"").split(b"\x00")
                        if (len(parts) != 3
                                or parts[1].decode() != self.sasl[0]
                                or parts[2].decode() != self.sasl[1]):
                            return  # auth failure: close, like a real broker
                        conn.sendall(struct.pack(">i", 0))  # empty token
                    else:
                        if not self._scram_serve(conn, mech):
                            return
                    authed = True
                    continue
                body = self._dispatch(api_key, api_version, r, node)
                resp = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (OSError, Exception):
            pass
        finally:
            conn.close()

    @classmethod
    def _recv_token(cls, conn: socket.socket) -> Optional[bytes]:
        head = cls._recv(conn, 4)
        if head is None:
            return None
        return cls._recv(conn, struct.unpack(">i", head)[0])

    def _scram_serve(self, conn: socket.socket, mech: str) -> bool:
        """RFC 5802 server side over raw token frames: verify the client
        proof against StoredKey, return the server signature. False =
        auth failure (caller closes, like a real broker)."""
        import base64
        import hashlib
        import hmac as hmac_mod
        import os

        algo = mech.replace("SCRAM-SHA-", "sha")

        def hm(key: bytes, data: bytes) -> bytes:
            return hmac_mod.new(key, data, algo).digest()

        first = self._recv_token(conn)
        if first is None or not first.startswith(b"n,,"):
            return False
        first_bare = first[3:].decode()
        f = dict(kv.split("=", 1) for kv in first_bare.split(","))
        user = f["n"].replace("=2C", ",").replace("=3D", "=")
        if user != self.sasl[0]:
            return False
        salt, iterations = os.urandom(12), self.scram_iterations
        snonce = f["r"] + base64.b64encode(os.urandom(12)).decode()
        server_first = (f"r={snonce},s={base64.b64encode(salt).decode()},"
                        f"i={iterations}")
        conn.sendall(struct.pack(">i", len(server_first))
                     + server_first.encode())
        final = self._recv_token(conn)
        if final is None:
            return False
        ff = dict(kv.split("=", 1) for kv in final.decode().split(","))
        if ff.get("c") != "biws" or ff.get("r") != snonce or "p" not in ff:
            return False
        salted = hashlib.pbkdf2_hmac(
            algo, self.sasl[1].encode(), salt, iterations)
        stored_key = hashlib.new(algo, hm(salted, b"Client Key")).digest()
        final_wo = final.decode().rsplit(",p=", 1)[0]
        auth_msg = ",".join((first_bare, server_first, final_wo)).encode()
        signature = hm(stored_key, auth_msg)
        client_key = bytes(a ^ b for a, b in zip(
            base64.b64decode(ff["p"]), signature))
        if hashlib.new(algo, client_key).digest() != stored_key:
            return False  # wrong password
        v = base64.b64encode(hm(hm(salted, b"Server Key"), auth_msg))
        server_final = b"v=" + v
        conn.sendall(struct.pack(">i", len(server_final)) + server_final)
        return True

    @staticmethod
    def _recv(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            c = conn.recv(n - len(buf))
            if not c:
                return None
            buf += c
        return bytes(buf)

    def close(self) -> None:
        self._running = False
        for sock in self._socks:
            try:
                sock.close()
            except OSError:
                pass

    # ---- state helpers -------------------------------------------------------

    def _ensure(self, topic: str) -> None:
        if topic not in self._topics:
            self._topics[topic] = self.partitions
            for p in range(self.partitions):
                self._logs[(topic, p)] = []

    def topic_size(self, topic: str) -> int:
        with self._lock:
            self._ensure(topic)
            return sum(len(self._logs[(topic, p)]) for p in range(self.partitions))

    # ---- api dispatch --------------------------------------------------------

    def _dispatch(self, api: int, version: int, r: Reader,
                  node: int = 0) -> bytes:
        if api == 18:
            return self._api_versions(r)
        if api == 3:
            return self._metadata(r)
        if api == 0:
            return self._produce(r, version, node)
        if api == 1:
            return self._fetch(r, version, node)
        if api == 2:
            return self._list_offsets(r, node)
        if api == 10:
            return self._find_coordinator(r, version)
        # Coordinator-owned RPCs: a node that is NOT the coordinator
        # answers NOT_COORDINATOR (16) in the API's error slot, like a
        # real broker after the coordinator moved.
        not_coord = node != self._coord_node
        if api == 8:
            return self._offset_commit(r, err_override=16 if not_coord else 0)
        if api == 9:
            return self._offset_fetch(r, err_override=16 if not_coord else 0)
        if api == 11:
            if not_coord:  # JoinGroup v0 error shape
                return bytes(Writer().i16(16).i32(-1).string("")
                             .string("").string("").i32(0).buf)
            return self._join_group(r)
        if api == 14:
            if not_coord:  # SyncGroup v0 error shape
                return bytes(Writer().i16(16).bytes_(b"").buf)
            return self._sync_group(r)
        if api == 12:
            if not_coord:
                return bytes(Writer().i16(16).buf)
            return self._heartbeat(r)
        if api == 13:
            if not_coord:
                return bytes(Writer().i16(16).buf)
            return self._leave_group(r)
        if api == 22:
            return self._init_producer_id(r) if not not_coord \
                else bytes(Writer().i32(0).i16(16).i64(-1).i16(-1).buf)
        if api == 24:
            return self._add_partitions_to_txn(r, err_override=16) \
                if not_coord else self._add_partitions_to_txn(r)
        if api == 25:
            return self._add_offsets_to_txn(r) if not not_coord \
                else bytes(Writer().i32(0).i16(16).buf)
        if api == 26:
            return self._end_txn(r) if not not_coord \
                else bytes(Writer().i32(0).i16(16).buf)
        if api == 28:
            return self._txn_offset_commit(r, err_override=16) \
                if not_coord else self._txn_offset_commit(r)
        raise RuntimeError(f"stub does not implement api {api}")


    def _api_versions(self, r: Reader) -> bytes:
        if self.api_versions == "closed":
            raise OSError("simulated pre-0.10 broker: hang up on probe")
        err = 0
        if self.api_versions is None:
            from storm_tpu.connectors.kafka_protocol import PINNED_API_VERSIONS
            ranges = {key: (min(vs), max(vs))
                      for key, (_n, vs) in PINNED_API_VERSIONS.items()}
            ranges[18] = (0, 0)
        elif (isinstance(self.api_versions, tuple)
              and self.api_versions[0] == "error35"):
            # KIP-511-era behavior: the broker rejects the request version
            # with UNSUPPORTED_VERSION but still advertises what it serves.
            err, ranges = 35, self.api_versions[1]
        else:
            ranges = self.api_versions
        w = Writer()
        w.i16(err)
        w.i32(len(ranges))
        for key, (lo, hi) in sorted(ranges.items()):
            w.i16(key).i16(lo).i16(hi)
        return bytes(w.buf)

    def _metadata(self, r: Reader) -> bytes:
        n = r.i32()
        topics = [r.string() for _ in range(n)]
        with self._lock:
            for t in topics:
                self._ensure(t)
            listing = {t: self._topics[t] for t in (topics or self._topics)}
        w = Writer()
        w.i32(self.nodes)
        for node in range(self.nodes):
            w.i32(node).string("127.0.0.1").i32(self.ports[node])
        w.i32(len(listing))
        for t, nparts in listing.items():
            w.i16(0).string(t)
            w.i32(nparts)
            for p in range(nparts):
                leader = self._leaders.get((t, p), 0)
                w.i16(0).i32(p).i32(leader)
                w.i32(1).i32(leader)  # replicas
                w.i32(1).i32(leader)  # isr
        return bytes(w.buf)

    def _init_producer_id(self, r: Reader) -> bytes:
        txn_id = r.string()
        r.i32()  # timeout_ms
        with self._lock:
            if txn_id is None:
                pid, epoch = self._next_pid, 0
                self._next_pid += 1
            else:
                st = self._txns.get(txn_id)
                if st is None:
                    st = {"pid": self._next_pid, "epoch": 0,
                          "pending": [], "parts": set(),
                          "pending_offsets": {}, "offset_groups": set()}
                    self._next_pid += 1
                    self._txns[txn_id] = st
                else:
                    # fencing: bump epoch, drop any half-open transaction
                    # (log_transactional mode: the fenced txn's appended
                    # records become an implicit abort range + marker,
                    # like a real coordinator's bumpEpoch abort)
                    if self.log_transactional:
                        for (topic, part), first in \
                                st.get("first", {}).items():
                            self._aborted.setdefault(
                                (topic, part), []).append(
                                    (st["pid"], first,
                                     len(self._logs[(topic, part)])))
                            self._logs[(topic, part)].append(
                                ("c", 0, time.time(), st["pid"]))
                    st["epoch"] += 1
                    st["pending"] = []
                    st["parts"] = set()
                    st["pending_offsets"] = {}
                    st["offset_groups"] = set()
                    st["first"] = {}
                pid, epoch = st["pid"], st["epoch"]
        w = Writer()
        w.i32(0).i16(0).i64(pid).i16(epoch)  # throttle, err, pid, epoch
        return bytes(w.buf)

    def _txn_check(self, txn_id, pid, epoch):
        """error code for a txn RPC: 48 INVALID_TXN_STATE if unknown,
        47 INVALID_PRODUCER_EPOCH if fenced."""
        st = self._txns.get(txn_id)
        if st is None or st["pid"] != pid:
            return None, 48
        if st["epoch"] != epoch:
            return None, 47
        return st, 0

    def _add_partitions_to_txn(self, r: Reader,
                               err_override: int = 0) -> bytes:
        txn_id = r.string()
        pid = r.i64()
        epoch = r.i16()
        topics = []
        for _ in range(r.i32()):
            t = r.string()
            for _ in range(r.i32()):
                topics.append((t, r.i32()))
        w = Writer()
        w.i32(0)  # throttle
        if err_override:
            err = err_override
        else:
            with self._lock:
                st, err = self._txn_check(txn_id, pid, epoch)
                if not err:
                    st["parts"].update(topics)
        by_topic: Dict[str, List[int]] = {}
        for t, p in topics:
            by_topic.setdefault(t, []).append(p)
        w.i32(len(by_topic))
        for t, ps in by_topic.items():
            w.string(t)
            w.i32(len(ps))
            for p in ps:
                w.i32(p).i16(err)
        return bytes(w.buf)

    def _add_offsets_to_txn(self, r: Reader) -> bytes:
        """AddOffsetsToTxn v0: register a group with the transaction; the
        group's TxnOffsetCommit offsets then land atomically at EndTxn."""
        txn_id = r.string()
        pid = r.i64()
        epoch = r.i16()
        group = r.string()
        with self._lock:
            st, err = self._txn_check(txn_id, pid, epoch)
            if not err:
                st["offset_groups"].add(group)
        w = Writer()
        w.i32(0).i16(err)  # throttle, error
        return bytes(w.buf)

    def _txn_offset_commit(self, r: Reader, err_override: int = 0) -> bytes:
        """TxnOffsetCommit v0: stage offsets inside the open transaction —
        visible in OffsetFetch only after EndTxn(commit)."""
        txn_id = r.string()
        group = r.string()
        pid = r.i64()
        epoch = r.i16()
        staged: List[Tuple[str, int, int]] = []
        w = Writer()
        w.i32(0)  # throttle
        n_topics = r.i32()
        w.i32(n_topics)
        with self._lock:
            if err_override:
                st, err = None, err_override
            else:
                st, err = self._txn_check(txn_id, pid, epoch)
                if not err and group not in st["offset_groups"]:
                    err = 48  # group not registered via AddOffsetsToTxn
            for _ in range(n_topics):
                topic = r.string()
                w.string(topic)
                n_parts = r.i32()
                w.i32(n_parts)
                for _ in range(n_parts):
                    part = r.i32()
                    off = r.i64()
                    r.string()  # metadata
                    if not err:
                        staged.append((topic, part, off))
                    w.i32(part).i16(err)
            if not err:
                for topic, part, off in staged:
                    st["pending_offsets"][(group, topic, part)] = off
        return bytes(w.buf)

    def _end_txn(self, r: Reader) -> bytes:
        txn_id = r.string()
        pid = r.i64()
        epoch = r.i16()
        commit = bool(r.i8())
        with self._lock:
            st, err = self._txn_check(txn_id, pid, epoch)
            if not err:
                if self.log_transactional:
                    # real-broker semantics: a control marker per touched
                    # partition, occupying one offset; aborts register the
                    # (pid, first_offset) range for Fetch v4 filtering
                    for (topic, part) in sorted(st["parts"]):
                        self._ensure(topic)
                        log = self._logs[(topic, part)]
                        first = st.get("first", {}).get((topic, part))
                        if not commit and first is not None:
                            self._aborted.setdefault(
                                (topic, part), []).append(
                                    (pid, first, len(log)))
                        log.append(("c", 1 if commit else 0,
                                    time.time(), pid))
                    st["first"] = {}
                elif commit:
                    for topic, part, key, value in st["pending"]:
                        self._ensure(topic)
                        self._logs[(topic, part)].append(
                            (key, value, time.time()))
                if commit:
                    # offsets land atomically with the records (KIP-98:
                    # the commit marker covers __consumer_offsets too)
                    for (group, topic, part), off in \
                            st["pending_offsets"].items():
                        self._commits[(group, topic, part)] = off
                st["pending"] = []
                st["parts"] = set()
                st["pending_offsets"] = {}
                st["offset_groups"] = set()
        w = Writer()
        w.i32(0).i16(err)
        return bytes(w.buf)

    @staticmethod
    def _batch_producer_fields(data: bytes):
        """(producer_id, base_sequence, record_count) of a magic-2 batch,
        or None for v0/v1 message sets / non-idempotent batches."""
        # baseOffset(8) len(4) leaderEpoch(4) magic(1) crc(4) attrs(2)
        # lastOffsetDelta(4) baseTs(8) maxTs(8) pid(8) epoch(2) baseSeq(4)
        # count(4)
        if len(data) < 61 or data[16] != 2:
            return None
        prod_id, = struct.unpack(">q", data[43:51])
        if prod_id < 0:
            return None
        epoch, = struct.unpack(">h", data[51:53])
        base_seq, = struct.unpack(">i", data[53:57])
        count, = struct.unpack(">i", data[57:61])
        return prod_id, base_seq, count, epoch

    def _produce(self, r: Reader, version: int = 2, node: int = 0) -> bytes:
        txn_id = r.string() if version >= 3 else None
        r.i16()  # acks
        r.i32()  # timeout
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                data = r.bytes_() or b""
                if self._leaders.get((topic, pid), 0) != node:
                    w.i32(pid).i16(6).i64(-1).i64(-1)  # NOT_LEADER
                    continue
                prod = self._batch_producer_fields(data)
                err = 0
                with self._lock:
                    self._ensure(topic)
                    log = self._logs[(topic, pid)]
                    base = len(log)
                    if txn_id is not None:
                        # transactional: buffer until EndTxn(commit) — or,
                        # in log_transactional mode, append immediately
                        # tagged with the producer id (real-broker
                        # semantics; visibility is the CONSUMER's job)
                        st = self._txns.get(txn_id)
                        p_pid, _, _, p_epoch = prod if prod else (
                            -1, -1, -1, -1)
                        if st is None or st["pid"] != p_pid:
                            err = 48  # INVALID_TXN_STATE
                        elif st["epoch"] != p_epoch:
                            err = 47  # INVALID_PRODUCER_EPOCH (fenced)
                        elif (topic, pid) not in st["parts"]:
                            err = 48  # partition not added to the txn
                        elif self.log_transactional:
                            st.setdefault("first", {}).setdefault(
                                (topic, pid), len(log))
                            for rec in decode_message_set(topic, pid, data):
                                log.append(("d", rec.key, rec.value,
                                            time.time(), p_pid))
                        else:
                            for rec in decode_message_set(topic, pid, data):
                                st["pending"].append(
                                    (topic, pid, rec.key, rec.value))
                        data = b""
                    elif prod is not None:
                        prod_id, base_seq, count, _ = prod
                        key = (prod_id, topic, pid)
                        last = self._pid_state.get(key)
                        expected = 0 if last is None else last[0] + last[1]
                        if last is not None and base_seq == last[0]:
                            # exact duplicate of the last batch: already
                            # appended; ack with the original base offset
                            # (or, in duplicate_error mode, answer the
                            # explicit DUPLICATE_SEQUENCE_NUMBER code some
                            # 0.11-era paths return — the client must
                            # treat BOTH as success)
                            if self.duplicate_error:
                                err = 46
                            base = last[2]
                            data = b""
                        elif base_seq != expected:
                            err = 45  # OUT_OF_ORDER_SEQUENCE_NUMBER
                            data = b""
                        else:
                            self._pid_state[key] = (base_seq, count, base)
                    if data:
                        for rec in decode_message_set(topic, pid, data):
                            if self.log_transactional:
                                # uniform tagged entries in this mode
                                # (pid -1 = non-transactional data)
                                log.append(("d", rec.key, rec.value,
                                            time.time(), -1))
                            else:
                                log.append((rec.key, rec.value, time.time()))
                w.i32(pid).i16(err).i64(base).i64(-1)
        w.i32(0)  # throttle
        return bytes(w.buf)

    @staticmethod
    def _encode_tagged(chunk, offset: int) -> bytes:
        """log_transactional entries -> record batches: consecutive data
        records from one producer share a batch; control markers get their
        own control batch (exactly the shapes a real broker serves)."""
        from storm_tpu.connectors.kafka_protocol import (
            encode_control_batch, encode_record_batch)

        out = bytearray()
        i = 0
        now_ms = int(time.time() * 1e3)
        while i < len(chunk):
            entry = chunk[i]
            if entry[0] == "c":
                out += encode_control_batch(entry[1], (entry[3], 0),
                                            offset + i, now_ms)
                i += 1
                continue
            run = [entry]
            while (i + len(run) < len(chunk)
                   and chunk[i + len(run)][0] == "d"
                   and chunk[i + len(run)][4] == entry[4]):
                run.append(chunk[i + len(run)])
            prod_id = entry[4]
            out += encode_record_batch(
                [(e[1], e[2]) for e in run], now_ms,
                base_offset=offset + i,
                producer=(prod_id, 0, 0) if prod_id >= 0 else None,
                transactional=prod_id >= 0)
            i += len(run)
        return bytes(out)

    def _fetch(self, r: Reader, version: int = 2, node: int = 0) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        if version >= 3:
            r.i32()  # response-level max_bytes
        isolation = 0
        if version >= 4:
            isolation = r.i8()
        w = Writer()
        w.i32(0)  # throttle
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                offset = r.i64()
                r.i32()  # max bytes
                if self._leaders.get((topic, pid), 0) != node:
                    w.i32(pid).i16(6).i64(-1)  # NOT_LEADER
                    if version >= 4:
                        w.i64(-1).i32(0)
                    w.bytes_(b"")
                    continue
                with self._lock:
                    self._ensure(topic)
                    log = self._logs[(topic, pid)]
                    hw = len(log)
                    # LSO = first offset of any OPEN transaction (real
                    # brokers never serve read_committed past it)
                    lso = hw
                    for st in self._txns.values():
                        first = st.get("first", {}).get((topic, pid))
                        if first is not None:
                            lso = min(lso, first)
                    end = min(offset + 256, lso) if isolation == 1 else \
                        offset + 256
                    chunk = log[offset:end]
                    aborted = [
                        (a_pid, first)
                        for a_pid, first, marker in
                        self._aborted.get((topic, pid), [])
                        if marker >= offset
                    ]
                tagged = bool(chunk) and len(chunk[0]) >= 4
                if tagged:
                    msgset = self._encode_tagged(chunk, offset)
                elif self.serve_batches and chunk:
                    from storm_tpu.connectors.kafka_protocol import (
                        encode_record_batch,
                    )

                    msgset = encode_record_batch(
                        [(k, v) for k, v, _ in chunk],
                        int(time.time() * 1e3),
                        base_offset=offset,
                    )
                else:
                    msgset = encode_message_set(
                        [(k, v) for k, v, _ in chunk],
                        int(time.time() * 1e3),
                        offsets=list(range(offset, offset + len(chunk))),
                    )
                w.i32(pid).i16(0).i64(hw)
                if version >= 4:
                    w.i64(lso)  # last stable offset
                    w.i32(len(aborted))
                    for a_pid, first in aborted:
                        w.i64(a_pid).i64(first)
                w.bytes_(msgset)
        return bytes(w.buf)

    def _list_offsets(self, r: Reader, node: int = 0) -> bytes:
        r.i32()  # replica
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                ts = r.i64()
                r.i32()  # max offsets
                if self._leaders.get((topic, pid), 0) != node:
                    w.i32(pid).i16(6).i32(0)  # NOT_LEADER, no offsets
                    continue
                with self._lock:
                    self._ensure(topic)
                    end = len(self._logs[(topic, pid)])
                off = 0 if ts == -2 else end
                w.i32(pid).i16(0)
                w.i32(1).i64(off)
        return bytes(w.buf)

    def _find_coordinator(self, r: Reader, version: int = 0) -> bytes:
        r.string()  # group / transactional id
        w = Writer()
        if version >= 1:
            r.i8()  # coordinator_type (group=0 / txn=1 — same node here)
            w.i32(0)  # throttle
            w.i16(0)  # error
            w.string(None)  # error_message
        else:
            w.i16(0)
        coord = self._coord_node
        w.i32(coord).string("127.0.0.1").i32(self.ports[coord])
        return bytes(w.buf)

    def _offset_commit(self, r: Reader, err_override: int = 0) -> bytes:
        group = r.string()
        r.i32()  # generation
        r.string()  # member
        r.i64()  # retention
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                off = r.i64()
                r.string()  # metadata
                if not err_override:
                    with self._lock:
                        self._commits[(group, topic, pid)] = off
                w.i32(pid).i16(err_override)
        return bytes(w.buf)

    def _offset_fetch(self, r: Reader, err_override: int = 0) -> bytes:
        group = r.string()
        w = Writer()
        n_topics = r.i32()
        w.i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            w.string(topic)
            n_parts = r.i32()
            w.i32(n_parts)
            for _ in range(n_parts):
                pid = r.i32()
                if err_override:
                    w.i32(pid).i64(-1).string(None).i16(err_override)
                    continue
                with self._lock:
                    off = self._commits.get((group, topic, pid), -1)
                w.i32(pid).i64(off).string(None).i16(0)
        return bytes(w.buf)

    # ---- consumer-group coordination (JoinGroup/SyncGroup/Heartbeat/Leave) ---
    # v0 request formats; "immediate join" semantics: a join bumps the
    # generation and existing members discover via REBALANCE_IN_PROGRESS
    # heartbeats, then rejoin — the real protocol flow without the broker's
    # join-window timers.

    _REBALANCE_IN_PROGRESS = 27
    _ILLEGAL_GENERATION = 22
    _UNKNOWN_MEMBER = 25

    def _group(self, gid: str) -> dict:
        g = self._groups.setdefault(gid, {
            "generation": 0, "members": {}, "leader": None,
            "assignments": {}, "stable": set(), "deadlines": {},
            "sessions": {},
        })
        # expire members that vanished without leave(): a dead member must
        # not wedge the group in permanent rebalance
        now = time.time()
        dead = [m for m, dl in g.get("deadlines", {}).items() if dl < now]
        for m in dead:
            g["members"].pop(m, None)
            g["stable"].discard(m)
            g["assignments"].pop(m, None)
            g["deadlines"].pop(m, None)
            g["sessions"].pop(m, None)
            if g["leader"] == m:
                g["leader"] = next(iter(g["members"]), None)
        if dead and g["members"]:
            g["generation"] += 1
            g["stable"] = set()
            g["assignments"] = {}
        return g

    def _join_group(self, r: Reader) -> bytes:
        gid = r.string()
        session_ms = r.i32()
        member = r.string() or ""
        r.string()  # protocol_type
        protos = []
        for _ in range(r.i32()):
            protos.append((r.string(), r.bytes_() or b""))
        with self._lock:
            g = self._group(gid)
            if not member:
                self._member_seq += 1
                member = f"member-{self._member_seq}"
            fresh = member not in g["members"]
            was_stable = g["members"] and g["stable"] == set(g["members"])
            g["members"][member] = protos[0][1] if protos else b""
            if fresh or was_stable:
                # a NEW member, or a stable member voluntarily rejoining,
                # starts a rebalance; rejoins DURING a rebalance just count
                # toward completion (bumping again would livelock)
                g["generation"] += 1
                g["stable"] = {member}
                g["assignments"] = {}
            else:
                g["stable"].add(member)
            g["sessions"][member] = session_ms / 1e3
            g["deadlines"][member] = time.time() + session_ms / 1e3
            if g["leader"] not in g["members"]:
                g["leader"] = member
            leader = g["leader"]
            gen = g["generation"]
            members = dict(g["members"]) if member == leader else {}
            proto_name = protos[0][0] if protos else "range"
        w = Writer()
        w.i16(0).i32(gen).string(proto_name).string(leader).string(member)
        w.i32(len(members))
        for mid, meta in members.items():
            w.string(mid)
            w.bytes_(meta)
        return bytes(w.buf)

    def _sync_group(self, r: Reader) -> bytes:
        gid = r.string()
        gen = r.i32()
        member = r.string()
        assignments = {}
        for _ in range(r.i32()):
            mid = r.string()
            assignments[mid] = r.bytes_() or b""
        w = Writer()
        with self._lock:
            g = self._group(gid)
            if member not in g["members"]:
                w.i16(self._UNKNOWN_MEMBER).bytes_(b"")
                return bytes(w.buf)
            if gen != g["generation"]:
                w.i16(self._ILLEGAL_GENERATION).bytes_(b"")
                return bytes(w.buf)
            if assignments:  # the leader distributes
                g["assignments"] = assignments
            g["stable"].add(member)
            mine = g["assignments"].get(member)
        if mine is None:
            w.i16(self._REBALANCE_IN_PROGRESS).bytes_(b"")
        else:
            w.i16(0).bytes_(mine)
        return bytes(w.buf)

    def _heartbeat(self, r: Reader) -> bytes:
        gid = r.string()
        gen = r.i32()
        member = r.string()
        w = Writer()
        with self._lock:
            g = self._group(gid)
            if member not in g["members"]:
                w.i16(self._UNKNOWN_MEMBER)
            else:
                session_s = g["sessions"].get(member)
                if session_s is not None:
                    # a heartbeat renews the member's session window
                    g["deadlines"][member] = time.time() + session_s
                if gen != g["generation"] or g["stable"] != set(g["members"]):
                    w.i16(self._REBALANCE_IN_PROGRESS)
                else:
                    w.i16(0)
        return bytes(w.buf)

    def _leave_group(self, r: Reader) -> bytes:
        gid = r.string()
        member = r.string()
        with self._lock:
            g = self._group(gid)
            g["members"].pop(member, None)
            g["stable"].discard(member)
            g["assignments"].pop(member, None)
            if g["members"]:
                g["generation"] += 1
                g["stable"] = set()
                g["assignments"] = {}
                if g["leader"] == member:
                    g["leader"] = next(iter(g["members"]))
            else:
                g["leader"] = None
        w = Writer()
        w.i16(0)
        return bytes(w.buf)
