"""Trace-driven fleet loadgen (round-16 tentpole).

The determinism contract — same spec + seed produces a *byte-identical*
trace file and an identical arrival schedule on any host — is what lets
SCORECARD_r16.json record only ``{spec, seed, sha256}`` per cell instead
of committing megabyte trace files: anyone can regenerate the exact
workload and check the hash. Replay is tested entirely in virtual time
(injectable clock/sleep), so round-trip equality costs no wall-clock.
Also covers the cell scoring gates, the window-cursor hygiene added for
the fleet driver (Histogram.drop_window / MetricsRegistry.drop_windows /
CapacityTracker cross-key pruning), and the ``scenario_phase`` flight
event shape.
"""

from __future__ import annotations

from types import SimpleNamespace

import pytest

from storm_tpu.loadgen import (
    CellTargets,
    Trace,
    TraceSpec,
    generate,
    load_trace,
    render_table,
    replay,
    score_cell,
)
from storm_tpu.obs.capacity import CapacityTracker
from storm_tpu.runtime.metrics import Histogram, MetricsRegistry
from storm_tpu.runtime.tracing import FlightRecorder


def _spec(**kw) -> TraceSpec:
    base = dict(seed=7, pattern="heavy_tail", duration_s=5.0,
                base_rate=300.0, tenants=200)
    base.update(kw)
    return TraceSpec(**base)


# ---- determinism -------------------------------------------------------------


def test_same_seed_trace_file_is_byte_identical(tmp_path):
    spec = _spec()
    a, b = generate(spec), generate(spec)
    assert a.rows == b.rows
    assert a.to_bytes() == b.to_bytes()
    assert a.sha256() == b.sha256()
    pa, pb = tmp_path / "a.trace", tmp_path / "b.trace"
    a.save(str(pa))
    b.save(str(pb))
    assert pa.read_bytes() == pb.read_bytes()


@pytest.mark.parametrize("pattern", ["heavy_tail", "diurnal", "flash_crowd"])
def test_same_seed_identical_schedule_every_pattern(pattern):
    spec = _spec(pattern=pattern, seed=16)
    a, b = generate(spec), generate(spec)
    assert len(a) > 100
    assert a.rows == b.rows
    assert [e for e in a.events()] == [e for e in b.events()]


def test_different_seeds_differ():
    assert generate(_spec(seed=1)).rows != generate(_spec(seed=2)).rows


def test_round_trip_load_replay_equality(tmp_path):
    spec = _spec(pattern="flash_crowd", seed=4, duration_s=4.0)
    tr = generate(spec)
    path = str(tmp_path / "t.trace")
    tr.save(path)
    loaded = load_trace(path)
    assert loaded.spec == spec
    assert loaded.rows == tr.rows
    assert loaded.sha256() == tr.sha256()

    def run(trace: Trace):
        clock = SimpleNamespace(t=0.0)
        out = []
        n = replay(trace, out.append,
                   clock=lambda: clock.t,
                   sleep=lambda dt: setattr(clock, "t", clock.t + dt))
        return n, out

    na, ea = run(tr)
    nb, eb = run(loaded)
    assert (na, ea) == (nb, eb)
    assert na == len(tr)


# ---- replay pacing -----------------------------------------------------------


def test_replay_paces_on_virtual_clock_and_honors_stop():
    tr = generate(_spec(seed=9, duration_s=2.0, base_rate=100.0))
    clock = SimpleNamespace(t=0.0)
    seen = []
    replay(tr, seen.append, clock=lambda: clock.t,
           sleep=lambda dt: setattr(clock, "t", clock.t + dt))
    # The virtual clock advanced to (at least) the last event's offset,
    # and every emit happened at/after its scheduled time.
    assert clock.t >= tr.rows[-1][0] / 1e6
    assert seen == list(tr.events())

    clock.t = 0.0
    few = []
    n = replay(tr, few.append, clock=lambda: clock.t,
               sleep=lambda dt: setattr(clock, "t", clock.t + dt),
               stop=lambda: len(few) >= 5)
    assert n == 5 and few == list(tr.events())[:5]


def test_replay_speed_compresses_virtual_time():
    tr = generate(_spec(seed=9, duration_s=2.0, base_rate=100.0))
    clock = SimpleNamespace(t=0.0)
    replay(tr, lambda e: None, speed=4.0, clock=lambda: clock.t,
           sleep=lambda dt: setattr(clock, "t", clock.t + dt))
    end = tr.rows[-1][0] / 1e6
    assert end / 4.0 <= clock.t < end


# ---- pattern shaping ---------------------------------------------------------


def test_heavy_tail_concentrates_on_top_tenants():
    st = generate(_spec(seed=11)).stats()
    # Zipf(1.1) over 200 tenants: the top-10 share dwarfs the uniform 5%.
    assert st["top10_tenant_share"] > 0.30
    assert st["distinct_tenants"] > 20
    assert set(st["lanes"]) == {"high", "normal", "best_effort"}


def test_diurnal_wave_moves_the_rate():
    spec = _spec(pattern="diurnal", seed=12, duration_s=8.0,
                 diurnal_period_s=8.0, diurnal_amp=0.6)
    assert spec.profile(0.0) == pytest.approx(0.4)   # trough at t=0
    assert spec.profile(4.0) == pytest.approx(1.6)   # peak mid-trace
    tr = generate(spec)
    mid = [r for r in tr.rows if 3.0e6 <= r[0] < 5.0e6]
    edge = [r for r in tr.rows if r[0] < 1.0e6 or r[0] >= 7.0e6]
    assert len(mid) > 1.5 * len(edge)


def test_flash_crowd_spikes_into_hot_tenants_on_one_lane():
    spec = _spec(pattern="flash_crowd", seed=13, duration_s=10.0,
                 flash_at_frac=0.3, flash_ramp_s=1.0, flash_hold_s=3.0,
                 flash_mult=4.0)
    assert spec.profile(0.0) == 1.0
    assert spec.profile(4.5) == pytest.approx(4.0)   # inside the hold
    tr = generate(spec)
    spike = [r for r in tr.rows if 4.0e6 <= r[0] < 7.0e6]
    calm = [r for r in tr.rows if r[0] < 3.0e6]
    # ~4x the rate during the spike vs the same-length calm window.
    assert len(spike) > 2.5 * len(calm)
    lane_be = spec.lanes.index("best_effort")
    crowd = [r for r in spike if r[1] < spec.flash_tenants
             and r[2] == lane_be]
    assert len(crowd) > 0.4 * len(spike)


def test_event_key_matches_admission_format():
    tr = generate(_spec(seed=3))
    ev = next(tr.events())
    tenant, lane = ev.key().decode().split(":")
    assert tenant == ev.tenant and lane == ev.lane
    assert tenant.startswith("t") and len(tenant) == 6


def test_spec_validation_rejects_nonsense():
    with pytest.raises(ValueError):
        generate(_spec(pattern="square_wave"))
    with pytest.raises(ValueError):
        generate(_spec(lane_mix=(0.5, 0.5, 0.5)))
    with pytest.raises(ValueError):
        generate(_spec(flash_lane="vip"))


# ---- cell scoring ------------------------------------------------------------


def _scores(**kw):
    base = dict(lane_p99_ms={"high": 40.0, "normal": 60.0},
                goodput_frac=0.95, shed_frac=0.0, burn_peak=0.2,
                burn_tripped=False)
    base.update(kw)
    return base


def test_score_cell_steady_gates():
    t = CellTargets(p99_ms=50.0, min_goodput_frac=0.8, max_shed_frac=0.05,
                    forbid_burn_trip=True)
    res = score_cell(_scores(), t)
    assert res["ok"] and all(g["ok"] for g in res["gates"].values())
    assert set(res["gates"]) == {"p99_high_ms", "goodput_frac",
                                 "shed_frac", "burn_not_tripped"}

    bad = score_cell(_scores(lane_p99_ms={"high": 80.0}, burn_tripped=True), t)
    assert not bad["ok"]
    assert not bad["gates"]["p99_high_ms"]["ok"]
    assert not bad["gates"]["burn_not_tripped"]["ok"]


def test_score_cell_overload_gates_require_protection():
    t = CellTargets(p99_ms=150.0, min_goodput_frac=0.3,
                    expect_shed=True, expect_burn_trip=True)
    quiet = score_cell(_scores(lane_p99_ms={"high": 100.0}), t)
    # Protection never engaged: an overload cell FAILS even though the
    # latency/goodput numbers look healthy.
    assert not quiet["ok"]
    assert not quiet["gates"]["shed_engaged"]["ok"]
    assert not quiet["gates"]["burn_tripped"]["ok"]

    hot = score_cell(_scores(lane_p99_ms={"high": 120.0}, goodput_frac=0.4,
                             shed_frac=0.3, burn_tripped=True), t)
    assert hot["ok"]


def test_score_cell_missing_measurement_fails_closed():
    t = CellTargets(p99_ms=50.0)
    res = score_cell(_scores(lane_p99_ms={}), t)
    assert not res["ok"]


def test_render_table_shows_verdict_and_tally():
    card = {"seed": 16, "cells": [{
        "scenario": "classify", "pattern": "flash_crowd", "ok": True,
        "scores": _scores(offered_rate_per_s=500.0, goodput_per_s=400.0,
                          shed_frac=0.31, burn_tripped=True),
        "bottleneck": {"leader": "inference-bolt"},
    }]}
    txt = render_table(card)
    assert "inference-bolt" in txt
    assert "PASS" in txt and "1/1 cells pass" in txt and "seed 16" in txt


# ---- window-cursor hygiene (satellite: prune on rebalance) -------------------


def test_histogram_drop_window_forgets_named_cursor():
    h = Histogram()
    h.observe(1.0)
    assert h.window("cell-a")["count"] == 0  # primes the cursor
    h.observe(2.0)
    assert "cell-a" in h.window_keys()
    assert h.drop_window("cell-a") is True
    assert "cell-a" not in h.window_keys()
    assert h.drop_window("cell-a") is False
    # Re-reading after drop re-primes instead of replaying the old delta.
    assert h.window("cell-a")["count"] == 0


def test_registry_drop_windows_sweeps_every_histogram():
    reg = MetricsRegistry()
    for comp in ("sink", "bolt"):
        hist = reg.histogram(comp, "e2e_ms")
        hist.observe(1.0)
        hist.window("cell-a")
        hist.window("keep")
    assert reg.drop_windows("cell-a") == 2
    assert reg.drop_windows("cell-a") == 0
    for comp in ("sink", "bolt"):
        assert reg.histogram(comp, "e2e_ms").window_keys() == ("keep",)


def _fake_exec(task_index=0):
    return SimpleNamespace(task_index=task_index, busy_s=0.0, wait_s=0.0,
                           flush_s=0.0)


def test_capacity_tracker_prunes_stale_tasks_across_all_keys():
    clock = SimpleNamespace(t=0.0)
    e0, e1 = _fake_exec(0), _fake_exec(1)
    rt = SimpleNamespace(metrics=MetricsRegistry(),
                         bolt_execs={"b": [e0, e1]}, spout_execs={})
    tr = CapacityTracker(rt, clock=lambda: clock.t)
    tr.sample(key="obs")
    tr.sample(key="cell")
    assert set(tr.cursor_keys()) == {"obs", "cell"}
    # Rebalance removes task 1. Only "obs" keeps sampling — but the
    # retired task's cursor must vanish from "cell" too, not linger until
    # that key happens to sample again (it may never).
    rt.bolt_execs["b"] = [e0]
    clock.t += 1.0
    tr.sample(key="obs")
    assert set(tr._cursors["cell"]) == {("b", 0)}
    assert set(tr._cursors["obs"]) == {("b", 0)}


def test_capacity_tracker_drop_forgets_whole_key():
    clock = SimpleNamespace(t=0.0)
    rt = SimpleNamespace(metrics=MetricsRegistry(),
                         bolt_execs={"b": [_fake_exec(0)]}, spout_execs={})
    tr = CapacityTracker(rt, clock=lambda: clock.t)
    tr.sample(key="cell")
    assert tr.drop("cell") is True
    assert tr.drop("cell") is False
    assert tr.cursor_keys() == ()


# ---- scenario_phase flight event (satellite) ---------------------------------


def test_scenario_phase_flight_event_shape():
    fr = FlightRecorder()
    assert fr.event("scenario_phase", scenario="classify",
                    pattern="flash_crowd", cell="cell-classify-flash_crowd",
                    phase="hold", offered=0)
    (ev,) = [e for e in fr.tail() if e["kind"] == "scenario_phase"]
    assert ev["scenario"] == "classify"
    assert ev["pattern"] == "flash_crowd"
    assert ev["phase"] == "hold"
    assert ev["cell"] == "cell-classify-flash_crowd"
    fr.close()
