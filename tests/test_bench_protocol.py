"""Unit tests for the bench harness's measurement protocol — the code the
round artifacts (BENCH_*_r0N.json) depend on. The protocol logic (backlog
guard, calibration bail-out, stage bookkeeping) must hold regardless of
tunnel weather, so it is tested synthetically here, without a device.
"""

import pathlib
import sys

import numpy as np
import pytest

# bench.py lives at the repo root, one level above tests/
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import bench  # noqa: E402


def test_offer_load_paces_and_completes():
    sent_ids = []
    sent, aborted = bench.offer_load(sent_ids.append, rate=2000.0,
                                     seconds=0.25)
    assert not aborted
    assert sent == len(sent_ids)
    # Upper bound only: the pacer must never overshoot the rate. A lower
    # bound would flake on this 1-core host when a scheduler stall spans
    # the end of the window (the catch-up loop can't recover past `end`).
    assert 0 < sent <= 600, sent


def test_offer_load_backlog_guard_trips_on_monotonic_growth():
    """An offered load the 'topology' never drains must abort (round 1
    integrated queueing delay without bound and recorded p50 = 52s)."""
    sent, aborted = bench.offer_load(
        lambda i: None, rate=2000.0, seconds=5.0,
        backlog_fn=lambda sent: sent,  # nothing ever delivered
        guard_checks=4, check_interval=0.05)
    assert aborted
    assert sent < 2000 * 5  # aborted well before the full window


def test_offer_load_guard_tolerates_bounded_backlog():
    """A backlog that stops growing (deadline batch in flight) must NOT
    trip the guard."""
    sent, aborted = bench.offer_load(
        lambda i: None, rate=500.0, seconds=0.4,
        backlog_fn=lambda sent: 10,  # constant small backlog
        guard_checks=3, check_interval=0.05)
    assert not aborted


def test_run_latency_phase_invalid_when_probe_never_drains(monkeypatch):
    """No clean calibration -> the phase reports valid=False rather than
    percentiles from a saturated window."""
    # No real 180s grace window in a unit test: an undrained system stays
    # undrained, so the wait can resolve instantly.
    monkeypatch.setattr(
        bench, "await_outputs",
        lambda size_fn, sent, grace_s=60.0: size_fn() >= sent)
    p50, p99, rate, valid = bench.run_latency_phase(
        produce_nth=lambda i: None,
        out_size_fn=lambda: 0,  # nothing is ever delivered
        reset_hists=lambda: None,
        read_lat=lambda: (123.0, 456.0),
        seconds=0.1)
    assert not valid
    assert rate == 0.0
    assert (p50, p99) == (123.0, 456.0)  # reported but flagged


def test_null_engine_contract():
    from storm_tpu.infer import NullEngine

    eng = NullEngine((28, 28, 1), 10)
    assert eng.input_shape == (28, 28, 1)
    out = eng.predict(np.zeros((7, 28, 28, 1), np.float32))
    assert out.shape == (7, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    eng.warmup()  # no-op, must not raise


def test_merge_offsets_max_wins():
    from storm_tpu.runtime.tuples import merge_offsets

    dst = {("t", 0): 5}
    merge_offsets(dst, [(("t", 0), 3), (("t", 1), 7), (("t", 0), 9)])
    assert dst == {("t", 0): 9, ("t", 1): 7}


def test_stage_list_matches_operator_histograms():
    """bench.STAGES must reference histograms the operator/sink actually
    record — a renamed metric would silently drop a stage from the
    decomposition artifact."""
    import inspect

    from storm_tpu.connectors import sink as sink_mod
    from storm_tpu.infer import operator as op_mod

    from storm_tpu.runtime.tracing import DEVICE_SUBSTAGES

    source = inspect.getsource(op_mod) + inspect.getsource(sink_mod)
    substage_keys = {key for key, _ in DEVICE_SUBSTAGES}
    for comp, hist, _label in bench.STAGES:
        if hist in substage_keys:
            # Device substages are recorded by iterating the shared
            # DEVICE_SUBSTAGES constant (the same one bench derives its
            # rows from), not by quoted literals.
            assert "DEVICE_SUBSTAGES" in source, \
                f"substage {hist} not recorded via DEVICE_SUBSTAGES"
            continue
        # Histograms are recorded either by their full name or via
        # span(..., "<base>") which appends "_ms" — both as QUOTED string
        # literals; a bare-word match would be satisfied by comments and
        # identifiers, making the check vacuous.
        base = hist[: -len("_ms")]
        quoted = (f'"{hist}"', f"'{hist}'", f'"{base}"', f"'{base}'")
        assert any(q in source for q in quoted), f"stage {hist} not recorded"


def test_offer_load_depth_guard_catches_bursty_saturation():
    """The absolute queue-depth guard: a backlog that OSCILLATES (bursty
    deliveries reset the monotonic-growth streak) but holds above 2.5s of
    offered work must abort — the saturation shape that produced 'valid'
    multi-second percentiles for heavy-payload configs before the fix."""
    calls = {"n": 0}

    def sawtooth_backlog(sent):
        calls["n"] += 1
        # oscillate between 3s and 4s of offered work: growth streak
        # resets every other check, depth stays above the 2.5s bound
        return int(100 * 2.5 * (1.2 + 0.3 * (calls["n"] % 2)))

    sent, aborted = bench.offer_load(
        lambda i: None, rate=100.0, seconds=3.0,
        backlog_fn=sawtooth_backlog,
        guard_checks=12, check_interval=0.05)
    assert aborted
    assert calls["n"] <= 3  # first depth check trips it


def test_offer_load_depth_guard_time_based_at_low_rates():
    """At low rates the bound must stay TIME-based (2.5s of work), not a
    fixed count — 50 queued messages at 2 msg/s is 25s of queueing."""
    sent, aborted = bench.offer_load(
        lambda i: None, rate=4.0, seconds=3.0,
        backlog_fn=lambda sent: 12,  # 3s of work at 4 msg/s
        guard_checks=12, check_interval=0.05)
    assert aborted


def test_repeatable_rows_selection():
    """Interleaved-repeat eligibility (--all --repeats): single-model
    configs only — 'multi' is a run_multi aggregate (run_single would
    KeyError, the bug the first r04 capture hit), demo rows aren't
    configs, and failed first passes don't repeat."""
    matrix = [("lenet5", {}), ("resnet20", {"weights": "int8"}),
              ("multi", {}), ("autoscale", {}), ("resnet50", {})]
    results = [{"value": 1}, {"value": 2}, {"value": 3}, {"value": 4},
               {"config": "resnet50", "error": "boom"}]
    rows = bench._repeatable_rows(matrix, results)
    assert [(i, n) for i, n, _ in rows] == [(0, "lenet5"), (1, "resnet20")]
