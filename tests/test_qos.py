"""Admission control & QoS (storm_tpu/qos/, round-6 tentpole): token-bucket
tenant quotas + lane classification at the spout edge, earliest-deadline-
first batch formation in the operator, the hysteresis load-shed controller,
shed-first/scale-second autoscaler coupling, and the typed ``Overloaded``
degradation path — unit-level on the qos package, then e2e through the
broker -> spout -> InferenceBolt -> sink slice, then the UI /qos route."""

import asyncio
import json
from types import SimpleNamespace

import numpy as np
import pytest

from storm_tpu.api.schema import decode_predictions
from storm_tpu.config import (
    BatchConfig, Config, ModelConfig, OffsetsConfig, QosConfig,
    ShardingConfig)
from storm_tpu.connectors import BrokerSink, BrokerSpout, MemoryBroker
from storm_tpu.infer import InferenceBolt
from storm_tpu.qos import (
    AdmissionController, LaneBatcher, LoadShedController, ShedPolicy,
    TokenBucket)
from storm_tpu.runtime import Bolt, Spout, TopologyBuilder, Values
from storm_tpu.runtime.autoscale import Autoscaler, AutoscalePolicy
from storm_tpu.runtime.cluster import AsyncLocalCluster
from storm_tpu.runtime.metrics import MetricsRegistry


# ---- token bucket ------------------------------------------------------------


def test_token_bucket_refill_is_continuous():
    b = TokenBucket(rate=10.0, burst=5.0, now=0.0)
    for _ in range(5):  # starts full: a fresh tenant gets its burst
        assert b.try_take(1.0, now=0.0)
    assert not b.try_take(1.0, now=0.0)
    # 0.5 s at 10/s refills 5 tokens, capped at burst.
    for _ in range(5):
        assert b.try_take(1.0, now=0.5)
    assert not b.try_take(1.0, now=0.5)
    # Refill never exceeds burst even after a long idle stretch.
    assert b.try_take(5.0, now=100.0)
    assert not b.try_take(1.0, now=100.0)


def test_token_bucket_burst_floor():
    # A tiny rate still admits at least one record per burst window.
    b = TokenBucket(rate=0.1, burst=0.01, now=0.0)
    assert b.burst == 1.0
    assert b.try_take(1.0, now=0.0)
    assert not b.try_take(1.0, now=0.0)


# ---- classification ----------------------------------------------------------


def test_classify_tenant_lane_key():
    ac = AdmissionController(QosConfig(enabled=True))
    assert ac.classify(b"gold:high") == ("gold", "high")
    assert ac.classify(b"free:best_effort") == ("free", "best_effort")
    # No lane / unknown lane -> default lane; no key -> topic as tenant.
    assert ac.classify(b"gold") == ("gold", "normal")
    assert ac.classify(b"gold:bogus") == ("gold", "normal")
    assert ac.classify(None, topic="clicks") == ("clicks", "normal")
    assert ac.classify(b"", topic="clicks") == ("clicks", "normal")
    assert ac.classify(b":high", topic="clicks") == ("clicks", "high")


def test_qos_config_lane_semantics():
    qos = QosConfig(enabled=True)
    assert qos.lane_index("high") == 0
    assert qos.lane_index("nonsense") == qos.lane_index("normal")
    assert qos.deadline_for("high") == 50.0
    assert qos.deadline_for("best_effort") == 1000.0
    assert qos.max_shed_level == 2
    # Level N sheds the N lowest-priority lanes; the top lane never sheds.
    assert not qos.shed_eligible("best_effort", 0)
    assert qos.shed_eligible("best_effort", 1)
    assert not qos.shed_eligible("normal", 1)
    assert qos.shed_eligible("normal", 2)
    assert not qos.shed_eligible("high", 2)
    assert not qos.shed_eligible("high", 99)  # clamped to max_shed_level
    # Per-tenant override beats the default rate.
    qos2 = QosConfig(enabled=True, tenant_rate=5.0,
                     tenant_rates={"gold": 50.0})
    assert qos2.rate_for("gold") == 50.0
    assert qos2.rate_for("anyone") == 5.0


def test_qos_config_validation():
    with pytest.raises(ValueError):
        QosConfig(lanes=("a", "a"))
    with pytest.raises(ValueError):
        QosConfig(lanes=("a", "b"), lane_deadline_ms=(1.0,))
    with pytest.raises(ValueError):
        QosConfig(default_lane="nope")


# ---- admission ---------------------------------------------------------------


def test_admit_throttles_over_quota_tenant():
    reg = MetricsRegistry()
    qos = QosConfig(enabled=True, tenant_rate=2.0, tenant_burst_s=1.0)
    ac = AdmissionController(qos, parallelism=1, metrics=reg)
    t0 = 100.0
    assert ac.admit("gold", "high", now=t0) == (True, "ok")
    assert ac.admit("gold", "high", now=t0) == (True, "ok")
    assert ac.admit("gold", "high", now=t0) == (False, "throttled")
    # A second into the future the bucket has refilled.
    assert ac.admit("gold", "high", now=t0 + 1.0) == (True, "ok")
    snap = reg.snapshot()["qos"]
    assert snap["admitted_gold"] == 3
    assert snap["throttled_gold"] == 1
    assert snap["admitted_lane_high"] == 3
    assert snap["throttled_lane_high"] == 1


def test_admit_splits_rate_across_spout_tasks():
    qos = QosConfig(enabled=True, tenant_rate=4.0, tenant_burst_s=1.0)
    ac = AdmissionController(qos, parallelism=2)
    t0 = 0.0
    assert ac.admit("gold", "normal", now=t0)[0]
    assert ac.admit("gold", "normal", now=t0)[0]
    # 4/s across 2 tasks = 2/s per task; the third local take fails.
    assert ac.admit("gold", "normal", now=t0) == (False, "throttled")


def test_admit_unlimited_tenant_never_throttles():
    ac = AdmissionController(QosConfig(enabled=True, tenant_rate=0.0))
    for _ in range(100):
        assert ac.admit("anyone", "normal", now=0.0) == (True, "ok")


def test_admit_sheds_lanes_at_raised_level():
    reg = MetricsRegistry()
    ac = AdmissionController(QosConfig(enabled=True), metrics=reg)
    reg.gauge("qos", "shed_level").set(1.0)
    assert ac.admit("free", "best_effort", now=0.0) == (False, "shed")
    assert ac.admit("gold", "high", now=0.0) == (True, "ok")
    assert ac.admit("gold", "normal", now=0.0) == (True, "ok")
    reg.gauge("qos", "shed_level").set(2.0)
    assert ac.admit("gold", "normal", now=0.0) == (False, "shed")
    assert ac.admit("gold", "high", now=0.0) == (True, "ok")
    snap = reg.snapshot()["qos"]
    assert snap["shed_free"] == 1
    assert snap["shed_gold"] == 1
    assert snap["shed_lane_best_effort"] == 1
    assert snap["shed_lane_normal"] == 1


# ---- EDF lane batcher --------------------------------------------------------


def _lb(max_batch, qos=None):
    return LaneBatcher(
        BatchConfig(max_batch=max_batch, max_wait_ms=5.0,
                    buckets=(max_batch,)),
        qos or QosConfig(enabled=True))


def test_lane_batcher_high_preempts_queued_best_effort():
    lb = _lb(4)
    x = np.zeros((1, 2), np.float32)
    t0 = 1000.0
    for i in range(3):
        assert lb.add(f"be{i}", x, ts=t0, lane="best_effort") is None
    # The 4th instance fills max_batch; the freshly-arrived high record
    # (deadline t0+50ms) pops AHEAD of best_effort queued first (t0+1s).
    batch = lb.add("hi", x, ts=t0, lane="high")
    assert batch is not None and batch.size == 4
    assert [it.lane for it in batch.items] == [
        "high", "best_effort", "best_effort", "best_effort"]
    assert [it.payload for it in batch.items] == ["hi", "be0", "be1", "be2"]
    assert len(lb) == 0


def test_lane_batcher_fifo_within_a_lane():
    lb = _lb(8)
    x = np.zeros((1, 2), np.float32)
    for i in range(4):
        lb.add(i, x, ts=1000.0, lane="normal")
    batch = lb.take_all()
    assert [it.payload for it in batch.items] == [0, 1, 2, 3]


def test_lane_batcher_leftovers_stay_pending():
    # Unlike the FIFO batcher, later-deadline items beyond max_batch stay
    # queued for the next take instead of forcing an immediate flush.
    lb = _lb(2)
    x = np.zeros((1, 2), np.float32)
    assert lb.add("a", x, ts=1000.0, lane="high") is None
    batch = lb.add("b", x, ts=1000.0, lane="best_effort")
    assert batch is not None and batch.size == 2
    assert lb.add("c", x, ts=1000.0, lane="best_effort") is None
    assert len(lb) == 1
    rest = lb.take_all()
    assert [it.payload for it in rest.items] == ["c"]
    assert lb.take_all() is None


def test_lane_batcher_take_if_due_is_age_based():
    import time as _time

    lb = _lb(64)
    x = np.zeros((1, 2), np.float32)
    old = _time.perf_counter() - 1.0
    lb.add("stale", x, ts=old, lane="best_effort")
    batch = lb.take_if_due()
    assert batch is not None and batch.items[0].payload == "stale"


def test_lane_batcher_oversized_record_still_ships():
    lb = _lb(2)
    batch = lb.add("big", np.zeros((5, 2), np.float32), ts=0.0, lane="high")
    assert batch is not None and batch.size == 5  # never wedges


# ---- load-shed controller ----------------------------------------------------


def _shed_rig(**kw):
    reg = MetricsRegistry()
    rt = SimpleNamespace(metrics=reg, bolt_execs={}, flight=None)
    pol = ShedPolicy(interval_s=1.0, breach_rate=1.0, hot_steps=2,
                     calm_steps=2, max_level=2, **kw)
    return reg, rt, LoadShedController(rt, pol)


def test_shed_controller_hysteresis_round_trip():
    reg, rt, ctl = _shed_rig()
    assert rt.qos is ctl  # exposed for the UI /qos route
    assert reg.gauge("qos", "shed_level").value == 0.0
    breaches = reg.counter("kafka-bolt", "slo_breaches")

    assert ctl.step() is None  # first step: no breach baseline yet
    breaches.inc(5)
    assert ctl.step() is None  # hot x1 — below hot_steps
    breaches.inc(5)
    assert ctl.step() == 1     # hot x2 -> shed one lane
    assert ctl.level == 1
    assert reg.gauge("qos", "shed_level").value == 1.0
    assert ctl.decisions == [("shed", 0, 1)]
    assert reg.snapshot()["qos"]["shed_decisions"] == 1

    # Signals go quiet: calm_steps consecutive calm intervals restore.
    assert ctl.step() is None
    assert ctl.step() == 0
    assert ctl.level == 0
    assert reg.gauge("qos", "shed_level").value == 0.0
    assert ctl.decisions[-1] == ("restore", 1, 0)


def test_shed_controller_caps_at_max_level():
    reg, rt, ctl = _shed_rig()
    breaches = reg.counter("kafka-bolt", "slo_breaches")
    ctl.step()
    for _ in range(12):  # relentless heat
        breaches.inc(10)
        ctl.step()
    assert ctl.level == 2  # max_level: the top lane is never shed
    assert reg.gauge("qos", "shed_level").value == 2.0


def test_shed_controller_middling_signals_reset_both_streaks():
    # 1 breach/interval on a 1.0/s threshold is NOT > 1.0 (never hot) and
    # not < 0.5 (never calm): both streaks reset, no decision ever fires.
    reg, rt, ctl = _shed_rig()
    breaches = reg.counter("kafka-bolt", "slo_breaches")
    ctl.step()
    for _ in range(8):
        breaches.inc(1)
        assert ctl.step() is None
    assert ctl.level == 0 and ctl.decisions == []


def test_shed_controller_inbox_signal():
    # The signal counts queued RECORDS (round 20): a parked frame tuple
    # contributes its row count, a plain tuple contributes 1.
    reg = MetricsRegistry()
    frame = SimpleNamespace(values=[list(range(45))])
    queued = [frame] + [SimpleNamespace(values=["rec"]) for _ in range(45)]
    full = SimpleNamespace(
        inbox=SimpleNamespace(_queue=queued, maxsize=100))
    rt = SimpleNamespace(metrics=reg,
                         bolt_execs={"inference-bolt": [full]}, flight=None)
    ctl = LoadShedController(rt, ShedPolicy(hot_steps=2, calm_steps=2))
    assert ctl.step() is None
    assert ctl.step() == 1  # 45-row frame + 45 tuples = 90% > 50%, two hot steps


def test_shed_policy_from_qos():
    qos = QosConfig(enabled=True, shed_interval_s=0.25, shed_breach_rate=3.0,
                    shed_hot_steps=4, shed_calm_steps=9)
    pol = ShedPolicy.from_qos(qos, component="mnist-inference",
                              latency_source="mnist-sink")
    assert pol.component == "mnist-inference"
    assert pol.latency_source == "mnist-sink"
    assert pol.interval_s == 0.25
    assert pol.breach_rate == 3.0
    assert pol.hot_steps == 4 and pol.calm_steps == 9
    assert pol.max_level == qos.max_shed_level == 2


# ---- shed-first / scale-second -----------------------------------------------


def _hot_autoscaler_rig(shedder):
    reg = MetricsRegistry()
    for _ in range(20):  # p50 far above high_ms: permanently hot
        reg.histogram("kafka-bolt", "e2e_latency_ms").observe(500.0)
    calls = []

    async def rebalance(component, n):
        calls.append((component, n))

    rt = SimpleNamespace(metrics=reg, bolt_execs={}, flight=None,
                         parallelism_of=lambda c: 1, rebalance=rebalance)
    sc = Autoscaler(rt, AutoscalePolicy(high_ms=100.0, interval_s=0.1),
                    shedder=shedder)
    return sc, calls


def test_autoscaler_defers_one_interval_while_shedder_calm(run):
    async def go():
        shedder = SimpleNamespace(level=0)
        sc, calls = _hot_autoscaler_rig(shedder)
        assert await sc.step() is None   # hot x1
        assert await sc.step() is None   # hot x2 but DEFERRED (level 0)
        assert calls == []
        assert await sc.step() == 2      # deferral spent: scale up
        assert calls == [("inference-bolt", 2)]
        assert sc.decisions == [("up", 1, 2)]

    run(go())


def test_autoscaler_scales_immediately_once_shedding_active(run):
    async def go():
        shedder = SimpleNamespace(level=1)
        sc, calls = _hot_autoscaler_rig(shedder)
        assert await sc.step() is None   # hot x1
        assert await sc.step() == 2      # shedder already reacted: no defer
        assert calls == [("inference-bolt", 2)]

    run(go())


def test_autoscaler_without_shedder_keeps_old_behavior(run):
    async def go():
        sc, calls = _hot_autoscaler_rig(None)
        assert await sc.step() is None
        assert await sc.step() == 2
        assert calls == [("inference-bolt", 2)]

    run(go())


# ---- e2e: broker -> spout -> operator -> sink with QoS -----------------------


def _payload(n=1, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    return json.dumps({"instances": x.tolist()})


async def _run_qos_e2e(keys, shed_level=0.0, spout_qos=True, n_expect=None):
    broker = MemoryBroker(default_partitions=2)
    cfg = Config()
    qos = QosConfig(enabled=True)
    model_cfg = ModelConfig(name="lenet5", dtype="float32",
                            input_shape=(28, 28, 1))
    batch_cfg = BatchConfig(max_batch=8, max_wait_ms=20, buckets=(8,))
    shard_cfg = ShardingConfig(data_parallel=0)

    tb = TopologyBuilder()
    tb.set_spout(
        "kafka-spout",
        BrokerSpout(broker, "input",
                    OffsetsConfig(policy="earliest", max_behind=None),
                    qos=qos if spout_qos else None),
        parallelism=1,
    )
    tb.set_bolt(
        "inference-bolt",
        InferenceBolt(model_cfg, batch_cfg, shard_cfg, warmup=False,
                      passthrough=("qos_lane",) if spout_qos else (),
                      qos=qos),
        parallelism=1,
    ).shuffle_grouping("kafka-spout")
    tb.set_bolt("kafka-bolt", BrokerSink(broker, "output", cfg.sink),
                parallelism=1).shuffle_grouping("inference-bolt")
    tb.set_bolt("dlq-bolt", BrokerSink(broker, "dead-letter", cfg.sink),
                parallelism=1)\
        .shuffle_grouping("inference-bolt", stream="dead_letter")

    cluster = AsyncLocalCluster()
    rt = await cluster.submit("qos-e2e", cfg, tb.build())
    if shed_level:
        # Normally the LoadShedController moves this gauge; pinning it
        # makes the shed paths deterministic under test.
        rt.metrics.gauge("qos", "shed_level").set(float(shed_level))

    for i, key in enumerate(keys):
        broker.produce("input", _payload(n=1, seed=i), key=key)

    total = len(keys) if n_expect is None else n_expect
    deadline = asyncio.get_event_loop().time() + 60
    while asyncio.get_event_loop().time() < deadline:
        done = broker.topic_size("output") + broker.topic_size("dead-letter")
        if done >= total:
            break
        await asyncio.sleep(0.05)
    await rt.drain(timeout_s=30)
    snap = rt.metrics.snapshot()
    outs = broker.drain_topic("output")
    dlq = broker.drain_topic("dead-letter")
    await cluster.shutdown()
    return outs, dlq, snap


def test_e2e_lane_field_and_per_lane_latency(run):
    keys = [b"gold:high"] * 3 + [b"free:best_effort"] * 3
    outs, dlq, snap = run(_run_qos_e2e(keys), timeout=120)
    assert len(outs) == 6 and len(dlq) == 0
    for r in outs:
        preds = decode_predictions(r.value)
        assert preds.data.shape == (1, 10)
    # Spout-edge admission accounting, by tenant and by lane.
    q = snap["qos"]
    assert q["admitted_gold"] == 3 and q["admitted_free"] == 3
    assert q["admitted_lane_high"] == 3
    assert q["admitted_lane_best_effort"] == 3
    # The lane rode the tuple (spout passthrough) all the way to the sink:
    # per-lane e2e histograms exist alongside the pooled one.
    sink = snap["kafka-bolt"]
    assert sink["e2e_latency_ms_high"]["count"] == 3
    assert sink["e2e_latency_ms_best_effort"]["count"] == 3
    assert sink["e2e_latency_ms"]["count"] == 6
    assert snap["kafka-spout"]["tree_acked"] == 6


def test_e2e_edge_shed_drops_best_effort_keeps_high(run):
    keys = [b"free:best_effort"] * 3 + [b"gold:high"] * 3
    outs, dlq, snap = run(
        _run_qos_e2e(keys, shed_level=1.0, n_expect=3), timeout=120)
    # Best-effort was dropped AT THE SPOUT (cursor advanced, no replay);
    # high-priority traffic was served untouched.
    assert len(outs) == 3 and len(dlq) == 0
    for r in outs:
        assert decode_predictions(r.value).data.shape == (1, 10)
    q = snap["qos"]
    assert q["shed_free"] == 3
    assert q["shed_lane_best_effort"] == 3
    assert q["admitted_gold"] == 3
    assert snap["kafka-spout"]["tree_acked"] == 3  # only admitted records
    assert snap["kafka-bolt"]["e2e_latency_ms_high"]["count"] == 3


def test_e2e_operator_shed_answers_overloaded(run):
    # Spout QoS off (no edge shedding) so records REACH the operator, which
    # must answer each with a typed Overloaded record — ack, never replay.
    keys = [None] * 4
    outs, dlq, snap = run(
        _run_qos_e2e(keys, shed_level=2.0, spout_qos=False), timeout=120)
    assert len(outs) == 4 and len(dlq) == 0
    for r in outs:
        msg = json.loads(r.value)
        assert msg["overloaded"] is True
        assert msg["shed_level"] == 2
    assert snap["inference-bolt"]["shed_rejected"] == 4
    assert snap["inference-bolt"].get("instances_inferred", 0) == 0
    assert snap["kafka-spout"]["tree_acked"] == 4


# ---- UI /qos route -----------------------------------------------------------


class _TrickleSpout(Spout):
    def open(self, context, collector):
        super().open(context, collector)
        self.n = 0

    async def next_tuple(self):
        await asyncio.sleep(0.01)
        await self.collector.emit(Values([self.n]), msg_id=self.n)
        self.n += 1
        return True

    def ack(self, msg_id):
        pass

    def fail(self, msg_id):
        pass


class _EchoBolt(Bolt):
    async def execute(self, t):
        self.collector.ack(t)


async def _http_get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        (f"GET {path} HTTP/1.1\r\nHost: localhost\r\n"
         f"Content-Length: 0\r\nConnection: close\r\n\r\n").encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), json.loads(body)


def test_ui_qos_route_serves_shed_state(run):
    from storm_tpu.runtime.ui import UIServer

    async def go():
        tb = TopologyBuilder()
        tb.set_spout("spout", _TrickleSpout(), parallelism=1)
        tb.set_bolt("echo", _EchoBolt(), parallelism=1)\
            .shuffle_grouping("spout")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("demo", Config(), tb.build())
        ui = await UIServer(cluster, port=0).start()
        try:
            ctl = LoadShedController(rt, ShedPolicy())
            ctl._set_level(1, "shed", {"inbox_frac": 0.9,
                                       "wait_p95_ms": 0.0,
                                       "breach_rate": 3.0})
            st, body = await _http_get(
                ui.port, "/api/v1/topology/demo/qos")
            assert st == 200
            assert body["topology"] == "demo"
            assert body["shed_level"] == 1
            assert body["decisions"] == [
                {"direction": "shed", "from": 0, "to": 1}]
            assert body["qos"]["shed_level"] == 1.0
            assert body["qos"]["shed_decisions"] == 1
        finally:
            await ui.stop()
            await cluster.shutdown()

    run(go(), timeout=60)
