"""Stateful-bolt tests: KeyValueState, checkpoint backends, restore across
supervisor restarts and across topology restarts (durable file backend).

The reference checkpoints nothing (SURVEY.md §5.4); this is the Storm
``IStatefulBolt``/``KeyValueState`` capability owned by the layer-1 runtime."""

import asyncio

import pytest

from storm_tpu.config import Config
from storm_tpu.runtime import (
    FileStateBackend,
    KeyValueState,
    MemoryStateBackend,
    StatefulBolt,
    TopologyBuilder,
    Values,
)
from storm_tpu.runtime.chaos import ChaosMonkey
from storm_tpu.runtime.cluster import AsyncLocalCluster

from test_runtime import ListSpout


class CountBolt(StatefulBolt):
    """Word-count: the canonical stateful operator."""

    async def execute(self, t):
        key = t.get("message")
        self.state.put(key, self.state.get(key, 0) + 1)
        self.collector.ack(t)


# ---- unit: state + backends --------------------------------------------------


def test_kv_state_basics():
    s = KeyValueState()
    assert not s.dirty
    s.put("a", 1)
    s.put("b", {"nested": [1, 2]})
    assert s.dirty
    assert s.get("a") == 1
    assert s.get("missing", 42) == 42
    assert "b" in s and len(s) == 2
    snap = s.snapshot()
    s.delete("a")
    assert "a" not in s
    assert snap["a"] == 1  # snapshot unaffected by later mutation
    restored = KeyValueState(snap)
    assert restored.get("a") == 1 and not restored.dirty


def test_memory_backend_roundtrip():
    b = MemoryStateBackend()
    assert b.load("c", 0) is None
    b.save("c", 0, 3, {"k": 1})
    assert b.load("c", 0) == (3, {"k": 1})
    b.save("c", 1, 1, {"other": True})
    assert b.load("c", 0) == (3, {"k": 1})  # tasks isolated


def test_file_backend_roundtrip(tmp_path):
    b = FileStateBackend(str(tmp_path))
    assert b.load("count-bolt", 2) is None
    b.save("count-bolt", 2, 1, {"x": [1, 2, 3]})
    b.save("count-bolt", 2, 2, {"x": [1, 2, 3, 4]})
    # fresh instance reads what a prior process wrote (durability)
    b2 = FileStateBackend(str(tmp_path))
    assert b2.load("count-bolt", 2) == (2, {"x": [1, 2, 3, 4]})
    # no stray tmp files from the atomic write
    assert all(not p.name.endswith(".tmp") for p in tmp_path.iterdir())


def test_file_backend_fsyncs_directory(tmp_path, monkeypatch):
    """save() must fsync the state DIRECTORY after os.replace: the rename
    is atomic but not durable, and losing the directory entry on a power
    cut would silently resurrect the previous checkpoint."""
    import os

    synced_inodes = set()
    real_fsync = os.fsync

    def spy_fsync(fd):
        synced_inodes.add(os.fstat(fd).st_ino)
        real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    b = FileStateBackend(str(tmp_path))
    b.save("count-bolt", 0, 1, {"k": 1})
    assert tmp_path.stat().st_ino in synced_inodes


# ---- integration: checkpoint + restore ---------------------------------------


def _config(**topo):
    cfg = Config()
    cfg.topology.message_timeout_s = topo.pop("message_timeout_s", 2.0)
    cfg.topology.checkpoint_interval_s = topo.pop("checkpoint_interval_s", 0.05)
    for k, v in topo.items():
        setattr(cfg.topology, k, v)
    return cfg


def test_supervisor_restore_after_crash(run):
    """Crash the stateful bolt's executor mid-stream: the supervisor
    replaces it, the replacement restores the last checkpoint, and the
    in-flight tuple replays — counts end >= exact (at-least-once)."""

    async def scenario():
        items = ["a", "b", "a", "c", "a", "b"]
        spout = ListSpout(items, replay_on_fail=True)

        builder = TopologyBuilder()
        builder.set_spout("spout", spout, 1)
        builder.set_bolt("count", CountBolt(), 1).shuffle_grouping("spout")
        cfg = _config()

        cluster = AsyncLocalCluster()
        rt = await cluster.submit("stateful", cfg, builder.build())
        try:
            # Phase 1: everything counted and at least one checkpoint taken.
            for _ in range(400):
                sp = rt.spout_execs["spout"][0].spout
                if len(sp.acked) >= len(items) and \
                        rt.metrics.snapshot().get("count", {}).get("checkpoints", 0) >= 1:
                    break
                await asyncio.sleep(0.05)
            got = rt.state_backend.load("count", 0)
            assert got is not None
            version, snap = got
            assert sum(snap.values()) == len(items)
            assert snap["a"] == 3

            # Phase 2: chaos-kill the executor on its next tuple.
            ChaosMonkey(rt).crash_bolt("count", 0)
            rt.spout_execs["spout"][0].spout.queue.extend(["c", "b"])
            for _ in range(400):
                snap2 = rt.metrics.snapshot().get("count", {})
                if snap2.get("executor_restarts", 0) >= 1:
                    break
                await asyncio.sleep(0.05)
            assert rt.metrics.snapshot()["count"]["executor_restarts"] >= 1

            # Phase 3: replacement restored state; replayed + new tuples
            # land on top of it. At-least-once: counts >= exact.
            for _ in range(400):
                got = rt.state_backend.load("count", 0)
                if got and got[1].get("c", 0) >= 2 and got[1].get("b", 0) >= 3:
                    break
                await asyncio.sleep(0.05)
            version2, final = rt.state_backend.load("count", 0)
            assert version2 > version
            assert final["a"] >= 3 and final["b"] >= 3 and final["c"] >= 2
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=60)


def test_durable_state_across_topology_restart(run, tmp_path):
    """File backend: a graceful kill checkpoints the tail; a new topology
    (fresh process-equivalent) resumes the counts."""

    async def scenario():
        cfg = _config(checkpoint_interval_s=30.0)  # only the final checkpoint
        cfg.topology.state_dir = str(tmp_path)

        async def run_once(items):
            builder = TopologyBuilder()
            builder.set_spout("spout", ListSpout(items), 1)
            builder.set_bolt("count", CountBolt(), 1).shuffle_grouping("spout")
            cluster = AsyncLocalCluster()
            rt = await cluster.submit("durable", cfg, builder.build())
            for _ in range(400):
                if len(rt.spout_execs["spout"][0].spout.acked) >= len(items):
                    break
                await asyncio.sleep(0.05)
            await cluster.kill("durable", wait_secs=5.0)  # graceful: checkpoints

        await run_once(["x", "y", "x"])
        await run_once(["y", "z"])

        got = FileStateBackend(str(tmp_path)).load("count", 0)
        assert got is not None
        _, counts = got
        assert counts == {"x": 2, "y": 2, "z": 1}

    run(scenario(), timeout=60)


def test_non_stateful_bolt_untouched(run):
    """Plain bolts: no state machinery, no checkpoint files, no counter."""

    async def scenario():
        from test_runtime import CaptureBolt

        CaptureBolt.seen = None
        builder = TopologyBuilder()
        builder.set_spout("spout", ListSpout(["m"]), 1)
        builder.set_bolt("cap", CaptureBolt(), 1).shuffle_grouping("spout")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("plain", _config(), builder.build())
        try:
            for _ in range(200):
                if CaptureBolt.seen:
                    break
                await asyncio.sleep(0.05)
            assert rt.state_backend.load("cap", 0) is None
            assert "checkpoints" not in rt.metrics.snapshot().get("cap", {})
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=30)
