"""Resilience layer (round 14): retry/backoff classification, circuit
breaker transitions, token-bucket replay pacing, chaos injection, the
engine fetch-ring watchdog, and the PeerSender park/reroute path.

Everything here is fast-tier: fakes for the gRPC/worker surfaces, one
real (CPU) engine for the watchdog->quarantine arc. The dist-level
chaos integration (worker kill, frame corruption over a live cluster)
lives in tests/test_dist.py (slow tier).
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from types import SimpleNamespace

import grpc
import numpy as np
import pytest

from storm_tpu.config import BatchConfig, ModelConfig, ResilienceConfig, \
    ShardingConfig
from storm_tpu.resilience import (ChaosDrop, ChaosInjector, CircuitBreaker,
                                  RetryPolicy, TokenBucket)
from storm_tpu.resilience.retry import (FATAL_CODES, RETRYABLE_BROAD,
                                        RETRYABLE_NARROW, is_fatal_rpc,
                                        is_retryable)


class FakeRpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


# ---- retry classification ----------------------------------------------------


def test_retryable_codes_classification():
    assert is_retryable(FakeRpcError(grpc.StatusCode.UNAVAILABLE))
    assert is_retryable(FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED))
    assert not is_retryable(FakeRpcError(grpc.StatusCode.UNAUTHENTICATED))
    assert not is_retryable(FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT))
    # the narrow (Deliver) set refuses DEADLINE_EXCEEDED: the payload may
    # already be enqueued on the receiver — re-sending double-delivers
    assert not is_retryable(FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED),
                            codes=RETRYABLE_NARROW)
    assert is_retryable(FakeRpcError(grpc.StatusCode.UNAVAILABLE),
                        codes=RETRYABLE_NARROW)


def test_non_rpc_connection_errors_are_retryable():
    assert is_retryable(ConnectionError("boom"))
    assert is_retryable(ChaosDrop("injected"))  # chaos drops = real outages
    assert not is_retryable(TypeError("encode bug"))
    assert not is_retryable(ValueError("protocol"))


def test_fatal_classification():
    for code in FATAL_CODES:
        assert is_fatal_rpc(FakeRpcError(code))
    assert not is_fatal_rpc(FakeRpcError(grpc.StatusCode.UNAVAILABLE))
    assert not is_fatal_rpc(ConnectionError("down"))


def test_backoff_full_jitter_bounds():
    p = RetryPolicy(base_s=0.1, cap_s=0.5)
    for attempt in range(6):
        for _ in range(20):
            d = p.backoff(attempt)
            assert 0.0 <= d <= min(0.5, 0.1 * 2 ** attempt)


def test_call_sync_retries_then_succeeds():
    p = RetryPolicy(attempts=3, base_s=0.001, cap_s=0.002, deadline_s=5.0)
    calls = []

    def flaky(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            raise FakeRpcError(grpc.StatusCode.UNAVAILABLE)
        return "ok"

    assert p.call_sync(flaky) == "ok"
    assert len(calls) == 3


def test_call_sync_fails_fast_on_fatal():
    p = RetryPolicy(attempts=5, base_s=0.001)
    calls = []

    def rejected(timeout):
        calls.append(1)
        raise FakeRpcError(grpc.StatusCode.UNAUTHENTICATED)

    with pytest.raises(grpc.RpcError):
        p.call_sync(rejected)
    assert len(calls) == 1  # no retry burned on an auth reject


def test_call_sync_exhausts_attempts():
    p = RetryPolicy(attempts=3, base_s=0.001, cap_s=0.002)
    calls = []

    def down(timeout):
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        p.call_sync(down)
    assert len(calls) == 3


def test_call_sync_respects_deadline_budget():
    p = RetryPolicy(attempts=100, base_s=0.05, cap_s=0.05, deadline_s=0.15)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        p.call_sync(lambda t: (_ for _ in ()).throw(ConnectionError("x")))
    assert time.monotonic() - t0 < 1.0  # budget, not 100 attempts


def test_call_async_retries():
    p = RetryPolicy(attempts=3, base_s=0.001, cap_s=0.002)
    calls = []

    def flaky(timeout):
        calls.append(1)
        if len(calls) < 2:
            raise ConnectionError("x")
        return 7

    assert asyncio.run(p.call_async(flaky)) == 7
    assert len(calls) == 2


# ---- circuit breaker ---------------------------------------------------------


def test_circuit_opens_after_consecutive_failures():
    opened, closed = [], []
    cb = CircuitBreaker(failures=3, reset_s=60.0,
                        on_open=lambda: opened.append(1),
                        on_close=lambda: closed.append(1))
    assert cb.allow()
    cb.record_failure()
    cb.record_failure()
    assert cb.allow()  # still closed below threshold
    cb.record_failure()
    assert not cb.allow()
    assert opened == [1] and cb.opens == 1


def test_circuit_success_resets_consecutive_count():
    cb = CircuitBreaker(failures=3, reset_s=60.0)
    cb.record_failure()
    cb.record_failure()
    cb.record_success()
    cb.record_failure()
    cb.record_failure()
    assert cb.allow()  # never hit 3 CONSECUTIVE


def test_circuit_half_open_probe_and_close():
    now = [0.0]
    closed = []
    cb = CircuitBreaker(failures=1, reset_s=5.0, clock=lambda: now[0],
                        on_close=lambda: closed.append(1))
    cb.record_failure()
    assert not cb.allow()
    now[0] = 6.0
    assert cb.allow()        # the ONE half-open probe
    assert not cb.allow()    # concurrent sends stay parked during the probe
    cb.record_success()
    assert cb.allow() and closed == [1]


def test_circuit_half_open_failure_reopens():
    now = [0.0]
    cb = CircuitBreaker(failures=1, reset_s=5.0, clock=lambda: now[0])
    cb.record_failure()
    now[0] = 6.0
    assert cb.allow()
    cb.record_failure()  # probe failed
    assert not cb.allow()
    now[0] = 7.0
    assert not cb.allow()  # reset clock restarted at the probe failure
    now[0] = 12.0
    assert cb.allow()
    assert cb.opens == 2


# ---- token bucket ------------------------------------------------------------


def test_token_bucket_paces_and_records_evidence():
    now = [0.0]
    tb = TokenBucket(rate=10.0, burst=10.0, clock=lambda: now[0])
    assert tb.take(10) == 0.0          # burst goes immediately
    w1 = tb.take(10)                   # next 10 must wait a full second
    assert w1 == pytest.approx(1.0)
    w2 = tb.take(10)                   # debt model: FIFO behind the first
    assert w2 == pytest.approx(2.0)
    assert tb.waits == 2
    assert tb.waited_s == pytest.approx(3.0)
    now[0] = 3.0
    assert tb.take(1) == 0.0  # refilled


# ---- chaos injector ----------------------------------------------------------


def test_injector_rejects_unknown_knob():
    inj = ChaosInjector()
    with pytest.raises(ValueError):
        inj.configure(wire_latency_msec=5)


def test_injector_corruption_flips_a_byte_and_consumes_budget():
    inj = ChaosInjector(seed=3)
    payload = bytes(range(64))
    assert inj.corrupt(payload) is None  # unarmed
    inj.configure(corrupt_next=1)
    bad = inj.corrupt(payload)
    assert bad is not None and bad != payload and len(bad) == len(payload)
    assert sum(a != b for a, b in zip(bad, payload)) == 1
    assert inj.corrupt(payload) is None  # budget consumed
    assert inj.counts.get("frame_corruption") == 1


def test_injector_corruption_breaks_the_binary_wire_crc():
    from storm_tpu.dist import transport, wire

    t = __import__("storm_tpu.runtime.tuples", fromlist=["Tuple"]).Tuple(
        values=["payload"], fields=("f",), source_component="s", edge_id=7)
    frame = wire.encode_deliveries([("b", 0, t)])
    # flip a byte INSIDE the frame (not the magic, which would just route
    # the payload to the JSON decoder and fail differently)
    bad = bytearray(frame)
    bad[len(bad) // 2] ^= 0x40
    with pytest.raises(wire.WireError):
        transport.decode_deliveries(bytes(bad))


def test_injector_engine_hang_budget():
    inj = ChaosInjector()
    assert inj.engine_hang_s() == 0.0
    inj.configure(engine_hang_ms=250.0, engine_hang_next=2)
    assert inj.engine_hang_s() == pytest.approx(0.25)
    assert inj.engine_hang_s() == pytest.approx(0.25)
    assert inj.engine_hang_s() == 0.0  # budget exhausted
    assert inj.counts["engine_hang"] == 2


def test_injector_drop_and_latency():
    inj = ChaosInjector(seed=1)
    assert not inj.should_drop()
    assert inj.wire_delay_s() == 0.0
    inj.configure(wire_drop_pct=1.0, wire_latency_ms=5.0)
    assert inj.should_drop()
    assert inj.wire_delay_s() == pytest.approx(0.005)


# ---- engine watchdog ---------------------------------------------------------


def test_fetch_loop_watchdog_trips_and_releases_ring():
    from storm_tpu.infer.engine import (EngineWatchdogTimeout, InflightBatch,
                                        StagingPool, _fetch_loop)

    class NeverReady:
        def is_ready(self):
            return False

    fetch_q: "queue.SimpleQueue" = queue.SimpleQueue()
    ring = threading.BoundedSemaphore(1)
    ring.acquire()
    staging = StagingPool(1)
    outcomes = []

    handle = InflightBatch(1, 8)
    handle._out = NeverReady()
    handle._buf = staging.acquire((8, 2), np.float32)
    handle.watchdog_ms = 40.0
    handle.on_done = outcomes.append

    t = threading.Thread(target=_fetch_loop, args=(fetch_q, ring, staging),
                         daemon=True)
    t.start()
    try:
        fetch_q.put(handle)
        with pytest.raises(EngineWatchdogTimeout):
            handle.future.result(timeout=5)
        # the stuck batch released its ring slot and staging buffer — the
        # pipeline is NOT wedged behind it
        assert ring.acquire(timeout=2)
        assert isinstance(outcomes[0], EngineWatchdogTimeout)
        assert handle._buf is None
    finally:
        fetch_q.put(None)
        t.join(timeout=5)


def test_fetch_loop_no_watchdog_blocks_normally():
    from storm_tpu.infer.engine import InflightBatch, StagingPool, _fetch_loop

    class Ready:
        def is_ready(self):
            return True

        def block_until_ready(self):
            return self

        def __array__(self, dtype=None):
            return np.zeros((4, 2), np.float32)

    fetch_q: "queue.SimpleQueue" = queue.SimpleQueue()
    ring = threading.BoundedSemaphore(1)
    ring.acquire()
    handle = InflightBatch(3, 4)
    handle._out = Ready()
    handle._t_launched = time.perf_counter()
    t = threading.Thread(target=_fetch_loop,
                         args=(fetch_q, ring, StagingPool(1)), daemon=True)
    t.start()
    try:
        fetch_q.put(handle)
        out = handle.future.result(timeout=5)
        assert out.shape == (3, 2)  # sliced to true n
    finally:
        fetch_q.put(None)
        t.join(timeout=5)


def test_watchdog_note_quarantines_on_consecutive_trips():
    from storm_tpu.infer.engine import (EngineWatchdogTimeout,
                                        InferenceEngine)

    fired = []
    eng = SimpleNamespace(
        batch_cfg=BatchConfig(watchdog_ms=10.0, watchdog_trips=2),
        model_cfg=SimpleNamespace(name="stub"),
        _watchdog_lock=threading.Lock(),
        _watchdog_trips=0,
        quarantined=False,
        on_quarantine=fired.append,
    )
    note = InferenceEngine._watchdog_note
    note(eng, EngineWatchdogTimeout("t1"))
    assert not eng.quarantined
    note(eng, None)  # a success resets the consecutive count
    note(eng, EngineWatchdogTimeout("t2"))
    note(eng, EngineWatchdogTimeout("t3"))
    assert eng.quarantined
    assert fired == [2]
    # already quarantined: further trips must not re-fire the hook
    note(eng, EngineWatchdogTimeout("t4"))
    assert fired == [2]


def test_engine_hang_injection_quarantines_real_engine():
    """End-to-end on a real (CPU) engine: armed engine-hang injections
    make dispatched batches miss their fetch deadline; two consecutive
    trips quarantine the engine and dispatch starts failing fast."""
    from storm_tpu.infer.engine import (EngineQuarantined,
                                        EngineWatchdogTimeout,
                                        InferenceEngine)
    from storm_tpu.resilience import get_injector

    eng = InferenceEngine(
        ModelConfig(name="lenet5", dtype="float32", input_shape=(28, 28, 1)),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,), watchdog_ms=100.0,
                    watchdog_trips=2),
    )
    eng.warmup()
    x = np.zeros((4, 28, 28, 1), np.float32)
    assert eng.dispatch((x,)).future.result(timeout=30).shape == (4, 10)
    inj = get_injector()
    inj.configure(engine_hang_ms=600.0, engine_hang_next=2)
    try:
        for _ in range(2):
            with pytest.raises(EngineWatchdogTimeout):
                eng.dispatch((x,)).future.result(timeout=10)
        assert eng.quarantined
        with pytest.raises(EngineQuarantined):
            eng.dispatch((x,))
    finally:
        inj.configure(engine_hang_ms=0.0, engine_hang_next=0)


# ---- PeerSender park / reroute ----------------------------------------------


def _tuple(v="x"):
    from storm_tpu.runtime.tuples import Tuple

    return Tuple(values=[v], fields=("f",), source_component="s", edge_id=9)


def test_sender_reroutes_while_circuit_open():
    from storm_tpu.dist.worker import PeerSender

    async def run():
        s = PeerSender("127.0.0.1:1",
                       resilience=ResilienceConfig(circuit_failures=1,
                                                   circuit_reset_s=60.0))
        s.circuit.record_failure()  # open
        rerouted = []

        async def reroute(c, i, t):
            rerouted.append((c, i, t))
            return True

        s.set_reroute(reroute)
        await asyncio.wait_for(s._flush([("b", 0, _tuple())], []), timeout=5)
        return rerouted

    rerouted = asyncio.run(run())
    assert len(rerouted) == 1 and rerouted[0][0] == "b"


def test_sender_parks_then_sends_after_probe():
    from storm_tpu.dist.worker import PeerSender

    async def run():
        s = PeerSender("127.0.0.1:1",
                       resilience=ResilienceConfig(circuit_failures=1,
                                                   circuit_reset_s=0.05))
        s.circuit.record_failure()  # open; no reroute hook -> park
        sent = []

        async def fake_negotiate():
            return True

        async def fake_send(fn, payload, *, codes):
            sent.append((payload, codes))

        s._negotiate = fake_negotiate
        s._send = fake_send
        await asyncio.wait_for(s._flush([("b", 0, _tuple())], []), timeout=5)
        return sent, s.circuit.allow()

    sent, closed = asyncio.run(run())
    # parked through the open window, then delivered on the probe — never
    # silently dropped — and the successful send closed the circuit
    assert len(sent) == 1 and closed


def test_sender_drops_only_non_retryable_failures():
    from storm_tpu.dist.worker import PeerSender

    async def run():
        s = PeerSender("127.0.0.1:1")
        calls = []

        async def fake_negotiate():
            return False

        async def fake_send(fn, payload, *, codes):
            calls.append(1)
            raise TypeError("raw bytes on the JSON wire")

        s._negotiate = fake_negotiate
        s._send = fake_send
        # returns (leaves the batch to ledger replay) instead of looping
        await asyncio.wait_for(s._flush([("b", 0, _tuple())], []), timeout=5)
        return calls

    assert asyncio.run(run()) == [1]


def test_sender_pacing_records_against_real_registry():
    """Regression: ``_pace`` must work against the REAL metrics objects —
    the first cut called ``Histogram.record`` (which doesn't exist), so
    every throttled flush raised AttributeError after the counter inc and
    ``_flush`` dropped the batch to replay as 'non-retryable'."""
    from storm_tpu.dist.worker import PeerSender
    from storm_tpu.runtime.metrics import MetricsRegistry
    from storm_tpu.runtime.tracing import FlightRecorder

    async def run():
        s = PeerSender("127.0.0.1:1")
        m = MetricsRegistry()
        s.bind_obs(m, FlightRecorder(), 3)
        # bind_obs resets the per-peer circuit gauge (a replacement sender
        # re-binds the same name; the dead one's open=1 must not latch).
        assert m.snapshot()["_transport"]["dist_circuit_open_w3"] == 0.0
        s.begin_recovery_pacing(rate=100.0, window_s=30.0)
        s._pacer.take(100)  # drain the burst allowance: next take waits
        await s._pace(5)    # ~50ms of debt at 100 tuples/s
        return m.snapshot()["_transport"]

    snap = asyncio.run(run())
    assert snap["dist_replay_throttled"] >= 1
    hist = snap["dist_replay_throttle_ms"]
    assert hist["count"] >= 1 and hist["max"] > 0


def test_reroute_tuple_respects_groupings():
    from storm_tpu.dist.worker import DistRuntime
    from storm_tpu.runtime.groupings import FieldsGrouping, ShuffleGrouping

    class Inbox:
        def __init__(self, sender):
            self._sender = sender
            self.got = []

        async def put(self, t):
            self.got.append(t)

    dead = object()
    live = object()
    inboxes = [Inbox(dead), Inbox(live), Inbox(live)]
    rt = SimpleNamespace(
        topology=SimpleNamespace(specs={"b": SimpleNamespace(
            inputs=[SimpleNamespace(grouping=ShuffleGrouping())])}),
        groups={"b": SimpleNamespace(inboxes=inboxes)},
        _reroute_rr=0,
    )
    t = _tuple()
    ok = asyncio.run(DistRuntime.reroute_tuple(rt, "b", 0, t, dead))
    assert ok
    assert sum(len(i.got) for i in inboxes[1:]) == 1
    assert not inboxes[0].got  # never back to the dead peer

    # fields grouping pins tuples to their task: reroute must refuse
    rt.topology.specs["b"].inputs = [
        SimpleNamespace(grouping=FieldsGrouping(["f"]))]
    assert not asyncio.run(DistRuntime.reroute_tuple(rt, "b", 0, t, dead))

    # no survivors (component wholly on the dead worker): park instead
    rt.topology.specs["b"].inputs = [
        SimpleNamespace(grouping=ShuffleGrouping())]
    rt.groups["b"].inboxes = [Inbox(dead)]
    assert not asyncio.run(DistRuntime.reroute_tuple(rt, "b", 0, t, dead))


# ---- wait_ready classification ----------------------------------------------


def test_wait_ready_fails_fast_on_auth_reject():
    from storm_tpu.dist.transport import WorkerClient

    c = WorkerClient("127.0.0.1:1")
    c._control = lambda *a, **kw: (_ for _ in ()).throw(
        FakeRpcError(grpc.StatusCode.UNAUTHENTICATED))
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="rejected the handshake"):
        c.wait_ready(timeout=30.0)
    assert time.monotonic() - t0 < 5.0  # no 30s of polling a hard reject
    c.close()


def test_wait_ready_times_out_on_connectivity():
    from storm_tpu.dist.transport import WorkerClient

    c = WorkerClient("127.0.0.1:1")
    c._control = lambda *a, **kw: (_ for _ in ()).throw(
        FakeRpcError(grpc.StatusCode.UNAVAILABLE))
    with pytest.raises(TimeoutError):
        c.wait_ready(timeout=0.3)
    c.close()
