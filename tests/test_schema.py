"""Wire-contract tests: the {"instances": ...}/{"predictions": ...} JSON API
(reference README.md:22-34, InstObj.java, PredObj.java)."""

import json

import numpy as np
import pytest

from storm_tpu.api.schema import (
    DeadLetter,
    Instances,
    SchemaError,
    decode_instances,
    decode_predictions,
    encode_predictions,
)


def test_decode_mnist_shape():
    # Reference input: 4-D NHWC batch (README.md:22-27).
    x = np.zeros((2, 28, 28, 1), dtype=np.float32)
    payload = json.dumps({"instances": x.tolist()})
    inst = decode_instances(payload)
    assert inst.data.shape == (2, 28, 28, 1)
    assert inst.data.dtype == np.float32
    assert inst.batch_size == 2


def test_decode_values_roundtrip():
    x = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4) / 7.0
    inst = decode_instances(json.dumps({"instances": x.tolist()}))
    np.testing.assert_allclose(inst.data, x, rtol=1e-6)


def test_decode_bytes_payload():
    payload = json.dumps({"instances": [[1.0, 2.0]]}).encode("utf-8")
    assert decode_instances(payload).data.shape == (1, 2)


def test_decode_rejects_bad_json():
    with pytest.raises(SchemaError):
        decode_instances("{not json")


def test_decode_rejects_missing_key():
    with pytest.raises(SchemaError):
        decode_instances('{"wrong": []}')


def test_decode_rejects_ragged():
    with pytest.raises(SchemaError):
        decode_instances('{"instances": [[1,2],[3]]}')


def test_decode_rejects_scalar_and_empty():
    with pytest.raises(SchemaError):
        decode_instances('{"instances": 3}')
    with pytest.raises(SchemaError):
        decode_instances('{"instances": []}')


def test_encode_predictions_contract():
    # Reference output: {"predictions": [[p0..p9]]} (README.md:29-34).
    p = np.linspace(0, 1, 10, dtype=np.float32)[None, :]
    payload = encode_predictions(p)
    obj = json.loads(payload)
    assert list(obj) == ["predictions"]
    assert len(obj["predictions"]) == 1 and len(obj["predictions"][0]) == 10
    back = decode_predictions(payload)
    np.testing.assert_allclose(back.data, p, atol=1e-6)


def test_dead_letter_serializes():
    dl = DeadLetter(payload="{bad", error="parse failed")
    obj = json.loads(dl.to_json())
    assert obj["stage"] == "decode"
    assert "parse failed" in obj["error"]
