"""Regenerate the JVM-boundary golden fixtures (run from the repo root).

The fixtures freeze the exact bytes the /storm_tpu.Inference/Predict
boundary ships: an Arrow IPC tensor request (N,H,W,C f32) and response
(N,K f32), as emitted by the production C++ marshaller
(storm_tpu/native/arrow_tensor.cpp). A JVM implementer validates their
Arrow writer/reader against these without running Python — see
docs/JVM_CLIENT.md.
"""
import numpy as np

from storm_tpu.serve.marshal import encode_tensor

def request_array() -> np.ndarray:
    # 2 MNIST-shaped instances, deterministic ramp (not random: the byte
    # pattern must be reproducible from the formula in the docs alone)
    n = 2 * 28 * 28 * 1
    return (np.arange(n, dtype=np.float32) / n).reshape(2, 28, 28, 1)

def response_array() -> np.ndarray:
    # 2 softmax-like rows over 10 classes: row i = softmax(arange(10)+i)
    z = np.stack([np.arange(10, dtype=np.float32) + i for i in range(2)])
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return (e / e.sum(axis=1, keepdims=True)).astype(np.float32)

if __name__ == "__main__":
    import pathlib
    here = pathlib.Path(__file__).parent
    (here / "predict_request.arrow").write_bytes(encode_tensor(request_array()))
    (here / "predict_response.arrow").write_bytes(encode_tensor(response_array()))
    print("wrote", *[p.name for p in here.glob("*.arrow")])
