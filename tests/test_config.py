import json

import pytest

from storm_tpu.config import BatchConfig, Config, OffsetsConfig, SinkConfig


def test_defaults_mirror_reference_constants():
    # MainTopology.java:25-28 — 2 spouts / 4 inference / 2 sinks.
    cfg = Config()
    assert cfg.topology.spout_parallelism == 2
    assert cfg.topology.inference_parallelism == 4
    assert cfg.topology.sink_parallelism == 2
    # Reference freshness semantics (MainTopology.java:101-103).
    assert cfg.offsets.policy == "latest"
    assert cfg.offsets.max_behind == 0
    # KafkaBolt defaults (KafkaBolt.java:50-54): async, not fire-and-forget.
    assert cfg.sink.mode == "async"


def test_bucket_selection():
    b = BatchConfig(max_batch=64, buckets=(8, 16, 64))
    assert b.bucket_for(1) == 8
    assert b.bucket_for(9) == 16
    assert b.bucket_for(64) == 64
    assert b.bucket_for(1000) == 64


def test_buckets_normalized():
    b = BatchConfig(max_batch=32, buckets=(64, 8))
    assert b.buckets[-1] == 32
    assert 64 not in b.buckets


def test_apply_dict_and_overrides():
    cfg = Config.from_dict({"topology": {"inference_parallelism": 8}})
    assert cfg.topology.inference_parallelism == 8
    cfg.apply_overrides(["model.name=resnet20", "batch.max_batch=128"])
    assert cfg.model.name == "resnet20"
    assert cfg.batch.max_batch == 128


def test_unknown_key_rejected():
    with pytest.raises(KeyError):
        Config.from_dict({"topology": {"nope": 1}})
    with pytest.raises(KeyError):
        Config.from_dict({"nope": {}})


def test_invalid_enum_values():
    with pytest.raises(ValueError):
        OffsetsConfig(policy="bogus")
    with pytest.raises(ValueError):
        SinkConfig(mode="bogus")


def test_load_json(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"broker": {"input_topic": "in-x"}}))
    cfg = Config.load(p)
    assert cfg.broker.input_topic == "in-x"


def test_load_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text('[model]\nname = "vit_b16"\nnum_classes = 1000\n')
    cfg = Config.load(p)
    assert cfg.model.name == "vit_b16"
    assert cfg.model.num_classes == 1000


def test_broker_config_validates_message_format():
    from storm_tpu.config import BrokerConfig

    assert BrokerConfig(message_format="v2").message_format == "v2"
    with pytest.raises(ValueError, match="message_format"):
        BrokerConfig(message_format="V2")
    with pytest.raises(ValueError, match="kind"):
        BrokerConfig(kind="rabbitmq")


def test_model_config_validates_weights():
    from storm_tpu.config import ModelConfig

    assert ModelConfig(weights="int8").weights == "int8"
    with pytest.raises(ValueError, match="weights"):
        ModelConfig(weights="int4")


def test_batch_config_max_inflight():
    from storm_tpu.config import BatchConfig

    assert BatchConfig().max_inflight == 2
    assert BatchConfig(max_inflight=4).max_inflight == 4
