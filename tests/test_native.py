"""Native C++ parser: build (if toolchain present), parity vs Python path."""

import json
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

NATIVE_DIR = Path(__file__).parent.parent / "storm_tpu" / "native"


@pytest.fixture(scope="module")
def native_lib():
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    r = subprocess.run(["make", "-C", str(NATIVE_DIR)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    import storm_tpu.native as n

    # force (re)load after build
    n._load_attempted = False
    n._lib = None
    if not n.native_available():
        pytest.skip("native lib failed to load")
    return n


def test_native_parity_with_python(native_lib):
    from storm_tpu.api.schema import decode_instances

    x = np.random.RandomState(0).rand(3, 5, 5, 2).astype(np.float32)
    payload = json.dumps({"instances": x.tolist(), "meta": {"k": [1, "s"]}})
    got = native_lib.parse_instances_native(payload)
    np.testing.assert_allclose(got, x, rtol=1e-6)
    # and through the public decode path
    inst = decode_instances(payload)
    np.testing.assert_allclose(inst.data, x, rtol=1e-6)


@pytest.mark.parametrize(
    "bad",
    [
        '{"instances": [[1,2],[3]]}',  # ragged
        '{"instances": [[1,2],[3,[4]]]}',  # mixed depth
        '{"nope": 1}',
        '{"instances": "x"}',
        "junk",
        '{"instances": []}',
        '{"instances": [[1,2]] } trailing',
    ],
)
def test_native_rejects_malformed(native_lib, bad):
    from storm_tpu.api.schema import SchemaError

    with pytest.raises(SchemaError):
        native_lib.parse_instances_native(bad)


def test_native_number_formats(native_lib):
    payload = '{"instances": [[1, -2.5, 3e2, 0.125e-2, 1E+2, -0.0]]}'
    got = native_lib.parse_instances_native(payload)
    np.testing.assert_allclose(
        got, np.array([[1, -2.5, 300, 0.00125, 100, -0.0]], np.float32), rtol=1e-6
    )


def test_python_fallback_when_disabled(native_lib, monkeypatch):
    monkeypatch.setenv("STORM_TPU_NO_NATIVE", "1")
    import storm_tpu.native as n

    n._load_attempted = False
    n._lib = None
    assert n.parse_instances_native('{"instances": [[1]]}') is None
    from storm_tpu.api.schema import decode_instances

    assert decode_instances('{"instances": [[1.0, 2.0]]}').data.shape == (1, 2)
    n._load_attempted = False
    n._lib = None


# ---- native predictions serializer -------------------------------------------


def test_format_predictions_native_roundtrip():
    from storm_tpu.api.schema import decode_predictions
    from storm_tpu.native import format_predictions_native, native_available

    if not native_available():
        pytest.skip("native library not built")
    a = np.array(
        [[0.1234567891, 0.5, 1e-9, 123456.789], [1.0, 0.0, -0.25, 3.14159265]],
        np.float32,
    )
    s = format_predictions_native(a)
    assert s is not None and s.startswith('{"predictions": [[')
    back = decode_predictions(s)
    np.testing.assert_allclose(back.data, a, rtol=1e-6, atol=1e-7)


def test_format_predictions_matches_python_path(monkeypatch):
    from storm_tpu.api import schema
    from storm_tpu.native import native_available

    if not native_available():
        pytest.skip("native library not built")
    rng = np.random.RandomState(0)
    a = rng.rand(4, 10).astype(np.float32)
    s_native = schema.encode_predictions(a)
    # Force the Python path and compare numerically.
    monkeypatch.setattr(
        "storm_tpu.native.format_predictions_native", lambda arr: None
    )
    s_py = schema.encode_predictions(a)
    d1 = schema.decode_predictions(s_native).data
    d2 = schema.decode_predictions(s_py).data
    np.testing.assert_allclose(d1, d2, rtol=1e-6, atol=1e-7)


def test_format_predictions_1d_and_nonfinite():
    from storm_tpu.api.schema import decode_predictions
    from storm_tpu.native import format_predictions_native, native_available

    if not native_available():
        pytest.skip("native library not built")
    s = format_predictions_native(np.array([0.25, 0.75], np.float32))
    assert decode_predictions(s).data.shape == (1, 2)
    s = format_predictions_native(np.array([[np.nan, np.inf, -np.inf]], np.float32))
    # json module accepts NaN/Infinity tokens (python json.dumps emits them too)
    back = decode_predictions(s).data
    assert np.isnan(back[0, 0]) and np.isinf(back[0, 1]) and back[0, 2] < 0


# ---------------------------------------------------------------------------
# Arrow IPC tensor marshaller (arrow_tensor.cpp) — the C++ zero-copy
# host<->engine boundary (SURVEY.md §2.2), wire-compatible with pyarrow.
# ---------------------------------------------------------------------------


def _need_native_tensor():
    from storm_tpu.native import _load, native_available

    if not native_available() or not hasattr(_load(), "stpu_tensor_encode"):
        pytest.skip("native tensor marshaller not built")


def test_arrow_tensor_roundtrip_all_dtypes():
    _need_native_tensor()
    from storm_tpu.native import decode_tensor_native, encode_tensor_native

    rng = np.random.RandomState(0)
    dtypes = [
        np.float32, np.float64, np.float16, np.uint8, np.int8, np.uint16,
        np.int16, np.uint32, np.int32, np.uint64, np.int64,
    ]
    for dt in dtypes:
        for shp in [(4,), (2, 3), (1, 28, 28, 1), (3, 1, 2)]:
            x = (rng.rand(*shp) * 100).astype(dt)
            y = decode_tensor_native(encode_tensor_native(x))
            assert y.dtype == x.dtype and y.shape == x.shape
            np.testing.assert_array_equal(y, x)


def test_arrow_tensor_pyarrow_cross_compat():
    _need_native_tensor()
    pa = pytest.importorskip("pyarrow")
    from storm_tpu.native import decode_tensor_native, encode_tensor_native

    rng = np.random.RandomState(1)
    for dt in [np.float32, np.float16, np.uint8, np.int64]:
        x = (rng.rand(2, 5, 3) * 50).astype(dt)
        # native writer -> pyarrow reader
        z = pa.ipc.read_tensor(pa.py_buffer(encode_tensor_native(x))).to_numpy()
        np.testing.assert_array_equal(z, x)
        # pyarrow writer -> native reader
        sink = pa.BufferOutputStream()
        pa.ipc.write_tensor(pa.Tensor.from_numpy(x), sink)
        w = decode_tensor_native(sink.getvalue().to_pybytes())
        assert w.dtype == x.dtype
        np.testing.assert_array_equal(w, x)


def test_arrow_tensor_decode_is_zero_copy_view():
    _need_native_tensor()
    from storm_tpu.native import decode_tensor_native, encode_tensor_native

    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    y = decode_tensor_native(encode_tensor_native(x))
    # A view over the message bytes: no ownership, read-only.
    assert not y.flags.owndata
    assert not y.flags.writeable
    np.testing.assert_array_equal(y, x)


def test_arrow_tensor_malformed_rejected():
    _need_native_tensor()
    from storm_tpu.native import decode_tensor_native

    for bad in [b"", b"\x00" * 12, b"\xff\xff\xff\xff\x10\x00\x00\x00" + b"\x00" * 32,
                b"garbage" * 5]:
        with pytest.raises(ValueError):
            decode_tensor_native(bad)


def test_marshal_prefers_native_path(monkeypatch):
    _need_native_tensor()
    from storm_tpu.serve import marshal

    calls = []
    real = marshal.encode_tensor_native

    def spy(x):
        calls.append(x.shape)
        return real(x)

    monkeypatch.setattr(marshal, "encode_tensor_native", spy)
    x = np.ones((2, 4), np.float32)
    buf = marshal.encode_tensor(x)
    assert calls == [(2, 4)]
    np.testing.assert_array_equal(marshal.decode_tensor(buf), x)


def test_arrow_tensor_fortran_order_falls_back():
    _need_native_tensor()
    pa = pytest.importorskip("pyarrow")
    from storm_tpu.native import decode_tensor_native
    from storm_tpu.serve.marshal import decode_tensor

    x = np.asfortranarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(pa.Tensor.from_numpy(x), sink)
    buf = sink.getvalue().to_pybytes()
    # Valid-but-unsupported layout: native path declines (None), the public
    # decode_tensor falls back to pyarrow and still returns the array.
    assert decode_tensor_native(buf) is None
    np.testing.assert_array_equal(decode_tensor(buf), x)


def test_arrow_tensor_adversarial_dims_rejected():
    _need_native_tensor()
    from storm_tpu.native import decode_tensor_native, encode_tensor_native

    good = encode_tensor_native(np.ones((2, 3), np.float32))
    idx = good.find((2).to_bytes(8, "little", signed=True), 8)
    assert idx > 0
    for evil in (-1, 2**62):
        patched = bytearray(good)
        patched[idx : idx + 8] = evil.to_bytes(8, "little", signed=True)
        with pytest.raises(ValueError):
            decode_tensor_native(bytes(patched))


def test_arrow_tensor_accepts_any_buffer_type():
    _need_native_tensor()
    from storm_tpu.native import decode_tensor_native, encode_tensor_native

    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    buf = encode_tensor_native(x)
    for cast in (bytes, bytearray, memoryview):
        y = decode_tensor_native(cast(buf))
        assert y is not None and not y.flags.owndata
        np.testing.assert_array_equal(y, x)


def test_arrow_tensor_unsupported_rank_falls_back():
    _need_native_tensor()
    pa = pytest.importorskip("pyarrow")
    from storm_tpu.native import decode_tensor_native
    from storm_tpu.serve.marshal import decode_tensor

    x = np.ones((1,) * 9, np.float32)  # rank 9 > the fast path's max rank 8
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(pa.Tensor.from_numpy(x), sink)
    buf = sink.getvalue().to_pybytes()
    assert decode_tensor_native(buf) is None  # fallback signal, not an error
    np.testing.assert_array_equal(decode_tensor(buf), x)
