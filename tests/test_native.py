"""Native C++ parser: build (if toolchain present), parity vs Python path."""

import json
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

NATIVE_DIR = Path(__file__).parent.parent / "storm_tpu" / "native"


@pytest.fixture(scope="module")
def native_lib():
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    r = subprocess.run(["make", "-C", str(NATIVE_DIR)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    import storm_tpu.native as n

    # force (re)load after build
    n._load_attempted = False
    n._lib = None
    if not n.native_available():
        pytest.skip("native lib failed to load")
    return n


def test_native_parity_with_python(native_lib):
    from storm_tpu.api.schema import decode_instances

    x = np.random.RandomState(0).rand(3, 5, 5, 2).astype(np.float32)
    payload = json.dumps({"instances": x.tolist(), "meta": {"k": [1, "s"]}})
    got = native_lib.parse_instances_native(payload)
    np.testing.assert_allclose(got, x, rtol=1e-6)
    # and through the public decode path
    inst = decode_instances(payload)
    np.testing.assert_allclose(inst.data, x, rtol=1e-6)


@pytest.mark.parametrize(
    "bad",
    [
        '{"instances": [[1,2],[3]]}',  # ragged
        '{"instances": [[1,2],[3,[4]]]}',  # mixed depth
        '{"nope": 1}',
        '{"instances": "x"}',
        "junk",
        '{"instances": []}',
        '{"instances": [[1,2]] } trailing',
    ],
)
def test_native_rejects_malformed(native_lib, bad):
    from storm_tpu.api.schema import SchemaError

    with pytest.raises(SchemaError):
        native_lib.parse_instances_native(bad)


def test_native_number_formats(native_lib):
    payload = '{"instances": [[1, -2.5, 3e2, 0.125e-2, 1E+2, -0.0]]}'
    got = native_lib.parse_instances_native(payload)
    np.testing.assert_allclose(
        got, np.array([[1, -2.5, 300, 0.00125, 100, -0.0]], np.float32), rtol=1e-6
    )


def test_python_fallback_when_disabled(native_lib, monkeypatch):
    monkeypatch.setenv("STORM_TPU_NO_NATIVE", "1")
    import storm_tpu.native as n

    n._load_attempted = False
    n._lib = None
    assert n.parse_instances_native('{"instances": [[1]]}') is None
    from storm_tpu.api.schema import decode_instances

    assert decode_instances('{"instances": [[1.0, 2.0]]}').data.shape == (1, 2)
    n._load_attempted = False
    n._lib = None
