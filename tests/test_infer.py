"""Micro-batcher + engine tests (SURVEY.md §7 step 5)."""

import time

import jax
import numpy as np
import pytest

from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
from storm_tpu.infer.batcher import MicroBatcher
from storm_tpu.infer.engine import InferenceEngine
from storm_tpu.models import build_model
from storm_tpu.models.registry import init_params


# ---- batcher -----------------------------------------------------------------


def _data(n):
    return np.zeros((n, 2, 2, 1), np.float32)


def test_batcher_fills_to_max():
    b = MicroBatcher(BatchConfig(max_batch=4, max_wait_ms=1000))
    assert b.add("a", _data(2)) is None
    batch = b.add("b", _data(2))
    assert batch is not None
    assert batch.size == 4
    assert len(b) == 0


def test_batcher_deadline():
    b = MicroBatcher(BatchConfig(max_batch=100, max_wait_ms=5))
    t0 = time.perf_counter()
    b.add("a", _data(1), ts=t0)
    assert b.take_if_due(now=t0 + 0.001) is None
    batch = b.take_if_due(now=t0 + 0.006)
    assert batch is not None and batch.size == 1


def test_batcher_never_overshoots_max_batch():
    """A record that would overshoot flushes the pending batch first
    (reachable via multi-instance records, e.g. bench --instances-per-msg 3)."""
    b = MicroBatcher(BatchConfig(max_batch=8, max_wait_ms=1000))
    assert b.add("a", _data(6)) is None
    flushed = b.add("b", _data(3))  # 6+3 > 8 -> flush the 6
    assert flushed is not None and flushed.size == 6
    assert len(b) == 3
    # oversized newcomer flushes the pending 3; itself waits for the deadline
    flushed2 = b.add("c", _data(20))
    assert flushed2 is not None and flushed2.size == 3
    assert len(b) == 20
    assert b.take_all().size == 20


def test_engine_handles_oversized_batch():
    eng = InferenceEngine(
        ModelConfig(name="lenet5", dtype="float32", input_shape=(28, 28, 1)),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,)),
    )
    out = eng.predict(np.zeros((11, 28, 28, 1), np.float32))  # > max_batch
    assert out.shape == (11, 10)


def test_batcher_multi_instance_records_split():
    b = MicroBatcher(BatchConfig(max_batch=8, max_wait_ms=1000))
    b.add("r1", np.full((3, 2), 1.0, np.float32))
    batch = b.add("r2", np.full((5, 2), 2.0, np.float32))
    assert batch.size == 8
    out = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    parts = batch.split(out)
    assert parts[0][0] == "r1" and parts[0][1].shape == (3, 4)
    assert parts[1][0] == "r2" and parts[1][1].shape == (5, 4)
    np.testing.assert_array_equal(parts[1][1], out[3:])


# ---- engine ------------------------------------------------------------------


@pytest.fixture(scope="module")
def lenet_engine():
    return InferenceEngine(
        ModelConfig(name="lenet5", dtype="float32", input_shape=(28, 28, 1)),
        ShardingConfig(data_parallel=0),  # all 8 virtual CPU devices
        BatchConfig(max_batch=16, buckets=(8, 16)),
    )


def test_engine_mesh_uses_all_devices(lenet_engine):
    assert lenet_engine.mesh.devices.size == len(jax.devices())


def test_engine_predict_matches_direct_apply(lenet_engine):
    model = build_model("lenet5")
    params, state = init_params(model, seed=0)
    x = np.asarray(
        jax.random.normal(jax.random.PRNGKey(3), (5, 28, 28, 1)), np.float32
    )
    got = lenet_engine.predict(x)
    logits, _ = model.apply(params, state, x)
    want = np.asarray(jax.nn.softmax(logits, -1))
    assert got.shape == (5, 10)
    np.testing.assert_allclose(got, want, atol=1e-5)
    np.testing.assert_allclose(got.sum(-1), np.ones(5), atol=1e-5)


def test_engine_pads_to_mesh_divisible(lenet_engine):
    dp = lenet_engine.mesh.devices.size
    padded = lenet_engine.pad_batch(1)
    assert padded % dp == 0
    # Result sliced back to the true batch size.
    out = lenet_engine.predict(np.zeros((3, 28, 28, 1), np.float32))
    assert out.shape == (3, 10)


def test_engine_warmup_compiles_buckets(lenet_engine):
    lenet_engine.warmup()
    assert lenet_engine.pad_batch(8) in lenet_engine.compiled_batches
    assert lenet_engine.pad_batch(16) in lenet_engine.compiled_batches


def test_engine_bf16_path():
    eng = InferenceEngine(
        ModelConfig(name="lenet5", dtype="bfloat16", input_shape=(28, 28, 1)),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,)),
    )
    out = eng.predict(np.random.randn(2, 28, 28, 1).astype(np.float32))
    assert out.dtype == np.float32  # probabilities come back f32
    np.testing.assert_allclose(out.sum(-1), np.ones(2), atol=1e-2)


def test_engine_uint8_transfer_matches_f32():
    """uint8 wire quantization (ModelConfig.transfer_dtype) must stay close to
    the full-precision path: inputs cross the link as 1 byte/elem + a per-batch
    (scale, offset), dequantized on device inside the jit program."""
    rng = np.random.RandomState(0)
    x = rng.rand(6, 28, 28, 1).astype(np.float32)  # pixel-like [0, 1)
    f32 = InferenceEngine(
        ModelConfig(name="lenet5", dtype="float32", input_shape=(28, 28, 1)),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,)),
    )
    q8 = InferenceEngine(
        ModelConfig(
            name="lenet5", dtype="float32", input_shape=(28, 28, 1),
            transfer_dtype="uint8",
        ),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,)),
    )
    want = f32.predict(x)
    got = q8.predict(x)
    assert got.shape == want.shape
    np.testing.assert_allclose(got.sum(-1), np.ones(6), atol=1e-4)
    np.testing.assert_allclose(got, want, atol=0.02)


def test_engine_uint8_constant_input_no_nan():
    """Degenerate range (hi == lo) must not divide by zero."""
    eng = InferenceEngine(
        ModelConfig(
            name="lenet5", dtype="float32", input_shape=(28, 28, 1),
            transfer_dtype="uint8",
        ),
        ShardingConfig(data_parallel=1),
        BatchConfig(max_batch=8, buckets=(8,)),
    )
    out = eng.predict(np.full((2, 28, 28, 1), 0.5, np.float32))
    assert np.isfinite(out).all()


def test_model_config_rejects_bad_transfer_dtype():
    with pytest.raises(ValueError):
        ModelConfig(transfer_dtype="int4")


# ---- weight-only int8 quantization (w8a16) -----------------------------------


def test_int8_weights_predictions_close_to_float():
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    x = np.random.RandomState(0).rand(4, 28, 28, 1).astype(np.float32)
    outs = {}
    for weights in ("float", "int8"):
        eng = InferenceEngine(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1), dtype="float32",
                        weights=weights),
            ShardingConfig(data_parallel=0),
            BatchConfig(max_batch=4, buckets=(4,)),
        )
        outs[weights] = eng.predict(x)
    np.testing.assert_allclose(outs["float"].sum(axis=1), 1.0, atol=1e-4)
    # per-channel symmetric int8 stays close on softmax outputs
    assert np.max(np.abs(outs["float"] - outs["int8"])) < 0.05
    # argmax must agree wherever the float decision is decisive (random-init
    # outputs are near-uniform; quantization may flip exact ties)
    top2 = np.sort(outs["float"], axis=1)[:, -2:]
    decisive = (top2[:, 1] - top2[:, 0]) > 0.05
    assert np.all(
        np.argmax(outs["float"], 1)[decisive]
        == np.argmax(outs["int8"], 1)[decisive]
    )


def test_int8_weights_shrink_param_bytes():
    import jax
    import numpy as np

    from storm_tpu.infer.engine import dequantize_params, quantize_params
    from storm_tpu.models import build_model
    from storm_tpu.models.registry import init_params

    model = build_model("lenet5")
    params, _ = init_params(model, seed=0)

    def nbytes(tree):
        return sum(np.asarray(l).nbytes
                   for l in jax.tree.leaves(tree))

    q = quantize_params(params)
    assert nbytes(q) < 0.4 * nbytes(params)  # f32 -> int8 + small scales
    # dequant round trip stays within one quantization step per channel
    deq = dequantize_params(q, np.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        if a.ndim >= 2:
            step = np.max(np.abs(a)) / 127.0
            assert np.max(np.abs(a - b)) <= step + 1e-6
        else:
            np.testing.assert_array_equal(a, b)  # biases untouched


def test_int8_weights_bf16_keeps_compute_dtype():
    """Non-quantized leaves are cast to the compute dtype: an f32 bias
    would promote every activation back to f32."""
    import jax
    import jax.numpy as jnp

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine, _is_qleaf

    eng = InferenceEngine(
        ModelConfig(name="lenet5", input_shape=(28, 28, 1), dtype="bfloat16",
                    weights="int8"),
        ShardingConfig(data_parallel=0),
        BatchConfig(max_batch=4, buckets=(4,)),
    )
    for leaf in jax.tree.leaves(
            eng.params, is_leaf=lambda l: _is_qleaf(l)):
        if _is_qleaf(leaf):
            assert leaf["__q"].dtype == jnp.int8
        else:
            assert leaf.dtype != jnp.float32, "f32 leaf would promote activations"


@pytest.mark.slow
def test_int8_fused_matches_int8():
    """"int8_fused" (Pallas fused dequant-matmul on TPU; jnp fallback here)
    quantizes identically to "int8" — outputs must agree tightly on a
    dense-only model (mixer: every matmul goes through layers.dense)."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    x = np.random.RandomState(1).rand(4, 32, 32, 3).astype(np.float32)
    outs = {}
    for weights in ("int8", "int8_fused"):
        eng = InferenceEngine(
            ModelConfig(name="mixer_tiny", input_shape=(32, 32, 3),
                        dtype="float32", weights=weights),
            ShardingConfig(data_parallel=0),
            BatchConfig(max_batch=4, buckets=(4,)),
        )
        outs[weights] = eng.predict(x)
    np.testing.assert_allclose(outs["int8"], outs["int8_fused"],
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_int8_fused_moe_model_runs():
    """Regression: the keep-dense predicate must be path-based — MoE params
    (2-D gate/biases consumed as raw arrays, not via layers.dense) crashed
    the rank-based version."""
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    x = np.random.RandomState(2).rand(4, 32, 32, 3).astype(np.float32)
    eng = InferenceEngine(
        ModelConfig(name="moe_vit_tiny", input_shape=(32, 32, 3),
                    dtype="float32", weights="int8_fused"),
        ShardingConfig(data_parallel=0),
        BatchConfig(max_batch=4, buckets=(4,)),
    )
    out = eng.predict(x)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-4)


def test_compile_cache_dir_populated(tmp_path):
    """compile_cache_dir wires up jax's persistent compilation cache: a
    fresh engine writes executables there on warmup."""
    import jax
    import numpy as np

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import InferenceEngine

    from jax._src import compilation_cache

    from storm_tpu.infer import engine as eng_mod

    cache = tmp_path / "xla-cache"
    prior_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        eng = InferenceEngine(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                        dtype="float32", compile_cache_dir=str(cache)),
            ShardingConfig(data_parallel=0),
            BatchConfig(max_batch=4, buckets=(4,)),
        )
        eng.predict(np.zeros((4, 28, 28, 1), np.float32))
        assert cache.exists() and any(cache.iterdir())
    finally:
        # Un-latch both jax's cache object and the engine's once-guard so
        # later tests neither read a deleted tmp dir nor skip their own dir.
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prior_min)
        jax.config.update("jax_compilation_cache_dir", None)
        compilation_cache.reset_cache()
        eng_mod._COMPILE_CACHE_DIR = None


def test_live_model_swap_under_traffic(run):
    """swap_model rolls a running inference component onto a new engine
    with zero downtime: traffic before, during, and after all acks; the
    new config is live; predictions change (different seed => different
    random-init weights)."""
    import asyncio
    import json as _json

    import numpy as np

    from storm_tpu.config import BatchConfig, Config, ModelConfig
    from storm_tpu.connectors import BrokerSink, BrokerSpout, MemoryBroker
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    async def go():
        broker = MemoryBroker()
        cfg = Config()
        tb = TopologyBuilder()
        tb.set_spout("spout", BrokerSpout(broker, "in"), parallelism=1)
        tb.set_bolt("infer", InferenceBolt(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                        dtype="float32", seed=0),
            BatchConfig(max_batch=8, max_wait_ms=10, buckets=(8,))),
            parallelism=2).shuffle_grouping("spout")
        tb.set_bolt("sink", BrokerSink(broker, "out", cfg.sink),
                    parallelism=1).shuffle_grouping("infer")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("swap", cfg, tb.build())

        x = np.random.RandomState(0).rand(1, 28, 28, 1).tolist()
        payload = _json.dumps({"instances": x})

        async def feed_and_collect(n):
            start = broker.topic_size("out")
            for _ in range(n):
                broker.produce("in", payload)
            for _ in range(200):
                if broker.topic_size("out") >= start + n:
                    break
                await asyncio.sleep(0.05)
            assert broker.topic_size("out") == start + n
            return _json.loads(
                broker.drain_topic("out")[-1].value)["predictions"]

        before = await feed_and_collect(4)
        new_cfg = await rt.swap_model("infer", {"seed": 123})
        assert new_cfg.seed == 123
        after = await feed_and_collect(4)
        assert not np.allclose(before, after), "new weights must be live"
        # every live instance switched
        for e in rt.bolt_execs["infer"]:
            assert e.bolt.model_cfg.seed == 123
        # unknown component / non-inference component / bad field
        with pytest.raises(KeyError):
            await rt.swap_model("nope", {"seed": 1})
        with pytest.raises(TypeError):
            await rt.swap_model("sink", {"seed": 1})
        with pytest.raises(TypeError):
            await rt.swap_model("infer", {"not_a_field": 1})
        await cluster.shutdown()

    run(go(), timeout=120)


def test_engine_inventory_tracks_coresident_models():
    """engine_inventory sums per-replica HBM param bytes across the
    process's live engines (the multi-model budget, BASELINE config 5)."""
    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import engine_inventory, shared_engine

    e1 = shared_engine(
        ModelConfig(name="lenet5", input_shape=(28, 28, 1), dtype="float32"),
        ShardingConfig(data_parallel=0), BatchConfig(max_batch=4, buckets=(4,)))
    e2 = shared_engine(
        ModelConfig(name="mixer_tiny", input_shape=(32, 32, 3),
                    dtype="float32"),
        ShardingConfig(data_parallel=0), BatchConfig(max_batch=4, buckets=(4,)))
    inv = engine_inventory()
    names = {r["model"] for r in inv["engines"]}
    assert {"lenet5", "mixer_tiny"} <= names
    assert e1.param_bytes() > 100_000  # lenet5 f32 ~ a few hundred KB
    assert inv["total_param_bytes"] >= e1.param_bytes() + e2.param_bytes()
    for r in inv["engines"]:
        assert r["param_bytes"] > 0


def test_eager_dispatch_low_latency_and_batching_under_load(run):
    """eager=True: an idle device gets records immediately (no max_wait
    aging); when all slots are busy, arrivals accumulate into one batch."""
    import asyncio

    import numpy as np

    from storm_tpu.config import BatchConfig, Config, ModelConfig
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import TopologyBuilder, Spout, Values
    from storm_tpu.runtime.cluster import AsyncLocalCluster
    import json as _json

    class TwoShotSpout(Spout):
        def open(self, ctx, col):
            super().open(ctx, col)
            self.sent = 0

        async def next_tuple(self):
            if self.sent >= 2:
                return False
            self.sent += 1
            await self.collector.emit(Values([
                _json.dumps({"instances": np.zeros((1, 28, 28, 1)).tolist()})
            ]), msg_id=self.sent)
            return True

    async def go():
        tb = TopologyBuilder()
        tb.set_spout("s", TwoShotSpout(), 1)
        # Huge deadline: only eager dispatch can flush these records fast.
        tb.set_bolt("infer", InferenceBolt(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                        dtype="float32"),
            BatchConfig(max_batch=64, max_wait_ms=30_000.0, buckets=(64,),
                        eager=True)),
            1).shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("eager", Config(), tb.build())
        import time as _time
        t0 = _time.perf_counter()
        for _ in range(100):
            snap = rt.metrics.snapshot()
            done = snap["infer"].get("instances_inferred", 0)
            if done >= 2:
                break
            await asyncio.sleep(0.1)
        dt = _time.perf_counter() - t0
        assert done >= 2, f"only {done} inferred"
        assert dt < 15.0, f"eager dispatch should beat the 30s deadline, took {dt:.1f}s"
        await cluster.shutdown()

    run(go(), timeout=120)


def test_canary_swap_single_task(run):
    """swap_model(tasks=[0]) rolls one instance only; component_stats shows
    the mixed model versions; a follow-up full swap converges everyone."""
    from storm_tpu.config import BatchConfig, Config, ModelConfig
    from storm_tpu.connectors import BrokerSpout, MemoryBroker
    from storm_tpu.infer import InferenceBolt
    from storm_tpu.runtime import TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    async def go():
        broker = MemoryBroker()
        tb = TopologyBuilder()
        tb.set_spout("s", BrokerSpout(broker, "in"), 1)
        tb.set_bolt("infer", InferenceBolt(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                        dtype="float32", seed=0),
            BatchConfig(max_batch=4, max_wait_ms=10, buckets=(4,))),
            2).shuffle_grouping("s")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("canary", Config(), tb.build())

        new_cfg = await rt.swap_model("infer", {"seed": 7}, tasks=[0])
        assert new_cfg.seed == 7
        seeds = {e.task_index: e.bolt.model_cfg.seed
                 for e in rt.bolt_execs["infer"]}
        assert seeds == {0: 7, 1: 0}
        # prototype unchanged: rebalance-added executors keep the majority
        assert rt.topology.specs["infer"].obj.model_cfg.seed == 0
        rows = rt.component_stats("infer")
        models = {r["task"]: r["model"] for r in rows}
        assert models[0] != models[1] and "seed=7" in models[0]
        # unknown task errors
        with pytest.raises(KeyError):
            await rt.swap_model("infer", {"seed": 9}, tasks=[5])
        # full swap converges
        await rt.swap_model("infer", {"seed": 7})
        seeds = {e.task_index: e.bolt.model_cfg.seed
                 for e in rt.bolt_execs["infer"]}
        assert set(seeds.values()) == {7}
        await cluster.shutdown()

    run(go(), timeout=120)


def test_eager_pending_restored_on_cancelled_dispatch(run):
    """An eager dispatch task cancelled during shutdown/drain — whether
    parked on the device-slot semaphore OR before its first step — must
    still decrement _eager_pending, or eager dispatch is permanently
    disabled for the bolt instance. Regression for ADVICE r1
    (operator.py:237) + review r2 (pre-first-step cancel window)."""
    import asyncio

    from storm_tpu.infer.operator import InferenceBolt

    class FakeBatcher:
        def __len__(self):
            return 1

        def take_all(self):
            return "batch"  # never reaches the engine: task is cancelled

    def skeleton(slots):
        bolt = object.__new__(InferenceBolt)  # no engine needed: cancelled
        bolt._eager = True
        bolt._eager_pending = 0
        bolt._dispatch_sem = asyncio.Semaphore(slots)
        bolt._inflight = set()
        bolt._flush_task = None
        bolt.batcher = FakeBatcher()
        return bolt

    async def main():
        # Window A: cancelled BEFORE the coroutine's first step (the task
        # never enters _dispatch, so only a done-callback can decrement).
        bolt = skeleton(slots=1)
        bolt._kick_flush()
        assert bolt._eager_pending == 1
        task = next(iter(bolt._inflight))
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        assert bolt._eager_pending == 0

        # Window B: cancelled while parked on the semaphore. Slot is free
        # at kick time (eager branch fires), then stolen before the task's
        # first step — the task parks on acquire.
        bolt = skeleton(slots=1)
        bolt._kick_flush()
        assert bolt._eager_pending == 1
        await bolt._dispatch_sem.acquire()  # steal the slot
        task = next(iter(bolt._inflight))
        await asyncio.sleep(0.01)  # let it park on the semaphore
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        assert bolt._eager_pending == 0

    run(main(), timeout=10)


def test_engine_cache_unload_and_lru_eviction():
    """shared_engine's process cache must be boundable: set a byte budget
    and LRU engines are dropped on insert; unload_engine drops a specific
    engine (e.g. after a completed model swap). Regression for ADVICE r1
    (engine.py:329 — cache grew monotonically across live swaps)."""
    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer.engine import (
        _ENGINES, set_engine_cache_limit, shared_engine, unload_engine)

    scfg = ShardingConfig(data_parallel=0)
    bcfg = BatchConfig(max_batch=4, buckets=(4,))

    def eng(seed):
        return shared_engine(
            ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                        dtype="float32", seed=seed), scfg, bcfg)

    import gc

    def cached_seeds():
        # seed is a stable component of the cache key (position 6)
        return {k[6] for k in _ENGINES}

    gc.collect()  # drop cycles from earlier tests so orphan-detection is crisp
    try:
        e1 = eng(101)
        one_engine_bytes = e1.param_bytes()
        # Budget fits exactly one lenet5: inserting a second wants to evict
        # the LRU — but e1 is still referenced by this frame (a live bolt),
        # so it must be SKIPPED (evicting would free nothing and force a
        # duplicate rebuild on the next lookup).
        set_engine_cache_limit(one_engine_bytes + 1)
        e2 = eng(102)
        assert e1 in list(_ENGINES.values())  # referenced -> kept
        assert e2 in list(_ENGINES.values())
        # Drop the external reference (bolt gone / swap completed): now the
        # orphan is evictable on the next insert.
        del e1
        e3 = eng(103)
        cached = list(_ENGINES.values())
        assert e2 in cached and e3 in cached  # referenced -> kept
        assert 101 not in cached_seeds()  # the orphan was evicted
        # Cache hit returns the same object and keeps it resident.
        assert eng(102) is e2

        # Explicit unload (post-swap rollback-cache cleanup).
        assert unload_engine(e2) is True
        assert e2 not in list(_ENGINES.values())
        assert unload_engine(e2) is False  # already gone
    finally:
        set_engine_cache_limit(None)



def test_shared_engine_concurrent_requests_build_once():
    """N tasks requesting the same engine concurrently (e.g. a model swap
    broadcast to every bolt task) must cost ONE build — one param copy in
    HBM, one compile — with the others waiting on the in-progress build."""
    import threading
    import time as _time

    from storm_tpu.config import BatchConfig, ModelConfig, ShardingConfig
    from storm_tpu.infer import engine as eng_mod

    builds = []
    orig_init = eng_mod.InferenceEngine.__init__

    def counting_init(self, *a, **kw):
        builds.append(threading.get_ident())
        _time.sleep(0.2)  # widen the race window
        orig_init(self, *a, **kw)

    eng_mod.InferenceEngine.__init__ = counting_init
    try:
        results = []

        def go():
            results.append(eng_mod.shared_engine(
                ModelConfig(name="lenet5", input_shape=(28, 28, 1),
                            dtype="float32", seed=201),
                ShardingConfig(data_parallel=0),
                BatchConfig(max_batch=4, buckets=(4,))))

        threads = [threading.Thread(target=go) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(builds) == 1, f"expected 1 build, got {len(builds)}"
        assert len(results) == 4
        assert all(r is results[0] for r in results)
    finally:
        eng_mod.InferenceEngine.__init__ = orig_init
