"""Trident-equivalent exactly-once layer (runtime/transactional.py):
numbered immutable batches, txid-idempotent state, idempotent egress,
coordinator crash recovery (SURVEY.md §1 layer 1 — storm-core ships
Trident; the reference inherits the capability)."""

import asyncio
import json

import pytest

from storm_tpu.config import Config
from storm_tpu.connectors.memory import MemoryBroker
from storm_tpu.runtime import TopologyBuilder, Values
from storm_tpu.runtime.cluster import AsyncLocalCluster
from storm_tpu.runtime.state import KeyValueState
from storm_tpu.runtime.transactional import (
    OpaqueState,
    TransactionalBolt,
    TransactionalSink,
    TransactionalSpout,
    TransactionalState,
)


# ---- state unit semantics ----------------------------------------------------


def test_transactional_state_skips_replayed_txid():
    st = TransactionalState(KeyValueState())
    assert st.apply("k", 10, lambda v: v + 1, init=0) == 1
    assert st.apply("k", 10, lambda v: v + 1, init=0) == 1  # replay: no-op
    assert st.apply("k", 9, lambda v: v + 1, init=0) == 1   # older: no-op
    assert st.apply("k", 11, lambda v: v + 1, init=0) == 2
    assert st.value("k") == 2


def test_opaque_state_reapplies_same_txid_over_prev():
    st = OpaqueState(KeyValueState())
    assert st.apply("k", 10, lambda v: v + 5, init=0) == 5
    # same txid, different content (source couldn't replay identically):
    # recomputed over prev, not skipped and not double-applied
    assert st.apply("k", 10, lambda v: v + 3, init=0) == 3
    assert st.apply("k", 11, lambda v: v + 1, init=0) == 4
    assert st.apply("k", 10, lambda v: v + 9, init=0) == 4  # older: no-op


# ---- spout batch contract ----------------------------------------------------


class _Capture:
    """Collector stand-in capturing spout emits."""

    def __init__(self):
        self.emits = []

    def set_output_fields(self, fields):
        pass

    async def emit(self, values, **kw):
        self.emits.append((list(values), kw.get("msg_id")))
        return 1


class _Ctx:
    def __init__(self, task_index=0):
        self.task_index = task_index
        self.parallelism = 1
        self.component_id = "tx-spout"
        self.config = None
        self.metrics = None


def _spout(broker, **kw):
    s = TransactionalSpout(broker, "in", **kw)
    cap = _Capture()
    s.open(_Ctx(), cap)
    return s, cap


def test_tx_spout_batches_are_immutable_under_replay(run):
    async def go():
        broker = MemoryBroker(default_partitions=2)
        for i in range(10):
            broker.produce("in", f"r{i}")
        s, cap = _spout(broker, batch_size=6)
        assert await s.next_tuple()
        batch1, txid1 = cap.emits[0][0], cap.emits[0][1]
        assert len(batch1[0]) == 6 and batch1[1] == txid1
        # more records arrive — a replay must still produce the same batch
        for i in range(5):
            broker.produce("in", f"late{i}")
        s.fail(txid1)
        assert await s.next_tuple()
        batch1r = cap.emits[1][0]
        assert batch1r[0] == batch1[0] and batch1r[1] == txid1
        # ack, then the next batch picks up from the committed cursor
        s.ack(txid1)
        assert await s.next_tuple()
        batch2, txid2 = cap.emits[2][0], cap.emits[2][1]
        assert txid2 > txid1
        assert set(batch2[0]).isdisjoint(set(batch1[0]))

    run(go(), timeout=30)


def test_tx_spout_coordinator_crash_reforms_identical_batch(run):
    async def go():
        broker = MemoryBroker(default_partitions=2)
        for i in range(8):
            broker.produce("in", f"r{i}")
        s1, cap1 = _spout(broker, batch_size=5)
        assert await s1.next_tuple()
        batch1, txid1 = cap1.emits[0][0], cap1.emits[0][1]
        # coordinator dies before ack; more records arrive meanwhile
        for i in range(4):
            broker.produce("in", f"late{i}")
        s2, cap2 = _spout(broker, batch_size=5)  # fresh instance, same broker
        assert await s2.next_tuple()
        rebatch, retx = cap2.emits[0][0], cap2.emits[0][1]
        assert retx == txid1, "re-formed batch must keep its txid"
        assert rebatch[0] == batch1[0], "re-formed batch must keep its records"
        s2.ack(retx)
        assert await s2.next_tuple()
        assert cap2.emits[1][1] > txid1  # txids stay monotonic after recovery

    run(go(), timeout=30)


def test_tx_spout_only_task0_coordinates(run):
    async def go():
        broker = MemoryBroker()
        broker.produce("in", "x")
        s = TransactionalSpout(broker, "in")
        s.open(_Ctx(task_index=1), _Capture())
        assert not await s.next_tuple()

    run(go(), timeout=10)


# ---- end-to-end exactly-once -------------------------------------------------


class CountBolt(TransactionalBolt):
    """Counts words per batch into transactional state; emits totals."""

    async def process_batch(self, txid, records, state):
        # fold the batch's occurrences, then apply once per word — the
        # txid-keyed cell makes a replayed batch a no-op
        totals = {}
        for rec in records:
            word = rec.split(":")[0]
            totals[word] = totals.get(word, 0) + 1
        msgs = []
        for word, n in sorted(totals.items()):
            final = state.apply(word, txid, lambda v, n=n: v + n, init=0)
            msgs.append(json.dumps({word: final}))
        return msgs


class FailFirstCount(CountBolt):
    """Fails the first batch delivery once — forcing a txid replay."""

    failed = False

    async def execute(self, t):
        if not FailFirstCount.failed:
            FailFirstCount.failed = True
            self.collector.fail(t)
            return
        await super().execute(t)


def test_exactly_once_counts_despite_replay(run):
    async def go():
        FailFirstCount.failed = False
        broker = MemoryBroker(default_partitions=1)
        words = ["a", "b", "a", "c", "a", "b"]
        for i, w in enumerate(words):
            broker.produce("in", f"{w}:{i}")

        cfg = Config()
        cfg.topology.message_timeout_s = 2.0
        tb = TopologyBuilder()
        tb.set_spout("tx-spout", TransactionalSpout(broker, "in", batch_size=3),
                     parallelism=1)
        tb.set_bolt("count", FailFirstCount(), parallelism=1)\
            .shuffle_grouping("tx-spout")
        tb.set_bolt("sink", TransactionalSink(broker, "out"), parallelism=1)\
            .shuffle_grouping("count")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("tx", cfg, tb.build())
        try:
            # batch1 {a,b} -> 2 msgs, batch2 {a,b,c} -> 3 msgs
            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                if broker.topic_size("out") >= 5:
                    break
                await asyncio.sleep(0.05)
            await rt.drain(timeout_s=10)
            # final per-word totals are exact despite the forced replay
            counts = {}
            for r in broker.drain_topic("out"):
                counts.update(json.loads(r.value))
            assert counts == {"a": 3, "b": 2, "c": 1}, counts
        finally:
            await cluster.shutdown()

    run(go(), timeout=60)


def test_tx_sink_skips_replayed_txid(run):
    async def go():
        broker = MemoryBroker()
        sink = TransactionalSink(broker, "out")
        sink.init_state(KeyValueState())

        class _Coll:
            def __init__(self):
                self.acked = []

            def ack(self, t):
                self.acked.append(t)

        sink.collector = _Coll()
        from storm_tpu.runtime.tuples import Tuple

        t1 = Tuple(values=[["m1", "m2"], 7], fields=("batch", "txid"),
                   source_component="c", source_task=0)
        await sink.execute(t1)
        await sink.execute(t1)  # replayed delivery of the same txid
        assert broker.topic_size("out") == 2  # not 4
        assert len(sink.collector.acked) == 2

    run(go(), timeout=10)


def test_tx_parallelism_above_one_refused(run):
    async def go():
        broker = MemoryBroker()
        tb = TopologyBuilder()
        tb.set_spout("tx-spout", TransactionalSpout(broker, "in"), parallelism=1)
        tb.set_bolt("sink", TransactionalSink(broker, "out"), parallelism=2)\
            .shuffle_grouping("tx-spout")
        cluster = AsyncLocalCluster()
        with pytest.raises(ValueError, match="parallelism=1"):
            await cluster.submit("tx", Config(), tb.build())
        await cluster.shutdown()

    run(go(), timeout=30)


def test_tx_spout_works_without_commit_many(run):
    """Real broker adapters may lack commit_many: per-partition fallback."""

    class NoCommitMany:
        def __init__(self, inner):
            self._b = inner

        def __getattr__(self, name):
            if name == "commit_many":
                raise AttributeError(name)
            return getattr(self._b, name)

    async def go():
        inner = MemoryBroker(default_partitions=2)
        for i in range(6):
            inner.produce("in", f"r{i}")
        broker = NoCommitMany(inner)
        assert getattr(broker, "commit_many", None) is None
        s = TransactionalSpout(broker, "in", batch_size=4)
        cap = _Capture()
        s.open(_Ctx(), cap)
        assert await s.next_tuple()
        txid = cap.emits[0][1]
        s.ack(txid)
        assert await s.next_tuple()  # flushes the deferred per-partition commits
        # offsets actually landed in the main group
        committed = sum(
            inner.committed("tx", "in", p) or 0
            for p in range(inner.partitions_for("in"))
        )
        assert committed >= 4

    run(go(), timeout=30)


def test_tx_state_checkpointed_before_ack(run, tmp_path):
    """A committed batch's state updates are already durable: the bolt
    checkpoints synchronously before acking (no window where offsets are
    committed but state exists only in memory)."""

    async def go():
        broker = MemoryBroker(default_partitions=1)
        for w in ["a", "a", "b"]:
            broker.produce("in", f"{w}:0")
        cfg = Config()
        cfg.topology.state_dir = str(tmp_path)
        cfg.topology.checkpoint_interval_s = 3600.0  # periodic timer never fires
        tb = TopologyBuilder()
        tb.set_spout("tx-spout", TransactionalSpout(broker, "in", batch_size=10),
                     parallelism=1)
        tb.set_bolt("count", CountBolt(), parallelism=1)\
            .shuffle_grouping("tx-spout")
        tb.set_bolt("sink", TransactionalSink(broker, "out"), parallelism=1)\
            .shuffle_grouping("count")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("tx", cfg, tb.build())
        try:
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline:
                if broker.topic_size("out") >= 2:
                    break
                await asyncio.sleep(0.05)
            await rt.drain(timeout_s=10)
            # state must already be on disk (the periodic timer can't have
            # fired), proving the synchronous pre-ack checkpoint ran
            from storm_tpu.runtime.state import FileStateBackend

            backend = FileStateBackend(str(tmp_path))
            got = backend.load("count", 0)
            assert got is not None
            _version, snap = got
            assert snap["a"]["v"] == 2 and snap["b"]["v"] == 1
        finally:
            await cluster.shutdown()

    run(go(), timeout=60)


def test_tx_sink_marker_survives_state_loss(run):
    """Broker-transaction-backed TransactionalSink: the txid marker commits
    atomically with the records (as a consumer-group offset inside the
    producer transaction), so losing the sink's LOCAL state — the old
    effectively-once crash window: records produced, crash before the
    checkpoint — no longer double-produces. A 'restarted' sink with empty
    state reads the durable marker back and skips the replayed txid."""
    from storm_tpu.config import Config
    from storm_tpu.runtime.base import TopologyContext
    from storm_tpu.runtime.tuples import Tuple

    class _Coll:
        def __init__(self):
            self.acked, self.failed = [], []

        def ack(self, t):
            self.acked.append(t)

        def fail(self, t):
            self.failed.append(t)

        def report_error(self, e):
            pass

    def make_sink(broker):
        sink = TransactionalSink(broker, "out")
        ctx = TopologyContext("sink", 0, 1, Config())
        sink.prepare(ctx, None)
        sink.collector = _Coll()
        sink.init_state(KeyValueState())
        return sink

    async def go():
        broker = MemoryBroker()
        t = Tuple(values=[["m1", "m2"], 7], fields=("batch", "txid"),
                  source_component="c", source_task=0)

        sink = make_sink(broker)
        assert sink._txn is not None  # MemoryBroker.txn engaged
        await sink.execute(t)
        assert broker.topic_size("out") == 2
        # marker committed atomically with the records
        assert broker.committed(sink._marker_group, "out", 0) == 7

        # crash: state checkpoint never happened -> fresh sink, empty state
        sink2 = make_sink(broker)
        await sink2.execute(t)  # replayed batch
        assert broker.topic_size("out") == 2  # NOT 4: marker recognized
        assert len(sink2.collector.acked) == 1

        # a genuinely new txid still produces
        t2 = Tuple(values=[["m3"], 8], fields=("batch", "txid"),
                   source_component="c", source_task=0)
        await sink2.execute(t2)
        assert broker.topic_size("out") == 3

    run(go(), timeout=10)
