"""Chunked ingestion (BrokerSpout chunk=N + InferenceBolt _ChunkHandle):
one tuple per N records — the host-side throughput lever that keeps the
reference's one-instance-per-message wire contract."""

import asyncio
import json

import numpy as np
import pytest

from storm_tpu.api.schema import decode_predictions
from storm_tpu.config import BatchConfig, Config, ModelConfig, OffsetsConfig, ShardingConfig
from storm_tpu.connectors import BrokerSink, BrokerSpout, MemoryBroker
from storm_tpu.infer import InferenceBolt
from storm_tpu.runtime import TopologyBuilder
from storm_tpu.runtime.cluster import AsyncLocalCluster


def _payload(seed=0):
    rng = np.random.RandomState(seed)
    return json.dumps({"instances": rng.rand(1, 28, 28, 1).tolist()})


async def _run_chunked(n_msgs, poison_at=None, chunk=4):
    broker = MemoryBroker(default_partitions=2)
    cfg = Config()
    tb = TopologyBuilder()
    tb.set_spout(
        "spout",
        BrokerSpout(broker, "input", OffsetsConfig(policy="earliest", max_behind=None),
                    chunk=chunk),
        parallelism=2,
    )
    tb.set_bolt(
        "infer",
        InferenceBolt(ModelConfig(name="lenet5", input_shape=(28, 28, 1)),
                      BatchConfig(max_batch=8, max_wait_ms=10, buckets=(8,)),
                      ShardingConfig(data_parallel=0), warmup=False),
        parallelism=2,
    ).shuffle_grouping("spout")
    tb.set_bolt("sink", BrokerSink(broker, "output", cfg.sink), parallelism=1)\
        .shuffle_grouping("infer")
    tb.set_bolt("dlq", BrokerSink(broker, "dead-letter", cfg.sink), parallelism=1)\
        .shuffle_grouping("infer", stream="dead_letter")

    for i in range(n_msgs):
        if poison_at is not None and i == poison_at:
            broker.produce("input", '{"instances": "garbage"}')
        else:
            broker.produce("input", _payload(seed=i))

    cluster = AsyncLocalCluster()
    rt = await cluster.submit("chunked", cfg, tb.build())
    deadline = asyncio.get_event_loop().time() + 60
    want = n_msgs
    while asyncio.get_event_loop().time() < deadline:
        if broker.topic_size("output") + broker.topic_size("dead-letter") >= want:
            break
        await asyncio.sleep(0.05)
    await rt.drain(timeout_s=30)
    snap = rt.metrics.snapshot()
    outs = broker.drain_topic("output")
    dlq = broker.drain_topic("dead-letter")
    await cluster.shutdown()
    return outs, dlq, snap


@pytest.mark.slow
def test_chunked_ingestion_end_to_end(run):
    outs, dlq, snap = run(_run_chunked(n_msgs=25, chunk=4), timeout=120)
    assert len(outs) == 25 and len(dlq) == 0
    for r in outs:
        preds = decode_predictions(r.value)
        assert preds.data.shape == (1, 10)
        np.testing.assert_allclose(preds.data.sum(), 1.0, atol=1e-4)
    assert snap["infer"]["instances_inferred"] == 25
    # chunked: far fewer spout ledger entries than records
    assert snap["spout"]["tree_acked"] < 25
    assert snap["spout"]["tree_acked"] >= 1


def test_chunked_poison_dead_letters_without_wedging_chunk(run):
    outs, dlq, snap = run(_run_chunked(n_msgs=12, poison_at=5, chunk=4), timeout=120)
    # 11 good records predicted, poison dead-lettered; its chunk-mates
    # still produced output (the chunk was not failed/replayed)
    assert len(outs) == 11
    assert len(dlq) == 1
    assert snap["infer"]["dead_lettered"] == 1
    assert snap["spout"].get("tree_failed", 0) == 0


def test_chunk_replay_is_whole_chunk(run):
    async def go():
        from storm_tpu.connectors.memory import MemoryBroker as MB

        broker = MB(default_partitions=1)
        for i in range(6):
            broker.produce("in", f"m{i}")
        spout = BrokerSpout(broker, "in",
                            OffsetsConfig(policy="earliest", max_behind=None),
                            chunk=3)

        emits = []

        class Cap:
            def set_output_fields(self, f):
                pass

            async def emit(self, values, **kw):
                emits.append((list(values), kw.get("msg_id")))
                return 1

        class Ctx:
            task_index = 0
            parallelism = 1
            component_id = "spout"
            config = None
            metrics = None

        spout.open(Ctx(), Cap())
        # one fetch -> ALL its records emitted, sliced into chunk tuples
        assert await spout.next_tuple()
        (chunk1,), mid1 = emits[0]
        (chunk2,), mid2 = emits[1]
        assert chunk1 == ["m0", "m1", "m2"] and mid1[0] == "c"
        assert chunk2 == ["m3", "m4", "m5"] and mid2[0] == "c"
        # fail -> the whole chunk replays as one identical tuple
        spout.fail(mid1)
        assert await spout.next_tuple()
        (chunk1r,), mid1r = emits[2]
        assert chunk1r == chunk1 and mid1r == mid1
        spout.ack(mid1r)
        spout.ack(mid2)
        assert not await spout.next_tuple()  # log drained

    run(go(), timeout=30)
