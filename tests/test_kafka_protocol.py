"""Kafka wire-protocol client tests against the in-process stub broker
(real sockets, real encoding — the integration the reference only ever got
by deploying to a live cluster, SURVEY.md §4)."""

import asyncio
import json
import time

import numpy as np
import pytest

from storm_tpu.config import Config, OffsetsConfig
from storm_tpu.connectors.kafka_protocol import (
    KafkaProtocolError,
    KafkaWireBroker,
    KafkaWireClient,
    decode_message_set,
    encode_message_set,
)
from tests.kafka_stub import KafkaStubBroker


@pytest.fixture()
def stub():
    b = KafkaStubBroker(partitions=2)
    yield b
    b.close()


@pytest.fixture()
def client(stub):
    c = KafkaWireClient(f"127.0.0.1:{stub.port}")
    yield c
    c.close()


def test_message_set_roundtrip():
    recs = [(b"k1", b"v1"), (None, b"v2")]
    data = encode_message_set(recs, 1234567, offsets=[5, 6])
    out = decode_message_set("t", 0, data)
    assert [(r.key, r.value, r.offset) for r in out] == [
        (b"k1", b"v1", 5), (None, b"v2", 6)
    ]


def test_metadata_and_partitions(client):
    assert client.partitions_for("topic-a") == 2


def test_produce_fetch_roundtrip(client):
    base = client.produce("t", 0, [(None, b"hello"), (b"k", b"world")])
    assert base == 0
    recs = client.fetch("t", 0, 0)
    assert [r.value for r in recs] == [b"hello", b"world"]
    assert recs[1].key == b"k"
    # fetch from mid-offset
    recs2 = client.fetch("t", 0, 1)
    assert [r.value for r in recs2] == [b"world"]


def test_list_offsets(client):
    assert client.list_offset("t2", 0, -1) == 0
    client.produce("t2", 0, [(None, b"x")] * 3)
    assert client.list_offset("t2", 0, -1) == 3
    assert client.list_offset("t2", 0, -2) == 0


def test_offset_commit_fetch(client):
    assert client.offset_fetch("g1", "t3", 0) is None
    client.offset_commit("g1", "t3", 0, 42)
    assert client.offset_fetch("g1", "t3", 0) == 42


def test_wire_broker_surface(stub):
    broker = KafkaWireBroker(f"127.0.0.1:{stub.port}")
    p, off = broker.produce("t4", "payload-1")
    assert off == 0
    assert broker.latest_offset("t4", p) == 1
    recs = broker.fetch("t4", p, 0)
    assert recs[0].value == b"payload-1"
    broker.commit("g", "t4", p, 1)
    assert broker.committed("g", "t4", p) == 1
    broker.close()


def test_wire_broker_key_affinity(stub):
    broker = KafkaWireBroker(f"127.0.0.1:{stub.port}")
    parts = {broker.produce("t5", f"v{i}", key="samekey")[0] for i in range(5)}
    assert len(parts) == 1
    broker.close()


def test_end_to_end_topology_over_sockets(stub, run):
    """Full streaming topology with ingress AND egress over the real wire
    protocol: socket in -> spout -> bolt -> sink -> socket out."""
    from tests.test_runtime import PassBolt
    from storm_tpu.connectors import BrokerSink, BrokerSpout
    from storm_tpu.connectors.sink import Producer
    from storm_tpu.runtime import TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    broker = KafkaWireBroker(f"127.0.0.1:{stub.port}")

    class WireProducer(Producer):
        async def send(self, topic, value, key):
            await asyncio.to_thread(broker.produce, topic, value, key)

    class WireSink(BrokerSink):
        def make_producer(self):
            return WireProducer()

    async def go():
        cfg = Config()
        tb = TopologyBuilder()
        tb.set_spout(
            "in",
            BrokerSpout(broker, "wire-in", OffsetsConfig(policy="earliest", max_behind=None)),
            2,
        )
        tb.set_bolt("mid", PassBolt(), 2).shuffle_grouping("in")
        tb.set_bolt("out", WireSink(None, "wire-out", cfg.sink), 1).shuffle_grouping("mid")
        cluster = AsyncLocalCluster()
        rt = await cluster.submit("wire", cfg, tb.build())
        for i in range(6):
            broker.produce("wire-in", f"msg-{i}")
        deadline = asyncio.get_event_loop().time() + 30
        while asyncio.get_event_loop().time() < deadline:
            if stub.topic_size("wire-out") >= 6:
                break
            await asyncio.sleep(0.05)
        out = []
        for p in range(2):
            out.extend(broker.fetch("wire-out", p, 0, 100))
        await cluster.shutdown()
        return out

    out = run(go(), timeout=60)
    assert sorted(r.value.decode() for r in out) == [f"msg-{i}" for i in range(6)]
    broker.close()


def test_wire_broker_fetch_buffers_remainder(stub):
    """A wire fetch decoding more than max_records must buffer the tail and
    serve it on the next poll instead of re-fetching the same bytes."""
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    b = KafkaWireBroker(f"127.0.0.1:{stub.port}")
    try:
        for i in range(20):
            b.produce("bulk", f"m{i}", partition=0)
        first = b.fetch("bulk", 0, 0, max_records=5)
        assert [r.offset for r in first] == [0, 1, 2, 3, 4]
        assert ("bulk", 0) in b._prefetch
        second = b.fetch("bulk", 0, 5, max_records=5)
        assert [r.offset for r in second] == [5, 6, 7, 8, 9]
        # A seek (offset mismatch) invalidates the buffer instead of serving it.
        seek = b.fetch("bulk", 0, 12, max_records=5)
        assert [r.offset for r in seek][0] == 12
    finally:
        b.close()


def test_gzip_wrapper_message_decode():
    """gzip-compressed wrapper (magic 1, KIP-31 relative inner offsets) is
    transparently decompressed; snappy/lz4 still reject."""
    import gzip
    import struct
    import zlib

    from storm_tpu.connectors.kafka_protocol import (
        Writer,
        decode_message_set,
        encode_message_set,
    )

    inner = encode_message_set(
        [(None, b"v0"), (None, b"v1"), (b"k", b"v2")],
        ts_ms=1_700_000_000_000,
        offsets=[0, 1, 2],  # relative per KIP-31
    )
    wrapped = gzip.compress(inner)
    msg = Writer()
    msg.i8(1)  # magic
    msg.i8(1)  # attributes: gzip
    msg.i64(1_700_000_000_000)
    msg.bytes_(None)
    msg.bytes_(wrapped)
    crc = zlib.crc32(bytes(msg.buf)) & 0xFFFFFFFF
    full = Writer()
    full.i64(107)  # wrapper offset = offset of LAST inner message
    full.i32(4 + len(msg.buf))
    full.buf += struct.pack(">I", crc)
    full.raw(bytes(msg.buf))

    recs = decode_message_set("t", 0, bytes(full.buf))
    assert [r.value for r in recs] == [b"v0", b"v1", b"v2"]
    assert [r.offset for r in recs] == [105, 106, 107]
    assert recs[2].key == b"k"

    # unsupported codec (zstd=4) still raises; gzip/snappy/lz4 all decode
    from storm_tpu.connectors.kafka_protocol import KafkaProtocolError

    msg2 = Writer()
    msg2.i8(1)
    msg2.i8(4)  # zstd
    msg2.i64(0)
    msg2.bytes_(None)
    msg2.bytes_(b"xx")
    crc2 = zlib.crc32(bytes(msg2.buf)) & 0xFFFFFFFF
    full2 = Writer()
    full2.i64(0)
    full2.i32(4 + len(msg2.buf))
    full2.buf += struct.pack(">I", crc2)
    full2.raw(bytes(msg2.buf))
    with pytest.raises(KafkaProtocolError, match="codec"):
        decode_message_set("t", 0, bytes(full2.buf))


def test_snappy_block_decode_literals_and_copies():
    """Raw snappy block format: literals, 1/2-byte-offset backref copies,
    and overlapping (RLE) copies — decoded against hand-crafted streams so
    the decoder is validated independently of our own encoder."""
    from storm_tpu.connectors.snappy import (SnappyError, compress,
                                             decompress, decompress_raw)

    # "abcdabcdabcd": literal "abcd" + overlapping copy len=8 off=4
    # tag copy-1: kind=1, len 8 -> ((8-4)&7)<<2 | 1 ; off=4 -> hi=0, lo=4
    crafted = bytearray()
    crafted.append(12)  # uncompressed length varint = 12
    crafted.append((3 << 2) | 0)  # literal, len 4
    crafted += b"abcd"
    crafted.append(((8 - 4) << 2) | 1)  # copy-1: len 8, offset hi bits 0
    crafted.append(4)  # offset lo byte = 4
    assert decompress_raw(bytes(crafted)) == b"abcdabcdabcd"

    # 2-byte-offset copy: 70 literal bytes then re-copy the first 10
    lit = bytes(range(60)) + b"0123456789"
    crafted2 = bytearray()
    crafted2.append(80)  # uncompressed length
    crafted2.append(60 << 2)  # literal code 60: 1-byte explicit length
    crafted2.append(len(lit) - 1)
    crafted2 += lit
    crafted2.append((9 << 2) | 2)  # copy-2: len 10
    crafted2 += (70).to_bytes(2, "little")  # offset 70 = start
    assert decompress_raw(bytes(crafted2)) == lit + lit[:10]

    # our literal-only encoder round-trips through the real decoder
    data = b"storm-tpu " * 500
    assert decompress(compress(data)) == data
    assert decompress(compress(data, xerial=True)) == data  # framed

    # corrupt streams fail loudly, not silently
    with pytest.raises(SnappyError):
        decompress_raw(b"\x05\x00")  # truncated literal
    with pytest.raises(SnappyError):
        decompress_raw(bytes([4, (3 << 2) | 1, 9]))  # offset past output
    # xerial magic present but version/compat ints truncated: must raise,
    # not silently decode a corrupt message as b"".
    from storm_tpu.connectors.snappy import _XERIAL_MAGIC
    with pytest.raises(SnappyError):
        decompress(_XERIAL_MAGIC + b"\x00\x01")


def test_snappy_record_batch_and_wrapper_fetch(stub):
    """End-to-end over sockets: a producer shipping snappy record batches
    (the stub parses them through the shared decode path) delivers intact
    records back on fetch — Kafka-0.11-era snappy producers are readable
    (reference pom.xml:55-78)."""
    from storm_tpu.connectors.kafka_protocol import (
        KafkaWireBroker, decode_message_set, encode_record_batch)
    from storm_tpu.connectors.snappy import compress

    # over real sockets: snappy-compressed v2 batches to the stub broker
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                        compression="snappy")
    try:
        b.produce("snap", b"s0", partition=0)
        b.produce("snap", b"s1", key=b"k", partition=0)
        recs = b.fetch("snap", 0, 0)
        assert [r.value for r in recs] == [b"s0", b"s1"]
        assert recs[1].key == b"k"
    finally:
        b.close()

    # unit: snappy batch encodes -> shared decode path reads it back
    batch = encode_record_batch(
        [(None, b"s0"), (b"k", b"s1")], ts_ms=1_700_000_000_000,
        base_offset=5, compression="snappy")
    recs = decode_message_set("t", 0, batch)
    assert [r.value for r in recs] == [b"s0", b"s1"]
    assert [r.offset for r in recs] == [5, 6]
    assert recs[1].key == b"k"

    # xerial-framed wrapper value (what snappy-java producers emit for
    # magic-1 message sets)
    import struct
    import zlib

    from storm_tpu.connectors.kafka_protocol import (Writer,
                                                     encode_message_set)

    inner = encode_message_set(
        [(None, b"x0"), (None, b"x1")], ts_ms=1_700_000_000_000,
        offsets=[0, 1])
    msg = Writer()
    msg.i8(1)  # magic
    msg.i8(2)  # attributes: snappy
    msg.i64(1_700_000_000_000)
    msg.bytes_(None)
    msg.bytes_(compress(inner, xerial=True))
    crc = zlib.crc32(bytes(msg.buf)) & 0xFFFFFFFF
    full = Writer()
    full.i64(1)  # wrapper offset = last inner
    full.i32(4 + len(msg.buf))
    full.buf += struct.pack(">I", crc)
    full.raw(bytes(msg.buf))
    recs = decode_message_set("t", 0, bytes(full.buf))
    assert [r.value for r in recs] == [b"x0", b"x1"]
    assert [r.offset for r in recs] == [0, 1]


# ---- record batches (format v2, KIP-98) --------------------------------------


def test_record_batch_roundtrip():
    from storm_tpu.connectors.kafka_protocol import (
        decode_record_batch,
        encode_record_batch,
    )

    records = [(None, b"v0"), (b"k1", b"v1"), (b"", b""), (b"k3", b"x" * 500)]
    batch = encode_record_batch(records, ts_ms=1_700_000_000_000, base_offset=42)
    out, consumed = decode_record_batch("t", 0, batch, verify_crc=True)
    assert consumed == len(batch)
    assert [(r.key, r.value) for r in out] == records
    assert [r.offset for r in out] == [42, 43, 44, 45]
    assert abs(out[0].timestamp - 1_700_000_000.0) < 1e-6


def test_record_batch_crc_is_crc32c():
    from storm_tpu.connectors.kafka_protocol import encode_record_batch
    from storm_tpu.native import crc32c

    batch = encode_record_batch([(b"k", b"v")], ts_ms=0)
    crc = int.from_bytes(batch[17:21], "big")
    assert crc == crc32c(batch[21:])


def test_record_batch_corruption_detected():
    from storm_tpu.connectors.kafka_protocol import (
        KafkaProtocolError,
        decode_record_batch,
        encode_record_batch,
    )

    batch = bytearray(encode_record_batch([(b"k", b"hello")], ts_ms=0))
    batch[-2] ^= 0xFF  # flip a payload byte
    with pytest.raises(KafkaProtocolError, match="CRC32C"):
        decode_record_batch("t", 0, bytes(batch), verify_crc=True)


def test_decode_message_set_sniffs_magic2():
    """A fetch response mixing v2 batches is decoded transparently."""
    from storm_tpu.connectors.kafka_protocol import (
        decode_message_set,
        encode_record_batch,
    )

    b1 = encode_record_batch([(None, b"a"), (None, b"b")], ts_ms=0, base_offset=0)
    b2 = encode_record_batch([(None, b"c")], ts_ms=0, base_offset=2)
    records = decode_message_set("t", 1, b1 + b2)
    assert [r.value for r in records] == [b"a", b"b", b"c"]
    assert [r.offset for r in records] == [0, 1, 2]


def test_varint_zigzag_edges():
    from storm_tpu.connectors.kafka_protocol import _read_varint, _write_varint

    for v in [0, 1, -1, 63, -64, 64, 300, -300, 2**31, -(2**31), 2**62]:
        buf = bytearray()
        _write_varint(buf, v)
        got, pos = _read_varint(bytes(buf), 0)
        assert got == v and pos == len(buf)


def test_wire_client_produces_and_fetches_v2_batches():
    """Full socket round trip: Produce v3 with a RecordBatch up, Fetch
    serving RecordBatches down."""
    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    stub = KafkaStubBroker(partitions=1)
    stub.serve_batches = True
    try:
        broker = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
        for i in range(5):
            broker.produce("t2", f"m{i}")
        got = broker.fetch("t2", 0, 0, max_records=10)
        assert [r.value for r in got] == [f"m{i}".encode() for i in range(5)]
        assert [r.offset for r in got] == list(range(5))
    finally:
        stub.close()


def test_record_batch_gzip_roundtrip():
    from storm_tpu.connectors.kafka_protocol import (
        decode_record_batch,
        encode_record_batch,
    )

    records = [(None, b"x" * 400)] * 10  # compressible
    plain = encode_record_batch(records, ts_ms=0)
    gz = encode_record_batch(records, ts_ms=0, compression="gzip")
    assert len(gz) < len(plain) / 3
    out, consumed = decode_record_batch("t", 0, gz, verify_crc=True)
    assert consumed == len(gz)
    assert [(r.key, r.value) for r in out] == records


def test_wire_client_gzip_v2_over_socket():
    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    stub = KafkaStubBroker(partitions=1)
    try:
        broker = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                                 message_format="v2", compression="gzip")
        for i in range(4):
            broker.produce("gz", f"msg-{i}" * 50)
        got = broker.fetch("gz", 0, 0, max_records=10)
        assert [r.value for r in got] == [f"msg-{i}".encode() * 50 for i in range(4)]
    finally:
        stub.close()


# ---- consumer-group coordination ---------------------------------------------


def _stabilize(members, timeout=20.0):
    """One loop per member, like real consumers: heartbeat; on rebalance,
    rejoin. Stops once every member is stable with an assignment."""
    import threading
    import time as _time

    assigns: dict = {}
    done = threading.Event()

    def run(m):
        end = _time.monotonic() + timeout
        while not done.is_set() and _time.monotonic() < end:
            try:
                if m not in assigns or m.generation < 0 or not m.heartbeat():
                    assigns[m] = m.join(max_attempts=5)
                else:
                    _time.sleep(0.02)
            except Exception:
                _time.sleep(0.05)

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    end = _time.monotonic() + timeout
    while _time.monotonic() < end:
        if all(m in assigns for m in members) and \
                all(m.heartbeat() for m in members):
            break
        _time.sleep(0.05)
    done.set()
    for t in threads:
        t.join(timeout=5)
    assert all(m in assigns for m in members), "members never stabilized"
    assert all(m.heartbeat() for m in members)
    return [assigns[m] for m in members]


def test_group_membership_splits_and_rebalances():
    """Two members split partitions via the join/sync protocol; one leaving
    rebalances the survivor onto everything — over real sockets."""
    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import GroupMembership, KafkaWireClient

    stub = KafkaStubBroker(partitions=4)
    try:
        c1 = KafkaWireClient(f"127.0.0.1:{stub.port}")
        c2 = KafkaWireClient(f"127.0.0.1:{stub.port}")
        c1.partitions_for("t")  # create the topic
        m1 = GroupMembership(c1, "g", ["t"])
        m2 = GroupMembership(c2, "g", ["t"])

        (a1,) = _stabilize([m1])
        assert sorted(a1) == [("t", 0), ("t", 1), ("t", 2), ("t", 3)]

        a1, a2 = _stabilize([m1, m2])
        assert sorted(a1 + a2) == [("t", 0), ("t", 1), ("t", 2), ("t", 3)]
        assert len(a1) == len(a2) == 2
        assert not set(a1) & set(a2)

        # member 2 leaves: survivor rebalances onto all partitions
        m2.leave()
        assert not m1.heartbeat()
        (a1,) = _stabilize([m1])
        assert sorted(a1) == [("t", 0), ("t", 1), ("t", 2), ("t", 3)]
        m1.leave()
    finally:
        stub.close()


def test_group_membership_three_members_range():
    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import GroupMembership, KafkaWireClient

    stub = KafkaStubBroker(partitions=5)
    try:
        clients = [KafkaWireClient(f"127.0.0.1:{stub.port}") for _ in range(3)]
        clients[0].partitions_for("t")
        members = [GroupMembership(c, "g3", ["t"]) for c in clients]
        assigns = _stabilize(members)
        allp = sorted(p for a in assigns for p in a)
        assert allp == [("t", i) for i in range(5)]
        sizes = sorted(len(a) for a in assigns)
        assert sizes == [1, 2, 2]  # 5 partitions over 3 members, range-style
    finally:
        stub.close()


def test_group_dead_member_expires():
    """A member that vanishes without leave() is expired by its session
    timeout, unwedging the survivors."""
    import time as _time

    from kafka_stub import KafkaStubBroker
    from storm_tpu.connectors.kafka_protocol import GroupMembership, KafkaWireClient

    stub = KafkaStubBroker(partitions=2)
    try:
        c1 = KafkaWireClient(f"127.0.0.1:{stub.port}")
        c2 = KafkaWireClient(f"127.0.0.1:{stub.port}")
        c1.partitions_for("t")
        m1 = GroupMembership(c1, "g", ["t"], session_timeout_ms=500)
        m2 = GroupMembership(c2, "g", ["t"], session_timeout_ms=500)
        a1, a2 = _stabilize([m1, m2])
        assert len(a1) == len(a2) == 1
        # m2 dies silently (no leave, no heartbeats)
        _time.sleep(0.8)
        assert not m1.heartbeat()  # expiry triggered a rebalance
        (a1,) = _stabilize([m1])
        assert sorted(a1) == [("t", 0), ("t", 1)]
    finally:
        stub.close()


def test_idempotent_produce_dedups_retried_batch():
    """KIP-98 idempotence: resending a batch with the same (pid, sequence)
    appends at most once; a sequence gap errors OUT_OF_ORDER (45)."""
    from storm_tpu.connectors.kafka_protocol import (
        KafkaProtocolError, KafkaWireClient)

    stub = KafkaStubBroker(partitions=1)
    try:
        c = KafkaWireClient(f"127.0.0.1:{stub.port}")
        pid, epoch = c.init_producer_id()
        assert pid >= 0 and epoch == 0
        # two distinct producers get distinct ids
        assert KafkaWireClient(f"127.0.0.1:{stub.port}").init_producer_id()[0] != pid

        off0 = c.produce("t", 0, [(None, b"a")], message_format="v2",
                         producer=(pid, epoch, 0))
        # simulated retry: same sequence again -> no second append, same offset
        off_dup = c.produce("t", 0, [(None, b"a")], message_format="v2",
                            producer=(pid, epoch, 0))
        assert off_dup == off0
        assert stub.topic_size("t") == 1
        # next sequence appends
        c.produce("t", 0, [(None, b"b")], message_format="v2",
                  producer=(pid, epoch, 1))
        assert stub.topic_size("t") == 2
        # gap -> out-of-order error
        with pytest.raises(KafkaProtocolError, match="45"):
            c.produce("t", 0, [(None, b"c")], message_format="v2",
                      producer=(pid, epoch, 5))
        assert stub.topic_size("t") == 2
        c.close()
    finally:
        stub.close()


def test_idempotent_broker_wrapper_sequences():
    """KafkaWireBroker(idempotent=True) stamps monotone sequences per
    partition and records survive a full produce/fetch round trip."""
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    stub = KafkaStubBroker(partitions=2)
    try:
        b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                            idempotent=True)
        parts = set()
        for i in range(6):
            p, off = b.produce("t", f"m{i}".encode(), partition=i % 2)
            parts.add(p)
        assert parts == {0, 1}
        assert stub.topic_size("t") == 6
        got = sorted(r.value.decode() for p in (0, 1)
                     for r in b.fetch("t", p, 0))
        assert got == [f"m{i}" for i in range(6)]
        # config validation: idempotent requires v2
        from storm_tpu.connectors.kafka_protocol import KafkaProtocolError
        with pytest.raises(KafkaProtocolError, match="message_format"):
            KafkaWireBroker(f"127.0.0.1:{stub.port}", idempotent=True)
        b.close()
    finally:
        stub.close()


def test_kafka_txn_commit_abort_fencing():
    """KafkaTxn over the wire: commit makes records visible atomically,
    abort drops them, and a re-initialized transactional id fences the
    old producer (INVALID_PRODUCER_EPOCH)."""
    from storm_tpu.connectors.kafka_protocol import (
        KafkaProtocolError, KafkaWireBroker)

    stub = KafkaStubBroker(partitions=1)
    try:
        b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
        txn = b.txn("txn-test-0")
        txn.begin()
        txn.produce("t", b"a")
        txn.produce("t", b"b")
        assert stub.topic_size("t") == 0  # buffered, not visible
        txn.commit()
        assert stub.topic_size("t") == 2

        txn.begin()
        txn.produce("t", b"dropped")
        txn.abort()
        assert stub.topic_size("t") == 2

        # zombie fencing: a second handle re-inits the same txn id (epoch
        # bump); the old handle's next transaction is rejected
        b2 = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
        t2 = b2.txn("txn-test-0")
        t2.begin()
        txn.begin()  # zombie: stale epoch
        with pytest.raises(KafkaProtocolError):
            txn.produce("t", b"zombie")
            txn.commit()
        t2.produce("t", b"winner")
        t2.commit()
        vals = [r.value for r in b.fetch("t", 0, 0)]
        assert vals == [b"a", b"b", b"winner"]
        b.close(); b2.close()
    finally:
        stub.close()


def test_kafka_txn_network_failure_resets_producer_id():
    """A socket-level failure (OSError) mid-transaction must reset the
    producer id so the next begin() re-runs InitProducerId: the epoch bump
    makes the coordinator abort the dangling open transaction. Without the
    reset, the replay is produced into the SAME open transaction and the
    eventual commit makes both the failed attempt's records and the replay
    visible — duplicates under read-committed (exactly-once broken)."""
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    stub = KafkaStubBroker(partitions=1)
    try:
        b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
        txn = b.txn("txn-net-0")
        txn.begin()
        txn.produce("t", b"attempt1")

        real_end_txn = b.client.end_txn

        def dead_socket(*a, **kw):
            raise OSError("connection reset by peer")

        # Records get appended (add_partitions + produce succeed), then the
        # socket dies on EndTxn: coordinator still holds the txn OPEN.
        b.client.end_txn = dead_socket
        with pytest.raises(OSError):
            txn.commit()
        assert txn._pid is None  # forces InitProducerId on next begin()
        b.client.end_txn = real_end_txn

        # Replay path: fresh begin() bumps the epoch, which drops the
        # dangling transaction's pending records at the coordinator.
        txn.begin()
        txn.produce("t", b"replay")
        txn.commit()

        vals = [r.value for r in b.fetch("t", 0, 0)]
        assert vals == [b"replay"], vals  # attempt1 aborted, no duplicate
        b.close()
    finally:
        stub.close()


def test_txn_offsets_commit_atomically(stub):
    """AddOffsetsToTxn (api 25) + TxnOffsetCommit (api 28): offsets staged
    via ``send_offsets`` become the group's committed position only when
    EndTxn commits — atomically with the produced records — and vanish on
    abort. The KIP-98 consume-transform-produce half the reference's Kafka
    0.11 era defined (pom.xml:55-78)."""
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
    try:
        txn = b.txn("eos-wire-0")
        txn.begin()
        txn.produce("eow-out", b"r0", partition=0)
        txn.send_offsets("eow-grp", {("eow-in", 0): 5})
        # nothing visible before commit: records pending, offsets unstaged
        assert b.committed("eow-grp", "eow-in", 0) is None
        assert b.client.fetch("eow-out", 0, 0) == []
        txn.commit()
        assert b.committed("eow-grp", "eow-in", 0) == 5
        assert [r.value for r in b.client.fetch("eow-out", 0, 0)] == [b"r0"]

        # abort drops the staged offsets along with the records
        txn.begin()
        txn.produce("eow-out", b"dropped", partition=0)
        txn.send_offsets("eow-grp", {("eow-in", 0): 9})
        txn.abort()
        assert b.committed("eow-grp", "eow-in", 0) == 5
        assert [r.value for r in b.client.fetch("eow-out", 0, 0)] == [b"r0"]

        # max-wins merge across send_offsets calls within one transaction
        txn.begin()
        txn.send_offsets("eow-grp", {("eow-in", 0): 7, ("eow-in", 1): 3})
        txn.send_offsets("eow-grp", {("eow-in", 0): 6})
        txn.commit()
        assert b.committed("eow-grp", "eow-in", 0) == 7
        assert b.committed("eow-grp", "eow-in", 1) == 3
    finally:
        b.close()


def test_txn_offset_commit_requires_add_offsets(client):
    """TxnOffsetCommit for a group never registered via AddOffsetsToTxn is
    rejected (INVALID_TXN_STATE) — the stub enforces the KIP-98 ordering so
    the client can't silently skip the registration step."""
    pid, epoch = client.init_producer_id(transactional_id="eos-order")
    with pytest.raises(KafkaProtocolError):
        client.txn_offset_commit("eos-order", "never-added", pid, epoch,
                                 {("t", 0): 1})


def test_eos_consume_transform_produce_crash(stub, run):
    """The canonical exactly-once loop over the stub broker, with a crash
    in its worst window. Spout (``policy='txn'``) -> transform ->
    TransactionalBrokerSink committing consumed offsets INSIDE the producer
    transaction. Between runs, a 'crashed' task leaves a transaction OPEN
    at the coordinator with records AND offsets already shipped but EndTxn
    never sent; the restarted task's epoch bump fences it. A read-committed
    consumer must see every input exactly once (no ghost, no dupes, no
    loss) and the group offset must cover the whole log. Closes the
    documented produce-vs-checkpoint 'effectively-once' window (VERDICT r2
    missing #2)."""
    from tests.test_runtime import PassBolt
    from storm_tpu.config import SinkConfig
    from storm_tpu.connectors import BrokerSpout, TransactionalBrokerSink
    from storm_tpu.runtime import TopologyBuilder
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    GROUP = "eos-g"
    offsets_cfg = OffsetsConfig(policy="txn", group_id=GROUP,
                                max_behind=None)
    sink_cfg = SinkConfig(mode="transactional", txn_batch=4, txn_ms=30.0,
                          offsets_group=GROUP)

    def make_broker():
        return KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")

    async def run_topology(broker, expect_out):
        tb = TopologyBuilder()
        tb.set_spout("in", BrokerSpout(broker, "eos-src", offsets_cfg), 1)
        tb.set_bolt("mid", PassBolt(), 1).shuffle_grouping("in")
        tb.set_bolt("sink",
                    TransactionalBrokerSink(broker, "eos-out", sink_cfg),
                    1).shuffle_grouping("mid")
        cluster = AsyncLocalCluster()
        await cluster.submit("eos-topo", Config(), tb.build())
        deadline = asyncio.get_event_loop().time() + 30
        while asyncio.get_event_loop().time() < deadline:
            if stub.topic_size("eos-out") >= expect_out:
                break
            await asyncio.sleep(0.05)
        await cluster.shutdown()

    # ---- run 1: six records flow through and commit --------------------------
    feeder = make_broker()
    for i in range(6):
        feeder.produce("eos-src", f"rec-{i}", partition=i % 2)
    b1 = make_broker()
    run(run_topology(b1, 6), timeout=60)
    b1.close()
    committed_after_1 = {
        p: feeder.committed(GROUP, "eos-src", p) for p in (0, 1)}
    assert committed_after_1 == {0: 3, 1: 3}, committed_after_1

    # ---- the crash: a task dies between produce and commit -------------------
    # Low-level on purpose: records and offsets are ALREADY at the broker
    # inside an open transaction for the SAME transactional id the
    # restarted sink task will claim ('<topology>-<component>-<task>');
    # EndTxn is never sent — the exact window runtime/transactional.py
    # documented as effectively-once.
    ghost = make_broker()
    txn_id = "eos-topo-sink-0"
    pid, epoch = ghost.client.init_producer_id(transactional_id=txn_id)
    ghost.client.add_partitions_to_txn(txn_id, pid, epoch, [("eos-out", 0)])
    ghost.client.produce("eos-out", 0, [(None, b"GHOST")], acks=-1,
                         message_format="v2", producer=(pid, epoch, 0),
                         transactional_id=txn_id)
    ghost.client.add_offsets_to_txn(txn_id, pid, epoch, GROUP)
    ghost.client.txn_offset_commit(txn_id, GROUP, pid, epoch,
                                   {("eos-src", 0): 999})
    ghost.close()  # crash: no EndTxn

    # open-transaction state is invisible to read-committed consumers
    assert feeder.committed(GROUP, "eos-src", 0) == 3
    assert stub.topic_size("eos-out") == 6

    # ---- run 2: restart fences the ghost, finishes the log -------------------
    for i in range(6, 10):
        feeder.produce("eos-src", f"rec-{i}", partition=i % 2)
    b2 = make_broker()
    run(run_topology(b2, 10), timeout=60)
    b2.close()

    out = []
    for p in range(2):
        out.extend(feeder.fetch("eos-out", p, 0, max_records=100))
    vals = sorted(r.value.decode() for r in out)
    assert vals == sorted(f"rec-{i}" for i in range(10)), vals
    committed = {p: feeder.committed(GROUP, "eos-src", p) for p in (0, 1)}
    assert committed == {0: 5, 1: 5}, committed
    feeder.close()


def test_eos_chaos_soak_moves_and_failures(run):
    """Exactly-once under COMBINED churn — the individual machines are
    each tested above; this soaks them together: txn spout -> fan-out
    transform (two outputs per record + one forced mid-stream tuple
    failure and replay) -> transactional sink, while the output
    partition's leader AND the group/txn coordinator both migrate
    mid-stream. A read-committed consumer must see each input's two
    outputs exactly once (no loss from the moves, no dupes from the
    replay), and the committed group offsets must cover the whole log."""
    from storm_tpu.config import SinkConfig
    from storm_tpu.connectors import BrokerSpout, TransactionalBrokerSink
    from storm_tpu.runtime import Bolt, TopologyBuilder, Values
    from storm_tpu.runtime.cluster import AsyncLocalCluster

    GROUP = "soak-g"
    N = 16
    stub = KafkaStubBroker(partitions=2, nodes=2)
    offsets_cfg = OffsetsConfig(policy="txn", group_id=GROUP,
                                max_behind=None)
    sink_cfg = SinkConfig(mode="transactional", txn_batch=4, txn_ms=30.0,
                          offsets_group=GROUP)

    class FanOut(Bolt):
        failed_once = False

        async def execute(self, t):
            msg = t.get("message")
            if not FanOut.failed_once and msg.endswith("-7"):
                FanOut.failed_once = True
                self.collector.fail(t)  # forced failure -> entry replay
                return
            await self.collector.emit(Values([f"{msg}/a"]), anchors=[t])
            await self.collector.emit(Values([f"{msg}/b"]), anchors=[t])
            self.collector.ack(t)

    async def wait_out(n, timeout=60.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if stub.topic_size("soak-out") >= n:
                return True
            await asyncio.sleep(0.05)
        return False

    async def go():
        FanOut.failed_once = False
        feeder = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                                 message_format="v2")
        # phase 1: first half (incl. the forced r-7 failure + replay)
        for i in range(N // 2):
            feeder.produce("soak-src", f"r-{i}", partition=i % 2)
        broker = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                                 message_format="v2")
        tb = TopologyBuilder()
        tb.set_spout("in", BrokerSpout(broker, "soak-src", offsets_cfg), 1)
        tb.set_bolt("fan", FanOut(), 1).shuffle_grouping("in")
        tb.set_bolt("sink",
                    TransactionalBrokerSink(broker, "soak-out", sink_cfg),
                    1).shuffle_grouping("fan")
        cluster = AsyncLocalCluster()
        await cluster.submit("soak-topo", Config(), tb.build())
        assert await wait_out(N), "phase 1 never completed"

        # churn strikes with ESTABLISHED state everywhere: live producer
        # id/epoch and sequences at the sink, cached coordinator, spout
        # mid-group — every retry path must renegotiate, not re-create
        stub.move_leader("soak-out", 0, 1)
        stub.move_leader("soak-src", 1, 1)
        stub.move_coordinator(1)

        # phase 2: second half must flow THROUGH the moved cluster
        for i in range(N // 2, N):
            feeder.produce("soak-src", f"r-{i}", partition=i % 2)
        assert await wait_out(2 * N), "phase 2 stalled after the moves"
        await cluster.shutdown()
        broker.close()
        rc = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                             isolation="read_committed")
        out = []
        for p in range(2):
            out.extend(rc.fetch("soak-out", p, 0, max_records=200))
        rc.close()
        vals = sorted(r.value.decode() for r in out)
        expect = sorted(f"r-{i}/{s}" for i in range(N) for s in "ab")
        assert vals == expect, (len(vals), vals[:8])
        committed = {p: feeder.committed(GROUP, "soak-src", p)
                     for p in (0, 1)}
        assert committed == {0: N // 2, 1: N // 2}, committed
        feeder.close()

    try:
        run(go(), timeout=120)
    finally:
        stub.close()


def test_txn_policy_orders_per_partition(run):
    """policy='txn' delivers per-partition ORDERED: while one entry's tuple
    tree is open, the spout must not fetch (let alone emit) later offsets
    of that partition — otherwise a later offset could commit in the sink's
    transaction and a crash would resume past the earlier, unprocessed
    record. Other partitions keep flowing (Kafka Streams' model)."""
    from storm_tpu.connectors.memory import MemoryBroker
    from storm_tpu.connectors.spout import BrokerSpout
    from storm_tpu.runtime.base import TopologyContext

    class _Emits:
        def __init__(self):
            self.emitted = []

        async def emit(self, values, *, msg_id=None, root_ts=None,
                       origins=None, **kw):
            self.emitted.append(msg_id)
            return 1

    async def go():
        broker = MemoryBroker(default_partitions=2)
        for i in range(6):
            broker.produce("t", f"m{i}", partition=i % 2)
        spout = BrokerSpout(
            broker, "t",
            OffsetsConfig(policy="txn", group_id="g", max_behind=None))
        col = _Emits()

        class _Ctx(TopologyContext):
            pass

        ctx = _Ctx("in", 0, 1, Config())

        class _M:
            def counter(self, *a):
                class C:
                    def inc(self, *_a):  # pragma: no cover
                        pass
                return C()
        ctx.metrics = _M()
        spout.open(ctx, col)

        # first poll round: exactly ONE entry per partition, not the log
        await spout.next_tuple()
        await spout.next_tuple()
        assert sorted(col.emitted) == [(0, 0), (1, 0)], col.emitted
        # both partitions blocked until their trees complete
        for _ in range(4):
            assert not await spout.next_tuple()
        assert sorted(col.emitted) == [(0, 0), (1, 0)]
        # ack partition 0's entry: ONLY partition 0 advances
        spout.ack((0, 0))
        await spout.next_tuple()
        assert not await spout.next_tuple()
        assert sorted(col.emitted) == [(0, 0), (0, 1), (1, 0)]
        # a FAILED entry keeps its partition blocked for new fetches; the
        # replay re-emits the same entry, and only its ack unblocks
        spout.fail((1, 0))
        await spout.next_tuple()  # serves the replay queue
        assert col.emitted.count((1, 0)) == 2
        assert not any(m == (1, 1) for m in col.emitted)
        spout.ack((1, 0))
        await spout.next_tuple()
        assert (1, 1) in col.emitted

    run(go(), timeout=10)


def test_lz4_block_decode_and_frame_roundtrip():
    """LZ4 decoder validated against hand-crafted block streams (literals,
    backref matches, overlapping RLE copies) independently of our encoder;
    frame round-trip through the literal-only encoder; corrupt streams
    fail loudly. xxh32 (frame header checksum) checked against published
    test vectors inside the module tests below."""
    from storm_tpu.connectors.lz4 import (Lz4Error, _xxh32, compress_frame,
                                          decompress_block, decompress_frame)

    # known xxh32 vectors (seed 0)
    assert _xxh32(b"") == 0x02CC5D05
    assert _xxh32(b"a") == 0x550D7456
    assert _xxh32(b"abc") == 0x32D153FF

    # literal 'abcd' + match len 8 off 4 (overlapping) -> 'abcdabcdabcd'
    blk = bytes([(4 << 4) | (8 - 4)]) + b"abcd" + bytes([4, 0])
    assert decompress_block(blk) == b"abcdabcdabcd"

    # extended lengths: 20 literals (15+5), then match len 23 (15+4+4)
    lit = bytes(range(20))
    blk2 = bytes([(15 << 4) | 15]) + bytes([5]) + lit + bytes([20, 0, 4])
    assert decompress_block(blk2) == lit + (lit * 2)[:23]

    # non-overlapping 2-byte offset match
    lit3 = b"0123456789" * 7  # 70 bytes
    blk3 = (bytes([(15 << 4) | (10 - 4)]) + bytes([70 - 15]) + lit3
            + bytes([70, 0]))
    assert decompress_block(blk3) == lit3 + lit3[:10]

    data = b"storm-tpu lz4 " * 500
    assert decompress_frame(compress_frame(data)) == data

    with pytest.raises(Lz4Error):
        decompress_block(bytes([(4 << 4)]) + b"ab")  # truncated literals
    with pytest.raises(Lz4Error):
        decompress_block(bytes([(0 << 4) | 0, 9, 0]))  # offset past output
    with pytest.raises(Lz4Error):
        decompress_frame(b"\x00\x01\x02\x03\x04\x05\x06\x07")  # bad magic
    with pytest.raises(Lz4Error):
        decompress_frame(compress_frame(data)[:-6])  # truncated block


def test_lz4_wrapper_message_and_batch_decode():
    """Both fetch decode paths read lz4: a v1 wrapper message (codec 3,
    KIP-31 relative inner offsets) and a v2 record batch (codec bits 3) —
    the last 0.11-era producer codec the ingest path was missing
    (reference pom.xml:55-78)."""
    import struct as _struct

    from storm_tpu.connectors.kafka_protocol import (decode_message_set,
                                                     encode_record_batch)
    from storm_tpu.connectors.lz4 import compress_frame

    # ---- v0/v1 wrapper: inner message set, lz4-framed, codec attrs=3 ----
    inner = encode_message_set([(None, b"in0"), (None, b"in1")], 1234,
                               offsets=[0, 1])
    compressed = compress_frame(inner)
    msg = bytearray()
    msg.append(1)   # magic 1
    msg.append(3)   # attributes: lz4
    msg += _struct.pack(">q", 1234)
    msg += _struct.pack(">i", -1)  # null key
    msg += _struct.pack(">i", len(compressed)) + compressed
    import zlib as _zlib
    full = bytearray()
    full += _struct.pack(">q", 11)  # wrapper offset = last inner (KIP-31)
    full += _struct.pack(">i", 4 + len(msg))
    full += _struct.pack(">I", _zlib.crc32(bytes(msg)) & 0xFFFFFFFF)
    full += msg
    recs = decode_message_set("t", 0, bytes(full))
    assert [(r.offset, r.value) for r in recs] == [(10, b"in0"), (11, b"in1")]

    # ---- v2 record batch with codec bits 3 ----
    batch = encode_record_batch([(b"k", b"v0"), (None, b"v1")], 5678,
                                compression="lz4")
    out = decode_message_set("t", 1, batch)
    assert [(r.key, r.value) for r in out] == [(b"k", b"v0"), (None, b"v1")]


def test_lz4_record_batch_over_sockets(stub):
    """End-to-end over real sockets: a producer shipping lz4 record batches
    delivers intact records back on fetch (stub parses through the shared
    decode path)."""
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    stub.serve_batches = True
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                        compression="lz4")
    try:
        for i in range(5):
            b.produce("lz", f"lz4-{i}", partition=0)
        got = [r.value.decode() for r in b.fetch("lz", 0, 0)]
        assert got == [f"lz4-{i}" for i in range(5)], got
    finally:
        b.close()
        stub.serve_batches = False


def test_api_versions_probe_and_compat(stub):
    """The connect-time ApiVersions probe: a broker advertising the pinned
    surface passes; one that dropped the legacy versions (KIP-896-era)
    fails LOUDLY with a per-api compatibility matrix; one that hangs up on
    the probe (pre-0.10) is assumed era-compatible."""
    from storm_tpu.connectors.kafka_protocol import PINNED_API_VERSIONS

    # happy path: stub advertises everything we pin
    c1 = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        advertised = c1.probe_api_versions()
        assert advertised is not None and 0 in advertised
        c1.check_broker_compat()  # no raise
        c1.refresh_metadata(["t"])  # probe integrated into first metadata
    finally:
        c1.close()

    # modern broker: legacy produce/fetch versions removed
    stub.api_versions = {key: (9, 17) for key in PINNED_API_VERSIONS}
    c2 = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        with pytest.raises(KafkaProtocolError) as ei:
            c2.refresh_metadata(["t"])
        msg = str(ei.value)
        assert "KIP-896" in msg and "Produce (api 0)" in msg \
            and "broker serves v9-v17" in msg
    finally:
        c2.close()
        stub.api_versions = None

    # pre-0.10 broker: connection dropped on the probe -> compatible
    stub.api_versions = "closed"
    c3 = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        assert c3.probe_api_versions() is None
        c3.refresh_metadata(["t"])  # proceeds, no raise
    finally:
        c3.close()
        stub.api_versions = None

    # genuine 0.10 broker: core apis served, NO transaction apis. The core
    # path must work (feature-aware check, not all-or-nothing); asking for
    # a transaction handle then fails loudly with the [txn] matrix.
    stub.api_versions = {0: (0, 2), 1: (0, 3), 2: (0, 1), 3: (0, 2),
                         8: (0, 2), 9: (0, 1), 10: (0, 0), 18: (0, 0)}
    c4 = KafkaWireBroker(f"127.0.0.1:{stub.port}")  # message_format v1
    try:
        c4.client.refresh_metadata(["t"])  # core OK, no raise
        with pytest.raises(KafkaProtocolError) as ei:
            c4.txn("t-0")
        assert "[txn]" in str(ei.value) and "EndTxn" in str(ei.value)
    finally:
        c4.close()
        stub.api_versions = None


def test_lz4_multiblock_frame_roundtrip():
    """Frames larger than one block: block boundaries must reassemble
    exactly, and truncating at a boundary fails loudly."""
    from storm_tpu.connectors.lz4 import Lz4Error, compress_frame, decompress_frame

    data = bytes(range(256)) * 2048  # 512KB
    framed = compress_frame(data, block_size=64 * 1024)  # 8 blocks
    assert decompress_frame(framed) == data
    with pytest.raises(Lz4Error):
        # drop the EndMark + final block's tail
        decompress_frame(framed[:-(64 * 1024 + 8)])


def test_txn_produce_with_lz4_codec(stub):
    """Transactional produce honors broker.compression: the committed
    records round-trip through the stub's shared decode path (codec 3)."""
    from storm_tpu.connectors.kafka_protocol import KafkaWireBroker

    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                        compression="lz4")
    try:
        txn = b.txn("lz4-txn-0")
        txn.begin()
        for i in range(3):
            txn.produce("lzt", f"tx-{i}", partition=0)
        txn.send_offsets("lzg", {("src", 0): 3})
        txn.commit()
        got = [r.value.decode() for r in b.fetch("lzt", 0, 0)]
        assert got == ["tx-0", "tx-1", "tx-2"], got
        assert b.committed("lzg", "src", 0) == 3
    finally:
        b.close()


def test_read_committed_filters_aborted_transactions(stub):
    """Fetch v4 + isolation_level=read_committed (KIP-98, the reference's
    own Kafka 0.11): with REAL-broker transactional log semantics
    (records append immediately, EndTxn appends a control marker), a
    read_committed consumer must see only committed transactions' records
    — aborted data is filtered via the broker's aborted_transactions
    ranges — while a read_uncommitted (v2-era) consumer sees everything."""
    # per-test stub instance: no cross-test leak to undo
    stub.log_transactional = True
    good = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                           message_format="v2", client_id="good")
    bad = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                          message_format="v2", client_id="bad")
    t_good = good.txn("rc-good")

    # interleave: good txn 1, aborted txn, good txn 2 — all partition 0
    t_good.begin()
    t_good.produce("rc", b"ok-0", partition=0)
    t_good.produce("rc", b"ok-1", partition=0)
    t_good.commit()
    # the aborting producer ships its records EAGERLY (low-level path:
    # KafkaTxn only puts buffered records on the wire at commit, so an
    # abort via the handle leaves nothing at the broker to filter)
    pid, epoch = bad.client.init_producer_id(transactional_id="rc-bad")
    bad.client.add_partitions_to_txn("rc-bad", pid, epoch, [("rc", 0)])
    bad.client.produce("rc", 0, [(None, b"POISON-0"),
                                 (None, b"POISON-1")], acks=-1,
                       message_format="v2", producer=(pid, epoch, 0),
                       transactional_id="rc-bad")
    bad.client.end_txn("rc-bad", pid, epoch, commit=False)
    t_good.begin()
    t_good.produce("rc", b"ok-2", partition=0)
    t_good.commit()

    # read_uncommitted (v2 era): sees committed AND aborted data
    all_vals = [r.value for r in good.client.fetch("rc", 0, 0)]
    assert b"POISON-0" in all_vals and b"ok-2" in all_vals

    # read_committed: aborted records filtered, committed kept, order
    # and offsets preserved (markers occupy offsets but carry no data)
    rc = good.client.fetch("rc", 0, 0, isolation="read_committed")
    assert [r.value for r in rc] == [b"ok-0", b"ok-1", b"ok-2"]
    offs = [r.offset for r in rc]
    assert offs == sorted(offs) and offs[0] == 0

    # KafkaWireBroker-level isolation plumbs through fetch()
    rc_broker = KafkaWireBroker(f"127.0.0.1:{stub.port}",
                                message_format="v2",
                                isolation="read_committed")
    vals = [r.value for r in rc_broker.fetch("rc", 0, 0)]
    assert vals == [b"ok-0", b"ok-1", b"ok-2"]
    rc_broker.close()
    good.close()
    bad.close()


def test_read_committed_bounded_at_open_transaction(stub):
    """An OPEN transaction's records sit past the LSO: read_committed
    consumers must not see them (the broker serves nothing beyond the
    LSO); after commit they appear."""
    # per-test stub instance: no cross-test leak to undo
    stub.log_transactional = True
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2",
                        isolation="read_committed")
    txn = b.txn("rc-open")
    txn.begin()
    txn.produce("rco", b"inflight", partition=0)
    # KafkaTxn buffers locally; push the records to the broker inside
    # the open transaction via the low-level path
    txn._client.add_partitions_to_txn("rc-open", txn._pid, txn._epoch,
                                      [("rco", 0)])
    txn._client.produce("rco", 0, [(None, b"inflight")], acks=-1,
                        message_format="v2",
                        producer=(txn._pid, txn._epoch, 0),
                        transactional_id="rc-open")
    txn._pending.clear()

    assert b.fetch("rco", 0, 0) == []  # open txn: invisible
    txn._open = True
    txn.commit()
    vals = [r.value for r in b.fetch("rco", 0, 0)]
    assert vals == [b"inflight"]
    b.close()


def test_read_committed_fencing_aborts_dangling_txn(stub):
    """A crashed producer's dangling transaction (records at the broker,
    EndTxn never sent) is epoch-fenced by the restarted task; the fencing
    abort must make those records invisible to read_committed consumers —
    the consume-side half of the crash test, under real-broker log
    semantics."""
    # per-test stub instance: no cross-test leak to undo
    stub.log_transactional = True
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
    pid, epoch = b.client.init_producer_id(transactional_id="rc-crash")
    b.client.add_partitions_to_txn("rc-crash", pid, epoch, [("rcc", 0)])
    b.client.produce("rcc", 0, [(None, b"GHOST")], acks=-1,
                     message_format="v2", producer=(pid, epoch, 0),
                     transactional_id="rc-crash")
    # crash: no EndTxn. Restarted task re-inits the same id -> fence.
    txn2 = b.txn("rc-crash")
    txn2.begin()
    txn2.produce("rcc", b"real", partition=0)
    txn2.commit()

    rc = b.client.fetch("rcc", 0, 0, isolation="read_committed")
    assert [r.value for r in rc] == [b"real"]
    # the ghost IS in the raw log (real-broker semantics)...
    raw = [r.value for r in b.client.fetch("rcc", 0, 0)]
    assert b"GHOST" in raw
    b.close()


def test_read_committed_fetch_past_abort_marker(stub):
    """Fetching from an offset PAST an abort marker must not re-activate
    the stale aborted range and drop the same producer's later COMMITTED
    records (regression: the stub reported every historical range, so the
    ABORT marker — outside the fetched region — never deactivated the
    producer and committed data vanished)."""
    stub.log_transactional = True
    b = KafkaWireBroker(f"127.0.0.1:{stub.port}", message_format="v2")
    pid, epoch = b.client.init_producer_id(transactional_id="rc-mid")
    # txn 1: aborted -> GHOST@0, ABORT marker@1
    b.client.add_partitions_to_txn("rc-mid", pid, epoch, [("rcm", 0)])
    b.client.produce("rcm", 0, [(None, b"GHOST")], acks=-1,
                     message_format="v2", producer=(pid, epoch, 0),
                     transactional_id="rc-mid")
    b.client.end_txn("rc-mid", pid, epoch, commit=False)
    # txn 2, SAME producer: committed -> real@2, COMMIT marker@3
    b.client.add_partitions_to_txn("rc-mid", pid, epoch, [("rcm", 0)])
    b.client.produce("rcm", 0, [(None, b"real")], acks=-1,
                     message_format="v2", producer=(pid, epoch, 1),
                     transactional_id="rc-mid")
    b.client.end_txn("rc-mid", pid, epoch, commit=True)

    # from 0: ghost filtered, real kept
    rc0 = b.client.fetch("rcm", 0, 0, isolation="read_committed")
    assert [r.value for r in rc0] == [b"real"]
    # from 2 (past the abort marker): the committed record must survive
    rc2 = b.client.fetch("rcm", 0, 2, isolation="read_committed")
    assert [r.value for r in rc2] == [b"real"], [r.value for r in rc2]
    b.close()


def test_api_versions_probe_parses_error_35(stub):
    """UNSUPPORTED_VERSION (35) replies still carry the supported-versions
    array (KIP-511): the probe must parse and validate it rather than
    treating the error as a silent no-answer — a modern broker answering
    v0 with error 35 is exactly what the loud KIP-896 check exists for
    (ADVICE r3-low)."""
    from storm_tpu.connectors.kafka_protocol import PINNED_API_VERSIONS

    # error 35 + modern ranges: must fail LOUDLY, not bypass the check
    stub.api_versions = ("error35",
                         {key: (9, 17) for key in PINNED_API_VERSIONS})
    c = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        advertised = c.probe_api_versions()
        assert advertised is not None and advertised[0] == (9, 17)
        with pytest.raises(KafkaProtocolError, match="KIP-896"):
            c.refresh_metadata(["t"])
    finally:
        c.close()
        stub.api_versions = None

    # error 35 + EMPTY array: nothing to learn -> era-compatible assumed
    stub.api_versions = ("error35", {})
    c2 = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        assert c2.probe_api_versions() is None
        c2.refresh_metadata(["t"])  # proceeds
    finally:
        c2.close()
        stub.api_versions = None


# ---- leader-election survival (VERDICT r3 missing #3) ------------------------


def test_produce_fetch_survive_leader_move():
    """Mid-stream leader election: the old leader answers
    NOT_LEADER_FOR_PARTITION (6); the client must refresh metadata and
    retry onto the new leader instead of dying — the 0.11-era
    kafka-clients behavior the wire client replaces."""
    stub = KafkaStubBroker(partitions=2, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        for i in range(3):
            client.produce("t", 0, [(None, f"a{i}".encode())])
        stub.move_leader("t", 0, 1)  # election: node 1 now leads t[0]
        for i in range(3):
            client.produce("t", 0, [(None, f"b{i}".encode())])
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value.decode() for r in recs] == \
            ["a0", "a1", "a2", "b0", "b1", "b2"]
        # move back mid-consumption: fetch survives the reverse move too
        stub.move_leader("t", 0, 0)
        recs = client.fetch("t", 0, 3, max_wait_ms=10)
        assert [r.value.decode() for r in recs] == ["b0", "b1", "b2"]
        assert client.list_offset("t", 0, -1) == 6
    finally:
        client.close()
        stub.close()


def test_idempotent_sequences_survive_leader_move():
    """An idempotent producer's sequence numbers stay valid across the
    election: the retried/continued batches neither duplicate nor hit
    OUT_OF_ORDER_SEQUENCE_NUMBER."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        pid, epoch = client.init_producer_id()
        client.produce("t", 0, [(None, b"s0"), (None, b"s1")],
                       message_format="v2", producer=(pid, epoch, 0))
        stub.move_leader("t", 0, 1)
        client.produce("t", 0, [(None, b"s2")],
                       message_format="v2", producer=(pid, epoch, 2))
        client.produce("t", 0, [(None, b"s3")],
                       message_format="v2", producer=(pid, epoch, 3))
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"s0", b"s1", b"s2", b"s3"]
    finally:
        client.close()
        stub.close()


def test_offset_commit_survives_coordinator_move():
    """NOT_COORDINATOR (16) drops the cached coordinator and re-finds it
    — commits keep landing after the group coordinator migrates."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        client.offset_commit("g", "t", 0, 5)
        assert client.offset_fetch("g", "t", 0) == 5
        stub.move_coordinator(1)
        client.offset_commit("g", "t", 0, 9)  # cached addr now answers 16
        assert client.offset_fetch("g", "t", 0) == 9
    finally:
        client.close()
        stub.close()


def test_open_transaction_survives_leader_and_coordinator_moves():
    """The hard case: an OPEN transaction rides out BOTH a partition
    leader election (mid-produce) and a coordinator migration (before the
    offsets commit + EndTxn). A read-committed consumer must see the
    whole transaction exactly once, with its offsets committed."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        txn_id = "eos-move"
        pid, epoch = client.init_producer_id(transactional_id=txn_id)
        client.add_partitions_to_txn(txn_id, pid, epoch, [("out", 0)])
        client.produce("out", 0, [(None, b"t0")], acks=-1,
                       message_format="v2", producer=(pid, epoch, 0),
                       transactional_id=txn_id)
        stub.move_leader("out", 0, 1)  # election mid-transaction
        client.produce("out", 0, [(None, b"t1")], acks=-1,
                       message_format="v2", producer=(pid, epoch, 1),
                       transactional_id=txn_id)
        stub.move_coordinator(1)  # coordinator migrates before commit
        client.add_offsets_to_txn(txn_id, pid, epoch, "g")
        client.txn_offset_commit(txn_id, "g", pid, epoch, {("in", 0): 7})
        client.end_txn(txn_id, pid, epoch, commit=True)

        recs = client.fetch("out", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"t0", b"t1"]
        assert client.offset_fetch("g", "t", 0) is None  # other topic clean
        assert client.offset_fetch("g", "in", 0) == 7
    finally:
        client.close()
        stub.close()


def test_leader_retry_exhaustion_surfaces():
    """A leadership error that never heals exhausts the bounded backoff
    and surfaces as a CODED error for the spout/sink fail path — no
    infinite retry loop. Simulated by electing a leader node that is not
    in the broker list: every reachable node keeps answering
    NOT_LEADER_FOR_PARTITION and metadata never heals."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        stub.move_leader("t", 0, 7)  # phantom node: election never settles
        t0 = time.perf_counter()
        with pytest.raises(KafkaProtocolError) as ei:
            client.produce("t", 0, [(None, b"x")])
        assert ei.value.code == 6, ei.value
        assert "NOT_LEADER_FOR_PARTITION" in str(ei.value)
        assert time.perf_counter() - t0 < 30  # bounded, not forever
    finally:
        client.close()
        stub.close()


def test_produce_survives_leader_broker_death():
    """The common real election trigger: the leader BROKER dies, so the
    stale cached leader address yields a socket error (not an in-band
    NOT_LEADER reply). The client must treat that as retriable, refresh
    metadata, and land on the re-elected leader."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        stub.move_leader("t", 0, 1)
        client.produce("t", 0, [(None, b"a")])  # leader is node 1, cached
        # node 1 dies; the controller re-elects node 0
        stub._socks[1].close()
        stub.move_leader("t", 0, 0)
        time.sleep(0.2)
        client.produce("t", 0, [(None, b"b")])  # stale addr -> OSError -> retry
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"a", b"b"]
    finally:
        client.close()
        stub.close()


# ---- transport security (SASL/PLAIN + SSL) -----------------------------------


def test_sasl_plain_round_trip():
    """SASL_PLAINTEXT: the 0.11-era handshake (Kafka-framed SaslHandshake
    api 17 + raw pre-KIP-152 token frames) authenticates every connection;
    produce/fetch work over the authenticated socket."""
    stub = KafkaStubBroker(partitions=1)
    stub.sasl = ("alice", "s3cret")
    sec = {"protocol": "SASL_PLAINTEXT", "sasl_username": "alice",
           "sasl_password": "s3cret"}
    client = KafkaWireClient(f"127.0.0.1:{stub.port}", security=sec)
    try:
        client.produce("t", 0, [(None, b"locked")])
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"locked"]
    finally:
        client.close()
        stub.close()

    # wrong password: the broker closes the connection -> loud failure
    stub2 = KafkaStubBroker(partitions=1)
    stub2.sasl = ("alice", "s3cret")
    bad = KafkaWireClient(
        f"127.0.0.1:{stub2.port}",
        security={"protocol": "SASL_PLAINTEXT", "sasl_username": "alice",
                  "sasl_password": "wrong"})
    try:
        with pytest.raises((KafkaProtocolError, OSError)):
            bad.produce("t", 0, [(None, b"x")])
    finally:
        bad.close()
        stub2.close()

    # unauthenticated client against a SASL broker: dropped pre-auth
    stub3 = KafkaStubBroker(partitions=1)
    stub3.sasl = ("alice", "s3cret")
    plain = KafkaWireClient(f"127.0.0.1:{stub3.port}")
    try:
        with pytest.raises((KafkaProtocolError, OSError)):
            plain.produce("t", 0, [(None, b"x")])
    finally:
        plain.close()
        stub3.close()


@pytest.mark.parametrize("mech", ["SCRAM-SHA-256", "SCRAM-SHA-512"])
def test_sasl_scram_round_trip(mech):
    """SASL/SCRAM (KIP-84): full RFC 5802 exchange over raw token frames —
    salted-password proof verified server-side, server signature verified
    client-side; produce/fetch work over the authenticated socket."""
    stub = KafkaStubBroker(partitions=1)
    stub.sasl = ("svc", "scram-pw")
    stub.sasl_mechanism = mech
    sec = {"protocol": "SASL_PLAINTEXT", "sasl_mechanism": mech,
           "sasl_username": "svc", "sasl_password": "scram-pw"}
    client = KafkaWireClient(f"127.0.0.1:{stub.port}", security=sec)
    try:
        client.produce("t", 0, [(None, b"scrammed")])
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"scrammed"]
    finally:
        client.close()
        stub.close()


def test_sasl_scram_wrong_password_fails_loudly():
    stub = KafkaStubBroker(partitions=1)
    stub.sasl = ("svc", "scram-pw")
    stub.sasl_mechanism = "SCRAM-SHA-256"
    bad = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SASL_PLAINTEXT",
                  "sasl_mechanism": "SCRAM-SHA-256",
                  "sasl_username": "svc", "sasl_password": "nope"})
    try:
        with pytest.raises((KafkaProtocolError, OSError)):
            bad.produce("t", 0, [(None, b"x")])
    finally:
        bad.close()
        stub.close()


def test_sasl_scram_refuses_downgraded_iteration_count():
    """A server (or MITM) requesting i < 4096 (RFC 7677 floor) must be
    refused — accepting would let an attacker dictionary-crack the proof
    thousands of times faster."""
    stub = KafkaStubBroker(partitions=1)
    stub.sasl = ("svc", "scram-pw")
    stub.sasl_mechanism = "SCRAM-SHA-256"
    stub.scram_iterations = 512
    client = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SASL_PLAINTEXT",
                  "sasl_mechanism": "SCRAM-SHA-256",
                  "sasl_username": "svc", "sasl_password": "scram-pw"})
    try:
        with pytest.raises(KafkaProtocolError, match="iteration count"):
            client.produce("t", 0, [(None, b"x")])
    finally:
        client.close()
        stub.close()


def test_scram_auth_survives_leader_move():
    """A leader election makes the client open a connection to a broker it
    has never spoken to; that fresh connection must run the full SCRAM
    exchange (multi-round-trip) before the retried produce — re-auth on
    the retry path, not just at bootstrap."""
    stub = KafkaStubBroker(partitions=1, nodes=2)
    stub.sasl = ("svc", "scram-pw")
    stub.sasl_mechanism = "SCRAM-SHA-256"
    client = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SASL_PLAINTEXT",
                  "sasl_mechanism": "SCRAM-SHA-256",
                  "sasl_username": "svc", "sasl_password": "scram-pw"})
    try:
        client.produce("t", 0, [(None, b"pre")])
        stub.move_leader("t", 0, 1)  # node 1: never-contacted broker
        client.produce("t", 0, [(None, b"post")])
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"pre", b"post"]
    finally:
        client.close()
        stub.close()


def test_sasl_scram_mechanism_mismatch_names_brokers_offer():
    """A PLAIN-only broker refusing SCRAM surfaces error 33 + the broker's
    supported list, not a hang or a silent close."""
    stub = KafkaStubBroker(partitions=1)
    stub.sasl = ("svc", "pw")  # mechanism stays PLAIN
    client = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SASL_PLAINTEXT",
                  "sasl_mechanism": "SCRAM-SHA-256",
                  "sasl_username": "svc", "sasl_password": "pw"})
    try:
        with pytest.raises(KafkaProtocolError, match="PLAIN"):
            client.produce("t", 0, [(None, b"x")])
    finally:
        client.close()
        stub.close()


@pytest.fixture(scope="module")
def ssl_certs(tmp_path_factory):
    import subprocess

    d = tmp_path_factory.mktemp("certs")
    crt, key = str(d / "broker.crt"), str(d / "broker.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", "2", "-subj",
         "/CN=127.0.0.1", "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return crt, key


def _ssl_server_context(ssl_certs):
    import ssl

    crt, key = ssl_certs
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(crt, key)
    return ctx


def test_ssl_round_trip(ssl_certs):
    """SSL: every broker connection is TLS-wrapped; the broker's cert is
    verified against the configured CA bundle."""
    crt, _ = ssl_certs
    stub = KafkaStubBroker(partitions=1)
    stub.ssl_context = _ssl_server_context(ssl_certs)
    client = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SSL", "ssl_cafile": crt,
                  "ssl_check_hostname": False})
    try:
        client.produce("t", 0, [(None, b"tls")])
        assert [r.value for r in client.fetch("t", 0, 0, max_wait_ms=10)] \
            == [b"tls"]
    finally:
        client.close()
        stub.close()


def test_sasl_ssl_round_trip(ssl_certs):
    """SASL_SSL: TLS first, then SASL/PLAIN over the encrypted channel —
    the full production transport stack of the 0.11 era."""
    crt, _ = ssl_certs
    stub = KafkaStubBroker(partitions=1)
    stub.ssl_context = _ssl_server_context(ssl_certs)
    stub.sasl = ("svc", "pw")
    client = KafkaWireClient(
        f"127.0.0.1:{stub.port}",
        security={"protocol": "SASL_SSL", "sasl_username": "svc",
                  "sasl_password": "pw", "ssl_cafile": crt,
                  "ssl_check_hostname": False})
    try:
        client.produce("t", 0, [(None, b"both")])
        assert [r.value for r in client.fetch("t", 0, 0, max_wait_ms=10)] \
            == [b"both"]
    finally:
        client.close()
        stub.close()


def test_group_membership_survives_coordinator_move():
    """Consumer-group membership survives a coordinator migration IN
    PLACE: the stale node answers NOT_COORDINATOR, the member re-finds
    the coordinator and retries the heartbeat — member and generation
    stay valid (group state lives in __consumer_offsets), so a routine
    broker roll does NOT force a group-wide rebalance. Join after the
    move also lands on the new coordinator."""
    from storm_tpu.connectors.kafka_protocol import GroupMembership

    stub = KafkaStubBroker(partitions=4, nodes=2)
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        m = GroupMembership(client, "mv-g", ["t"])
        parts = m.join()
        assert sorted(p for _, p in parts) == [0, 1, 2, 3]
        assert m.heartbeat()

        stub.move_coordinator(1)
        # stale cached coordinator answers 16 -> re-find + retry in place
        assert m.heartbeat() is True
        # a later rejoin (e.g. after a REAL rebalance) finds node 1 too
        parts2 = m.join()
        assert sorted(p for _, p in parts2) == [0, 1, 2, 3]
        assert m.heartbeat()

        stub.move_coordinator(0)  # and back
        assert m.heartbeat() is True
    finally:
        client.close()
        stub.close()


def test_idempotent_duplicate_sequence_reply_is_success():
    """A broker answering an idempotent resend with
    DUPLICATE_SEQUENCE_NUMBER (46) is saying 'already appended' — the
    client must treat it as success, not reset the producer and
    re-produce under a fresh pid (which would create the duplicate
    idempotence exists to prevent)."""
    stub = KafkaStubBroker(partitions=1)
    stub.duplicate_error = True
    client = KafkaWireClient(f"127.0.0.1:{stub.port}")
    try:
        pid, epoch = client.init_producer_id()
        client.produce("t", 0, [(None, b"once")],
                       message_format="v2", producer=(pid, epoch, 0))
        # resend of the same sequence (lost-response retry): broker says 46
        client.produce("t", 0, [(None, b"once")],
                       message_format="v2", producer=(pid, epoch, 0))
        recs = client.fetch("t", 0, 0, max_wait_ms=10)
        assert [r.value for r in recs] == [b"once"]  # exactly one copy
    finally:
        client.close()
        stub.close()
