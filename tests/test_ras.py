"""Resource-aware placement (Storm's RAS equivalent): worst-fit-decreasing
bin-packing of component demands onto worker capacities, refusing
oversubscription; wired into DistCluster auto-placement via
topology.component_resources."""

import pytest

from storm_tpu.dist.controller import DistCluster

plan = DistCluster.plan_placement


def _caps(n, memory_mb=1000.0, cpu=400.0):
    return [{"memory_mb": memory_mb, "cpu": cpu} for _ in range(n)]


def test_wfd_packs_biggest_first():
    demands = {
        "small": {"memory_mb": 100, "cpu": 10},
        "big": {"memory_mb": 900, "cpu": 50},
        "mid": {"memory_mb": 500, "cpu": 20},
    }
    p = plan(demands, _caps(2))
    # big (900) takes one worker; mid (500) the other; small fits beside mid
    assert p["big"] != p["mid"]
    assert p["small"] == p["mid"]


def test_refuses_oversubscription():
    with pytest.raises(ValueError, match="fits no worker"):
        plan({"huge": {"memory_mb": 5000, "cpu": 10}}, _caps(3))
    # cpu constrains independently of memory
    with pytest.raises(ValueError, match="fits no worker"):
        plan({"spin": {"memory_mb": 1, "cpu": 800}}, _caps(2))


def test_spout_prefers_worker0_when_it_fits():
    demands = {
        "spout": {"memory_mb": 100, "cpu": 10, "is_spout": True},
        "bolt": {"memory_mb": 800, "cpu": 10},
    }
    p = plan(demands, _caps(2))
    assert p["spout"] == 0
    # spouts place FIRST: a big bolt must not evict the spout from 0
    demands = {
        "hog": {"memory_mb": 950, "cpu": 10},
        "spout": {"memory_mb": 100, "cpu": 10, "is_spout": True},
    }
    p = plan(demands, _caps(2))
    assert p["spout"] == 0 and p["hog"] == 1


def test_zero_demand_components_always_place():
    demands = {"a": {}, "b": {}, "c": {"memory_mb": 1000}}
    p = plan(demands, _caps(1))
    assert set(p) == {"a", "b", "c"}


def test_dist_auto_place_uses_hints():
    """component_resources drives placement through the real controller
    (no worker processes needed: attach to fake addrs, plan only)."""
    from storm_tpu.config import Config

    class FakeClient:
        def __init__(self, target):
            self.target = target

    cluster = DistCluster.__new__(DistCluster)
    cluster.clients = [FakeClient("a:1"), FakeClient("b:2")]
    cluster._worker_resources = {"memory_mb": 2048.0, "cpu": 400.0}

    cfg = Config()
    cfg.model.name = "lenet5"
    cfg.topology.component_resources = {
        "inference-bolt": {"memory_mb": 400, "cpu": 50},  # x4 tasks = 1600
        "kafka-bolt": {"memory_mb": 300},  # x2 = 600
    }
    placement = cluster._auto_place(cfg, "standard")
    # inference (1600) and kafka-bolt (600) cannot share a 2048 worker
    assert placement["inference-bolt"] != placement["kafka-bolt"]
    assert set(placement.values()) <= {0, 1}


def test_dist_auto_place_refuses_when_too_big():
    from storm_tpu.config import Config

    class FakeClient:
        def __init__(self, target):
            self.target = target

    cluster = DistCluster.__new__(DistCluster)
    cluster.clients = [FakeClient("a:1")]
    cluster._worker_resources = {"memory_mb": 1024.0, "cpu": 400.0}
    cfg = Config()
    cfg.topology.component_resources = {
        "inference-bolt": {"memory_mb": 400},  # x4 = 1600 > 1024
    }
    with pytest.raises(ValueError, match="fits no worker"):
        cluster._auto_place(cfg, "standard")


def test_declarer_resource_hints():
    from storm_tpu.runtime import Bolt, Spout, TopologyBuilder

    class S(Spout):
        async def next_tuple(self):
            return False

    class B(Bolt):
        async def execute(self, t):
            pass

    tb = TopologyBuilder()
    tb.set_spout("s", S(), 1).set_memory_load(64)
    tb.set_bolt("b", B(), 2).shuffle_grouping("s")\
        .set_memory_load(512).set_cpu_load(150)
    topo = tb.build()
    assert topo.specs["s"].resources == {"memory_mb": 64.0}
    assert topo.specs["b"].resources == {"memory_mb": 512.0, "cpu": 150.0}


def test_capacity_missing_key_means_unconstrained():
    p = plan({"a": {"memory_mb": 10, "cpu": 10}}, [{"memory_mb": 100}])
    assert p == {"a": 0}
    p = plan({"a": {"memory_mb": 10}}, [{"cpu": 100}])
    assert p == {"a": 0}


def test_zero_demand_components_spread():
    demands = {"a": {}, "b": {}, "c": {}, "d": {"memory_mb": 100}}
    p = plan(demands, _caps(3))
    # one hint must not collapse the unhinted components onto one worker
    assert len({p["a"], p["b"], p["c"]}) == 3


def test_unknown_hint_key_rejected():
    from storm_tpu.config import Config

    class FakeClient:
        def __init__(self, target):
            self.target = target

    cluster = DistCluster.__new__(DistCluster)
    cluster.clients = [FakeClient("a:1")]
    cluster._worker_resources = {"memory_mb": 4096.0, "cpu": 400.0}
    cfg = Config()
    cfg.topology.component_resources = {"inference_bolt": {"memory_mb": 10}}
    with pytest.raises(ValueError, match="unknown components"):
        cluster._auto_place(cfg, "standard")


def test_cpu_only_hints_spread():
    demands = {f"b{i}": {"cpu": 100} for i in range(4)}
    p = plan(demands, _caps(2, memory_mb=4096, cpu=400))
    # memory never changes; the cpu/count tie-break must still spread
    from collections import Counter

    assert sorted(Counter(p.values()).values()) == [2, 2]


def test_unknown_resource_key_rejected():
    from storm_tpu.config import Config

    class FakeClient:
        def __init__(self, target):
            self.target = target

    cluster = DistCluster.__new__(DistCluster)
    cluster.clients = [FakeClient("a:1")]
    cluster._worker_resources = {"memory_mb": 4096.0, "cpu": 400.0}
    cfg = Config()
    cfg.topology.component_resources = {"inference-bolt": {"mem_mb": 400}}
    with pytest.raises(ValueError, match="unknown keys"):
        cluster._auto_place(cfg, "standard")
