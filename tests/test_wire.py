"""Dist wire codecs: binary frames (storm_tpu/dist/wire.py) and the JSON
envelope fallback (storm_tpu/dist/transport.py).

The hypothesis versions of these round-trips live in test_properties.py;
this file carries the same coverage as deterministic examples plus
seeded-random fuzz loops so the codec contract is enforced in tier-1 even
where hypothesis isn't installed (the property suite is collection-skipped
there). Satellite checklist coverage: unicode incl. lone surrogates,
bytes, NaN/Inf floats, empty tuples, >64 KiB values, corrupted-CRC frames
failing loudly.
"""

from __future__ import annotations

import math
import random

import numpy as np
import pytest

from storm_tpu.dist import transport, wire
from storm_tpu.runtime.tracing import TraceContext
from storm_tpu.runtime.tuples import Tuple


def mk_tuple(values, trace=None, origins=frozenset(), anchors=frozenset(),
             fields=None):
    return Tuple(values=list(values),
                 fields=tuple(fields) if fields is not None
                 else tuple(f"f{i}" for i in range(len(values))),
                 source_component="spout", source_task=2, stream="default",
                 edge_id=(7 << 56) | 12345, anchors=anchors, root_ts=100.0,
                 origins=origins, trace=trace)


def values_eq(a, b):
    """NaN-tolerant, type-faithful equality (bool is not 1)."""
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, float) and isinstance(b, float):
        return (a != a and b != b) or a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(map(values_eq, a, b))
    return type(a) is type(b) and a == b


def rand_value(rng: random.Random, depth=0):
    kinds = ["none", "bool", "int", "bigint", "float", "str", "surrogate",
             "bytes"]
    if depth == 0:
        kinds.append("list")
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.randint(-(2**63), 2**63 - 1)
    if k == "bigint":
        return rng.randint(2**63, 2**80) * rng.choice((1, -1))
    if k == "float":
        return rng.choice([float("nan"), float("inf"), float("-inf"),
                           -0.0, rng.uniform(-1e300, 1e300)])
    if k == "str":
        return "".join(chr(rng.randint(32, 0x2FFF)) for _ in range(rng.randint(0, 24)))
    if k == "surrogate":
        # lone surrogates: must cross via surrogatepass, not crash
        return "a" + chr(rng.randint(0xD800, 0xDFFF)) + "z"
    if k == "bytes":
        return rng.randbytes(rng.randint(0, 64))
    return [rand_value(rng, depth + 1) for _ in range(rng.randint(0, 4))]


# ---- binary frame round trips ------------------------------------------------


def test_binary_roundtrip_exhaustive_example():
    trace = TraceContext("ab" * 16, "cd" * 8)
    t = mk_tuple(
        [b"\x00\xffraw", "unié" + chr(0xD800), 3.5, float("nan"),
         float("-inf"), None, True, False, -(2**63), 2**70,
         [1, "a", b"b", [None]], {"k": 1}],
        trace=trace,
        origins=frozenset({("topic-x", 2, 999), ("topic-y", 0, 2**60)}),
        anchors=frozenset({(7 << 56) | 1, 2, 2**64 - 1}))
    frame = wire.encode_deliveries([("inference-bolt", 1, t)], now=200.0)
    assert frame[0] == wire.DELIVERY_MAGIC and frame[1] == wire.WIRE_VERSION
    (c, i, t2), = wire.decode_deliveries(frame, now=200.0)
    assert (c, i) == ("inference-bolt", 1)
    assert values_eq(t2.values[:11], t.values[:11])
    assert t2.values[11] == {"k": 1}
    assert t2.fields == t.fields
    assert t2.stream == "default" and t2.source_component == "spout"
    assert t2.source_task == 2 and t2.edge_id == t.edge_id
    assert t2.anchors == t.anchors and t2.origins == t.origins
    assert abs(t2.root_ts - t.root_ts) < 1e-6
    assert t2.trace.trace_id == "ab" * 16 and t2.trace.span_id == "cd" * 8


def test_binary_roundtrip_seeded_fuzz():
    """300 random delivery batches (the hypothesis strategy, seeded)."""
    rng = random.Random(0xB7)
    for _ in range(300):
        deliveries = []
        for i in range(rng.randint(0, 4)):
            vals = [rand_value(rng) for _ in range(rng.randint(0, 5))]
            trace = (TraceContext(f"{rng.getrandbits(128):032x}",
                                  f"{rng.getrandbits(64):016x}")
                     if rng.random() < 0.3 else None)
            origins = frozenset(
                ("t" * rng.randint(1, 3), rng.randint(0, 2**31 - 1),
                 rng.randint(0, 2**63 - 1))
                for _ in range(rng.randint(0, 2)))
            anchors = frozenset(rng.randint(0, 2**64 - 1)
                                for _ in range(rng.randint(0, 3)))
            deliveries.append(
                ("bolt", i, mk_tuple(vals, trace, origins, anchors)))
        frame = wire.encode_deliveries(deliveries, now=50.0)
        out = wire.decode_deliveries(frame, now=50.0)
        assert len(out) == len(deliveries)
        for (c0, i0, t0), (c1, i1, t1) in zip(deliveries, out):
            assert (c0, i0) == (c1, i1)
            assert values_eq(t0.values, t1.values), (t0.values, t1.values)
            assert t1.anchors == t0.anchors and t1.origins == t0.origins
            assert t1.edge_id == t0.edge_id
            if t0.trace is None:
                assert t1.trace is None
            else:
                assert t1.trace.trace_id == t0.trace.trace_id
                assert t1.trace.span_id == t0.trace.span_id


def test_binary_empty_frame_and_empty_tuple():
    assert wire.decode_deliveries(
        wire.encode_deliveries([], now=0.0), now=0.0) == []
    (c, i, t), = wire.decode_deliveries(
        wire.encode_deliveries([("b", 0, mk_tuple([]))], now=0.0), now=0.0)
    assert t.values == [] and t.fields == ()


def test_binary_large_values_cross_intact():
    big_bytes = bytes(range(256)) * 400              # 102,400 B
    big_str = "packet-é" * 9000                 # > 64 KiB utf-8
    frame = wire.encode_deliveries(
        [("b", 3, mk_tuple([big_bytes, big_str]))], now=1.0)
    (_, _, t), = wire.decode_deliveries(frame, now=1.0)
    assert t.values[0] == big_bytes
    assert t.values[1] == big_str


def test_binary_numpy_scalars_and_age_rebase():
    t = mk_tuple([np.float32(1.5), np.int64(-7), np.bool_(True)])
    frame = wire.encode_deliveries([("b", 0, t)], now=130.0)  # age 30
    (_, _, t2), = wire.decode_deliveries(frame, now=500.0)
    assert t2.values == [1.5, -7, True]
    assert abs(t2.root_ts - 470.0) < 1e-6  # rebased: new_now - age


def test_binary_wire_ndarray_slot_roundtrip():
    try:
        from storm_tpu.serve.marshal import encode_tensor
        encode_tensor(np.zeros((1,), np.float32))
    except ImportError:
        pytest.skip("no tensor marshaller available (native or pyarrow)")
    arr = np.arange(2 * 28 * 28, dtype=np.float32).reshape(2, 28, 28)
    frame = wire.encode_deliveries([("b", 0, mk_tuple([arr]))], now=0.0)
    got = wire.decode_deliveries(frame, now=0.0)[0][2].values[0]
    assert isinstance(got, np.ndarray)
    assert got.dtype == arr.dtype and got.shape == arr.shape
    assert np.array_equal(got, arr)


# ---- corruption must fail loudly ---------------------------------------------


def test_corrupted_crc_fails_loudly():
    frame = bytearray(wire.encode_deliveries(
        [("b", 0, mk_tuple([b"payload", 1.0]))], now=5.0))
    frame[len(frame) // 2] ^= 0x5A
    with pytest.raises(wire.WireError, match="CRC"):
        wire.decode_deliveries(bytes(frame), now=5.0)
    # trailer corruption too
    frame = bytearray(wire.encode_deliveries(
        [("b", 0, mk_tuple(["x"]))], now=5.0))
    frame[-1] ^= 0x01
    with pytest.raises(wire.WireError, match="CRC"):
        wire.decode_deliveries(bytes(frame), now=5.0)


def test_every_single_byte_flip_is_detected():
    """CRC32 detects every burst <= 32 bits, so no single-byte corruption
    may ever decode (at any position: magic, version, flags, lengths,
    payload, trailer)."""
    frame = wire.encode_deliveries(
        [("bolt", 2, mk_tuple(["msg", b"\x01\x02", 3]))], now=9.0)
    for pos in range(len(frame)):
        bad = bytearray(frame)
        bad[pos] ^= 0x80
        with pytest.raises(wire.WireError):
            wire.decode_deliveries(bytes(bad), now=9.0)


def test_truncated_frames_fail_loudly():
    frame = wire.encode_deliveries([("b", 0, mk_tuple(["hello"]))], now=1.0)
    for cut in (0, 3, 11, len(frame) // 2, len(frame) - 1):
        with pytest.raises(wire.WireError):
            wire.decode_deliveries(frame[:cut], now=1.0)


def test_newer_version_and_bad_magic_rejected():
    frame = bytearray(wire.encode_deliveries([], now=0.0))
    frame[1] = wire.WIRE_VERSION + 1
    with pytest.raises(wire.WireError, match="version"):
        wire.decode_deliveries(bytes(frame), now=0.0)
    frame = bytearray(wire.encode_deliveries([], now=0.0))
    frame[0] = 0x7B
    with pytest.raises(wire.WireError, match="magic"):
        wire.decode_deliveries(bytes(frame), now=0.0)


# ---- acks --------------------------------------------------------------------


def test_ack_codecs_roundtrip_and_autodetect():
    rng = random.Random(7)
    for _ in range(100):
        ops = [(rng.choice(("xor", "anc", "ake", "fail")),
                rng.randint(0, 2**64 - 1), rng.randint(0, 2**64 - 1))
               for _ in range(rng.randint(0, 40))]
        assert transport.decode_acks(wire.encode_acks(ops)) == ops
        assert transport.decode_acks(transport.encode_acks(ops)) == ops


def test_ack_frame_corruption_fails_loudly():
    acks = wire.encode_acks([("xor", 1, 2), ("fail", 3, 4)])
    bad = bytearray(acks)
    bad[9] ^= 0x40
    with pytest.raises(wire.WireError):
        wire.decode_acks(bytes(bad))
    with pytest.raises(wire.WireError):
        wire.decode_acks(acks[:-2])


def test_ack_unknown_op_dropped_not_fatal():
    """Forward compat: an op code from a future sender is skipped, matching
    the JSON decoder's unknown-op stance (worker logs + tree replays)."""
    frame = bytearray(wire.encode_acks([("xor", 5, 6)]))
    body = frame[:-4]
    body[8] = 250  # unknown op code
    flags = body[2]
    import zlib

    from storm_tpu.native import crc32c
    crc = (zlib.crc32(body) & 0xFFFFFFFF) if flags & 1 else crc32c(bytes(body))
    reframed = bytes(body) + crc.to_bytes(4, "little")
    assert wire.decode_acks(reframed) == []


# ---- format auto-detection + JSON fallback -----------------------------------


def test_transport_decoders_autodetect_both_formats():
    t = mk_tuple(["hello", 1, 2.5])
    jpay = transport.encode_deliveries([("b", 0, t)])
    bpay = wire.encode_deliveries([("b", 0, t)], now=100.0)
    assert jpay[:1] == b"["          # JSON array
    assert bpay[0] == wire.DELIVERY_MAGIC
    for payload in (jpay, bpay):
        (c, i, t2), = transport.decode_deliveries(payload)
        assert (c, i) == ("b", 0)
        assert t2.values == ["hello", 1, 2.5]


def test_json_wire_roundtrip_preserves_nan_and_surrogates():
    vals = ["a" + chr(0xDC80), float("nan"), float("inf"), None, True,
            -(2**63)]
    payload = transport.encode_deliveries([("b", 1, mk_tuple(vals))])
    (_, _, t), = transport.decode_deliveries(payload)
    assert values_eq(t.values, vals)


def test_json_wire_still_rejects_bytes_values():
    """The fallback wire keeps its loud TypeError on bytes — that is what
    negotiation falls back TO, so the restriction must stay visible."""
    with pytest.raises(TypeError, match="binary"):
        transport.encode_deliveries([("b", 0, mk_tuple([b"raw"]))])


def test_math_extremes_roundtrip_binary():
    vals = [math.pi, 5e-324, 1.7976931348623157e308, -0.0]
    frame = wire.encode_deliveries([("b", 0, mk_tuple(vals))], now=0.0)
    out = wire.decode_deliveries(frame, now=0.0)[0][2].values
    assert out == vals
    assert math.copysign(1.0, out[3]) == -1.0
