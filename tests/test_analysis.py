"""Unit tests for the invariant analyzer (storm_tpu/analysis/).

Each rule gets a positive fixture (a minimal snippet that MUST trip it)
and a negative fixture (the sanctioned idiom that must NOT) — the negative
fixtures are the idioms the real tree relies on (condition-wait under its
own lock, finally-based deferral, static_argnames branching), so a checker
regression shows up here before it floods the clean-tree gate."""

import json
import os
import textwrap

import pytest

from storm_tpu.analysis import (
    LintConfig,
    filter_new,
    lint_source,
    load_baseline,
    load_config,
    write_baseline,
)
from storm_tpu.analysis.core import parse_source
from storm_tpu.analysis.locks import check_ordering
from storm_tpu.analysis.observability import check_kinds, generate_registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, **cfg):
    return lint_source(textwrap.dedent(src), "fixture.py",
                       LintConfig(**cfg) if cfg else None)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# LCK001: blocking call under a lock
# ---------------------------------------------------------------------------


def test_lck001_sleep_under_with_lock():
    fs = lint("""
        import threading, time
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def f(self):
                with self._lock:
                    time.sleep(1)
    """)
    assert rules_of(fs) == {"LCK001"}
    (f,) = fs
    assert f.detail == "time.sleep"
    assert "hint" in f.to_dict() and f.line == 8


def test_lck001_sleep_outside_lock_ok():
    fs = lint("""
        import threading, time
        class C:
            def f(self):
                with self._lock:
                    x = 1
                time.sleep(1)
    """)
    assert fs == []


def test_lck001_acquire_release_region():
    fs = lint("""
        import time
        def f(lock):
            lock.acquire()
            time.sleep(1)
            lock.release()
            time.sleep(2)
    """)
    assert [f.rule for f in fs] == ["LCK001"]
    assert fs[0].line == 5  # only the sleep inside the region


def test_lck001_condition_wait_on_held_lock_exempt():
    # Condition.wait releases the lock — the sanctioned sleep-under-lock
    # (continuous batcher's dispatcher loop).
    fs = lint("""
        class C:
            def f(self):
                with self._cond:
                    while not self._ready:
                        self._cond.wait(timeout=0.1)
    """)
    assert fs == []


def test_lck001_foreign_wait_under_lock_flagged():
    fs = lint("""
        class C:
            def f(self):
                with self._lock:
                    self._event.wait()
    """)
    assert rules_of(fs) == {"LCK001"}


def test_lck001_queue_get_vs_dict_get():
    fs = lint("""
        class C:
            def f(self):
                with self._lock:
                    item = self.queue.get()
                    val = self._cache.get("key")
    """)
    assert len(fs) == 1 and fs[0].detail == "self.queue.get"


def test_lck001_future_result_and_zero_arg_join():
    fs = lint("""
        class C:
            def f(self):
                with self._lock:
                    v = fut.result()
                    self._thread.join()
                    s = ",".join(parts)
    """)
    assert sorted(f.detail for f in fs) == ["fut.result", "self._thread.join"]


def test_lck001_configured_blocking_method():
    src = """
        class C:
            def f(self):
                with self._lock:
                    self.client.control("drain")
    """
    assert lint(src) == []  # not blocking by default
    fs = lint(src, blocking_methods=["control"])
    assert rules_of(fs) == {"LCK001"}


# ---------------------------------------------------------------------------
# LCK002: lock-order inversion
# ---------------------------------------------------------------------------


def _files(*srcs):
    return [parse_source(textwrap.dedent(s), f"mod{i}.py")
            for i, s in enumerate(srcs)]


def test_lck002_inversion_flagged():
    fs = check_ordering(_files("""
        class A:
            def f(self):
                with self._lock_a:
                    with self._lock_b:
                        pass
            def g(self):
                with self._lock_b:
                    with self._lock_a:
                        pass
    """), LintConfig())
    assert [f.rule for f in fs] == ["LCK002"]
    assert "opposite order" in fs[0].message


def test_lck002_consistent_order_ok():
    fs = check_ordering(_files("""
        class A:
            def f(self):
                with self._lock_a:
                    with self._lock_b:
                        pass
            def g(self):
                with self._lock_a:
                    with self._lock_b:
                        pass
    """), LintConfig())
    assert fs == []


def test_lck002_cross_file_inversion():
    fs = check_ordering(_files(
        """
        import m
        def f():
            with GLOBAL_LOCK:
                with m.OTHER_LOCK:
                    pass
        """,
        """
        import m
        def g():
            with m.OTHER_LOCK:
                with GLOBAL_LOCK:
                    pass
        """), LintConfig())
    # different modules -> different global-lock identities; only the
    # m.OTHER_LOCK pair unifies, and the GLOBAL_LOCK halves are
    # per-module — no shared 2-cycle unless identities match
    assert all(f.rule == "LCK002" for f in fs)


# ---------------------------------------------------------------------------
# XO001: exactly-once discipline
# ---------------------------------------------------------------------------


def test_xo001_unhandled_else_path():
    fs = lint("""
        class FooBolt:
            def execute(self, t):
                if t.values[0] > 0:
                    self.collector.ack(t)
    """)
    assert rules_of(fs) == {"XO001"}


def test_xo001_all_paths_acked_ok():
    fs = lint("""
        class FooBolt:
            def execute(self, t):
                if t.values[0] > 0:
                    self.collector.ack(t)
                else:
                    self.collector.fail(t)
    """)
    assert fs == []


def test_xo001_finally_deferral_rescues_all_paths():
    fs = lint("""
        class BarBolt:
            def execute(self, t):
                try:
                    risky(t.values)
                    if maybe():
                        return
                finally:
                    self._pending.append(t)
    """)
    assert fs == []


def test_xo001_exception_edge_swallowed_unhandled():
    # the except arm swallows the error without failing the tuple: the
    # ledger waits forever — the exact silent-drop class
    fs = lint("""
        class QuxBolt:
            def execute(self, t):
                try:
                    self.collector.ack(t)
                except Exception:
                    pass
    """)
    assert rules_of(fs) == {"XO001"}


def test_xo001_raise_through_is_handled():
    # BoltExecutor._run catches and fails the tuple
    fs = lint("""
        class BazBolt:
            def execute(self, t):
                if not valid(t.values):
                    raise ValueError("bad")
                self.collector.ack(t)
    """)
    assert fs == []


def test_xo001_test_position_call_not_ownership():
    fs = lint("""
        class TickBolt:
            def execute(self, t):
                if is_tick(t):
                    return
                self.collector.ack(t)
    """)
    # `if is_tick(t)` reads the tuple; the True arm returns it unhandled
    assert rules_of(fs) == {"XO001"}


def test_xo001_deferral_and_store_count():
    fs = lint("""
        class DeferBolt:
            def execute(self, t):
                if fast(t.values):
                    self.registry.defer(t)
                else:
                    self._by_key[t.values[0]] = t
    """)
    assert fs == []


def test_xo001_non_tuple_classes_skipped():
    fs = lint("""
        class Helper:
            def execute(self, t):
                return 1
    """)
    assert fs == []


def test_xo001_abstract_body_skipped():
    fs = lint("""
        class BaseBolt:
            def execute(self, t):
                raise NotImplementedError
        class PassBolt:
            def execute(self, t):
                ...
    """)
    assert fs == []


# ---------------------------------------------------------------------------
# JIT001-004: tracer hygiene
# ---------------------------------------------------------------------------


def test_jit001_numpy_on_traced_arg():
    fs = lint("""
        import jax
        import numpy as np
        @jax.jit
        def f(x):
            return np.sum(x)
    """)
    assert rules_of(fs) == {"JIT001"}


def test_jit001_jnp_ok():
    fs = lint("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return jnp.sum(x)
    """)
    assert fs == []


def test_jit002_branch_on_tracer():
    fs = lint("""
        import jax
        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)
    assert rules_of(fs) == {"JIT002"}


def test_jit002_static_argname_branch_ok():
    fs = lint("""
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("flag",))
        def f(x, flag):
            if flag:
                return x
            return -x
    """)
    assert fs == []


def test_jit002_shape_branch_ok():
    # x.shape is concrete at trace time — the kernels' row-block math
    fs = lint("""
        import jax
        @jax.jit
        def f(x):
            rows = x.shape[0]
            r8 = rows if rows > 8 else 8
            assert x.ndim == 2
            return x * r8
    """)
    assert fs == []


def test_jit003_clock_read():
    fs = lint("""
        import jax, time
        @jax.jit
        def f(x):
            t0 = time.time()
            return x * t0
    """)
    assert rules_of(fs) == {"JIT003"}


def test_jit004_host_sync():
    fs = lint("""
        import jax
        @jax.jit
        def f(x):
            y = x * 2
            y.block_until_ready()
            return float(y)
    """)
    assert rules_of(fs) == {"JIT004"} and len(fs) == 2


def test_jit_call_form_target_resolved():
    # the engine builds fwd as a closure, then self._fwd = jax.jit(fwd)
    fs = lint("""
        import jax
        import numpy as np
        def build():
            def fwd(params, batch):
                return np.dot(params, batch)
            return jax.jit(fwd)
    """)
    assert rules_of(fs) == {"JIT001"}


def test_unjitted_function_ignored():
    fs = lint("""
        import numpy as np, time
        def f(x):
            if x > 0:
                time.sleep(0)
            return np.sum(x)
    """)
    assert fs == []


# ---------------------------------------------------------------------------
# OBS001-003: observability hygiene
# ---------------------------------------------------------------------------


def test_obs001_unknown_metric_name():
    fs = lint("""
        def f(m):
            m.counter("bolt", "bogus_metric_typo").inc()
    """)
    assert rules_of(fs) == {"OBS001"}
    assert "registry" in fs[0].message


def test_obs001_registered_name_ok():
    fs = lint("""
        def f(m):
            m.counter("bolt", "emitted").inc()
            m.histogram("bolt", "execute_ms").observe(1.0)
    """)
    assert fs == []


def test_obs001_fstring_pattern_matches_registry():
    # tracing's span() records f"{name}_ms" -> pattern "*_ms"
    fs = lint("""
        def f(m, name):
            m.histogram("bolt", f"{name}_ms").observe(1.0)
    """)
    assert fs == []


def test_obs002_unbalanced_trace():
    fs = lint("""
        import jax
        def f(d):
            jax.profiler.start_trace(d)
            work()
    """)
    assert rules_of(fs) == {"OBS002"}


def test_obs002_balanced_trace_ok():
    fs = lint("""
        import jax
        def f(d):
            jax.profiler.start_trace(d)
            try:
                work()
            finally:
                jax.profiler.stop_trace()
    """)
    assert fs == []


def test_obs003_conflicting_kinds():
    fs = check_kinds(_files(
        'def f(m):\n    m.counter("a", "dual_series").inc()\n',
        'def g(m):\n    m.histogram("b", "dual_series").observe(1)\n',
    ), LintConfig())
    assert [f.rule for f in fs] == ["OBS003"]


def test_registry_generation_roundtrip():
    src = generate_registry(_files(
        'def f(m):\n'
        '    m.counter("a", "gen_fixture_total").inc()\n'
        '    m.histogram("a", f"lane_{k}_ms").observe(1)\n'))
    ns = {}
    exec(compile(src, "metric_names.py", "exec"), ns)
    assert "gen_fixture_total" in ns["METRIC_NAMES"]
    assert "lane_*_ms" in ns["METRIC_PATTERNS"]
    assert ns["is_known"]("lane_7_ms") and not ns["is_known"]("nope")


# ---------------------------------------------------------------------------
# baseline, config, CLI
# ---------------------------------------------------------------------------

_POSITIVE = """
    import threading, time
    class C:
        def f(self):
            with self._lock:
                time.sleep(1)
"""


def test_baseline_suppression_roundtrip(tmp_path):
    fs = lint(_POSITIVE)
    assert fs
    path = str(tmp_path / "baseline.json")
    write_baseline(path, fs)
    baseline = load_baseline(path)
    assert filter_new(fs, baseline) == []
    # an unrelated edit moving the line must NOT invalidate the entry
    moved = lint("\n\n# comment\n" + textwrap.dedent(_POSITIVE))
    assert moved[0].line != fs[0].line
    assert filter_new(moved, baseline) == []
    # preserving prior justifications across rewrites
    data = json.loads(open(path).read())
    data["findings"][0]["why"] = "reviewed: intentional"
    open(path, "w").write(json.dumps(data))
    write_baseline(path, fs, prior=load_baseline(path))
    assert "intentional" in open(path).read()


def test_config_from_pyproject(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.storm-tpu.lint]
        disable = ["LCK002"]
        exclude = ["generated/*"]
        blocking_methods = ["rpc_call"]
        exclude_XO001 = ["storm_tpu/legacy/*"]
    """))
    cfg = load_config(str(tmp_path))
    assert "LCK002" not in cfg.enable and "LCK001" in cfg.enable
    assert cfg.blocking_methods == ["rpc_call"]
    assert cfg.excluded("LCK001", "generated/x.py")
    assert cfg.excluded("XO001", "storm_tpu/legacy/old.py")
    assert not cfg.excluded("LCK001", "storm_tpu/legacy/old.py")


def test_repo_config_has_grpc_blocking_methods():
    cfg = load_config(ROOT)
    assert "control" in cfg.blocking_methods
    # Round-14 retry/backoff wrappers: a deadline-budgeted retry loop can
    # sleep for SECONDS — under a lock that is a pipeline-wide stall, so
    # the repo config must keep them in the blocking-call table.
    for m in ("call_sync", "throttle_sync", "wait_ready"):
        assert m in cfg.blocking_methods, m


def test_lck001_retry_loop_under_lock():
    """A retry wrapper invoked while holding a lock is an LCK001 finding
    with the repo's configured blocking-method table."""
    src = """
        class C:
            def f(self):
                with self._lock:
                    self._retry.call_sync(self._send, b"x")
    """
    assert lint(src) == []  # unknown method without the table
    fs = lint(src, blocking_methods=load_config(ROOT).blocking_methods)
    assert rules_of(fs) == {"LCK001"}


def test_cli_json_schema(capsys):
    from storm_tpu.main import main
    rc = main(["lint", "--root", ROOT, "--json",
               "storm_tpu/analysis/core.py"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(out) == {"findings", "total", "baselined", "new"}
    for f in out["findings"]:
        assert {"rule", "description", "path", "line", "scope", "message",
                "hint", "key"} <= set(f)


def test_cli_rules_listing(capsys):
    from storm_tpu.main import main
    assert main(["lint", "--rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("LCK001", "LCK002", "XO001", "JIT001", "OBS001"):
        assert rule in out


def test_cli_bad_path(capsys):
    from storm_tpu.main import main
    assert main(["lint", "--root", ROOT, "no/such/dir"]) == 2


def test_cli_nonzero_on_new_finding(tmp_path, capsys):
    from storm_tpu.main import main
    pkg = tmp_path / "storm_tpu" / "analysis"
    pkg.mkdir(parents=True)
    bad = tmp_path / "mod.py"
    bad.write_text(textwrap.dedent(_POSITIVE))
    assert main(["lint", "--root", str(tmp_path), "mod.py"]) == 1
    err = capsys.readouterr()
    assert "LCK001" in err.out
